#!/bin/bash
# Throughput/scaling sweep — counterpart of the reference's
# HydraGNN-scaling-test.sh (up to 8192 GCDs, HYDRAGNN_VALTEST=0
# throughput mode). Runs the bench vector and a val/test-free training
# pass at increasing batch sizes on one slice; repeat across slice
# shapes (v5p-8/16/32...) for the scaling curve.
#
# Usage:
#   TPU_NAME=my-v5p-8 ZONE=us-east5-a bash run-scripts/tpu-scaling-test.sh
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME}
ZONE=${ZONE:?set ZONE}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "
    cd ~/hydragnn_tpu_repo &&
    python bench.py &&
    # throughput mode: skip val/test epochs (reference HYDRAGNN_VALTEST=0)
    HYDRAGNN_TPU_VALTEST=0 HYDRAGNN_TPU_MAX_NUM_BATCH=200 \
    python examples/qm9/qm9.py --synthetic --mols 4096 --epochs 3
  "
