#!/bin/bash
# Multibranch GFM training on a TPU pod slice — counterpart of the
# reference's 128-node Frontier multibranch job
# (run-scripts/SC25-multibranch.sh: per-dataset branch process groups
# over NCCL + DDStore). Here the branch device groups are sub-meshes
# (parallel/multibranch.py); the proportional split matches the
# reference's HYDRAGNN_TASK_PARALLEL_PROPORTIONAL_SPLIT behavior.
#
# Usage:
#   TPU_NAME=my-v5p-32 ZONE=us-east5-a bash run-scripts/tpu-multibranch-gfm.sh
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME to the pod-slice name}
ZONE=${ZONE:?set ZONE}
EPOCHS=${EPOCHS:-30}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "
    cd ~/hydragnn_tpu_repo &&
    # proportional device split by dataset size (default; =0 -> uniform)
    HYDRAGNN_TPU_TASK_PARALLEL_PROPORTIONAL_SPLIT=1 \
    python examples/multibranch/train.py --epochs $EPOCHS
  "
