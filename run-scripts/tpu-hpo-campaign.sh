#!/bin/bash
# HPO campaign over a fleet of single-chip TPU VMs — the counterpart
# of the reference's DeepHyper SLURM campaigns (reference run-scripts/
# job-omnistat-deephyper.sh + examples/multidataset_hpo_sc26/
# gfm_deephyper_multi_all_mpnn.py: one trial per allocation, search
# over mpnn_type x width x lr).
#
# TPU shape: trials are independent single-chip trainings, so the
# natural launch is N queued-resource VMs, each taking a strided slice
# of the deterministically-shuffled search grid (--worker i
# --num-workers N in the driver) — a true partition, no duplicated
# trials. The persistent compile cache (HYDRAGNN_TPU_COMPILE_CACHE)
# makes repeat architectures reload executables instead of recompiling.
#
# Usage:
#   TPU_PREFIX=hpo-worker N_WORKERS=4 ZONE=us-east5-a \
#     bash run-scripts/tpu-hpo-campaign.sh \
#     examples/multidataset_hpo_sc26/train_hpo.py --trials 8
set -euo pipefail

TPU_PREFIX=${TPU_PREFIX:?set TPU_PREFIX (VM names <prefix>-0..N-1)}
N_WORKERS=${N_WORKERS:?set N_WORKERS}
ZONE=${ZONE:?set ZONE}
DRIVER=${1:?usage: tpu-hpo-campaign.sh <hpo_driver.py> [args...]}
shift
# %q-quote caller args so they survive the remote shell verbatim.
ARGS=$(printf ' %q' "$@")

pids=()
for i in $(seq 0 $((N_WORKERS - 1))); do
  gcloud compute tpus tpu-vm ssh "${TPU_PREFIX}-${i}" --zone "$ZONE" \
    --command "
      cd ~/hydragnn_tpu_repo &&
      HYDRAGNN_TPU_COMPILE_CACHE=~/.hydragnn_xla_cache \
      python $DRIVER$ARGS --worker ${i} --num-workers ${N_WORKERS} \
        2>&1 | tee hpo_worker_${i}.log
    " &
  pids+=($!)
done

# set -e does not cover backgrounded jobs: collect each worker's exit
# status so a failed slice fails the campaign loudly.
fail=0
for i in "${!pids[@]}"; do
  if ! wait "${pids[$i]}"; then
    echo "worker ${i} FAILED (see hpo_worker_${i}.log)" >&2
    fail=1
  fi
done
if [ "$fail" -ne 0 ]; then
  echo 'campaign FAILED: at least one worker slice did not finish' >&2
  exit 1
fi
echo 'campaign done; collect hpo_worker_*.log best lines'
