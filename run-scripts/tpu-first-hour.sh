#!/bin/bash
# tpu-first-hour.sh — total conversion of a live TPU window, one command.
#
# The build container's TPU tunnel has been dead for most of rounds 3-5
# (README "TPU availability log"); live windows are rare and short. When
# one opens, this script captures EVERYTHING the perf story needs in one
# shot and commits it:
#
#   1. probe       tiny jit end-to-end (a half-alive tunnel enumerates
#                  devices but hangs the first compile)
#   2. bench       the 5-config parity bench -> BENCH_TPU.json
#                  (graphs/s, per-config model-FLOPs anchors, pad_ratio,
#                  mfu, vs_baseline range)
#   3. roofline    tools/roofline_segment.py -> ROOFLINE_TPU.txt
#                  (achieved HBM GB/s + the HYDRAGNN_TPU_SEGMENT_IMPL
#                  pallas/xla decision rows, per shape/dtype)
#   4. tracer      a short traced training run -> TRACE_TPU_timing.csv
#                  (per-region wall clock + libtpu HBM/duty-cycle
#                  columns from DeviceMetricsTracer)
#   5. commit      all artifacts in one commit
#
# Usage:
#   bash run-scripts/tpu-first-hour.sh            # real capture (TPU)
#   bash run-scripts/tpu-first-hour.sh --dry-run  # CPU rehearsal: same
#       pipeline on the CPU backend with tiny shapes/budgets, writes
#       *_DRYRUN artifacts, never commits
set -uo pipefail

cd "$(dirname "$0")/.."
REPO=$(pwd)
DRY=0
[ "${1:-}" = "--dry-run" ] && DRY=1

STAMP=$(date -u +%Y-%m-%dT%H:%MZ)
PROBE_LOG=logs/tpu_probes.log
mkdir -p logs

if [ "$DRY" = 1 ]; then
  # CPU rehearsal: pin the CPU backend the same way tests/conftest.py
  # does (unsetting PALLAS_AXON_POOL_IPS is what disables the plugin).
  export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
  export HYDRAGNN_BENCH_BUDGET=240 HYDRAGNN_ROOFLINE_SHAPES=small
  BENCH_OUT=BENCH_TPU_DRYRUN.json
  ROOF_OUT=ROOFLINE_TPU_DRYRUN.txt
  TRACE_OUT=TRACE_TPU_DRYRUN_timing.csv
  echo "== dry run (CPU backend, tiny shapes; artifacts not committed)"
else
  BENCH_OUT=BENCH_TPU.json
  ROOF_OUT=ROOFLINE_TPU.txt
  TRACE_OUT=TRACE_TPU_timing.csv
  echo "== probing TPU tunnel (180s timeout)"
  if timeout 180 python -c \
      'import jax, jax.numpy as jnp; d=jax.devices(); print(jax.jit(lambda x: x+1)(jnp.zeros(()))); print("live:", d)'
  then
    echo "$STAMP probe OK — capturing" | tee -a "$PROBE_LOG"
  else
    echo "$STAMP probe timed out/failed — tunnel still dead" | tee -a "$PROBE_LOG"
    exit 1
  fi
fi

FAILED=0

echo "== [1/3] bench (5 parity configs)"
if python bench.py >/tmp/bench_capture.out 2>/tmp/bench_capture.err; then
  tail -1 /tmp/bench_capture.out > "$BENCH_OUT"
  echo "   -> $BENCH_OUT"
else
  echo "   bench FAILED (stderr tail):"; tail -5 /tmp/bench_capture.err
  FAILED=1
fi

echo "== [2/3] roofline + segment-impl decision rows"
if python tools/roofline_segment.py >"$ROOF_OUT" 2>/tmp/roofline.err; then
  echo "   -> $ROOF_OUT ($(grep -c . "$ROOF_OUT") lines)"
else
  echo "   roofline FAILED (stderr tail):"; tail -5 /tmp/roofline.err
  FAILED=1
fi

echo "== [3/3] traced training run (DeviceMetricsTracer CSV)"
if HYDRAGNN_TPU_TRACE_LEVEL=1 python - "$TRACE_OUT" <<'EOF' 2>/tmp/trace.err
import json, shutil, sys, glob, os
from hydragnn_tpu.runner import run_training
from hydragnn_tpu.data.loader import split_dataset

sys.path.insert(0, ".")
from bench import _molecules, _schnet_config

samples = _molecules(256, 9, 30, 4.0, 32, seed=7)
tr, va, te = split_dataset(samples, 0.8)
config = _schnet_config(64)
config["NeuralNetwork"]["Training"]["num_epoch"] = 3
config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
run_training(config, datasets=(tr, va, te))
csvs = sorted(glob.glob("logs/*/timing.p0.csv"), key=os.path.getmtime)
shutil.copy(csvs[-1], sys.argv[1])
EOF
then
  echo "   -> $TRACE_OUT"
else
  echo "   traced run FAILED (stderr tail):"; tail -5 /tmp/trace.err
  FAILED=1
fi

if [ "$DRY" = 1 ]; then
  echo "== dry run complete (FAILED=$FAILED); artifacts:"
  ls -la "$BENCH_OUT" "$ROOF_OUT" "$TRACE_OUT" 2>/dev/null
  exit $FAILED
fi

echo "== committing capture"
git add "$BENCH_OUT" "$ROOF_OUT" "$TRACE_OUT" "$PROBE_LOG"
git commit -m "Capture TPU window: bench + roofline + device-metrics trace ($STAMP)"
echo "== done (FAILED=$FAILED)"
exit $FAILED
