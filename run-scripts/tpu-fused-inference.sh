#!/bin/bash
# AOT fused-inference deployment on a TPU VM — the counterpart of the
# reference's fused-inference campaign scripts
# (reference run-scripts/SC26_fused_inference.sh + examples/
# multidataset_hpo_sc26/inference_fused.py: torch-compiled fused
# inference over exported checkpoints).
#
# The TPU-native pipeline is two stages:
#   1. EXPORT once, anywhere: serialize the trained forward (weights
#      baked in) as a StableHLO artifact per padding bucket —
#      hydragnn_tpu.export_inference (hydragnn_tpu/export.py), as the
#      qm7x inference driver does (examples/qm7x/inference.py).
#   2. SERVE on the TPU VM with no model code, config, or checkpoint:
#      hydragnn_tpu.load_exported(artifact) and call it on batches
#      padded to the artifact's bucket.
#
# Usage (runs the end-to-end export->serve demo driver on the VM):
#   TPU_NAME=my-v5e ZONE=us-east5-a \
#     bash run-scripts/tpu-fused-inference.sh
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME}
ZONE=${ZONE:?set ZONE}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --command "
  cd ~/hydragnn_tpu_repo &&
  python examples/qm7x/inference.py
"
