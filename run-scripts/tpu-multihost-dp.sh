#!/bin/bash
# Multi-host data-parallel training on a Cloud TPU pod slice — the
# TPU-native counterpart of the reference's SLURM/srun launches
# (reference run-scripts/SC25-baseline.sh: sbatch + srun over NCCL).
#
# On TPU there is no mpirun: every host of the slice runs the SAME
# script; jax.distributed discovers rank/coordinator from the TPU
# metadata environment, and hydragnn_tpu's runtime shards the dataset
# per process (parallel/runtime.py maybe_initialize_distributed ->
# shard_for_process).
#
# Usage:
#   TPU_NAME=my-v5p-32 ZONE=us-east5-a bash run-scripts/tpu-multihost-dp.sh \
#       examples/qm9/qm9.py --epochs 30
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME to the pod-slice name}
ZONE=${ZONE:?set ZONE}
DRIVER=${1:?usage: tpu-multihost-dp.sh <driver.py> [args...]}
shift

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "
    cd ~/hydragnn_tpu_repo &&
    # Mesh: all chips on the data axis; add fsdp via
    # HYDRAGNN_TPU_MESH='data=16,fsdp=2' or Training.Parallelism.
    HYDRAGNN_TPU_TRACE_LEVEL=\${HYDRAGNN_TPU_TRACE_LEVEL:-0} \
    python $DRIVER $*
  "
