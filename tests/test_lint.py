"""graftlint: rule-family fixtures (positive snippet must flag,
negative must not), suppression/baseline mechanics, the jax-api
regression on the seed's ``jax.shard_map`` breakage, and the tier-1
full-tree gate (``--check`` must stay clean against the checked-in
baseline).

Pure host-side AST analysis — no device work — so everything here is
cheap even on the 2-vCPU CI host except the one subprocess CLI
contract test.
"""

import json
import os
import subprocess
import sys

import pytest

import tests._cpu  # noqa: F401  (side effect: pin CPU platform)

from hydragnn_tpu.analysis import lint_sources, run_lint, write_baseline
from hydragnn_tpu.analysis.engine import run_on_context, collect_files
from hydragnn_tpu.analysis.rules.config_schema import ConfigSchemaRule
from hydragnn_tpu.analysis.rules.host_sync import HostSyncRule
from hydragnn_tpu.analysis.rules.jax_api import JaxApiRule
from hydragnn_tpu.analysis.rules.nondet import NondetRule
from hydragnn_tpu.analysis.rules.retrace import RetraceRule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings_of(sources, rules):
    return lint_sources(sources, rules)


# ---------------------------------------------------------------------------
# jax-api


# The exact decorator idiom the seed shipped in
# hydragnn_tpu/parallel/graphshard.py:377 (pre-fix): jax.shard_map does
# not exist in jax 0.4.x — it broke all 7 graphshard tests, both
# giant-graph example tests, and the dryrun_graphshard entry leg.
SEED_SHARD_MAP_SNIPPET = '''
from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P


def halo_mpnn_forward(params, shards, mesh):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(),) + (P("graph"),) * 7,
        out_specs=P(),
    )
    def fwd(params, x):
        return x

    return fwd(params, shards)
'''


def test_jax_api_flags_seed_shard_map_pattern():
    f = findings_of({"pkg/graphshard.py": SEED_SHARD_MAP_SNIPPET},
                    [JaxApiRule()])
    assert len(f) == 1
    assert f[0].rule == "jax-api"
    assert "`jax.shard_map` does not exist" in f[0].message
    # the relocation probe must point at the real home
    assert "jax.experimental.shard_map.shard_map" in f[0].message


def test_jax_api_accepts_valid_chains():
    src = '''
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils


def f(x):
    y = jnp.sum(x) + lax.psum(x, "i")
    jax.block_until_ready(y)
    z = jax.ops.segment_sum(x, x, num_segments=4)
    sm = getattr(jax, "shard_map", None)  # sanctioned version probe
    return jax.experimental.shard_map.shard_map, P(), z, sm
'''
    assert findings_of({"m.py": src}, [JaxApiRule()]) == []


def test_jax_api_flags_bad_from_import_and_aliased_chain():
    src = '''
import jax.numpy as jnp
from jax.lax import not_a_real_primitive_xyz


def f(x):
    return jnp.definitely_not_an_api_xyz(x)
'''
    f = findings_of({"m.py": src}, [JaxApiRule()])
    msgs = " | ".join(x.message for x in f)
    assert "jax.lax.not_a_real_primitive_xyz" in msgs
    assert "jax.numpy.definitely_not_an_api_xyz" in msgs


def test_jax_api_current_graphshard_is_clean():
    """Regression: the fixed graphshard module resolves everything."""
    path = os.path.join(REPO, "hydragnn_tpu/parallel/graphshard.py")
    with open(path) as fh:
        src = fh.read()
    f = findings_of({"hydragnn_tpu/parallel/graphshard.py": src},
                    [JaxApiRule()])
    assert f == []
    # and the runtime accessor actually resolved
    from hydragnn_tpu.parallel import graphshard

    assert callable(graphshard.shard_map)


# ---------------------------------------------------------------------------
# retrace


def test_retrace_flags_fstring_of_traced_param():
    src = '''
import jax


@jax.jit
def step(x):
    label = f"value={x}"
    return x, label
'''
    f = findings_of({"m.py": src}, [RetraceRule()])
    assert any("f-string interpolates traced parameter `x`" in x.message
               for x in f)


def test_retrace_allows_loop_index_fstring():
    """params[f"filter_{i}"] over range() is idiomatic jax — the loop
    var is a Python int, not a tracer. Must NOT flag."""
    src = '''
import jax


@jax.jit
def fwd(params, x):
    for i in range(4):
        x = x @ params[f"filter_{i}"]
    return x
'''
    assert findings_of({"m.py": src}, [RetraceRule()]) == []


def test_retrace_flags_concretizing_call():
    src = '''
import jax


@jax.jit
def step(x):
    return float(x)
'''
    f = findings_of({"m.py": src}, [RetraceRule()])
    assert any("`float()` of traced parameter" in x.message for x in f)


def test_retrace_container_param_without_static():
    src = '''
import jax
from functools import partial


@jax.jit
def bad(x, cfg: dict):
    return x


@partial(jax.jit, static_argnames=("cfg",))
def good(x, cfg: dict):
    return x
'''
    f = findings_of({"m.py": src}, [RetraceRule()])
    assert len(f) == 1
    assert "`bad` takes container parameter `cfg`" in f[0].message


def test_retrace_jit_in_loop():
    src = '''
import jax


def train(fns, xs):
    out = []
    for fn in fns:
        out.append(jax.jit(fn)(xs))
    step = jax.jit(fns[0])  # hoisted: fine
    return out, step
'''
    f = findings_of({"m.py": src}, [RetraceRule()])
    assert len(f) == 1
    assert "inside a loop body" in f[0].message


def test_retrace_factory_decorator_in_loop_reported_once():
    """A @jax.jit() factory decorator on a def inside a loop is ONE
    defect — the Call branch must not double-report the decorator."""
    src = '''
import jax


def build(xs):
    out = []
    for x in xs:
        @jax.jit(donate_argnums=0)
        def step(v):
            return v + x

        out.append(step(x))
    return out
'''
    f = findings_of({"m.py": src}, [RetraceRule()])
    assert len(f) == 1
    assert "defined inside a loop body" in f[0].message


def test_retrace_loop_else_clause_not_flagged():
    """A for/while else-clause runs once after the loop — jit there is
    the hoisted pattern, not a per-iteration rebuild."""
    src = '''
import jax


def train(fns, xs):
    for fn in fns:
        pass
    else:
        step = jax.jit(fns[0])
    return step(xs)
'''
    assert findings_of({"m.py": src}, [RetraceRule()]) == []


# ---------------------------------------------------------------------------
# host-sync (call-graph reachability)

HOT_LOOP_FIXTURE = '''
import jax


def _metrics(acc):
    return acc.item()


def _cold_report(acc):
    # identical pattern, NOT reachable from the step path: no finding
    return acc.item()


def _run_epoch(step_fn, state, loader):
    acc = None
    for batch in loader:
        state, loss = step_fn(state, batch)
        acc = loss if acc is None else acc + loss
    return _metrics(acc)
'''


def test_host_sync_reachability_from_run_epoch():
    f = findings_of({"pkg/train/loop.py": HOT_LOOP_FIXTURE},
                    [HostSyncRule()])
    assert len(f) == 1
    assert "_metrics" in f[0].message and ".item()" in f[0].message


def test_host_sync_inside_jitted_flags_np():
    src = '''
import jax
import numpy as np


@jax.jit
def step(x):
    return np.asarray(x).sum()
'''
    f = findings_of({"m.py": src}, [HostSyncRule()])
    assert len(f) == 1
    assert "np.asarray" in f[0].message


def test_host_sync_reaches_nested_defs():
    """Nested helper functions are where hot-path sync calls hide —
    reachability must descend into a function's own nested defs."""
    src = '''
import jax


def _run_epoch(step_fn, state, loader):
    def _metrics(acc):
        return acc.item()

    acc = None
    for batch in loader:
        state, loss = step_fn(state, batch)
        acc = loss if acc is None else acc + loss
    return _metrics(acc)
'''
    f = findings_of({"pkg/train/loop.py": src}, [HostSyncRule()])
    assert len(f) == 1
    assert "_metrics" in f[0].message and ".item()" in f[0].message


def test_host_sync_np_in_helper_reachable_from_jit():
    """Helpers called from jitted code are inlined into the trace —
    np.asarray there is the same hard error as in the jitted body."""
    src = '''
import jax
import numpy as np


def helper(x):
    return np.asarray(x)


@jax.jit
def step(x):
    return helper(x)
'''
    f = findings_of({"m.py": src}, [HostSyncRule()])
    assert len(f) == 1
    assert "np.asarray" in f[0].message
    assert "reachable from jit-compiled code" in f[0].message


def test_host_sync_negative_plain_host_code():
    src = '''
import numpy as np


def collate(batch):
    return np.asarray(batch).item()
'''
    assert findings_of({"m.py": src}, [HostSyncRule()]) == []


# ---------------------------------------------------------------------------
# nondet

PLAN_FIXTURE = '''
import time

import numpy as np


def _order(n):
    return np.random.permutation(n)


def _seeded_order(n, seed):
    return np.random.default_rng(seed).permutation(n)


class GraphLoader:
    def epoch_plan(self, epoch):
        t0 = time.time()
        idx = _order(8)
        ok = _seeded_order(8, epoch)
        return t0, idx, ok


def host_timer():
    # not reachable from the plan: no finding
    return time.time()
'''


def test_nondet_epoch_plan_reachability():
    f = findings_of({"pkg/data/loader.py": PLAN_FIXTURE}, [NondetRule()])
    msgs = " | ".join(x.message for x in f)
    assert "`time.time()`" in msgs
    assert "np.random.permutation" in msgs
    assert "_seeded_order" not in msgs  # seeded draw is allowed
    assert "host_timer" not in msgs
    assert len(f) == 2


def test_nondet_reaches_nested_defs():
    src = '''
import time


class GraphLoader:
    def epoch_plan(self, epoch):
        def _stamp():
            return time.time()

        return _stamp()
'''
    f = findings_of({"pkg/data/loader.py": src}, [NondetRule()])
    assert len(f) == 1 and "`time.time()`" in f[0].message


def test_nondet_inside_jit():
    src = '''
import random

import jax


@jax.jit
def step(x):
    return x * random.random()
'''
    f = findings_of({"m.py": src}, [NondetRule()])
    assert len(f) == 1
    assert "random.random()" in f[0].message


# ---------------------------------------------------------------------------
# config-schema


def test_config_schema_flags_typo():
    reader = '''
def read(config):
    arch = config["NeuralNetwork"]["Architecture"]
    verbosity = config.get("Verbosity", {}).get("level", 0)
    return arch.get("hidden_dim"), verbosity
'''
    cfg = json.dumps({
        "Verbosity": {"level": 0},
        "NeuralNetwork": {"Architecture": {"hidden_dmi": 32}},
    })
    f = findings_of(
        {"pkg/reader.py": reader, "examples/a/a.json": cfg},
        [ConfigSchemaRule()],
    )
    assert len(f) == 1
    assert "`hidden_dmi`" in f[0].message
    assert "NeuralNetwork.Architecture.hidden_dmi" in f[0].message


def test_config_schema_accepts_known_and_branch_keys():
    reader = '''
def read(config):
    for split in ("train", "validate", "test"):
        _ = config["Dataset"]["path"].get(split)
    return config["NeuralNetwork"]["Training"].get("batch_size", 32)
'''
    cfg = json.dumps({
        "Dataset": {"path": {"train": "x", "test": "y"}},
        "NeuralNetwork": {"Training": {"batch_size": 8}},
        "_private": 1,
        "heads": {"branch-0": {}},
    })
    # "heads" itself unknown -> 1 finding; branch-0 and _private exempt
    f = findings_of(
        {"pkg/reader.py": reader, "tests/inputs/c.json": cfg},
        [ConfigSchemaRule()],
    )
    assert len(f) == 1 and "`heads`" in f[0].message


def test_config_schema_json_outside_scope_ignored():
    cfg = json.dumps({"totally_unknown": 1})
    assert findings_of({"bench/b.json": cfg}, [ConfigSchemaRule()]) == []


def test_config_schema_vocabulary_covers_packing_keys():
    """The Training.Parallelism.packing block (ISSUE 3 bin-packed batch
    forming) must be legal config vocabulary: the keys are harvested
    from the real reader (parallel/runtime._packing_from_config), so a
    config using them lints clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(REPO, ["hydragnn_tpu/parallel/runtime.py"])
    keys = harvest_accepted_keys(ctx)
    assert {
        "packing", "enabled", "max_budgets", "slack", "max_graphs"
    } <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Parallelism": {
                    "scheme": "single",
                    "packing": {
                        "enabled": "auto",
                        "max_budgets": 2,
                        "slack": 1.04,
                        "max_graphs": 128,
                    },
                }
            }
        }
    })
    reader = open(
        os.path.join(REPO, "hydragnn_tpu/parallel/runtime.py")
    ).read()
    f = findings_of(
        {
            "hydragnn_tpu/parallel/runtime.py": reader,
            # the schema walker needs the section names too
            "hydragnn_tpu/config/reader_stub.py": (
                'def read(c):\n'
                '    t = c["NeuralNetwork"]["Training"]\n'
                '    return t.get("Parallelism", {})\n'
            ),
            "examples/pk/pk.json": cfg,
        },
        [ConfigSchemaRule()],
    )
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_simulation_keys():
    """The top-level Simulation block (ISSUE 15 MD rollouts) must be
    legal config vocabulary: the keys are harvested from the real
    reader (simulate/engine.simulation_settings), so an example config
    carrying a rollout stanza lints clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(REPO, ["hydragnn_tpu/simulate/engine.py"])
    keys = harvest_accepted_keys(ctx)
    assert {
        "Simulation",
        "steps",
        "dt",
        "superstep_k",
        "temperature_k",
        "thermostat",
        "friction",
        "kb",
        "mass",
        "record_trajectory",
        "neighbor",
        "skin",
        "max_edges",
        "rebuild_policy",
        "guard",
        "max_capacity_growths",
        "capacity_growth",
        "max_dt_halvings",
        "on_nonfinite",
        "checkpoint",
        "interval_steps",
    } <= keys
    cfg = json.dumps(
        {
            "Simulation": {
                "steps": 200,
                "dt": 0.002,
                "superstep_k": 16,
                "temperature_k": 0.2,
                "thermostat": "langevin",
                "neighbor": {
                    "skin": 0.3,
                    "max_edges": 512,
                    "rebuild_policy": "displacement",
                },
                "guard": {
                    "on_nonfinite": "dt_halve",
                    "max_dt_halvings": 2,
                },
                "checkpoint": {"enabled": True, "interval_steps": 64},
            }
        }
    )
    reader = open(
        os.path.join(REPO, "hydragnn_tpu/simulate/engine.py")
    ).read()
    f = findings_of(
        {
            "hydragnn_tpu/simulate/engine.py": reader,
            "examples/sim/sim.json": cfg,
        },
        [ConfigSchemaRule()],
    )
    assert f == [], [x.message for x in f]


def test_host_sync_rollout_integrator_item_flags():
    """ISSUE 15 acceptance: an injected ``.item()`` in the integrator
    must flag — the rollout scan body is HOT_SEEDS-covered through the
    macro builder's nested defs, and the integrator functions are
    pulled in over the cross-module call edges."""
    integrator = '''
def half_kick(vel, forces, inv_m, dt):
    return vel + (0.5 * dt.item()) * forces * inv_m
'''
    engine = '''
import jax

from hydragnn_tpu.simulate.integrators import half_kick


class RolloutEngine:
    def _build_macro(self, k):
        def macro(state, dt):
            def body(st, _):
                vel = half_kick(st[0], st[1], 1.0, dt)
                return (vel, st[1]), vel

            return jax.lax.scan(body, state, None, length=k)

        return jax.jit(macro)
'''
    f = findings_of(
        {
            "hydragnn_tpu/simulate/integrators.py": integrator,
            "hydragnn_tpu/simulate/engine.py": engine,
        },
        [HostSyncRule()],
    )
    assert len(f) == 1, [x.message for x in f]
    assert "half_kick" in f[0].message and ".item()" in f[0].message


def test_host_sync_current_simulate_is_clean():
    """The shipped simulate/ package carries no unsuppressed host sync
    on the hot path (the per-macro policy fetch is the designed,
    justified exception)."""
    from hydragnn_tpu.analysis.engine import collect_files, run_on_context

    ctx = collect_files(
        REPO,
        [
            "hydragnn_tpu/simulate",
            "hydragnn_tpu/train/mlip.py",
            "hydragnn_tpu/ops/neighbors.py",
        ],
    )
    res = run_on_context(ctx, [HostSyncRule()])
    assert [f for f in res.findings if not f.suppressed] == []


# ---------------------------------------------------------------------------
# suppression + baseline mechanics


def test_suppression_same_line_next_line_file_and_all():
    base = '''
import jax


@jax.jit
def step(x):
    return float(x){SUFFIX}
'''
    flagged = findings_of({"m.py": base.replace("{SUFFIX}", "")},
                          [RetraceRule()])
    assert flagged
    same = base.replace(
        "{SUFFIX}", "  # graftlint: disable=retrace -- fixture"
    )
    assert findings_of({"m.py": same}, [RetraceRule()]) == []
    nxt = base.replace("{SUFFIX}", "").replace(
        "    return float(x)",
        "    # graftlint: disable-next-line=retrace -- fixture\n"
        "    return float(x)",
    )
    assert findings_of({"m.py": nxt}, [RetraceRule()]) == []
    allrules = base.replace(
        "{SUFFIX}", "  # graftlint: disable=all"
    )
    assert findings_of({"m.py": allrules}, [RetraceRule()]) == []
    filewide = "# graftlint: disable-file=retrace\n" + base.replace(
        "{SUFFIX}", ""
    )
    assert findings_of({"m.py": filewide}, [RetraceRule()]) == []
    # an unrelated rule name does NOT suppress
    wrong = base.replace(
        "{SUFFIX}", "  # graftlint: disable=jax-api"
    )
    assert findings_of({"m.py": wrong}, [RetraceRule()]) != []


def test_baseline_roundtrip(tmp_path):
    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    bad = src_dir / "m.py"
    bad.write_text(
        "import jax\n\n\n@jax.jit\ndef step(x):\n    return float(x)\n"
    )
    baseline = tmp_path / "baseline.json"

    res = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                   baseline_path=str(baseline))
    assert not res.ok and len(res.new) == 1

    # grandfather it -> check turns green
    write_baseline(str(baseline), res.findings)
    res2 = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                    baseline_path=str(baseline))
    assert res2.ok and len(res2.baselined) == 1 and not res2.new

    # a NEW finding is still reported even with the baseline present
    bad.write_text(
        bad.read_text() + "\n\n@jax.jit\ndef step2(y):\n    return int(y)\n"
    )
    res3 = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                    baseline_path=str(baseline))
    assert not res3.ok and len(res3.new) == 1 and len(res3.baselined) == 1

    # fixing everything leaves stale entries, detected for pruning
    bad.write_text("import jax\n")
    res4 = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                    baseline_path=str(baseline))
    assert res4.ok and len(res4.stale_baseline) == 1


def test_baseline_count_ratchet(tmp_path):
    """One grandfathered finding must NOT cover a second, new
    occurrence with the same (rule, path, message)."""
    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    bad = src_dir / "m.py"
    one = "import jax\n\n\n@jax.jit\ndef step(x):\n    return float(x)\n"
    bad.write_text(one)
    baseline = tmp_path / "baseline.json"
    res = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                   baseline_path=str(baseline))
    write_baseline(str(baseline), res.findings)
    # duplicate the offending line inside the same function: identical
    # fingerprint, second occurrence
    bad.write_text(one.replace(
        "    return float(x)\n",
        "    y = float(x)\n    return float(x)\n",
    ))
    res2 = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                    baseline_path=str(baseline))
    assert len(res2.baselined) == 1 and len(res2.new) == 1
    assert not res2.ok


def test_cli_json_marks_duplicates_by_identity(tmp_path, capsys):
    """With baseline count=1 and two identical findings, --json must
    mark exactly one as baselined (identity, not equality)."""
    cli = _load_cli()
    bad = tmp_path / "m.py"
    one = "import jax\n\n\n@jax.jit\ndef step(x):\n    return float(x)\n"
    bad.write_text(one)
    baseline = tmp_path / "baseline.json"
    # same root as the CLI (fingerprints include the relative path)
    res = run_lint(REPO, paths=[str(bad)], rules=[RetraceRule()])
    write_baseline(str(baseline), res.findings)
    bad.write_text(one.replace(
        "    return float(x)\n",
        "    y = float(x)\n    return float(x)\n",
    ))
    rc = cli.main([str(bad), "--json", "--baseline", str(baseline),
                   "--rules", "retrace"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0  # informational mode
    assert doc["new"] == 1 and doc["baselined"] == 1
    flags = sorted(e["baselined"] for e in doc["findings"])
    assert flags == [False, True]


def test_config_schema_restricted_path_run_uses_default_vocabulary():
    """`graftlint examples/x/x.json` must not flag every legitimate
    key just because no reader module is in the restricted path set."""
    res = run_lint(
        REPO,
        paths=["examples/lsms/lsms.json"],
        rules=[ConfigSchemaRule()],
        baseline_path=os.path.join(REPO, "tools/graftlint_baseline.json"),
    )
    assert res.ok, "\n".join(f.render() for f in res.new)
    assert len(res.baselined) == 1  # the grandfathered dim key


def test_line_moves_do_not_invalidate_baseline(tmp_path):
    """Fingerprints exclude line numbers: edits above a finding keep
    the baseline entry matching."""
    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    bad = src_dir / "m.py"
    body = "import jax\n\n\n@jax.jit\ndef step(x):\n    return float(x)\n"
    bad.write_text(body)
    baseline = tmp_path / "baseline.json"
    res = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                   baseline_path=str(baseline))
    write_baseline(str(baseline), res.findings)
    bad.write_text("# a new comment line\n" + body)
    res2 = run_lint(str(tmp_path), paths=["pkg"], rules=[RetraceRule()],
                    baseline_path=str(baseline))
    assert res2.ok and len(res2.baselined) == 1


# ---------------------------------------------------------------------------
# full-tree gate + CLI contract


def test_full_tree_check_is_clean():
    """The tier-1 gate: the whole package + examples + config JSONs
    must lint clean against the checked-in baseline. A regression in
    any rule family fails HERE, at commit time, instead of hours into
    a TPU run."""
    res = run_lint(
        REPO,
        baseline_path=os.path.join(REPO, "tools/graftlint_baseline.json"),
    )
    assert res.ok, "new graftlint findings:\n" + "\n".join(
        f.render() for f in res.new
    )
    # the two grandfathered reference-metadata keys stay recorded
    assert not res.stale_baseline, (
        "baseline has stale entries — prune with "
        "`python tools/graftlint.py --write-baseline`"
    )


def test_cli_exit_code_contract(tmp_path):
    """--check exit codes: 0 on a clean tree, 1 when a new finding
    exists. One subprocess each (bounded: host-side AST work only)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    bad = tmp_path / "drifted.py"
    bad.write_text("import jax\n\nx = jax.shard_map\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftlint.py"),
         str(bad), "--check", "--baseline", ""],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=240,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "jax.shard_map" in r.stdout
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftlint.py"),
         "--check", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=240,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    doc = json.loads(r2.stdout)
    assert doc["ok"] is True and doc["new"] == 0


def _load_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graftlint_cli", os.path.join(REPO, "tools/graftlint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_nonexistent_path_is_usage_error(capsys):
    """A typo'd path must exit 2, not lint nothing and report green."""
    cli = _load_cli()
    rc = cli.main(["hydragnn_tpu/paralel", "--check", "--baseline", ""])
    assert rc == 2
    assert "no such file or directory" in capsys.readouterr().err


def test_cli_write_baseline_refuses_restricted_runs(capsys):
    """--write-baseline over a subset would silently drop grandfathered
    entries outside the restriction."""
    cli = _load_cli()
    assert cli.main(["hydragnn_tpu", "--write-baseline"]) == 2
    assert cli.main(["--rules", "jax-api", "--write-baseline"]) == 2
    err = capsys.readouterr().err
    assert "full default-scope run" in err


def test_jax_api_message_fingerprint_stable_across_jax_versions():
    """Finding messages must not embed the jax version — baseline
    fingerprints have to survive upgrades."""
    f = findings_of({"pkg/graphshard.py": SEED_SHARD_MAP_SNIPPET},
                    [JaxApiRule()])
    import jax

    assert jax.__version__ not in f[0].message


def test_rule_catalog_and_selection():
    from hydragnn_tpu.analysis import all_rules, rules_by_name

    names = {r.name for r in all_rules()}
    assert names == {
        "jax-api", "retrace", "host-sync", "nondet", "config-schema",
        "fp-contract", "donation", "thread-discipline", "hot-coverage",
        "suppression", "lock-order", "guarded-field",
        "barrier-discipline",
    }
    assert [r.name for r in rules_by_name(["jax-api"])] == ["jax-api"]
    with pytest.raises(ValueError):
        rules_by_name(["no-such-rule"])


def test_host_sync_superstep_scan_body_is_hot():
    """ISSUE 4: the superstep scan body is passed BY VALUE to lax.scan
    (no call edge), yet it runs K times per dispatch — hot seeds must
    pull in functions NESTED under them, so a stray .item() inside the
    body (or the jitted closure) is a lint error."""
    src = '''
import jax


def make_superstep_fn(model, tx):
    def superstep(state, acc, batches):
        def body(carry, batch):
            state, lsum = carry
            loss = model(state, batch)
            lsum = lsum + loss.item()
            return (state, lsum), None

        return jax.lax.scan(body, (state, acc), batches)

    return jax.jit(superstep, donate_argnums=(0, 1))
'''
    f = findings_of({"pkg/train/loop.py": src}, [HostSyncRule()])
    assert len(f) == 1
    assert ".item()" in f[0].message and "body" in f[0].message


def test_host_sync_real_superstep_fn_is_covered_and_clean():
    """The REAL make_superstep_fn (and its scan bodies) must be inside
    the host-sync hot set — and clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/train/loop.py"])
    graph = build_callgraph(ctx)
    assert any(
        graph.find(p, q) for p, q in HOT_SEEDS
        if q == "make_superstep_fn"
    ), "make_superstep_fn not found among host-sync hot seeds"
    # nested scan bodies exist in the graph under the seed's qualname
    nested = [
        k for k in graph.funcs
        if k[1].startswith("make_superstep_fn.")
    ]
    assert nested, "superstep scan bodies not registered as nested defs"
    f = findings_of(
        {"hydragnn_tpu/train/loop.py": ctx.py_files[0].text},
        [HostSyncRule()],
    )
    # the one intentional sync (trace-mode barrier) is suppressed in
    # the real file; nothing new may appear
    assert f == [], [x.message for x in f]


def test_host_sync_dp_superstep_and_epoch_driver_are_covered():
    """ISSUE 5: the dp superstep scan body (make_dp_superstep_fn) and
    the dp epoch drivers (DPLoader's plain + grouped iterators) are
    host-sync hot seeds — their nested defs register, and the real file
    stays clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/parallel/dp.py"])
    graph = build_callgraph(ctx)
    for qual in (
        "make_dp_superstep_fn",
        "DPLoader.__iter__",
        "DPLoader._iter_superstep",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    nested = [
        k for k in graph.funcs
        if k[1].startswith("make_dp_superstep_fn.")
    ]
    assert nested, "dp scan bodies not registered as nested defs"
    f = findings_of(
        {"hydragnn_tpu/parallel/dp.py": ctx.py_files[0].text},
        [HostSyncRule()],
    )
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_superstep_keys():
    """The Training.Parallelism.superstep block (ISSUE 4 superstep
    executor) must be legal config vocabulary: keys are harvested from
    the real reader (parallel/runtime._superstep_from_config)."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(REPO, ["hydragnn_tpu/parallel/runtime.py"])
    keys = harvest_accepted_keys(ctx)
    assert {"superstep", "steps", "max_host_bytes"} <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Parallelism": {
                    "scheme": "single",
                    "superstep": {
                        "steps": "auto",
                        "max_host_bytes": 268435456,
                    },
                }
            }
        }
    })
    reader = open(
        os.path.join(REPO, "hydragnn_tpu/parallel/runtime.py")
    ).read()
    f = findings_of(
        {
            "hydragnn_tpu/parallel/runtime.py": reader,
            "hydragnn_tpu/config/reader_stub.py": (
                'def read(c):\n'
                '    t = c["NeuralNetwork"]["Training"]\n'
                '    return t.get("Parallelism", {})\n'
            ),
            "examples/ss/ss.json": cfg,
        },
        [ConfigSchemaRule()],
    )
    assert f == [], [x.message for x in f]


def test_host_sync_checkpoint_writer_and_skip_to_are_covered():
    """ISSUE 6 (durability): the async CheckpointWriter's caller-thread
    save (its only legal sync is the designed snapshot barrier,
    suppressed in place) and background worker, plus the resume
    fast-forward helpers, are host-sync hot seeds — and the real files
    stay clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    files = [
        "hydragnn_tpu/utils/checkpoint.py",
        "hydragnn_tpu/data/loader.py",
        "hydragnn_tpu/data/pipeline.py",
    ]
    ctx = collect_files(REPO, files)
    graph = build_callgraph(ctx)
    for qual in (
        "CheckpointWriter.save",
        "CheckpointWriter._worker_main",
        "GraphLoader.skip_to",
        "drop_consumed_groups",
        "skip_delivered_items",
        "ParallelPipelineLoader.skip_to",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    sources = {
        sf.relpath: sf.text for sf in ctx.py_files
    }
    f = findings_of(sources, [HostSyncRule()])
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_checkpoint_keys():
    """The Training.Checkpoint durability block (ISSUE 6: async writer
    knobs) and Training.bn_recalibration must be legal config
    vocabulary: keys are harvested from the real readers
    (utils/checkpoint.checkpoint_settings,
    train/loop._bn_recalibration_epochs)."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    files = [
        "hydragnn_tpu/utils/checkpoint.py",
        "hydragnn_tpu/train/loop.py",
    ]
    ctx = collect_files(REPO, files)
    keys = harvest_accepted_keys(ctx)
    assert {
        "Checkpoint", "enabled", "async", "interval_steps", "retries",
        "backoff", "bn_recalibration", "epochs",
        "walltime_min_seconds_left",
    } <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Checkpoint": {
                    "enabled": True,
                    "async": True,
                    "interval_steps": 200,
                    "retries": 3,
                    "backoff": 0.25,
                },
                "bn_recalibration": {"enabled": True, "epochs": 1},
            }
        }
    })
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    sources["examples/ck/ck.json"] = cfg
    f = findings_of(sources, [ConfigSchemaRule()])
    assert f == [], [x.message for x in f]


def test_host_sync_telemetry_emit_paths_are_covered():
    """ISSUE 7: the run-telemetry emit paths (StepClock.record/finish,
    TelemetryStream.emit and the stream worker) are host-sync hot
    seeds; the ONLY syncs in the real file are the config-gated
    sampled fence and the one epoch-end batched fetch, both suppressed
    in place — nothing new may appear."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/utils/telemetry.py"])
    graph = build_callgraph(ctx)
    for qual in (
        "StepClock.record",
        "StepClock.finish",
        "TelemetryStream.emit",
        "TelemetryStream._worker_main",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    src = ctx.py_files[0].text
    # the suppressions are load-bearing: stripping them must flag both
    # the sampled fence and the epoch-end fetch
    stripped = "\n".join(
        line
        for line in src.splitlines()
        if "graftlint: disable-next-line=host-sync" not in line
    )
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": stripped}, [HostSyncRule()]
    )
    msgs = [x.message for x in f]
    assert any("block_until_ready" in m for m in msgs), msgs
    assert any("device_get" in m for m in msgs), msgs
    # and with the suppressions in place the real file is clean
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": src}, [HostSyncRule()]
    )
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_telemetry_keys():
    """The Training.Telemetry block (ISSUE 7 run telemetry) must be
    legal config vocabulary: keys are harvested from the real reader
    (utils/telemetry.telemetry_settings)."""
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(REPO, ["hydragnn_tpu/utils/telemetry.py"])
    keys = harvest_accepted_keys(ctx)
    assert {
        "Telemetry",
        "enabled",
        "stream_path",
        "sync_interval_steps",
        "rollup",
        "queue_depth",
    } <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Telemetry": {
                    "enabled": True,
                    "stream_path": "logs/run/telemetry.jsonl",
                    "sync_interval_steps": 16,
                    "rollup": True,
                }
            }
        }
    })
    reader = open(
        os.path.join(REPO, "hydragnn_tpu/utils/telemetry.py")
    ).read()
    f = findings_of(
        {
            "hydragnn_tpu/utils/telemetry.py": reader,
            "hydragnn_tpu/config/reader_stub.py": (
                'def read(c):\n'
                '    t = c["NeuralNetwork"]["Training"]\n'
                '    return t.get("Telemetry", {})\n'
            ),
            "examples/tel/tel.json": cfg,
        },
        [ConfigSchemaRule()],
    )
    assert f == [], [x.message for x in f]


def test_host_sync_roofline_capture_paths_are_covered():
    """ISSUE 8: the first-dispatch executable capture, the memory
    sampler and the trace-annotation helpers run on (or adjacent to)
    the step thread — all are host-sync hot seeds, so a stray
    ``.item()``/``device_get`` in any of them lints; and the REAL
    files stay clean (the capture lowers/compiles but never syncs)."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(
        REPO,
        ["hydragnn_tpu/utils/telemetry.py", "hydragnn_tpu/utils/tracer.py"],
    )
    graph = build_callgraph(ctx)
    for qual in (
        "StepClock._maybe_capture",
        "memory_row",
        "note_trace_step",
        "step_annotation",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    # a sync smuggled into the capture MUST flag (fixture shaped like
    # the real method, plus the forbidden call)
    bad = (
        "class StepClock:\n"
        "    def _maybe_capture(self, fn, args, spec, k):\n"
        "        compiled = fn.lower(*args).compile()\n"
        "        loss = args[0]\n"
        "        v = loss.item()\n"
        "        return compiled, v\n"
    )
    f = findings_of({"hydragnn_tpu/utils/telemetry.py": bad}, [HostSyncRule()])
    assert any(".item()" in x.message for x in f), [x.message for x in f]
    bad_tr = (
        "import jax\n"
        "def note_trace_step():\n"
        "    jax.device_get(0)\n"
    )
    f = findings_of({"hydragnn_tpu/utils/tracer.py": bad_tr}, [HostSyncRule()])
    assert any("device_get" in x.message for x in f), [x.message for x in f]
    # the real tracer file is clean under the rule (the telemetry
    # file's cleanliness is pinned by the ISSUE-7 test above)
    src = next(
        sf.text
        for sf in ctx.py_files
        if sf.relpath.endswith("tracer.py")
    )
    f = findings_of({"hydragnn_tpu/utils/tracer.py": src}, [HostSyncRule()])
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_profiling_and_roofline_keys():
    """The Training.Profiling block (ISSUE 8 profiler alignment) and
    the Telemetry.cost_analysis key must be legal config vocabulary,
    harvested from the REAL readers (utils/tracer.Profiler and
    utils/telemetry.telemetry_settings)."""
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(
        REPO,
        ["hydragnn_tpu/utils/tracer.py", "hydragnn_tpu/utils/telemetry.py"],
    )
    keys = harvest_accepted_keys(ctx)
    assert {
        "Profiling",
        "enabled",
        "epoch",
        "steps",
        "trace_dir",
        "cost_analysis",
    } <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Telemetry": {"enabled": True, "cost_analysis": True},
                "Profiling": {
                    "enabled": True,
                    "epoch": 1,
                    "steps": 20,
                    "trace_dir": "logs/run/jax_trace",
                },
            }
        }
    })
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    sources["examples/prof/prof.json"] = cfg
    f = findings_of(sources, [ConfigSchemaRule()])
    assert f == [], [x.message for x in f]


def test_host_sync_fused_edge_pipeline_is_covered_and_clean():
    """ISSUE 9: the fused edge-pipeline kernel entry points
    (edge_pipeline_planned, the kernel body, and the pallas_call
    builder whose index_map lambdas are passed by value) are host-sync
    hot seeds — nested defs register through the qualname expansion,
    and the real file stays clean."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/ops/pallas_segment.py"])
    graph = build_callgraph(ctx)
    for qual in (
        "edge_pipeline_planned",
        "_edge_pipeline_kernel",
        "_pallas_edge_pipeline",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    # the pallas_call builder's index_map lambdas / kernel partials are
    # nested defs under the seeds' qualnames
    nested = [
        k
        for k in graph.funcs
        if k[1].startswith(("_pallas_edge_pipeline.", "_edge_pipeline_kernel."))
    ]
    assert nested, "pallas_call nested defs not registered"
    f = findings_of(
        {"hydragnn_tpu/ops/pallas_segment.py": ctx.py_files[0].text},
        [HostSyncRule()],
    )
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_segment_and_precision_keys():
    """ISSUE 9 config surface: the bf16 precision key and the
    segment-kernel grammar (Training.use_segment_plan /
    Training.segment_impl) are legal vocabulary harvested from the
    REAL readers (runner.run_training, train/state.resolve_precision)
    — a config carrying them must lint clean."""
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(
        REPO,
        ["hydragnn_tpu/runner.py", "hydragnn_tpu/train/state.py"],
    )
    keys = harvest_accepted_keys(ctx)
    assert {"precision", "use_segment_plan", "segment_impl"} <= keys
    cfg = json.dumps(
        {
            "Training": {
                "precision": "bf16",
                "use_segment_plan": "auto",
                "segment_impl": "pallas_fused",
            }
        }
    )
    readers = {
        os.path.join("hydragnn_tpu", "runner.py"): open(
            os.path.join(REPO, "hydragnn_tpu", "runner.py")
        ).read(),
        os.path.join("hydragnn_tpu", "train", "state.py"): open(
            os.path.join(REPO, "hydragnn_tpu", "train", "state.py")
        ).read(),
        os.path.join("examples", "seg.json"): cfg,
    }
    f = findings_of(readers, [ConfigSchemaRule()])
    assert f == [], [x.message for x in f]


def test_host_sync_guard_paths_are_covered():
    """ISSUE 10: the divergence guard's traced core (guarded_commit +
    the poison helpers — by-value inside the superstep scan body, so
    the nested-def expansion matters) and the monitor's per-dispatch
    observe/check are host-sync hot seeds. A stray ``.item()`` in the
    predicate must lint; the REAL file's only sync is the designed
    resolution fetch in check(), suppressed in place — stripping the
    suppression must flag it, and the real file stays clean."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS, HostSyncRule

    ctx = collect_files(REPO, ["hydragnn_tpu/train/guard.py"])
    graph = build_callgraph(ctx)
    for qual in (
        "guarded_commit",
        "poison_scalar",
        "poison_tree",
        "poison_batch",
        "GuardMonitor.observe",
        "GuardMonitor.check",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    src = ctx.py_files[0].text
    stripped = "\n".join(
        line
        for line in src.splitlines()
        if "graftlint: disable-next-line=host-sync" not in line
    )
    f = findings_of(
        {"hydragnn_tpu/train/guard.py": stripped}, [HostSyncRule()]
    )
    assert any("device_get" in x.message for x in f), [
        x.message for x in f
    ]
    f = findings_of(
        {"hydragnn_tpu/train/guard.py": src}, [HostSyncRule()]
    )
    assert f == [], [x.message for x in f]
    # an injected .item() in the traced predicate flags
    poisoned = src.replace(
        "ok = jnp.isfinite(tot) & jnp.isfinite(gnorm)",
        "ok = jnp.isfinite(tot) & jnp.isfinite(gnorm)\n"
        "    _ = gnorm.item()",
    )
    assert poisoned != src
    f = findings_of(
        {"hydragnn_tpu/train/guard.py": poisoned}, [HostSyncRule()]
    )
    assert any(".item()" in x.message for x in f), [
        x.message for x in f
    ]


def test_config_schema_vocabulary_covers_guard_keys():
    """The Training.Guard block (ISSUE 10) and the new
    Checkpoint.validate_finite / Optimizer.clip_grad_norm knobs must
    be legal config vocabulary: keys harvested from the REAL readers
    (train/guard.guard_settings, utils/checkpoint.checkpoint_settings,
    train/optimizer.select_optimizer)."""
    from hydragnn_tpu.analysis.rules.config_schema import (
        ConfigSchemaRule,
        harvest_accepted_keys,
    )

    files = [
        "hydragnn_tpu/train/guard.py",
        "hydragnn_tpu/utils/checkpoint.py",
        "hydragnn_tpu/train/optimizer.py",
    ]
    ctx = collect_files(REPO, files)
    keys = harvest_accepted_keys(ctx)
    assert {
        "Guard",
        "enabled",
        "policy",
        "max_bad_steps",
        "window_steps",
        "check_interval_steps",
        "lr_backoff",
        "max_rollbacks",
        "validate_finite",
        "clip_grad_norm",
    } <= keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Guard": {
                    "enabled": True,
                    "policy": "rollback",
                    "max_bad_steps": 2,
                    "window_steps": 200,
                    "check_interval_steps": 50,
                    "lr_backoff": 0.5,
                    "max_rollbacks": 2,
                },
                "Checkpoint": {"enabled": True, "validate_finite": True},
                "Optimizer": {"clip_grad_norm": 1.0},
            }
        }
    })
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    sources["hydragnn_tpu/config/reader_stub.py"] = (
        'def read(c):\n'
        '    t = c["NeuralNetwork"]["Training"]\n'
        '    return t.get("Guard", {})\n'
    )
    sources["examples/guard/guard.json"] = cfg
    f = findings_of(sources, [ConfigSchemaRule()])
    assert f == [], [x.message for x in f]


# ---------------------------------------------------------------------------
# ISSUE 12: fp-contract


SCAN_FMA_FIXTURE = '''
import jax
import jax.numpy as jnp


def fold(acc, prods, gs):
    def body(carry, xs):
        lsum, ng = carry
        p, g = xs
        # the injected fault: a fusable multiply-add in the scan body
        lsum = lsum + p * g
        return (lsum, ng + g), None

    acc, _ = jax.lax.scan(body, acc, (prods, gs))
    return acc
'''


def test_fp_contract_flags_fma_in_scan_body():
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    f = findings_of({"pkg/train/loop.py": SCAN_FMA_FIXTURE},
                    [FpContractRule()])
    assert len(f) == 1
    assert "fusable multiply-add" in f[0].message
    assert "body" in f[0].message


def test_fp_contract_multiply_free_accumulation_is_clean():
    """The sanctioned idiom — products rounded outside, add-only scan
    body — must NOT flag (the real fold_step_metrics shape)."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    src = '''
import jax


def fold(acc, tots, gs):
    prods = tots * gs

    def body(carry, xs):
        lsum, ng = carry
        p, g = xs
        return (lsum + p, ng + g), None

    acc, _ = jax.lax.scan(body, acc, (prods, gs))
    return acc
'''
    assert findings_of({"pkg/train/loop.py": src},
                       [FpContractRule()]) == []


def test_fp_contract_flags_additive_identity_in_bitwise_seed():
    """x + 0.0 inside a bitwise-contract seed (poison_scalar's module
    position) flags with the select-not-add guidance."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    src = '''
import jax.numpy as jnp


def poison_scalar(rules, site, step, x):
    return x + 0.0
'''
    f = findings_of({"pkg/train/guard.py": src}, [FpContractRule()])
    assert len(f) == 1
    assert "additive identity" in f[0].message
    assert "select-not-add" in f[0].message


def test_fp_contract_ignores_code_outside_scope():
    """The same a*b+c in a plain host function (no scan, no seed) is
    legal float arithmetic — must not flag."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    src = '''
def metric(a, b, c):
    return a * b + c + 0.0
'''
    assert findings_of({"pkg/utils/misc.py": src},
                       [FpContractRule()]) == []


def test_fp_contract_reaches_scan_body_helpers():
    """A helper CALLED from the scan body fuses into the same loop —
    reachability must extend beyond the body function itself."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    src = '''
import jax


def rescale(l, corr, s):
    return l * corr + s


def scan_fn(carry, xs):
    l, corr, s = xs
    return rescale(l, corr, s), None


def run(init, xs):
    return jax.lax.scan(scan_fn, init, xs)
'''
    f = findings_of({"pkg/ops/attn.py": src}, [FpContractRule()])
    assert len(f) == 1 and "rescale" in f[0].message


def test_fp_contract_real_superstep_and_guard_are_clean():
    """The real bitwise-contract surfaces lint clean: the superstep
    builders, fold_step_metrics and the guard's traced core all hold
    the multiply-free / select-not-add discipline."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    files = [
        "hydragnn_tpu/train/loop.py",
        "hydragnn_tpu/train/guard.py",
        "hydragnn_tpu/parallel/dp.py",
    ]
    ctx = collect_files(REPO, files)
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    f = findings_of(sources, [FpContractRule()])
    assert f == [], [x.render() for x in f]


def test_fp_contract_ring_attention_suppressions_load_bearing():
    """The ring-attention online-softmax rescales are DESIGNED
    mul+adds, suppressed in place — stripping the suppressions must
    flag both accumulator updates."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    path = os.path.join(REPO, "hydragnn_tpu/parallel/graphshard.py")
    src = open(path).read()
    rel = "hydragnn_tpu/parallel/graphshard.py"
    assert findings_of({rel: src}, [FpContractRule()]) == []
    stripped = "\n".join(
        line for line in src.splitlines()
        if "graftlint: disable-next-line=fp-contract" not in line
    )
    f = findings_of({rel: stripped}, [FpContractRule()])
    assert len(f) == 2, [x.render() for x in f]
    assert all("fusable multiply-add" in x.message for x in f)


# ---------------------------------------------------------------------------
# ISSUE 12: donation


DONATION_FIXTURE = '''
import jax


def loop(step, state, acc, batches):
    jit_step = jax.jit(step, donate_argnums=(1,))
    for batch in batches:
        state, loss = jit_step(state, acc)
    return state, acc  # the injected fault: acc was donated
'''


def test_donation_flags_read_after_donated_call():
    from hydragnn_tpu.analysis.rules.donation import DonationRule

    f = findings_of({"pkg/train/loop.py": DONATION_FIXTURE},
                    [DonationRule()])
    assert len(f) == 1
    assert "`acc` was donated" in f[0].message
    assert "PR-7" in f[0].message


def test_donation_rebind_is_clean():
    """The sanctioned idiom — rebinding every donated name from the
    return value — must NOT flag (the universal loop shape here)."""
    from hydragnn_tpu.analysis.rules.donation import DonationRule

    src = '''
import jax


def loop(step, state, acc, batches):
    jit_step = jax.jit(step, donate_argnums=(0, 1))
    for batch in batches:
        state, acc = jit_step(state, acc)
    return state, acc
'''
    assert findings_of({"pkg/train/loop.py": src},
                       [DonationRule()]) == []


def test_donation_tracks_decorated_functions():
    from hydragnn_tpu.analysis.rules.donation import DonationRule

    src = '''
from functools import partial

import jax


@partial(jax.jit, donate_argnums=0)
def step(state, batch):
    return state


def drive(state, batch):
    new = step(state, batch)
    return new, state.params
'''
    f = findings_of({"pkg/m.py": src}, [DonationRule()])
    assert len(f) == 1 and "`state` was donated" in f[0].message


def test_donation_tracks_builder_returns():
    """Donation must follow the dominant shape here: a builder whose
    return statement is jax.jit(inner, donate_argnums=...) — the
    caller never sees a jit call."""
    from hydragnn_tpu.analysis.rules.donation import DonationRule

    src = '''
import jax


def make_step(model):
    def step(state, batch):
        return state

    return jax.jit(step, donate_argnums=0)


def drive(model, state, batches):
    fn = make_step(model)
    for b in batches:
        out = fn(state, b)
    return state  # donated on the first call, then read
'''
    f = findings_of({"pkg/m.py": src}, [DonationRule()])
    assert len(f) == 1 and "`state` was donated" in f[0].message
    assert "make_step" in f[0].message


def test_donation_real_tree_is_clean():
    """Every real loop rebinds its donated names — the production
    train/serve/parallel surfaces carry zero donation findings."""
    from hydragnn_tpu.analysis.rules.donation import DonationRule

    files = [
        "hydragnn_tpu/train/loop.py",
        "hydragnn_tpu/parallel/dp.py",
        "hydragnn_tpu/parallel/multibranch.py",
        "hydragnn_tpu/serve/engine.py",
        "hydragnn_tpu/utils/telemetry.py",
    ]
    ctx = collect_files(REPO, files)
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    f = findings_of(sources, [DonationRule()])
    assert f == [], [x.render() for x in f]


# ---------------------------------------------------------------------------
# ISSUE 12: thread-discipline


NEVER_BLOCK_FIXTURE = '''
import queue


class TelemetryStream:
    def __init__(self):
        self._q = queue.Queue(maxsize=4)

    def emit(self, row):
        self._q.put(row)
        return True
'''


def test_thread_discipline_flags_put_in_never_block_path():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    f = findings_of({"pkg/utils/telemetry.py": NEVER_BLOCK_FIXTURE},
                    [ThreadDisciplineRule()])
    assert len(f) == 1
    assert "blocking `.put(...)`" in f[0].message
    assert "put_nowait" in f[0].message


def test_thread_discipline_put_nowait_and_cold_code_clean():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
import queue
import time


class TelemetryStream:
    def __init__(self):
        self._q = queue.Queue(maxsize=4)

    def emit(self, row):
        try:
            self._q.put_nowait(row)
        except queue.Full:
            return False
        return True


def cold_path(q, t):
    q.put(1)        # not reachable from a never-block seed
    time.sleep(t)   # ditto
    t.join()
'''
    assert findings_of({"pkg/utils/telemetry.py": src},
                       [ThreadDisciplineRule()]) == []


def test_thread_discipline_flags_wait_join_sleep_open():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
import time


def _run_epoch(step_fn, state, loader, ev, worker):
    ev.wait()
    worker.join()
    time.sleep(0.1)
    with open("/tmp/x", "w") as f:
        f.write("row")
    ev.wait(timeout=1.0)  # bounded: fine
    ", ".join(["a"])      # str.join takes an arg: fine
    return state
'''
    f = findings_of({"pkg/train/loop.py": src}, [ThreadDisciplineRule()])
    kinds = sorted(x.message.split("`")[1] for x in f)
    assert len(f) == 4, [x.render() for x in f]
    assert any("unbounded `.wait()`" in x.message for x in f)
    assert any("unbounded `.join()`" in x.message for x in f)
    assert any("time.sleep" in x.message for x in f)
    assert any("sync file I/O" in x.message for x in f)


def test_thread_discipline_worker_without_finally_flags():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
import threading


class Writer:
    def __init__(self):
        self._thread = threading.Thread(target=self._main, daemon=True)
        self._thread.start()

    def _main(self):
        pass

    def close(self):
        pass


def trial(cfg):
    w = Writer()
    w.close()          # not in a finally: an exception above leaks it
    return cfg


def good_trial(cfg):
    w = Writer()
    try:
        return cfg
    finally:
        w.close()


def factory():
    w = Writer()
    return w           # ownership escapes: caller owns teardown


class Owner:
    def __init__(self):
        self.w = Writer()   # ownership escapes to the instance
'''
    f = findings_of({"pkg/utils/writer.py": src},
                    [ThreadDisciplineRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "without close()/stop() in a finally" in f[0].message
    assert "`trial`" in f[0].message


def test_thread_discipline_worker_class_without_closer_flags():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
import threading


class Leaky:
    def start(self):
        self._thread = threading.Thread(target=self._main)
        self._thread.start()

    def _main(self):
        pass
'''
    f = findings_of({"pkg/utils/leaky.py": src},
                    [ThreadDisciplineRule()])
    assert len(f) == 1
    assert "defines no close()/stop()/shutdown()" in f[0].message


def test_thread_discipline_generator_scoped_threads_not_workers():
    """PrefetchLoader-style threads — local to a generator that tears
    them down in its own finally — are NOT persistent workers; the
    close-in-finally contract does not apply."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    ctx = collect_files(
        REPO,
        ["hydragnn_tpu/data/prefetch.py", "hydragnn_tpu/data/pipeline.py"],
    )
    sources = {sf.relpath: sf.text for sf in ctx.py_files}
    f = findings_of(sources, [ThreadDisciplineRule()])
    assert f == [], [x.render() for x in f]


def test_thread_discipline_real_checkpoint_suppressions_load_bearing():
    """The checkpoint writer's designed stalls (single-writer
    backpressure, the cv barrier, the sync-fallback writes, retry
    backoff) are suppressed in place — the real file is clean, and
    stripping the suppressions must flag them."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    rel = "hydragnn_tpu/utils/checkpoint.py"
    src = open(os.path.join(REPO, rel)).read()
    assert findings_of({rel: src}, [ThreadDisciplineRule()]) == []
    stripped = "\n".join(
        line for line in src.splitlines()
        if "graftlint: disable-next-line=thread-discipline" not in line
    )
    f = findings_of({rel: stripped}, [ThreadDisciplineRule()])
    msgs = [x.message for x in f]
    assert any("unbounded `.wait()`" in m for m in msgs), msgs
    assert any("sync file I/O" in m for m in msgs), msgs
    assert any("time.sleep" in m for m in msgs), msgs


def test_thread_discipline_real_batcher_submit_never_blocks():
    """Regression for the fixed hazard: DynamicBatcher.submit must use
    put_nowait (an injected plain put flags)."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    rel = "hydragnn_tpu/serve/batcher.py"
    src = open(os.path.join(REPO, rel)).read()
    assert "self._q.put_nowait(req)" in src
    assert findings_of({rel: src}, [ThreadDisciplineRule()]) == []
    poisoned = src.replace(
        "self._q.put_nowait(req)", "self._q.put(req)"
    )
    f = findings_of({rel: poisoned}, [ThreadDisciplineRule()])
    assert any("blocking `.put(...)`" in x.message for x in f)


# ---------------------------------------------------------------------------
# ISSUE 12: hot-coverage ratchet


RATCHET_FIXTURE = '''
import jax


def make_shiny_step(model):
    @jax.jit
    def step(state, batch):
        return state

    return step


def run_training(config):
    fn = make_shiny_step(config)
    return fn
'''


def test_hot_coverage_flags_uncovered_jit_entry():
    """A jitted entry point reachable from run_training but absent
    from HOT_SEEDS fails the ratchet (the forgotten-append class)."""
    from hydragnn_tpu.analysis.rules.hot_coverage import HotCoverageRule

    f = findings_of({"pkg/runner.py": RATCHET_FIXTURE},
                    [HotCoverageRule()])
    assert len(f) == 1
    assert "make_shiny_step.step" in f[0].message
    assert "HOT_SEEDS" in f[0].message


def test_hot_coverage_seeded_builder_is_covered():
    """Nesting under a HOT_SEEDS-matched builder counts as covered —
    the existing seeding convention."""
    from hydragnn_tpu.analysis.rules.hot_coverage import HotCoverageRule

    src = RATCHET_FIXTURE.replace("make_shiny_step", "make_train_step")
    # the builder name matches the real ('train/loop.py',
    # 'make_train_step') seed only with the right path suffix
    f = findings_of({"pkg/train/loop.py": (
        "import jax\n\n\ndef make_train_step(model):\n"
        "    @jax.jit\n    def step(state, batch):\n"
        "        return state\n\n    return step\n"
    ), "pkg/runner.py": (
        "from pkg.train.loop import make_train_step\n\n\n"
        "def run_training(config):\n"
        "    return make_train_step(config)\n"
    )}, [HotCoverageRule()])
    assert f == [], [x.render() for x in f]


def test_hot_coverage_unreachable_jit_not_flagged():
    """A jitted function nobody reaches from an entry point is not the
    ratchet's business (host-sync still scans it via the jit seeds)."""
    from hydragnn_tpu.analysis.rules.hot_coverage import HotCoverageRule

    src = '''
import jax


@jax.jit
def orphan(x):
    return x


def run_training(config):
    return config
'''
    assert findings_of({"pkg/runner.py": src}, [HotCoverageRule()]) == []


def test_hot_coverage_real_tree_is_covered():
    """The ratchet holds on the real tree: every jitted function
    reachable from run_training / run_prediction / ServingEngine is
    HOT_SEEDS-covered or explicitly exempted."""
    from hydragnn_tpu.analysis.rules.hot_coverage import HotCoverageRule

    res = run_lint(REPO, rules=[HotCoverageRule()], baseline_path=None)
    assert res.findings == [], [x.render() for x in res.findings]


def test_hot_coverage_exemption_requires_reason():
    """The exemption grammar is (path, qualname) -> reason; every
    entry must carry a non-empty reason string."""
    from hydragnn_tpu.analysis.rules.hot_coverage import HOT_EXEMPT

    for (path, qual), reason in HOT_EXEMPT.items():
        assert isinstance(reason, str) and reason.strip(), (path, qual)


def test_hot_coverage_ratchet_catches_hot_seed_removal():
    """Deleting a HOT_SEEDS entry re-opens coverage findings — the
    ratchet direction (coverage can only grow)."""
    from hydragnn_tpu.analysis.rules import host_sync
    from hydragnn_tpu.analysis.rules.hot_coverage import HotCoverageRule

    kept = host_sync.HOT_SEEDS
    try:
        host_sync.HOT_SEEDS = tuple(
            s for s in kept if s[1] != "make_train_step"
        )
        res = run_lint(REPO, rules=[HotCoverageRule()],
                       baseline_path=None)
        assert any(
            "make_train_step.step" in x.message for x in res.findings
        ), [x.render() for x in res.findings]
    finally:
        host_sync.HOT_SEEDS = kept


# ---------------------------------------------------------------------------
# ISSUE 12: suppression hygiene + --diff / --explain


def test_bare_suppression_flags_and_justified_does_not():
    from hydragnn_tpu.analysis.rules.suppression import SuppressionRule

    bare = '''
import jax


@jax.jit
def step(x):
    return float(x)  # graftlint: disable=retrace
'''
    f = findings_of({"m.py": bare}, [SuppressionRule()])
    assert len(f) == 1
    assert "bare `graftlint: disable=retrace`" in f[0].message
    justified = bare.replace(
        "disable=retrace", "disable=retrace -- fixture reason"
    )
    assert findings_of({"m.py": justified}, [SuppressionRule()]) == []


def test_bare_suppression_still_suppresses_target():
    """Honoring is unchanged — a bare disable silences its rule (the
    hygiene finding gates instead)."""
    from hydragnn_tpu.analysis.rules.suppression import SuppressionRule

    bare = '''
import jax


@jax.jit
def step(x):
    return float(x)  # graftlint: disable=retrace
'''
    f = findings_of({"m.py": bare}, [RetraceRule(), SuppressionRule()])
    assert [x.rule for x in f] == ["suppression"]


def test_bare_disable_all_cannot_silence_the_hygiene_finding():
    """disable=all must not cover the complaint about itself; only an
    explicit justified disable=suppression does."""
    from hydragnn_tpu.analysis.rules.suppression import SuppressionRule

    bare_all = '''
import jax


@jax.jit
def step(x):
    return float(x)  # graftlint: disable=all
'''
    f = findings_of({"m.py": bare_all},
                    [RetraceRule(), SuppressionRule()])
    assert [x.rule for x in f] == ["suppression"]
    excused = bare_all.replace(
        "disable=all",
        "disable=all,suppression -- grandfathered fixture",
    )
    assert findings_of(
        {"m.py": excused}, [RetraceRule(), SuppressionRule()]
    ) == []


def test_bare_suppression_grandfathers_through_baseline(tmp_path):
    """The migration path for pre-existing bare disables: baseline
    them; a SECOND bare disable still gates (count ratchet)."""
    from hydragnn_tpu.analysis.rules.suppression import SuppressionRule

    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    bad = src_dir / "m.py"
    one = (
        "import jax\n\n\n@jax.jit\ndef step(x):\n"
        "    return float(x)  # graftlint: disable=retrace\n"
    )
    bad.write_text(one)
    baseline = tmp_path / "baseline.json"
    res = run_lint(str(tmp_path), paths=["pkg"],
                   rules=[SuppressionRule()],
                   baseline_path=str(baseline))
    assert len(res.new) == 1
    write_baseline(str(baseline), res.findings)
    res2 = run_lint(str(tmp_path), paths=["pkg"],
                    rules=[SuppressionRule()],
                    baseline_path=str(baseline))
    assert res2.ok and len(res2.baselined) == 1
    bad.write_text(one + (
        "\n\n@jax.jit\ndef step2(y):\n"
        "    return int(y)  # graftlint: disable=retrace\n"
    ))
    res3 = run_lint(str(tmp_path), paths=["pkg"],
                    rules=[SuppressionRule()],
                    baseline_path=str(baseline))
    assert not res3.ok and len(res3.new) == 1


def test_new_family_fingerprints_are_line_stable():
    """New-family findings round-trip the baseline across line moves
    (fingerprints exclude line numbers)."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    f1 = findings_of({"pkg/train/loop.py": SCAN_FMA_FIXTURE},
                     [FpContractRule()])
    shifted = "# moved\n# down\n" + SCAN_FMA_FIXTURE
    f2 = findings_of({"pkg/train/loop.py": shifted}, [FpContractRule()])
    assert len(f1) == len(f2) == 1
    assert f1[0].fingerprint == f2[0].fingerprint
    assert f1[0].line != f2[0].line


def test_cli_explain_prints_seed_registry(capsys):
    cli = _load_cli()
    assert cli.main(["--explain", "hot-coverage"]) == 0
    out = capsys.readouterr().out
    assert "seed registry" in out
    assert "run_training" in out and "ServingEngine" in out
    assert "exemptions:" in out
    assert cli.main(["--explain", "thread-discipline"]) == 0
    out = capsys.readouterr().out
    assert "DynamicBatcher.submit" in out
    assert cli.main(["--explain", "no-such-rule"]) == 2


def test_cli_diff_mode(tmp_path):
    """--diff lints only changed-vs-rev files (restricted view, default
    vocabulary fallback) and refuses --write-baseline."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # a clean worktree vs HEAD: nothing (or only this session's
    # already-clean edits) to lint — must exit 0 under --check
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftlint.py"),
         "--diff", "HEAD", "--check"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # a bad rev is a usage error, never a green no-op
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftlint.py"),
         "--diff", "no-such-rev-xyz", "--check"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=240,
    )
    assert r2.returncode == 2, r2.stdout + r2.stderr
    cli = _load_cli()
    assert cli.main(["--diff", "HEAD", "--write-baseline"]) == 2
    assert cli.main(["--diff", "HEAD", "some/path.py"]) == 2


def test_fp_contract_flags_fused_multiply_subtract():
    """x - a*b contracts into FMS exactly like x + a*b into FMA —
    both signs and both AugAssign forms must flag (review gap)."""
    from hydragnn_tpu.analysis.rules.fp_contract import FpContractRule

    src = '''
import jax


def fold(acc, prods, gs):
    def body(carry, xs):
        lsum, ng = carry
        p, g = xs
        lsum = lsum - p * g
        ng -= p * g
        return (lsum, ng), None

    acc, _ = jax.lax.scan(body, acc, (prods, gs))
    return acc
'''
    f = findings_of({"pkg/train/loop.py": src}, [FpContractRule()])
    assert len(f) == 2, [x.render() for x in f]
    assert all("fusable multiply-add" in x.message for x in f)


def test_thread_discipline_block_true_still_flags():
    """Only an explicit constant block=False is the non-blocking put
    form — block=True (or a variable) must not wave it through."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = NEVER_BLOCK_FIXTURE.replace(
        "self._q.put(row)", "self._q.put(row, block=True)"
    )
    f = findings_of({"pkg/utils/telemetry.py": src},
                    [ThreadDisciplineRule()])
    assert len(f) == 1 and "blocking `.put(...)`" in f[0].message
    ok = NEVER_BLOCK_FIXTURE.replace(
        "self._q.put(row)", "self._q.put(row, block=False)"
    )
    assert findings_of({"pkg/utils/telemetry.py": ok},
                       [ThreadDisciplineRule()]) == []


def test_thread_discipline_from_import_sleep_flags():
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
from time import sleep


def _run_epoch(step_fn, state, loader):
    sleep(0.1)
    return state
'''
    f = findings_of({"pkg/train/loop.py": src}, [ThreadDisciplineRule()])
    assert len(f) == 1 and "time.sleep" in f[0].message


def test_thread_discipline_annassign_thread_is_worker():
    """A type-annotated self._thread: threading.Thread = ... binding
    still marks the class as a persistent worker (review gap)."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        ThreadDisciplineRule,
    )

    src = '''
import threading


class Writer:
    def __init__(self):
        self._thread: threading.Thread = threading.Thread(
            target=self._main, daemon=True
        )
        self._thread.start()

    def _main(self):
        pass

    def close(self):
        pass


def trial(cfg):
    w = Writer()
    w.close()
    return cfg
'''
    f = findings_of({"pkg/utils/writer.py": src},
                    [ThreadDisciplineRule()])
    assert len(f) == 1
    assert "without close()/stop() in a finally" in f[0].message


def test_host_sync_multibranch_driver_and_barrier_path_are_covered():
    """ISSUE 13: the multibranch epoch driver + plan-domain resume
    cursor (MultiBranchLoader.__iter__/skip_to) are host-sync hot
    seeds, and the checkpoint writer's barrier-riding worker path
    (_process_barrier, reached from CheckpointWriter._worker_main via
    the emit chain) is inside the seeded scope — an injected sync in
    either flags; the real files stay clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.callgraph import build_callgraph, seed_scope
    from hydragnn_tpu.analysis.rules.host_sync import (
        HOT_SEEDS,
        HostSyncRule,
    )

    files = [
        "hydragnn_tpu/parallel/multibranch.py",
        "hydragnn_tpu/utils/checkpoint.py",
    ]
    ctx = collect_files(REPO, files)
    graph = build_callgraph(ctx)
    for qual in (
        "MultiBranchLoader.__iter__",
        "MultiBranchLoader.skip_to",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    # the worker's barrier path is reachable from the seeded writer
    scope = seed_scope(graph, HOT_SEEDS)
    assert any(
        q == "_process_barrier" for (_, q) in scope
    ), "_process_barrier not in the host-sync seeded scope"
    assert any(
        q == "_processes_agree_finite" for (_, q) in scope
    ), "_processes_agree_finite not in the host-sync seeded scope"
    f = findings_of(
        {p: pf.text for p, pf in zip(files, ctx.py_files)},
        [HostSyncRule()],
    )
    assert f == [], [x.message for x in f]


def test_host_sync_fleet_emit_paths_are_covered():
    """ISSUE 14: the fleet emit paths — the barrier-row emitter, the
    liveness counters/phase marks (on the feed hot paths), the
    heartbeat builder and its thread — are host-sync hot seeds, so a
    sync smuggled into any of them lints; and the REAL file stays
    clean."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/utils/telemetry.py"])
    graph = build_callgraph(ctx)
    for qual in (
        "bump",
        "note_phase",
        "heartbeat_row",
        "emit_barrier",
        "TelemetryStream._heartbeat_main",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    # an injected host-sync fixture must flag: a device fetch inside
    # the heartbeat builder (a background thread touching the device
    # would serialize against the training stream)
    bad = (
        "import jax\n"
        "def heartbeat_row(seq, interval_s):\n"
        "    row = {'t': 'heartbeat', 'seq': seq}\n"
        "    row['loss'] = jax.device_get(_LAST_LOSS)\n"
        "    return row\n"
    )
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": bad}, [HostSyncRule()]
    )
    assert any("device_get" in x.message for x in f), [
        x.message for x in f
    ]
    # and one inside the barrier emitter
    bad = (
        "import jax\n"
        "def emit_barrier(site, seq, total_s, barrier_s=None):\n"
        "    jax.block_until_ready(total_s)\n"
        "    return True\n"
    )
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": bad}, [HostSyncRule()]
    )
    assert any("block_until_ready" in x.message for x in f), [
        x.message for x in f
    ]
    # the real file is clean under the expanded seed set
    src = ctx.py_files[0].text
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": src}, [HostSyncRule()]
    )
    assert f == [], [x.message for x in f]


def test_host_sync_barrier_instrumentation_is_covered_and_clean():
    """ISSUE 14: `_process_barrier` / `_processes_agree_finite` are
    now seeded directly (they run on the writer thread AND the
    caller thread at end-of-run) — a jax sync added to the barrier
    timing would fence the training stream and must lint."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    ctx = collect_files(REPO, ["hydragnn_tpu/utils/checkpoint.py"])
    graph = build_callgraph(ctx)
    for qual in ("_process_barrier", "_processes_agree_finite"):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not found among host-sync hot seeds"
    bad = (
        "import jax\n"
        "def _process_barrier(tag, seq=None):\n"
        "    jax.block_until_ready(tag)\n"
    )
    f = findings_of(
        {"hydragnn_tpu/utils/checkpoint.py": bad}, [HostSyncRule()]
    )
    assert any("block_until_ready" in x.message for x in f), [
        x.message for x in f
    ]


def test_thread_discipline_fleet_emitters_never_block():
    """ISSUE 14: emit_barrier/bump/note_phase are never-block seeds —
    a blocking `q.put` (or a sleep) added to the barrier-row path
    would stall the checkpoint worker behind telemetry, and must
    lint."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        NEVER_BLOCK_SEEDS,
        ThreadDisciplineRule,
    )

    for qual in ("emit_barrier", "bump", "note_phase"):
        assert any(
            q == qual for _, q in NEVER_BLOCK_SEEDS
        ), f"{qual} not found among never-block seeds"
    bad = (
        "def emit_barrier(site, seq, total_s, barrier_s=None):\n"
        "    _Q.put({'t': 'barrier', 'site': site})\n"
        "    return True\n"
    )
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": bad},
        [ThreadDisciplineRule()],
    )
    assert any("put" in x.message for x in f), [x.message for x in f]
    # the real module stays clean (put_nowait discipline throughout)
    ctx = collect_files(REPO, ["hydragnn_tpu/utils/telemetry.py"])
    f = findings_of(
        {"hydragnn_tpu/utils/telemetry.py": ctx.py_files[0].text},
        [ThreadDisciplineRule()],
    )
    assert f == [], [x.message for x in f]


def test_config_schema_vocabulary_covers_fleet_keys():
    """The heartbeat_interval_s key (ISSUE 14) must be legal config
    vocabulary, harvested from the real reader
    (utils/telemetry.telemetry_settings)."""
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(REPO, ["hydragnn_tpu/utils/telemetry.py"])
    keys = harvest_accepted_keys(ctx)
    assert "heartbeat_interval_s" in keys
    cfg = json.dumps({
        "NeuralNetwork": {
            "Training": {
                "Telemetry": {
                    "enabled": True,
                    "heartbeat_interval_s": 0.5,
                }
            }
        }
    })
    reader = open(
        os.path.join(REPO, "hydragnn_tpu/utils/telemetry.py")
    ).read()
    f = findings_of(
        {
            "hydragnn_tpu/utils/telemetry.py": reader,
            "hydragnn_tpu/config/reader_stub.py": (
                'def read(c):\n'
                '    t = c["NeuralNetwork"]["Training"]\n'
                '    return t.get("Telemetry", {})\n'
            ),
            "examples/fleet/fleet.json": cfg,
        },
        [ConfigSchemaRule()],
    )
    assert f == [], [x.message for x in f]

# ---------------------------------------------------------------------------
# ISSUE 17 — lock-order


ABBA_FIXTURE = '''
import threading


class Pipeline:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
        threading.Thread(target=self._fill).start()
        threading.Thread(target=self._drain).start()

    def _fill(self):
        with self._head:
            with self._tail:
                pass

    def _drain(self):
        with self._tail:
            with self._head:
                pass
'''


def test_lock_order_flags_abba_cycle():
    """Two worker threads taking the same pair of locks in opposite
    orders is an ABBA deadlock; the thread entries are DISCOVERED from
    the Thread(target=...) ctors, not registered seeds."""
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    f = findings_of({"pkg/serve/pipe.py": ABBA_FIXTURE},
                    [LockOrderRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "lock-order cycle" in f[0].message
    assert "ABBA" in f[0].message
    assert "Pipeline._head" in f[0].message
    assert "Pipeline._tail" in f[0].message


def test_lock_order_single_lock_shape_is_clean():
    """The rollover shape the serving tier actually uses — submit and
    swap serialized on the SAME handle lock, no second acquisition
    under it — must produce NO order edges and no findings."""
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    src = '''
import threading


class Handle:
    def __init__(self):
        self._lock = threading.Lock()
        self.engine = None
        threading.Thread(target=self._pump).start()

    def _pump(self):
        with self._lock:
            e = self.engine
        e.step()

    def swap(self, eng):
        with self._lock:
            self.engine = eng
'''
    assert findings_of({"pkg/serve/handle.py": src},
                       [LockOrderRule()]) == []


def test_lock_order_cross_function_edge_makes_cycle():
    """Held sets propagate through resolvable call edges: the cycle
    exists even though no single function takes both locks."""
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    src = '''
import threading


class Pipeline:
    def __init__(self):
        self._head = threading.Lock()
        self._tail = threading.Lock()
        threading.Thread(target=self._fill).start()
        threading.Thread(target=self._drain).start()

    def _fill(self):
        with self._head:
            self._append()

    def _append(self):
        with self._tail:
            pass

    def _drain(self):
        with self._tail:
            self._pop()

    def _pop(self):
        with self._head:
            pass
'''
    f = findings_of({"pkg/serve/pipe.py": src}, [LockOrderRule()])
    assert any("lock-order cycle" in x.message for x in f), [
        x.render() for x in f
    ]


def test_lock_order_blocking_under_lock_and_condition_carveout():
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    src = '''
import queue
import threading
import time


class Feeder:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q = queue.Queue(maxsize=2)
        threading.Thread(target=self._main).start()

    def _main(self):
        with self._lock:
            self._q.put(1)
            time.sleep(0.1)
        with self._lock:
            self._q.put_nowait(2)
            self._q.put(3, block=False)
        with self._cv:
            self._cv.wait()
        with self._lock:
            ev = threading.Event()
            ev.wait()
'''
    f = findings_of({"pkg/serve/feeder.py": src}, [LockOrderRule()])
    msgs = sorted(x.message for x in f)
    assert len(f) == 3, [x.render() for x in f]
    assert any("blocking `.put(...)`" in m for m in msgs)
    assert any("time.sleep" in m for m in msgs)
    # cv.wait() on the HELD Condition releases the lock (the protocol)
    # and is NOT among the findings; ev.wait() on a foreign object is.
    assert any("foreign object" in m for m in msgs)
    assert all("Feeder._cv`" not in m or "foreign" in m for m in msgs)


def test_lock_order_injected_fault_gates_only_when_enabled():
    """Acceptance: the ABBA fixture flags with lock-order enabled and
    stays silent under the OTHER new families (cross-family
    independence)."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    srcs = {"pkg/serve/pipe.py": ABBA_FIXTURE}
    assert findings_of(srcs, [LockOrderRule()]) != []
    assert findings_of(
        srcs, [GuardedFieldRule(), BarrierDisciplineRule()]
    ) == []


# ---------------------------------------------------------------------------
# ISSUE 17 — guarded-field


GUARDED_FIXTURE = '''
import threading


class Handle:
    def __init__(self):
        self._lock = threading.Lock()
        self.engine = None
        self.beat = 0.0
        threading.Thread(target=self._pump).start()

    def swap(self, eng):
        with self._lock:
            self.engine = eng

    def _pump(self):
        e = self.engine
        self.beat = 1.0

    def qsize(self):
        with self._lock:
            e = self.engine
        return e
'''


def test_guarded_field_flags_unlocked_read():
    """`engine` is written under `_lock` in swap(), so the lock-free
    read from the pump thread races the swap; `beat` is NEVER accessed
    under the lock (a deliberate benign race) and stays unflagged."""
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule

    f = findings_of({"pkg/serve/handle.py": GUARDED_FIXTURE},
                    [GuardedFieldRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "unlocked read of `self.engine`" in f[0].message
    assert "Handle._pump" in f[0].message
    assert "snapshot it under the lock" in f[0].message


def test_guarded_field_sanctions_init_assignment_and_held_helper():
    """Negatives: single-assignment-before-thread-start (`_q` bound in
    __init__ only) and the private-helper escape (`_flush` called only
    with `_lock` held inherits the critical section)."""
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule

    src = '''
import queue
import threading


class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._count = 0
        threading.Thread(target=self._main).start()

    def _main(self):
        with self._lock:
            self._q.put_nowait(1)
            self._count = self._count + 1
            self._flush()

    def emit(self):
        self._q.put_nowait(3)

    def _flush(self):
        self._count = 0
'''
    f = findings_of({"pkg/serve/writer.py": src}, [GuardedFieldRule()])
    assert f == [], [x.render() for x in f]


def test_guarded_field_unexposed_class_is_clean():
    """A class with a lock but NO thread exposure (no spawn, not in
    the thread scope) is single-threaded as far as the linted tree
    can tell — no findings."""
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule

    src = '''
import threading


class Cold:
    def __init__(self):
        self._lock = threading.Lock()
        self.x = 0

    def locked(self):
        with self._lock:
            self.x = 1

    def unlocked(self):
        return self.x
'''
    assert findings_of({"pkg/util/cold.py": src},
                       [GuardedFieldRule()]) == []


def test_guarded_field_injected_fault_gates_only_when_enabled():
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    srcs = {"pkg/serve/handle.py": GUARDED_FIXTURE}
    assert findings_of(srcs, [GuardedFieldRule()]) != []
    assert findings_of(
        srcs, [LockOrderRule(), BarrierDisciplineRule()]
    ) == []


# ---------------------------------------------------------------------------
# ISSUE 17 — barrier-discipline


# The PR-13 wedge, verbatim shape: a barrier name minted from the
# call-site counter instead of the writer's enqueue-time sequence.
WEDGE_FIXTURE = '''
from hydragnn_tpu.utils.checkpoint import _barrier_seq


def publish(client, tag):
    seq = _barrier_seq(f"b:{tag}")
    name = f"hgtpu_save:{tag}:{seq}"
    client.wait_at_barrier(name)


def publish_ok(client, tag, job_seq):
    client.wait_at_barrier(f"hgtpu_save:{tag}:{job_seq}")
'''


def test_barrier_discipline_flags_counter_minted_name():
    """The PR-13 shape verbatim: `_barrier_seq` at the call site
    flags AT THE MINT LINE; the enqueue-time-parameter shape is the
    sanctioned idiom and stays clean."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    f = findings_of({"pkg/utils/publish.py": WEDGE_FIXTURE},
                    [BarrierDisciplineRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "_barrier_seq(...)" in f[0].message
    assert "PR-13 wedge class" in f[0].message
    assert "enqueue-time" in f[0].message
    # anchored at the mint, not the wait
    assert f[0].line == WEDGE_FIXTURE.splitlines().index(
        '    seq = _barrier_seq(f"b:{tag}")'
    ) + 1


def test_barrier_discipline_flags_time_and_next_mints():
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    src = '''
import itertools
import time

_COUNTER = itertools.count()


def settle(client):
    n = f"walltime:{time.time()}"
    client.key_value_set(n, "1")
    client.wait_at_barrier(f"gen:{next(_COUNTER)}")
'''
    f = findings_of({"pkg/utils/settle.py": src},
                    [BarrierDisciplineRule()])
    labels = sorted(x.message for x in f)
    assert len(f) == 2, [x.render() for x in f]
    assert any("time.time()" in m for m in labels)
    assert any("next(...)" in m for m in labels)


def test_barrier_discipline_flags_seqless_process_barrier():
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    src = '''
def finalize(barrier):
    _process_barrier("final")


def finalize_ok(job_seq):
    _process_barrier("final", seq=job_seq)
'''
    f = findings_of({"pkg/runner2.py": src}, [BarrierDisciplineRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "without `seq=`" in f[0].message
    assert "finalize" in f[0].message


def test_barrier_discipline_conditional_rendezvous():
    """A barrier WAIT under a process_index test flags; asymmetric KV
    set under the same test (the designed O(P) aggregation) and waits
    under uniform process_count tests do not."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    src = '''
import jax


def publish(client, name):
    if jax.process_index() == 0:
        client.wait_at_barrier(name)


def agree(client, name, payload):
    if jax.process_index() == 0:
        client.key_value_set(name, payload)
    if jax.process_count() > 1:
        client.wait_at_barrier(name)
'''
    f = findings_of({"pkg/utils/agree.py": src},
                    [BarrierDisciplineRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "under a `process_index` test" in f[0].message
    assert "publish" in f[0].message


def test_barrier_discipline_collective_on_coord_path_only():
    """sync_global_devices on a coordination path flags (jax 0.4.37
    CPU has no multi-process XLA); the same collective in compute code
    NOT reachable from any coordination site is out of scope."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    src = '''
from jax.experimental import multihost_utils


def settle(client, name):
    multihost_utils.sync_global_devices(name)
    client.key_value_set(name, "done")


def gather_metrics(x):
    return multihost_utils.process_allgather(x)
'''
    f = findings_of({"pkg/utils/settle.py": src},
                    [BarrierDisciplineRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "sync_global_devices" in f[0].message
    assert "settle" in f[0].message


def test_barrier_discipline_injected_fault_gates_only_when_enabled():
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    srcs = {"pkg/utils/publish.py": WEDGE_FIXTURE}
    assert findings_of(srcs, [BarrierDisciplineRule()]) != []
    assert findings_of(
        srcs, [LockOrderRule(), GuardedFieldRule()]
    ) == []


# ---------------------------------------------------------------------------
# ISSUE 17 — baseline/fingerprint mechanics for the new families


def test_concurrency_family_fingerprints_are_line_stable():
    """Findings from all three new families keep their fingerprints
    when the file shifts (fingerprints exclude line numbers)."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    for rel, fixture, rule in (
        ("pkg/serve/pipe.py", ABBA_FIXTURE, LockOrderRule()),
        ("pkg/serve/handle.py", GUARDED_FIXTURE, GuardedFieldRule()),
        ("pkg/utils/publish.py", WEDGE_FIXTURE, BarrierDisciplineRule()),
    ):
        f1 = findings_of({rel: fixture}, [rule])
        f2 = findings_of({rel: "# moved\n# down\n" + fixture}, [rule])
        assert len(f1) == len(f2) == 1, (rule.name, f1, f2)
        assert f1[0].fingerprint == f2[0].fingerprint
        assert f1[0].line != f2[0].line


def test_concurrency_family_baseline_grandfather(tmp_path):
    """A pre-existing wedge grandfathers through the baseline; a
    SECOND mint site still gates (count ratchet applies to the new
    families like any other)."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )

    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    bad = src_dir / "m.py"
    bad.write_text(WEDGE_FIXTURE)
    baseline = tmp_path / "baseline.json"
    res = run_lint(str(tmp_path), paths=["pkg"],
                   rules=[BarrierDisciplineRule()],
                   baseline_path=str(baseline))
    assert not res.ok and len(res.new) == 1
    write_baseline(str(baseline), res.findings)
    res2 = run_lint(str(tmp_path), paths=["pkg"],
                    rules=[BarrierDisciplineRule()],
                    baseline_path=str(baseline))
    assert res2.ok and len(res2.baselined) == 1
    bad.write_text(WEDGE_FIXTURE + (
        "\n\ndef publish_two(client, tag):\n"
        "    client.wait_at_barrier(f\"again:{_barrier_seq(tag)}\")\n"
    ))
    res3 = run_lint(str(tmp_path), paths=["pkg"],
                    rules=[BarrierDisciplineRule()],
                    baseline_path=str(baseline))
    assert not res3.ok and len(res3.new) == 1


def test_suppression_silences_new_families_with_reason():
    """The in-place `disable-next-line=RULE -- why` grammar covers the
    new families (the triage mechanism the real tree uses)."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.suppression import SuppressionRule

    src = WEDGE_FIXTURE.replace(
        '    seq = _barrier_seq(f"b:{tag}")',
        "    # graftlint: disable-next-line=barrier-discipline"
        " -- symmetric smoke path\n"
        '    seq = _barrier_seq(f"b:{tag}")',
    )
    f = findings_of({"pkg/utils/publish.py": src},
                    [BarrierDisciplineRule(), SuppressionRule()])
    assert f == [], [x.render() for x in f]


# ---------------------------------------------------------------------------
# ISSUE 17 — real-tree proofs and seed registry (fleet surfaces)


def test_lock_order_real_fleet_rollover_shape_is_safe():
    """The ISSUE-17 proof obligation: the REAL serving tier — replica
    pumps, beat threads, swap/submit on `ReplicaHandle._lock`, the
    tier monitor — has NO lock-order findings (no ABBA cycle, no
    blocking call under a held lock)."""
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    srcs = {}
    for rel in (
        "hydragnn_tpu/serve/fleet.py",
        "hydragnn_tpu/serve/router.py",
        "hydragnn_tpu/serve/batcher.py",
        "hydragnn_tpu/serve/engine.py",
    ):
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            srcs[rel] = open(path).read()
    assert "hydragnn_tpu/serve/fleet.py" in srcs
    f = findings_of(srcs, [LockOrderRule()])
    assert f == [], [x.render() for x in f]


def test_guarded_field_real_fleet_gauges_are_clean():
    """The gauge paths read `batcher`/`engine` via snapshot-under-lock
    after the ISSUE-17 fix — the real fleet module must carry no
    guarded-field findings."""
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule

    rel = "hydragnn_tpu/serve/fleet.py"
    src = open(os.path.join(REPO, rel)).read()
    f = findings_of({rel: src}, [GuardedFieldRule()])
    assert f == [], [x.render() for x in f]


def test_guarded_field_catches_reintroduced_gauge_race():
    """Seed-registry load test: stripping the snapshot-under-lock from
    a gauge reintroduces the exact race this PR fixed — and the rule
    catches it on the REAL class shape."""
    from hydragnn_tpu.analysis.rules.guarded_field import GuardedFieldRule

    bad = '''
import threading


class ReplicaHandle:
    def __init__(self):
        self._lock = threading.Lock()
        self.batcher = None
        threading.Thread(target=self._pump_main).start()

    def _pump_main(self):
        with self._lock:
            b = self.batcher
        b.drain()

    def swap(self, batcher):
        with self._lock:
            self.batcher = batcher

    def qsize(self):
        return self.batcher.qsize()
'''
    f = findings_of({"hydragnn_tpu/serve/fleet.py": bad},
                    [GuardedFieldRule()])
    assert len(f) == 1, [x.render() for x in f]
    assert "unlocked read of `self.batcher`" in f[0].message
    assert "qsize" in f[0].message


def test_thread_discipline_fleet_kill_paths_are_seeded():
    """ISSUE 17 satellite: ReplicaHandle.kill / ServingTier.kill_replica
    are never-block seeds — a blocking join/sleep smuggled into the
    kill path stalls rollover; and the REAL module stays clean."""
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        NEVER_BLOCK_SEEDS,
        ThreadDisciplineRule,
    )

    for qual in ("ReplicaHandle.kill", "ServingTier.kill_replica"):
        assert any(
            q == qual for p, q in NEVER_BLOCK_SEEDS
            if p == "serve/fleet.py"
        ), f"{qual} not found among never-block seeds"
    bad = (
        "import time\n"
        "class ReplicaHandle:\n"
        "    def kill(self):\n"
        "        time.sleep(1.0)\n"
    )
    f = findings_of(
        {"hydragnn_tpu/serve/fleet.py": bad}, [ThreadDisciplineRule()]
    )
    assert any("time.sleep" in x.message for x in f), [
        x.message for x in f
    ]
    real = open(
        os.path.join(REPO, "hydragnn_tpu/serve/fleet.py")
    ).read()
    f = findings_of(
        {"hydragnn_tpu/serve/fleet.py": real}, [ThreadDisciplineRule()]
    )
    assert f == [], [x.message for x in f]


def test_host_sync_fleet_router_and_pump_paths_are_seeded():
    """ISSUE 17 satellite: the router hot path and the replica
    pump/beat/kill mains are host-sync hot seeds — a device fence in
    the beat thread is a liveness hazard (a wedged device marks every
    replica dead)."""
    from hydragnn_tpu.analysis.rules.host_sync import HOT_SEEDS

    for rel, qual in (
        ("serve/router.py", "Router._route"),
        ("serve/router.py", "Router._shed"),
        ("serve/fleet.py", "ReplicaHandle._pump_main"),
        ("serve/fleet.py", "ReplicaHandle._beat_main"),
        ("serve/fleet.py", "ReplicaHandle.kill"),
        ("serve/fleet.py", "ServingTier.kill_replica"),
    ):
        assert (rel, qual) in HOT_SEEDS, f"{qual} not a hot seed"
    bad = (
        "import jax\n"
        "class ReplicaHandle:\n"
        "    def _beat_main(self):\n"
        "        jax.block_until_ready(self._last)\n"
    )
    f = findings_of(
        {"hydragnn_tpu/serve/fleet.py": bad}, [HostSyncRule()]
    )
    assert any("block_until_ready" in x.message for x in f), [
        x.message for x in f
    ]
    bad = (
        "import jax\n"
        "class Router:\n"
        "    def _route(self, req):\n"
        "        return jax.device_get(req)\n"
    )
    f = findings_of(
        {"hydragnn_tpu/serve/router.py": bad}, [HostSyncRule()]
    )
    assert any("device_get" in x.message for x in f), [
        x.message for x in f
    ]


# ---------------------------------------------------------------------------
# ISSUE 17 — per-rule stats


def test_per_rule_stats_buckets(tmp_path):
    """LintResult.per_rule counts new/baselined/suppressed per family
    (the --stats table and the JSON payload both read it)."""
    from hydragnn_tpu.analysis.rules.barrier_discipline import (
        BarrierDisciplineRule,
    )
    from hydragnn_tpu.analysis.rules.lock_order import LockOrderRule

    src_dir = tmp_path / "pkg"
    src_dir.mkdir()
    (src_dir / "m.py").write_text(WEDGE_FIXTURE)
    res = run_lint(str(tmp_path), paths=["pkg"],
                   rules=[BarrierDisciplineRule(), LockOrderRule()],
                   baseline_path=None)
    assert res.per_rule["barrier-discipline"] == {
        "new": 1, "baselined": 0, "suppressed": 0,
    }
    assert res.per_rule["lock-order"] == {
        "new": 0, "baselined": 0, "suppressed": 0,
    }


def test_cli_stats_table_and_json_per_rule(tmp_path, capsys):
    cli = _load_cli()
    bad = tmp_path / "m.py"
    bad.write_text(WEDGE_FIXTURE)
    rc = cli.main([str(bad), "--stats", "--baseline", ""])
    out = capsys.readouterr().out
    assert rc == 0  # informational mode
    assert "barrier-discipline" in out
    assert "baselined" in out and "suppressed" in out
    assert "total" in out
    rc = cli.main([str(bad), "--json", "--baseline", ""])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    per_rule = payload["per_rule"]
    for fam in ("lock-order", "guarded-field", "barrier-discipline"):
        assert fam in per_rule
    assert per_rule["barrier-discipline"]["new"] == 1
