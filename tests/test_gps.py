"""GPS global attention: masking correctness + E2E training.

Reference coverage analog: tests/test_graphs.py:238-252 (global attention
variants) — plus a padding-invariance check that only a masked dense
attention can pass.
"""

import numpy as np
import pytest

from hydragnn_tpu.config import update_config
from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
from hydragnn_tpu.models.create import create_model_config, init_params
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.ops.pe import laplacian_pe, relative_pe


def _samples(n_samples=3, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        n = int(rng.integers(4, 8))
        pos = rng.uniform(0, 2.5, size=(n, 3)).astype(np.float32)
        ei = radius_graph(pos, 2.0, max_neighbours=8)
        pe = laplacian_pe(ei, n, 4)
        out.append(
            GraphSample(
                x=rng.normal(size=(n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei,
                pe=pe,
                rel_pe=relative_pe(ei, pe),
                y_graph=np.array([rng.normal()], dtype=np.float32),
            )
        )
    return out


def _gps_config(attn_type):
    return {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.0,
                "max_neighbours": 8,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "global_attn_engine": "GPS",
                "global_attn_type": attn_type,
                "global_attn_heads": 2,
                "pe_dim": 4,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {"batch_size": 3},
        }
    }


@pytest.mark.parametrize("attn_type", ["multihead", "performer"])
def test_gps_padding_invariance(attn_type):
    """Outputs on real graphs must not change when padding grows."""
    samples = _samples()
    config = update_config(_gps_config(attn_type), samples)
    model, cfg = create_model_config(config)

    small = collate(samples, PadSpec.for_samples(samples, bucketed=False))
    spec = PadSpec.for_samples(samples, bucketed=False)
    big = collate(
        samples,
        PadSpec(
            num_nodes=spec.num_nodes + 17,
            num_edges=spec.num_edges + 23,
            num_graphs=spec.num_graphs + 2,
        ),
    )
    params, bstats = init_params(model, small)
    out_small = model.apply(
        {"params": params, "batch_stats": bstats}, small, train=False
    )
    out_big = model.apply(
        {"params": params, "batch_stats": bstats}, big, train=False
    )
    g = len(samples)
    for a, b in zip(out_small, out_big):
        np.testing.assert_allclose(
            np.asarray(a)[:g], np.asarray(b)[:g], atol=2e-5
        )


def _samples_atomic(n_samples=40, seed=0, target_scale=1.0):
    """Molecules with integer atomic numbers (MACE-compatible) + PE."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        n = int(rng.integers(4, 9))
        pos = rng.uniform(0, 2.5, size=(n, 3)).astype(np.float32)
        x = rng.integers(1, 6, size=(n, 1)).astype(np.float32)
        ei = radius_graph(pos, 2.0, max_neighbours=8)
        pe = laplacian_pe(ei, n, 4)
        out.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=ei,
                pe=pe,
                rel_pe=relative_pe(ei, pe),
                y_graph=np.array(
                    [target_scale * float(x.mean())], dtype=np.float32
                ),
            )
        )
    return out


def _gps_stack_config(mpnn_type):
    """GPS over non-invariant stacks (reference wraps ANY conv in
    GPSConv, Base.py:234-247)."""
    config = _gps_config("multihead")
    arch = config["NeuralNetwork"]["Architecture"]
    arch["mpnn_type"] = mpnn_type
    arch["hidden_dim"] = 16
    arch["num_radial"] = 6
    if mpnn_type == "MACE":
        arch.update(max_ell=2, node_max_ell=2, correlation=2)
    config["NeuralNetwork"]["Training"].update(
        num_epoch=12,
        Optimizer={"type": "AdamW", "learning_rate": 5e-3},
    )
    return config


@pytest.mark.parametrize("mpnn_type", ["PAINN", "PNAEq", "MACE"])
def test_gps_trains_on_equivariant_and_mace_stacks(mpnn_type):
    """GPS composes with every stack family: train loss must drop
    (reference analog: global attention variants in
    tests/test_graphs.py:238-252 wrap any mpnn_type)."""
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_training

    samples = _samples_atomic(n_samples=96, seed=1)
    tr, va, te = split_dataset(samples, 0.75)
    config = _gps_stack_config(mpnn_type)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    _, _, cfg, hist, _ = run_training(config, datasets=(tr, va, te), seed=0)
    assert cfg.use_global_attn
    assert hist.train_loss[-1] < hist.train_loss[0] * 0.6, hist.train_loss
