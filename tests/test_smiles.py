"""SMILES -> GraphSample path without rdkit (SURVEY.md §2.7; reference
hydragnn/utils/descriptors_and_embeddings/smiles_utils.py:36-127).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.utils.smiles import (
    get_node_attribute_name,
    graph_sample_from_smiles,
    parse_smiles,
)

TYPES = {"C": 0, "O": 1, "N": 2, "H": 3}


@pytest.mark.parametrize(
    "smiles,n_atoms,n_bonds",
    [
        ("C", 5, 4),  # methane: C + 4 implicit H
        ("CC", 8, 7),
        ("C=C", 6, 5),
        ("C#N", 3, 2),
        ("c1ccccc1", 12, 12),  # benzene: 6 C + 6 H, 6 ring + 6 C-H
        ("c1ccc2ccccc2c1", 18, 19),  # fused rings, reused digit
        ("CC(=O)O", 8, 7),  # branch + double bond
        ("c1ccncc1", 11, 11),  # pyridine: aromatic N gets no H
        ("[NH4+]", 5, 4),  # bracket charge + explicit H count
        ("O=C=O", 3, 2),  # cumulated doubles
        ("ClCCl", 5, 4),  # two-letter organic atoms
        ("C/C=C/C", 12, 11),  # stereo bonds parse as single
        ("C%10CC%10", 9, 9),  # %nn ring closure
        ("CCO.CC", 17, 15),  # dot-disconnected components
    ],
)
def test_parse_atom_and_bond_counts(smiles, n_atoms, n_bonds):
    mol = parse_smiles(smiles)
    assert mol.num_atoms == n_atoms
    assert len(mol.bonds) == n_bonds


def test_parse_errors():
    with pytest.raises(ValueError, match="Unclosed ring"):
        parse_smiles("C1CC")
    with pytest.raises(ValueError, match="Unsupported"):
        parse_smiles("C?C")


def test_feature_layout_matches_reference():
    """x = [type one-hot | Z | aromatic | sp | sp2 | sp3 | num_h];
    edge_attr = one-hot over (single, double, triple, aromatic);
    edges both directions sorted by src*N+dst."""
    s = graph_sample_from_smiles("CC(=O)O", [1.23], TYPES)
    assert s.x.shape == (8, len(TYPES) + 6)
    assert s.edge_index.shape == (2, 14)  # 7 bonds, both directions
    assert s.edge_attr.shape == (14, 4)
    np.testing.assert_allclose(s.y_graph, [1.23])
    # sorted edge keys
    keys = s.edge_index[0] * 8 + s.edge_index[1]
    assert (np.diff(keys) >= 0).all()
    # carbonyl C (atom 1) is sp2; methyl C (atom 0) is sp3 with 3 H
    base = len(TYPES)
    assert s.x[1, base + 3] == 1.0  # sp2
    assert s.x[0, base + 4] == 1.0  # sp3
    assert s.x[0, base + 5] == 3.0  # 3 H neighbours
    # one double bond -> exactly 2 directed edges of class 1
    assert int((s.edge_attr.argmax(1) == 1).sum()) == 2


def test_benzene_aromatic_features():
    s = graph_sample_from_smiles("c1ccccc1", [0.0], TYPES)
    base = len(TYPES)
    carbons = s.x[:, TYPES["C"]] == 1.0
    assert int(carbons.sum()) == 6
    # all ring atoms aromatic + sp2, one H each
    assert (s.x[carbons, base + 1] == 1.0).all()
    assert (s.x[carbons, base + 3] == 1.0).all()
    assert (s.x[carbons, base + 5] == 1.0).all()
    # 6 aromatic bonds (class 3) -> 12 directed aromatic edges
    assert int((s.edge_attr.argmax(1) == 3).sum()) == 12


def test_unknown_type_rejected():
    with pytest.raises(KeyError, match="not in the `types` map"):
        graph_sample_from_smiles("CS", [0.0], TYPES)


def test_node_attribute_names():
    names, dims = get_node_attribute_name(TYPES)
    assert names[: len(TYPES)] == ["atomC", "atomO", "atomN", "atomH"]
    assert names[len(TYPES) :] == [
        "atomicnumber",
        "IsAromatic",
        "HSP",
        "HSP2",
        "HSP3",
        "Hprop",
    ]
    assert dims == [1] * len(names)


def test_trains_end_to_end():
    """A tiny SchNet-free (topology-only) model learns a closed-form
    target from parsed SMILES graphs — the csce-driver path."""
    import hydragnn_tpu

    smiles_pool = [
        "C", "CC", "CCC", "CCCC", "CCO", "CC(=O)O", "c1ccccc1",
        "c1ccncc1", "C=C", "C#N", "CCN", "CO", "C1CC1", "CC(C)C",
    ]
    samples = []
    for rep in range(6):
        for smi in smiles_pool:
            mol = parse_smiles(smi)
            # target: mean atomic number (learnable from x alone)
            y = float(np.mean(mol.atomic_numbers)) / 8.0
            samples.append(graph_sample_from_smiles(smi, [y], TYPES))
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "GIN",
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": list(range(len(TYPES) + 6)),
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "num_epoch": 12,
                "batch_size": 16,
                "perc_train": 0.8,
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
            },
        },
    }
    state, model, cfg, hist, _ = hydragnn_tpu.run_training(
        config, datasets=(samples[:64], samples[64:74], samples[74:])
    )
    assert np.isfinite(hist.train_loss).all()
    assert hist.train_loss[-1] < 0.5 * hist.train_loss[0]


def test_molecule_from_positions_bond_perception():
    """xyz->bond-graph perception (minimal xyz2mol equivalent): bond
    orders from covalent-radius distance ratios."""
    from hydragnn_tpu.utils.smiles import molecule_from_positions

    cases = [
        ([[0, 0, 0], [1.54, 0, 0]], [6, 6], [(0, 1, 1.0)]),
        ([[0, 0, 0], [1.33, 0, 0]], [6, 6], [(0, 1, 2.0)]),
        ([[0, 0, 0], [1.20, 0, 0]], [6, 6], [(0, 1, 3.0)]),
        (
            [[0, 0, 0], [1.16, 0, 0], [-1.16, 0, 0]],
            [6, 8, 8],
            [(0, 1, 2.0), (0, 2, 2.0)],
        ),
    ]
    for pos, z, bonds in cases:
        mol = molecule_from_positions(np.array(pos, float), z)
        assert sorted(mol.bonds) == sorted(bonds), (pos, mol.bonds)

    # water: two single O-H bonds, no H-H bond
    mol = molecule_from_positions(
        np.array([[0.0, 0, 0], [0.96, 0, 0], [-0.24, 0.93, 0]]), [8, 1, 1]
    )
    assert sorted((i, j) for i, j, _ in mol.bonds) == [(0, 1), (0, 2)]
    assert mol.symbols == ["O", "H", "H"]


def test_molecule_from_positions_feeds_featurizer():
    """The perceived molecule drops into the same feature layout via
    graph_sample_from_smiles(mol=...)."""
    from hydragnn_tpu.utils.smiles import (
        graph_sample_from_smiles,
        molecule_from_positions,
    )

    mol = molecule_from_positions(
        np.array([[0.0, 0, 0], [1.33, 0, 0]]), [6, 6]
    )
    s = graph_sample_from_smiles("", [1.0], TYPES, mol=mol)
    assert s.x.shape == (2, len(TYPES) + 6)
    # both carbons sp2 from the double bond
    assert (s.x[:, len(TYPES) + 3] == 1.0).all()
    assert int((s.edge_attr.argmax(1) == 1).sum()) == 2


def test_descriptors_entrypoint_falls_back_to_native_parser(monkeypatch):
    """generate_graphdata_from_smilestr (the reference-named entry
    point in utils/descriptors.py) works without rdkit by routing
    through the native parser. The no-rdkit condition is FORCED so the
    fallback branch is exercised even on hosts with rdkit installed."""
    import builtins

    real_import = builtins.__import__

    def no_rdkit(name, *a, **kw):
        if name.startswith("rdkit"):
            raise ImportError("forced for test")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_rdkit)
    from hydragnn_tpu.utils.descriptors import (
        generate_graphdata_from_smilestr,
    )

    s = generate_graphdata_from_smilestr("CC(=O)O", [1.0], TYPES)
    ref = graph_sample_from_smiles("CC(=O)O", [1.0], TYPES)
    np.testing.assert_array_equal(s.x, ref.x)
    np.testing.assert_array_equal(s.edge_index, ref.edge_index)
    np.testing.assert_array_equal(s.edge_attr, ref.edge_attr)
    np.testing.assert_allclose(s.y_graph, [1.0])


def test_parse_smiles_malformed_inputs_raise_valueerror():
    """Malformed SMILES must fail with a ValueError naming the string,
    not a confusing TypeError/IndexError from parser internals."""
    import pytest

    from hydragnn_tpu.utils.smiles import parse_smiles

    for bad in ("1CC1", "CC)C", "C=1CC-1"):
        with pytest.raises(ValueError, match="C"):
            parse_smiles(bad)
    # Matching explicit ring-bond orders on both ends are legal.
    mol = parse_smiles("C=1CC=1", with_hydrogen=False)
    assert sorted(o for _, _, o in mol.bonds)[-1] == 2.0


def test_bond_promotion_restricted_to_organic_pairs():
    """The double/triple promotion thresholds are calibrated on C/N/O/S
    multiple bonds; outside that chemistry (metal-ligand, Si) even a
    compressed contact must stay a single bond."""
    import numpy as np

    from hydragnn_tpu.utils.smiles import molecule_from_positions

    # O2 at 1.21 A: rel = 1.21 / (0.66 + 0.66) = 0.917 -> double bond.
    o2 = molecule_from_positions(
        np.array([[0.0, 0.0, 0.0], [1.21, 0.0, 0.0]]), [8, 8]
    )
    assert o2.bonds == [(0, 1, 2.0)]
    # Fe-O at the same RELATIVE compression (rel ~ 0.91): stays single —
    # the organic calibration does not transfer to metal-ligand bonds.
    feo = molecule_from_positions(
        np.array([[0.0, 0.0, 0.0], [1.80, 0.0, 0.0]]), [26, 8]
    )
    assert feo.bonds == [(0, 1, 1.0)]
    # Si-Si compressed contact (rel ~ 0.9): single.
    si2 = molecule_from_positions(
        np.array([[0.0, 0.0, 0.0], [2.00, 0.0, 0.0]]), [14, 14]
    )
    assert si2.bonds == [(0, 1, 1.0)]
