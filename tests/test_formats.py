"""Raw format readers (XYZ, AtomEye CFG) and the energy-regression
baseline (reference xyzdataset.py / cfg_raw_dataset_loader.py /
energy_linear_regression.py).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.energy_regression import (
    apply_energy_baseline,
    element_counts,
    fit_energy_baseline,
    solve_least_squares_svd,
    subtract_energy_baseline,
)
from hydragnn_tpu.data.formats import (
    read_cfg_file,
    read_xyz_directory,
    read_xyz_file,
)
from hydragnn_tpu.data.graph import GraphSample


def test_read_xyz(tmp_path):
    p = tmp_path / "mol.xyz"
    p.write_text(
        "3\ncomment line\n"
        "O 0.0 0.0 0.0\n"
        "H 0.757 0.586 0.0\n"
        "H -0.757 0.586 0.0\n"
    )
    (tmp_path / "mol_energy.txt").write_text("-76.4 extra stuff\n")
    s = read_xyz_file(str(p))
    assert s.x.shape == (3, 1)
    np.testing.assert_array_equal(s.x[:, 0], [8, 1, 1])
    np.testing.assert_allclose(s.pos[1], [0.757, 0.586, 0.0], atol=1e-6)
    np.testing.assert_allclose(s.y_graph, [-76.4])
    assert len(read_xyz_directory(str(tmp_path))) == 1


def test_read_xyz_unknown_element(tmp_path):
    p = tmp_path / "bad.xyz"
    p.write_text("1\nc\nQq 0 0 0\n")
    with pytest.raises(ValueError, match="unknown element"):
        read_xyz_file(str(p))


def test_read_cfg(tmp_path):
    p = tmp_path / "struct.cfg"
    p.write_text(
        "Number of particles = 2\n"
        "A = 1.0 Angstrom\n"
        "H0(1,1) = 4.0\nH0(1,2) = 0.0\nH0(1,3) = 0.0\n"
        "H0(2,1) = 0.0\nH0(2,2) = 4.0\nH0(2,3) = 0.0\n"
        "H0(3,1) = 0.0\nH0(3,2) = 0.0\nH0(3,3) = 4.0\n"
        ".NO_VELOCITY.\n"
        "entry_count = 7\n"
        "auxiliary[0] = c_peratom\n"
        "auxiliary[1] = fx\n"
        "auxiliary[2] = fy\n"
        "auxiliary[3] = fz\n"
        "55.85\n"
        "Fe\n"
        "0.0 0.0 0.0 1.5 0.1 0.2 0.3\n"
        "0.5 0.5 0.5 2.5 -0.1 -0.2 -0.3\n"
    )
    (tmp_path / "struct.bulk").write_text("123.0\n")
    s = read_cfg_file(str(p))
    assert s.x.shape == (2, 6)  # Z, mass, 4 aux
    np.testing.assert_array_equal(s.x[:, 0], [26, 26])
    np.testing.assert_allclose(s.x[:, 1], [55.85, 55.85])
    np.testing.assert_allclose(s.pos[1], [2.0, 2.0, 2.0])
    np.testing.assert_allclose(s.cell, np.eye(3) * 4.0)
    np.testing.assert_allclose(s.y_graph, [123.0])


def test_energy_regression_roundtrip():
    rng = np.random.default_rng(0)
    true_coeff = np.zeros(118)
    true_coeff[0] = -13.6  # H
    true_coeff[7] = -2000.0  # O
    samples = []
    for _ in range(20):
        n_h = int(rng.integers(0, 5))
        n_o = int(rng.integers(1, 4))
        zs = np.array([1.0] * n_h + [8.0] * n_o).reshape(-1, 1)
        residual = float(rng.normal(scale=0.01))
        e = n_h * true_coeff[0] + n_o * true_coeff[7] + residual
        samples.append(
            GraphSample(x=zs.astype(np.float32), energy=e)
        )
    coeff = fit_energy_baseline(samples)
    np.testing.assert_allclose(coeff[0], -13.6, atol=0.1)
    np.testing.assert_allclose(coeff[7], -2000.0, atol=0.1)
    assert np.abs(np.delete(coeff, [0, 7])).max() < 1e-6

    corrected = subtract_energy_baseline(samples, coeff)
    # residual energies are tiny; originals untouched
    assert abs(corrected[0].energy) < 1.0
    assert samples[0].energy != corrected[0].energy
    # adding the baseline back recovers totals
    res = np.array([s.energy for s in corrected])
    totals = apply_energy_baseline(samples, res, coeff)
    np.testing.assert_allclose(
        totals, [s.energy for s in samples], atol=1e-8
    )


def test_svd_least_squares_rank_deficient():
    a = np.array([[1.0, 0.0, 0.0], [2.0, 0.0, 0.0]])
    b = np.array([1.0, 2.0])
    x = solve_least_squares_svd(a, b)
    np.testing.assert_allclose(x, [1.0, 0.0, 0.0], atol=1e-10)
