"""Superstep executor (ISSUE 4): K train steps per device dispatch via
``lax.scan`` over same-spec stacked macro-batches.

The load-bearing invariant is BITWISE identity: a K-group dispatch
(train/loop.make_superstep_fn) must reproduce K sequential single-step
dispatches exactly — loss sums, per-task sums, params — with packing on
and off, across serial and pipeline delivery, through run tails shorter
than K, and at K=1 (where nothing is wrapped at all).
"""

import dataclasses

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample, MacroBatch, PadSpec
from hydragnn_tpu.ops.neighbors import radius_graph


def _mols(n, lo=5, hi=11, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(r.integers(lo, hi))
        pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.integers(0, 3, (k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                y_graph=np.array([r.normal()], np.float32),
            )
        )
    return out


def _config(steps="auto", workers=0, num_epoch=2, batch_size=4):
    return {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.2,
                "max_neighbours": 16,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "num_epoch": num_epoch,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
                "Parallelism": {
                    "scheme": "single",
                    "pipeline": {"workers": workers},
                    "superstep": {"steps": steps},
                },
            },
        }
    }


@pytest.fixture(scope="module")
def tiny_model():
    """One compiled model family shared by every step-parity test."""
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.train.optimizer import select_optimizer

    samples = _mols(64, seed=3)
    cfgd = update_config(_config(), samples)
    model, cfg = create_model_config(cfgd)
    batch0 = next(iter(GraphLoader(samples, 4)))
    params, bs = init_params(model, batch0)
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    # HOST copies: donated steps delete their input buffers, so every
    # test must start from an independent device copy (_fresh_state).
    params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )
    return samples, model, cfg, tx, params, bs


def _fresh_state(tiny_model):
    from hydragnn_tpu.train.state import create_train_state

    _, _, _, tx, params, bs = tiny_model
    # jnp.array COPIES: donation must never reach the fixture's host
    # buffers (XLA:CPU device_put would zero-copy them).
    dev_params = jax.tree_util.tree_map(jnp.array, params)
    dev_bs = jax.tree_util.tree_map(jnp.array, bs)
    return create_train_state(dev_params, tx, dev_bs)


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(u), np.asarray(v))
        for u, v in zip(la, lb)
    )


# ----------------------------------------------------------------------
# Grouping arithmetic (pure functions of the plan)
# ----------------------------------------------------------------------


def _spec(n, e, g):
    return PadSpec(num_nodes=n, num_edges=e, num_graphs=g)


def test_superstep_groups_runs_and_tails():
    from hydragnn_tpu.data.padschedule import superstep_groups

    a, b = _spec(16, 32, 5), _spec(24, 48, 5)
    plan = [(i, a) for i in range(10)] + [(i, b) for i in range(3)]
    groups = superstep_groups(plan, 4)
    # 10-run of a: two full 4-groups + 2 singletons; 3-run of b: singles
    assert [len(g) for g in groups] == [4, 4, 1, 1, 1, 1, 1]
    # order and content preserved exactly
    assert [e for g in groups for e in g] == plan
    # k=1: all singletons, plan order untouched
    assert [g[0] for g in superstep_groups(plan, 1)] == plan
    # deterministic (pure)
    assert superstep_groups(plan, 4) == groups


def test_superstep_groups_interleaved_specs_never_group_across_runs():
    from hydragnn_tpu.data.padschedule import superstep_groups

    a, b = _spec(16, 32, 5), _spec(24, 48, 5)
    plan = [(0, a), (1, b), (2, a), (3, b)]
    groups = superstep_groups(plan, 2)
    assert [len(g) for g in groups] == [1, 1, 1, 1]


def test_superstep_groups_none_spec_stays_single():
    from hydragnn_tpu.data.padschedule import superstep_groups

    a = _spec(16, 32, 5)
    plan = [(0, a), (1, None), (2, a), (3, a)]
    groups = superstep_groups(plan, 2)
    assert [len(g) for g in groups] == [1, 1, 2]
    assert groups[1][0][1] is None


def test_auto_superstep_k_floor_cap_and_fragmentation():
    from hydragnn_tpu.data.padschedule import (
        auto_superstep_k,
        estimate_spec_bytes,
        superstep_groups,  # noqa: F401  (same grouping the auto sims)
    )

    a = _spec(64, 128, 9)
    long_run = [(i, a) for i in range(128)]
    # long uniform run: largest candidate wins
    assert auto_superstep_k(long_run) == 32
    # short plans never engage (dispatch amortization is a long-epoch
    # optimization; unit-test-sized runs keep today's exact shape)
    assert auto_superstep_k(long_run[:32]) == 1
    assert auto_superstep_k([], ) == 1
    # memory cap: K * est bytes must fit
    cap = estimate_spec_bytes(a) * 8
    assert auto_superstep_k(long_run, max_host_bytes=cap) == 8
    # fragmentation: alternating specs -> no runs -> 1
    b = _spec(80, 160, 9)
    frag = [(i, a if i % 2 else b) for i in range(128)]
    assert auto_superstep_k(frag) == 1


def test_resolve_superstep_k_scheme_and_pinning(tiny_model):
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.runtime import (
        ParallelPlan,
        resolve_superstep_k,
    )

    samples, *_ = tiny_model
    loader = GraphLoader(samples, 4, fixed_pad=True)
    # explicit pin wins whatever the plan length
    plan = ParallelPlan(scheme="single", superstep_steps=8)
    assert resolve_superstep_k(plan, loader) == 8
    # auto on a short (16-step) plan: floor keeps K=1
    plan = ParallelPlan(scheme="single", superstep_steps="auto")
    assert resolve_superstep_k(plan, loader) == 1
    # multibranch — and a degenerate meshless dp plan — always 1
    # (dp WITH a mesh now resolves K at step level:
    # tests/test_dp_fastpath.py::test_resolve_superstep_k_dp)
    plan = ParallelPlan(scheme="dp", superstep_steps=8)
    assert resolve_superstep_k(plan, loader) == 1
    plan = ParallelPlan(scheme="multibranch", superstep_steps=8)
    assert resolve_superstep_k(plan, loader) == 1
    # the batches-per-epoch measurement cap forces K=1 (a macro runs K
    # steps atomically and would overshoot the cap by up to K-1)
    plan = ParallelPlan(scheme="single", superstep_steps=8)
    monkey = pytest.MonkeyPatch()
    try:
        monkey.setenv("HYDRAGNN_TPU_MAX_NUM_BATCH", "10")
        assert resolve_superstep_k(plan, loader) == 1
    finally:
        monkey.undo()


def test_estimate_spec_bytes_counts_triplets():
    from hydragnn_tpu.data.padschedule import estimate_spec_bytes

    base = PadSpec(num_nodes=64, num_edges=256, num_graphs=9)
    trip = PadSpec(
        num_nodes=64, num_edges=256, num_graphs=9, num_triplets=4096
    )
    # DimeNet-class padded triplet counts dwarf E: the host-RAM cap
    # must see them, or auto-K blows max_host_bytes on exactly the
    # densest batches.
    assert estimate_spec_bytes(trip) > 2 * estimate_spec_bytes(base)


def test_config_superstep_grammar():
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.parallel.runtime import _superstep_from_config

    assert _superstep_from_config({})["superstep_steps"] == "auto"
    assert (
        _superstep_from_config({"superstep": {"steps": 8}})[
            "superstep_steps"
        ]
        == 8
    )
    with pytest.raises(ValueError, match="superstep.steps"):
        _superstep_from_config({"superstep": {"steps": "fast"}})
    with pytest.raises(ValueError, match="boolean"):
        _superstep_from_config({"superstep": {"steps": True}})
    # update_config rejects unknown keys in the block eagerly
    cfg = _config()
    cfg["NeuralNetwork"]["Training"]["Parallelism"]["superstep"] = {
        "step": 8
    }
    with pytest.raises(ValueError, match="unknown keys"):
        update_config(cfg, _mols(2))


# ----------------------------------------------------------------------
# Bitwise parity: scan vs sequential steps
# ----------------------------------------------------------------------


@pytest.mark.parametrize("packing", [False, True])
def test_scan_bitwise_vs_sequential_steps(tiny_model, packing):
    """K scanned steps == K sequential jitted train_step calls, bit for
    bit (loss/task sums AND final params), with the packed former on
    and off."""
    from hydragnn_tpu.data.graph import stack_batches
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_superstep_fn, make_train_step

    samples, model, cfg, tx, params, bs = tiny_model
    loader = GraphLoader(
        samples, 4, shuffle=True, seed=7,
        **({"packing": True} if packing else {"fixed_pad": True}),
    )
    batches = [
        jax.tree_util.tree_map(np.asarray, b) for b in loader
    ]
    # packing may emit a tail bin on a different budget: keep the
    # leading same-spec run only (that is all a macro group ever holds)
    K = 1
    while (
        K < len(batches)
        and batches[K].num_nodes == batches[0].num_nodes
        and batches[K].num_edges == batches[0].num_edges
        and batches[K].num_graphs == batches[0].num_graphs
    ):
        K += 1
    K = min(K, 6)
    assert K >= 2, "need a same-spec run to stack"

    step = make_train_step(model, tx, cfg, donate=False)
    state = _fresh_state(tiny_model)
    lsum = tsum = ngsum = None
    for b in batches[:K]:
        ng = jnp.sum(b.graph_mask).astype(jnp.float32)
        state, loss, tasks = step(state, b)
        if lsum is None:
            lsum, tsum, ngsum = loss * ng, tasks * ng, ng
        else:
            lsum, tsum, ngsum = lsum + loss * ng, tsum + tasks * ng, ngsum + ng

    sstep = make_superstep_fn(model, tx, cfg, train=True, donate=False)
    macro = stack_batches(batches[:K])
    assert macro.k == K
    state2 = _fresh_state(tiny_model)
    zero = jnp.zeros((), jnp.float32)
    state2, (l2, t2, g2) = sstep(
        state2,
        (zero, jnp.zeros((1,), jnp.float32), zero),
        jax.device_put(macro.batch),
    )
    assert float(lsum) == float(l2)
    assert np.array_equal(np.asarray(tsum), np.asarray(t2))
    assert float(ngsum) == float(g2)
    assert _leaves_equal(
        jax.device_get(state.params), jax.device_get(state2.params)
    )
    assert int(state2.step) == K


def test_eval_superstep_bitwise(tiny_model):
    from hydragnn_tpu.data.graph import stack_batches
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_eval_step, make_superstep_fn

    samples, model, cfg, tx, params, bs = tiny_model
    batches = [
        jax.tree_util.tree_map(np.asarray, b)
        for b in GraphLoader(samples, 4, fixed_pad=True)
    ][:4]
    state = _fresh_state(tiny_model)
    estep = make_eval_step(model, cfg)
    lsum = tsum = ngsum = None
    for b in batches:
        ng = jnp.sum(b.graph_mask).astype(jnp.float32)
        loss, tasks = estep(state, b)
        if lsum is None:
            lsum, tsum, ngsum = loss * ng, tasks * ng, ng
        else:
            lsum, tsum, ngsum = lsum + loss * ng, tsum + tasks * ng, ngsum + ng
    sstep = make_superstep_fn(model, tx, cfg, train=False, donate=False)
    zero = jnp.zeros((), jnp.float32)
    l2, t2, g2 = sstep(
        state,
        (zero, jnp.zeros((1,), jnp.float32), zero),
        jax.device_put(stack_batches(batches).batch),
    )
    assert float(lsum) == float(l2)
    assert np.array_equal(np.asarray(tsum), np.asarray(t2))
    assert float(ngsum) == float(g2)


def test_donation_safety_across_repeated_dispatches(tiny_model):
    """The donated form (state AND accumulator through the carry) must
    be safe to call in a loop: every buffer the caller rebinds, none it
    reuses. Two epochs of grouped dispatches, then the donated result
    must still match the non-donated sequential loop."""
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.train.loop import (
        _run_epoch,
        make_superstep_fn,
        make_train_step,
        superstep_task_count,
    )

    samples, model, cfg, tx, params, bs = tiny_model
    mk = lambda: GraphLoader(  # noqa: E731
        samples, 4, shuffle=True, seed=5, fixed_pad=True
    )
    step = make_train_step(model, tx, cfg)  # donated, like production
    sstep = make_superstep_fn(model, tx, cfg, train=True)  # donated
    n_tasks = superstep_task_count(cfg)

    state_a = _fresh_state(tiny_model)
    base = mk()
    for ep in range(2):
        base.set_epoch(ep)
        state_a, loss_a, tasks_a = _run_epoch(
            step, state_a, base, train=True
        )

    state_b = _fresh_state(tiny_model)
    wrapped = SuperstepLoader(mk(), 4)
    for ep in range(2):
        wrapped.set_epoch(ep)
        state_b, loss_b, tasks_b = _run_epoch(
            step, state_b, wrapped, train=True,
            superstep_fn=sstep, n_tasks=n_tasks,
        )
    assert loss_a == loss_b
    assert np.array_equal(tasks_a, tasks_b)
    assert _leaves_equal(
        jax.device_get(state_a.params), jax.device_get(state_b.params)
    )


def test_tail_shorter_than_k_falls_back_to_singles(tiny_model):
    """A 16-step epoch at K=6 -> two macro groups + four singles; the
    mixed delivery must still reproduce the per-step loop bitwise."""
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.train.loop import (
        _run_epoch,
        make_superstep_fn,
        make_train_step,
        superstep_task_count,
    )

    samples, model, cfg, tx, params, bs = tiny_model
    mk = lambda: GraphLoader(samples, 4, fixed_pad=True)  # noqa: E731
    wrapped = SuperstepLoader(mk(), 6)
    items = list(wrapped)
    ks = [it.k if isinstance(it, MacroBatch) else 1 for it in items]
    assert ks == [6, 6, 1, 1, 1, 1]
    assert len(wrapped) == len(items)

    step = make_train_step(model, tx, cfg, donate=False)
    sstep = make_superstep_fn(model, tx, cfg, train=True, donate=False)
    state_a = _fresh_state(tiny_model)
    state_a, loss_a, tasks_a = _run_epoch(step, state_a, mk(), train=True)
    state_b = _fresh_state(tiny_model)
    state_b, loss_b, tasks_b = _run_epoch(
        step, state_b, wrapped, train=True,
        superstep_fn=sstep, n_tasks=superstep_task_count(cfg),
    )
    assert loss_a == loss_b and np.array_equal(tasks_a, tasks_b)
    assert _leaves_equal(
        jax.device_get(state_a.params), jax.device_get(state_b.params)
    )


# ----------------------------------------------------------------------
# Delivery: serial vs pipeline, caches, K=1 identity
# ----------------------------------------------------------------------


def test_grouping_determinism_serial_vs_pipeline():
    """Serial SuperstepLoader and the pipeline's worker-side stacking
    must deliver the SAME items — same group boundaries, same stacked
    bytes — for a seeded shuffled epoch (packing on: the production
    shape)."""
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _mols(96, seed=11)
    mk = lambda: GraphLoader(  # noqa: E731
        samples, 4, shuffle=True, seed=2, packing=True
    )
    for epoch in (0, 1):
        serial = SuperstepLoader(mk(), 8)
        serial.set_epoch(epoch)
        pipe = ParallelPipelineLoader(
            mk(), workers=2, depth=2, packed=True, chunk=2, superstep_k=8
        )
        pipe.set_epoch(epoch)
        items_s, items_p = list(serial), list(pipe)
        assert len(items_s) == len(items_p)
        for a, b in zip(items_s, items_p):
            assert isinstance(a, MacroBatch) == isinstance(b, MacroBatch)
            if isinstance(a, MacroBatch):
                assert a.k == b.k
            assert _leaves_equal(a, b)


def test_superstep_loader_cache_replay_and_sharing(tiny_model):
    """Fixed-order eval loaders with cache_batches replay identical
    grouped deliveries from a cache SHARED on the base loader — so the
    val/test pattern (two wrappers over one cached eval loader)
    collates and holds the epoch once. GraphLoader's own per-step
    cache stays untouched (it must never hold macro items)."""
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader

    samples, *_ = tiny_model
    base = GraphLoader(samples, 4, fixed_pad=True, cache_batches=True)
    wrapped = SuperstepLoader(base, 4)
    first = list(wrapped)
    assert getattr(base, "_superstep_cache", None) is not None
    assert base._superstep_cache[0] == 4
    assert base._batch_cache is None  # per-step cache untouched
    second = list(wrapped)
    assert len(first) == len(second)
    for a, b in zip(first, second):
        assert _leaves_equal(a, b)
    # a sibling wrapper over the SAME base replays the shared cache
    # (no re-collate, no second copy): mutate the cache sentinel-style
    # and observe the sibling seeing it.
    sibling = SuperstepLoader(base, 4)
    third = list(sibling)
    assert len(third) == len(first)
    for a, b in zip(first, third):
        assert _leaves_equal(a, b)
    # K-mismatched wrapper must NOT replay the k=4 group boundaries
    other = SuperstepLoader(base, 3)
    ks = [it.k if isinstance(it, MacroBatch) else 1 for it in other]
    assert max(ks) == 3


def test_k1_run_bit_identical_to_superstep_run(tiny_model):
    """The acceptance invariant end-to-end: run_training with
    superstep steps=8 reproduces steps=1 (today's loop) bitwise —
    losses per epoch, val/test metrics, final params — through the
    parallel pipeline feed."""
    from hydragnn_tpu.runner import run_training

    samples, *_ = tiny_model
    tr, va, te = samples[:64], _mols(12, seed=21), _mols(12, seed=22)
    out = {}
    for steps in (1, 8):
        cfg = _config(steps=steps, workers=2, num_epoch=2)
        state, model, mcfg, hist, _ = run_training(
            cfg, (tr, va, te), seed=0
        )
        out[steps] = (
            hist.train_loss,
            hist.val_loss,
            hist.test_loss,
            jax.device_get(state.params),
        )
    assert out[1][0] == out[8][0]
    assert out[1][1] == out[8][1]
    assert out[1][2] == out[8][2]
    assert _leaves_equal(out[1][3], out[8][3])


def test_wrap_loader_k1_returns_todays_wrappers(tiny_model):
    """steps=1 (or auto on a short plan) must not change the feed-path
    object graph at all — K=1 reproduces today's behavior exactly."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.runtime import ParallelPlan, wrap_loader

    samples, *_ = tiny_model
    for steps in (1, "auto"):
        plan = ParallelPlan(
            scheme="single", superstep_steps=steps, pipeline_workers=0
        )
        wrapped = wrap_loader(
            plan, GraphLoader(samples, 4, fixed_pad=True)
        )
        chain = [type(x).__name__ for x in _chain(wrapped)]
        assert "SuperstepLoader" not in chain
        plan2 = ParallelPlan(
            scheme="single", superstep_steps=steps, pipeline_workers=2
        )
        wrapped2 = wrap_loader(
            plan2, GraphLoader(samples, 4, fixed_pad=True)
        )
        assert getattr(wrapped2, "superstep_k", 1) == 1


def _chain(loader):
    from hydragnn_tpu.data.loader import iter_loader_chain

    return iter_loader_chain(loader)


def test_run_epoch_raises_without_superstep_fn(tiny_model):
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.train.loop import _run_epoch, make_train_step

    samples, model, cfg, tx, params, bs = tiny_model
    step = make_train_step(model, tx, cfg, donate=False)
    wrapped = SuperstepLoader(GraphLoader(samples, 4, fixed_pad=True), 4)
    with pytest.raises(RuntimeError, match="MacroBatch"):
        _run_epoch(step, _fresh_state(tiny_model), wrapped, train=True)


def test_superstep_task_count(tiny_model):
    from hydragnn_tpu.train.loop import superstep_task_count

    _, _, cfg, *_ = tiny_model
    assert superstep_task_count(cfg) == len(cfg.heads)
    mlip_cfg = dataclasses.replace(
        cfg, enable_interatomic_potential=True
    )
    assert superstep_task_count(mlip_cfg) == 3
