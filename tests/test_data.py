"""Dataset-layer tests: pickle roundtrip, splitting, raw ingestion, PBC."""

import numpy as np
import pytest

from hydragnn_tpu.data.graph import GraphSample
from hydragnn_tpu.data.loader import GraphLoader, split_dataset
from hydragnn_tpu.data.pickledataset import SimplePickleDataset, SimplePickleWriter
from hydragnn_tpu.data.raw import minmax_normalize, read_lsms_directory, process_raw_samples
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.ops.neighbors import radius_graph_pbc


def _samples(n=20, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(rng.integers(2, 6))
        out.append(
            GraphSample(
                x=np.full((k, 1), float(i % 3), dtype=np.float32),
                pos=rng.uniform(0, 2, (k, 3)).astype(np.float32),
                edge_index=np.array([[0], [1]]),
                y_graph=np.array([float(i)], dtype=np.float32),
            )
        )
    return out


def test_pickle_roundtrip(tmp_path):
    samples = _samples(12)
    SimplePickleWriter(samples, str(tmp_path), attrs={"pna_deg": [1, 2, 3]})
    ds = SimplePickleDataset(str(tmp_path))
    assert len(ds) == 12
    assert ds.attrs["pna_deg"] == [1, 2, 3]
    np.testing.assert_allclose(ds[3].y_graph, samples[3].y_graph)
    np.testing.assert_allclose(ds[-1].x, samples[-1].x)


def test_pickle_offset_writing(tmp_path):
    samples = _samples(10)
    SimplePickleWriter(samples[:5], str(tmp_path), total=10, write_meta=True)
    SimplePickleWriter(
        samples[5:], str(tmp_path), offset=5, total=10, write_meta=False
    )
    ds = SimplePickleDataset(str(tmp_path))
    assert len(ds) == 10
    np.testing.assert_allclose(ds[7].y_graph, samples[7].y_graph)


def test_split_fractions():
    train, val, test = split_dataset(_samples(100), 0.7, seed=1)
    assert len(train) == 70
    assert len(val) == 15
    assert len(test) == 15


def test_split_stratified_covers_compositions():
    samples = _samples(60)
    # add a singleton composition
    samples.append(
        GraphSample(
            x=np.full((3, 1), 9.0, dtype=np.float32),
            pos=np.zeros((3, 3), dtype=np.float32),
            edge_index=np.array([[0], [1]]),
            y_graph=np.array([1.0], dtype=np.float32),
        )
    )
    train, val, test = split_dataset(samples, 0.7, stratified=True, seed=1)

    def comps(part):
        return {tuple(np.unique(s.x[:, 0])) for s in part}

    all_comps = comps(samples)
    assert comps(train) == all_comps
    assert comps(val) == all_comps
    assert comps(test) == all_comps


def test_lsms_roundtrip_and_processing(tmp_path):
    path = str(tmp_path / "lsms")
    deterministic_graph_data(path, number_configurations=10, seed=3)
    ds_cfg = {
        "node_features": {"column_index": [0, 6, 7]},
        "graph_features": {"column_index": [0]},
    }
    raw = read_lsms_directory(path, ds_cfg)
    assert len(raw) == 10
    config = {
        "NeuralNetwork": {
            "Architecture": {"radius": 2.0, "max_neighbours": 10},
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_index": [0, 1],
                "type": ["graph", "node"],
            },
        }
    }
    samples = process_raw_samples(raw, config)
    s = samples[0]
    assert s.x.shape[1] == 1
    assert s.y_graph.shape == (1,)
    assert s.y_node.shape == (s.x.shape[0], 1)
    # normalization bounds
    allx = np.concatenate([t.x for t in samples])
    assert allx.min() >= 0.0 and allx.max() <= 1.0


def test_pbc_shifts_consistent_with_unwrapped_positions():
    # An atom outside the cell (frac 1.05): shifts must compensate so the
    # caller's unwrapped positions give the right edge length.
    cell = np.eye(3) * 4.0
    pos = np.array([[4.2, 2.0, 2.0], [0.1, 2.0, 2.0]])  # dist 0.1 via identity
    ei, shifts = radius_graph_pbc(pos, cell, 0.5)
    vec = pos[ei[0]] + shifts - pos[ei[1]]
    lengths = np.linalg.norm(vec, axis=1)
    np.testing.assert_allclose(lengths, 0.1, atol=1e-9)


def test_loader_worst_case_edges():
    # Small-but-dense graph must not overflow the fixed pad spec.
    samples = _samples(8)
    dense = GraphSample(
        x=np.ones((3, 1), dtype=np.float32),
        pos=np.zeros((3, 3), dtype=np.float32),
        edge_index=np.array(
            [[0, 0, 1, 1, 2, 2, 0, 1, 2] * 10, [1, 2, 0, 2, 0, 1, 0, 1, 2] * 10]
        ),
        y_graph=np.array([0.0], dtype=np.float32),
    )
    samples.append(dense)
    loader = GraphLoader(samples, 4, shuffle=True)
    for epoch in range(3):
        loader.set_epoch(epoch)
        for batch in loader:
            pass  # must not raise PadSpec-too-small


def test_loader_oversampling_num_samples():
    """num_samples resamples the epoch to a fixed size (reference
    oversampling RandomSampler, load_data.py:240-250), with replacement
    when the dataset is smaller than the target."""
    from hydragnn_tpu.data.loader import GraphLoader

    import pytest

    samples = _samples(5)
    with pytest.raises(ValueError, match="shuffle"):
        GraphLoader(samples, 4, num_samples=12, seed=1)
    loader = GraphLoader(samples, 4, shuffle=True, num_samples=12, seed=1)
    assert len(loader) == 3
    batches = list(loader)
    total = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    assert total == 12
    # deterministic per epoch, different across epochs
    again = list(loader)
    a0 = np.asarray(batches[0].x)
    b0 = np.asarray(again[0].x)
    np.testing.assert_allclose(a0, b0)
    loader.set_epoch(1)
    c0 = np.asarray(list(loader)[0].x)
    assert not np.allclose(a0, c0)


def test_select_input_features():
    """Variables_of_interest.input_node_features must be applied to
    directly-passed datasets (reference update_atom_features,
    graph_samples_checks_and_updates.py:648-659); regression: PAINN on
    wider-than-selected x crashed with a broadcast mismatch."""
    from hydragnn_tpu.data.graph import GraphSample, select_input_features

    s = [
        GraphSample(
            x=np.arange(12, dtype=np.float32).reshape(3, 4),
            edge_index=np.array([[0, 1], [1, 0]]),
        )
    ]
    # no-op when selection covers all columns in order
    assert select_input_features(s, [0, 1, 2, 3])[0] is s[0]
    out = select_input_features(s, [1, 3])
    np.testing.assert_allclose(
        out[0].x, np.array([[1, 3], [5, 7], [9, 11]], np.float32)
    )
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        select_input_features(s, [0, 4])


def test_run_training_applies_input_feature_selection():
    """End-to-end: a dataset whose x carries extra columns trains with a
    config selecting a subset (one-hot species + trailing raw-Z column,
    the examples/common/molecules.py 'onehot' layout)."""
    import jax

    from hydragnn_tpu.runner import run_training

    rng = np.random.default_rng(0)
    samples = []
    for _ in range(24):
        n = 6
        x = np.zeros((n, 3), np.float32)
        x[np.arange(n), rng.integers(0, 2, n)] = 1.0
        x[:, 2] = rng.integers(1, 17, n)  # raw Z column, excluded below
        pos = rng.uniform(0, 3, (n, 3)).astype(np.float32)
        ei = np.stack(
            [np.repeat(np.arange(n), n - 1),
             np.concatenate([np.delete(np.arange(n), i) for i in range(n)])]
        )
        samples.append(
            GraphSample(
                x=x, pos=pos, edge_index=ei,
                y_graph=np.array([x[:, 0].sum()], np.float32),
            )
        )
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "PAINN",
                "radius": 4.0, "max_neighbours": 8, "num_radial": 6,
                "hidden_dim": 8, "num_conv_layers": 2,
                "graph_pooling": "add",
                "output_heads": {"graph": {
                    "num_sharedlayers": 1, "dim_sharedlayers": 8,
                    "num_headlayers": 1, "dim_headlayers": [8],
                }},
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0, 1],
                "output_names": ["t"], "output_index": [0],
                "type": ["graph"], "output_dim": [1],
            },
            "Training": {
                "batch_size": 8, "num_epoch": 2, "perc_train": 0.8,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.002},
            },
        },
    }
    tr, va, te = samples[:16], samples[16:20], samples[20:]
    state, model, cfg, hist, _ = run_training(
        config, datasets=(tr, va, te), seed=0
    )
    assert cfg.input_dim == 2
    assert np.isfinite(hist.train_loss[-1])


def test_mixed_dataset_uniform_batch_structure():
    """A dataset mixing periodic (cell/edge_shifts) and gas-phase
    samples must yield ONE pytree structure across batches: presence
    differences recompile under jit and hard-fail dp device stacking
    (regression: multidataset GFM example crashed in stack_batches
    once a batch happened to contain no crystal sample)."""
    import jax

    rng = np.random.default_rng(0)
    mols, crys = [], []
    for _ in range(4):
        n = 5
        pos = rng.uniform(0, 3, (n, 3)).astype(np.float32)
        ei = np.stack([np.arange(n), np.roll(np.arange(n), 1)])
        mols.append(
            GraphSample(
                x=np.ones((n, 1), np.float32), pos=pos, edge_index=ei,
                y_graph=np.zeros(1, np.float32),
            )
        )
        crys.append(
            GraphSample(
                x=np.ones((n, 1), np.float32), pos=pos, edge_index=ei,
                edge_shifts=np.zeros((n, 3), np.float32),
                cell=np.eye(3, dtype=np.float32),
                y_graph=np.zeros(1, np.float32),
            )
        )
    loader = GraphLoader(mols + crys, 4)  # batch 1 all-molecule
    batches = list(loader)
    assert len(batches) == 2
    t0 = jax.tree_util.tree_structure(batches[0])
    t1 = jax.tree_util.tree_structure(batches[1])
    assert t0 == t1
    assert batches[0].edge_shifts is not None  # zero-filled, present
    assert batches[0].cell is not None
    np.testing.assert_allclose(np.asarray(batches[0].edge_shifts), 0.0)


def test_loader_auto_pad_selects_ladder_when_uniform():
    """fixed_pad='auto': near-uniform sizes -> few bucket specs -> the
    loader takes the per-batch ladder; the spec simulation matches the
    specs the real iteration produces."""
    samples = _samples(32, seed=4)
    loader = GraphLoader(samples, 8, shuffle=True, fixed_pad="auto")
    assert loader.fixed_pad is False
    keys = loader.planned_spec_keys(epochs=2)
    assert 1 <= len(keys) <= 6
    seen = set()
    for epoch in range(2):
        loader.set_epoch(epoch)
        for b in loader:
            seen.add(
                (b.x.shape[0], b.senders.shape[0], b.graph_mask.shape[0])
            )
    assert seen == keys


def test_loader_auto_pad_falls_back_on_wide_spread(monkeypatch):
    """Wildly heterogeneous sizes blow past the bucket budget -> auto
    resolves to the single worst-case shape."""
    rng = np.random.default_rng(0)
    samples = []
    for i in range(64):
        k = int(rng.integers(2, 200))
        e = int(rng.integers(1, 4 * k))
        samples.append(
            GraphSample(
                x=np.ones((k, 1), dtype=np.float32),
                pos=rng.uniform(0, 2, (k, 3)).astype(np.float32),
                edge_index=rng.integers(0, k, (2, e)),
                y_graph=np.array([0.0], dtype=np.float32),
            )
        )
    monkeypatch.setenv("HYDRAGNN_TPU_MAX_PAD_BUCKETS", "3")
    loader = GraphLoader(samples, 4, shuffle=True, fixed_pad="auto")
    assert loader.fixed_pad is True
    assert loader.pad_spec is not None


def test_loader_cache_batches_replays_eval_epochs():
    """Fixed-order loaders replay identical collated batches from the
    cache; shuffled loaders ignore the flag; a partially-consumed
    epoch must not poison the cache."""
    samples = _samples(20, seed=6)
    loader = GraphLoader(samples, 4, cache_batches=True)

    partial = iter(loader)
    next(partial)
    del partial  # consumer broke early -> no cache stored
    assert loader._batch_cache is None

    first = list(loader)
    assert loader._batch_cache is not None
    second = list(loader)
    third = list(loader)
    for a, b, c in zip(first, second, third):
        np.testing.assert_array_equal(np.asarray(a.x), b.x)
        assert b.x is c.x  # replayed object, not re-collated
        # cache holds HOST copies (never pins accelerator memory)
        assert isinstance(b.x, np.ndarray)
    assert len(first) == len(second) == 5

    shuffled = GraphLoader(
        samples, 4, shuffle=True, cache_batches=True
    )
    assert not shuffled.cache_batches


def test_loader_materializes_generators():
    """A generator (len-less one-shot iterable) must be materialized by
    GraphLoader and shard_dataset_for_process instead of failing later
    at len()/indexing (round-4 advisor)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.runtime import shard_dataset_for_process

    base = _samples(8)
    loader = GraphLoader((s for s in base), 4)
    assert len(loader) == 2
    assert sum(int(b.graph_mask.sum()) for b in loader) == 8
    sharded = shard_dataset_for_process(s for s in base)
    assert len(sharded) == 8
