"""Worker for the 2-process multi-host test (tests/test_multihost.py).

Each coordinated process runs the SAME run_training call (SPMD); the
rendezvous comes from HYDRAGNN_TPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID
(hydragnn_tpu.parallel.runtime.maybe_initialize_distributed) with 4
virtual CPU devices per process — the TPU analog of the reference's
2-rank MPI CI job (.github/workflows/CI.yml:62-67).

Writes {out}/hist_{pid}.json with the loss history and exits 0 on
success.
"""

import json
import os
import sys


def main():
    out_dir = sys.argv[1]
    # Rendezvous BEFORE any jax backend use (env set by the parent).
    from hydragnn_tpu.parallel import runtime

    runtime.maybe_initialize_distributed()
    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np

    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.utils.checkpoint import checkpoint_exists

    def _make(n, seed, scale=1.7):
        r = np.random.default_rng(seed)  # same dataset on every process
        out = []
        for _ in range(n):
            k = int(r.integers(5, 10))
            pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
            x = r.normal(size=(k, 1)).astype(np.float32)
            out.append(
                GraphSample(
                    x=x,
                    pos=pos,
                    edge_index=radius_graph(pos, 2.5, max_neighbours=12),
                    y_graph=np.array([scale * float(x.mean())], np.float32),
                )
            )
        return out

    multibranch = (
        json.loads(
            os.environ.get("HYDRAGNN_TEST_PARALLELISM", "{}")
        ).get("scheme")
        == "multibranch"
    )
    if multibranch:
        datasets = [
            split_dataset(_make(96, seed=bi, scale=1.0 + bi), 0.75)
            for bi in range(2)
        ]
    else:
        tr_s, va_s, te_s = split_dataset(_make(128, seed=0), 0.75)
        # odd test-set size: one sample is NOT divisible across the 2
        # processes — exercises run_prediction's leftover merge
        te_s = te_s + _make(1, seed=99)
        datasets = (tr_s, va_s, te_s)

    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "max_neighbours": 12,
                "num_gaussians": 8,
                "num_filters": 16,
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": 4,
                "num_epoch": 3,
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
                # Parallelism override from the test harness (e.g. an
                # fsdp axis spanning processes); default pure-dp.
                "Parallelism": json.loads(
                    os.environ.get(
                        "HYDRAGNN_TEST_PARALLELISM",
                        '{"scheme": "dp", "data": 8}',
                    )
                ),
            },
        }
    }

    if multibranch:
        config["NeuralNetwork"]["Architecture"]["output_heads"] = {
            "graph": [
                {
                    "type": f"branch-{i}",
                    "architecture": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    },
                }
                for i in range(2)
            ]
        }
    state, model, cfg, hist, out_config = run_training(
        config, datasets=datasets, seed=0
    )
    pid = jax.process_index()
    log_name = out_config["_log_name"]

    # Multi-host per-sample collection (reference gather_tensor_ranks):
    # every process must get the FULL true/pred set from run_prediction.
    pred = {}
    if not multibranch:
        from hydragnn_tpu.runner import run_prediction

        err, per_task, trues, preds = run_prediction(
            out_config, datasets=datasets, state=state, model=model,
            cfg=cfg,
        )
        pred = {
            "pred_error": float(err),
            "pred_n_samples": int(trues[0].shape[0]),
            "pred_n_pred": int(preds[0].shape[0]),
        }

        # Same prediction against LAZY container datasets (mmap-backed
        # BinDataset, odd test size): the leftover-merge path must
        # index, not slice, lazy datasets (round-3 advisor finding) and
        # keep them unmaterialized end to end.
        from hydragnn_tpu.data.binformat import (
            BinDataset,
            write_bin_dataset,
        )

        paths = {}
        for split, ds in zip(("tr", "va", "te"), datasets):
            paths[split] = os.path.join(out_dir, f"{split}_{pid}.hgb")
            write_bin_dataset(paths[split], list(ds))
        lazy = tuple(BinDataset(paths[k]) for k in ("tr", "va", "te"))
        err2, _, trues2, preds2 = run_prediction(
            out_config, datasets=lazy, state=state, model=model, cfg=cfg,
        )
        pred["pred_lazy_n"] = int(trues2[0].shape[0])
        pred["pred_lazy_error"] = float(err2)

    with open(os.path.join(out_dir, f"hist_{pid}.json"), "w") as f:
        json.dump(
            {
                "train": [float(x) for x in hist.train_loss],
                "val": [float(x) for x in hist.val_loss],
                "ckpt_exists": bool(checkpoint_exists(log_name)),
                "process_index": pid,
                **pred,
            },
            f,
        )
    print(f"worker {pid}: OK train={hist.train_loss}")


if __name__ == "__main__":
    main()
