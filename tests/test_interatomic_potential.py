"""MLIP energy+force training path (reference
tests/test_interatomic_potential.py:23-87): mock molecular data with
energy/forces targets, energy_force_loss evaluation, and a short training
run that must reduce the weighted loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.train.mlip import energy_and_forces, energy_force_loss


def mock_molecular_samples(n_graphs=4, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_graphs):
        n = int(rng.integers(6, 11))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        ei = radius_graph(pos, 2.5, max_neighbours=16)
        out.append(
            GraphSample(
                x=rng.integers(1, 10, (n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei,
                energy=float(rng.normal()),
                forces=rng.normal(size=(n, 3)).astype(np.float32) * 0.1,
            )
        )
    return out


def _mlip_config(head_type="node", pooling="mean", mpnn_type="SchNet"):
    head = (
        HeadSpec("energy", "node", 1)
        if head_type == "node"
        else HeadSpec("energy", "graph", 1)
    )
    return ModelConfig(
        mpnn_type=mpnn_type,
        input_dim=1,
        hidden_dim=16,
        num_conv_layers=2,
        heads=(head,),
        graph_branches=(BranchSpec(),),
        node_branches=(BranchSpec(),),
        task_weights=(1.0,),
        radius=2.5,
        num_gaussians=8,
        num_filters=16,
        num_radial=6,
        graph_pooling=pooling,
        enable_interatomic_potential=True,
        energy_weight=1.0,
        energy_peratom_weight=0.5,
        force_weight=10.0,
    )


@pytest.mark.parametrize("head_type", ["node", "graph"])
@pytest.mark.parametrize("mpnn_type", ["SchNet", "EGNN"])
def test_energy_force_loss_runs(head_type, mpnn_type):
    pooling = "add" if head_type == "graph" else "mean"
    cfg = _mlip_config(head_type, pooling, mpnn_type)
    model = create_model(cfg)
    batch = collate(mock_molecular_samples())
    params, bs = init_params(model, batch)
    variables = {"params": params, "batch_stats": bs}

    tot, tasks, _ = jax.jit(
        lambda v, b: energy_force_loss(model, v, b, cfg)
    )(variables, batch)
    assert np.isfinite(float(tot))
    assert tasks.shape == (3,)
    assert np.all(np.isfinite(np.asarray(tasks)))


def test_forces_are_negative_energy_gradient():
    cfg = _mlip_config("node")
    model = create_model(cfg)
    batch = collate(mock_molecular_samples(n_graphs=2, seed=3))
    params, bs = init_params(model, batch)
    variables = {"params": params, "batch_stats": bs}

    ge, forces, _ = energy_and_forces(model, variables, batch, cfg)
    # Finite difference check on one coordinate of one real atom.
    eps = 1e-3
    i, d = 2, 1

    def total_e(pos):
        g, _, _ = energy_and_forces(
            model, variables, batch.replace(pos=pos), cfg
        )
        return float(jnp.sum(g))

    pos = np.asarray(batch.pos).copy()
    pos_p = pos.copy()
    pos_p[i, d] += eps
    pos_m = pos.copy()
    pos_m[i, d] -= eps
    fd = -(total_e(jnp.asarray(pos_p)) - total_e(jnp.asarray(pos_m))) / (
        2 * eps
    )
    assert abs(fd - float(forces[i, d])) < 5e-2 * max(1.0, abs(fd))
    # Forces on padding atoms must be exactly zero.
    nm = np.asarray(batch.node_mask)
    assert np.all(np.asarray(forces)[~nm] == 0.0)


def test_energy_forces_jax_graph_matches_host_graph():
    """The MD rollout engine's correctness anchor (ISSUE 15):
    ``energy_and_forces`` under a ``radius_graph_jax``-built masked
    edge set equals the same state scored on the host-built graph.
    With the host edges pre-sorted into the jit builder's
    receiver-major slot order the two batches are element-identical on
    the real slots, and energies/forces are BITWISE equal; an
    arbitrary host ordering only permutes the segment-sum reduction
    and must stay ulp-bounded."""
    import dataclasses

    from hydragnn_tpu.data.graph import PadSpec
    from hydragnn_tpu.ops.neighbors import radius_graph_jax

    rng = np.random.default_rng(11)
    n = 9
    pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
    sample = GraphSample(
        x=np.ones((n, 1), np.float32),
        pos=pos,
        # No max_neighbours cap: the jit builder never caps, and the
        # parity contract is over the FULL radius graph.
        edge_index=radius_graph(pos.astype(np.float64), 2.5),
    )
    cfg = _mlip_config("node")
    model = create_model(cfg)
    variables = None

    def scored(batch):
        nonlocal variables
        if variables is None:
            params, bs = init_params(model, batch)
            variables = {"params": params, "batch_stats": bs}
        ge, forces, _ = jax.jit(
            lambda v, b: energy_and_forces(model, v, b, cfg)
        )(variables, batch)
        return np.asarray(ge), np.asarray(forces)

    # Host batch in receiver-major order, padded so the padding-node
    # slot (n == N-1) matches the jit builder's pad convention.
    ei = sample.edge_index
    order = np.lexsort((ei[0], ei[1]))
    cap = 128
    pad = PadSpec(num_nodes=n + 1, num_edges=cap, num_graphs=2)
    batch_host = collate(
        [dataclasses.replace(sample, edge_index=ei[:, order])], pad
    )
    snd, rcv, em, ovf = radius_graph_jax(
        batch_host.pos, 2.5, batch_host.node_graph_idx,
        batch_host.node_mask, cap,
    )
    assert int(ovf) == 0
    batch_jax = batch_host.replace(
        senders=snd, receivers=rcv, edge_mask=em
    )
    # Identical edge ordering on the real slots.
    e_real = ei.shape[1]
    assert np.array_equal(
        np.asarray(batch_host.senders)[:e_real],
        np.asarray(snd)[:e_real],
    )
    ge_h, f_h = scored(batch_host)
    ge_j, f_j = scored(batch_jax)
    assert np.array_equal(ge_h, ge_j)
    assert np.array_equal(f_h, f_j)

    # Arbitrary (cell-list) host ordering: same physics, ulp-bounded.
    batch_unsorted = collate([sample], pad)
    ge_u, f_u = scored(batch_unsorted)
    np.testing.assert_allclose(ge_u, ge_j, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(f_u, f_j, rtol=1e-4, atol=1e-5)


def test_graph_head_requires_sum_pooling():
    cfg = _mlip_config("graph", pooling="mean")
    model = create_model(cfg)
    batch = collate(mock_molecular_samples(n_graphs=2))
    params, bs = init_params(model, batch)
    variables = {"params": params, "batch_stats": bs}
    with pytest.raises(ValueError, match="sum pooling"):
        energy_force_loss(model, variables, batch, cfg)


def test_mlip_training_reduces_loss():
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    cfg = _mlip_config("node")
    model = create_model(cfg)
    samples = mock_molecular_samples(n_graphs=8, seed=1)
    batch = collate(samples)
    params, bs = init_params(model, batch)
    tx = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 3e-3}}
    )
    state = create_train_state(params, tx, bs)
    step = make_train_step(model, tx, cfg, compute_grad_energy=True)

    losses = []
    for _ in range(30):
        state, tot, tasks = step(state, batch)
        losses.append(float(tot))
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]
