"""MACE stack and its E(3) math core.

Gates (SURVEY.md §7: "Treat as its own milestone with equivariance
property tests as the gate"):
- real Wigner 3j tensors are rotation invariant (generation asserts it;
  re-checked here through public API),
- spherical harmonics have component normalization and transform by the
  fitted Wigner D matrices,
- SymmetricContraction output is equivariant,
- full MACE model: scalar outputs rotation/translation invariant,
  forces equivariant,
- short training run reduces loss.
"""

import itertools

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.e3 import (
    real_wigner_3j,
    sh_basis,
    sh_dim,
    wigner_d_from_sh,
)
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.ops.symmetric_contraction import (
    SymmetricContraction,
    u_matrix_real,
)


def _rotation(seed=5):
    q, _ = np.linalg.qr(np.random.default_rng(seed).normal(size=(3, 3)))
    if np.linalg.det(q) < 0:
        q[:, 0] = -q[:, 0]
    return q


def test_sh_component_normalization():
    v = np.random.default_rng(0).normal(size=(16, 3))
    y = np.asarray(sh_basis(jnp.asarray(v), 3))
    for l in range(4):
        n = (y[:, l * l : (l + 1) ** 2] ** 2).sum(axis=1)
        np.testing.assert_allclose(n, 2 * l + 1, rtol=1e-5)


def test_sh_transforms_by_wigner_d():
    rot = _rotation()
    v = np.random.default_rng(1).normal(size=(10, 3))
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    for l in range(1, 4):
        d = wigner_d_from_sh(l, rot)
        y = np.asarray(sh_basis(jnp.asarray(v), l))[:, l * l :]
        yr = np.asarray(sh_basis(jnp.asarray(v @ rot.T), l))[:, l * l :]
        np.testing.assert_allclose(y @ d.T, yr, atol=1e-5)
        # D is orthogonal (real representation)
        np.testing.assert_allclose(d @ d.T, np.eye(2 * l + 1), atol=1e-6)


def test_wigner_3j_invariance():
    rot = _rotation(seed=9)
    for l1, l2, l3 in itertools.product(range(3), repeat=3):
        if not abs(l1 - l2) <= l3 <= l1 + l2:
            continue
        t = real_wigner_3j(l1, l2, l3)
        d1, d2, d3 = (wigner_d_from_sh(l, rot) for l in (l1, l2, l3))
        t2 = np.einsum("au,bv,cw,uvw->abc", d1, d2, d3, t)
        np.testing.assert_allclose(t2, t, atol=1e-5)


def test_u_matrix_shapes_and_symmetry():
    u = u_matrix_real(2, 0, 3)
    assert u.shape[:4] == (1, 9, 9, 9)
    assert u.shape[-1] > 0
    # permutation symmetric over the factor axes
    np.testing.assert_allclose(u, np.transpose(u, (0, 2, 1, 3, 4)), atol=1e-10)
    np.testing.assert_allclose(u, np.transpose(u, (0, 3, 2, 1, 4)), atol=1e-10)


def test_symmetric_contraction_equivariance():
    lmax, Z, C, N = 2, 3, 4, 6
    mod = SymmetricContraction(
        lmax_in=lmax, lmax_out=lmax, correlation=3, num_elements=Z
    )
    rng = np.random.default_rng(0)
    M = sh_dim(lmax)
    x = rng.normal(size=(N, C, M))
    y = np.zeros((N, Z))
    y[np.arange(N), rng.integers(0, Z, N)] = 1.0
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(y))
    rot = _rotation(seed=3)
    D = np.zeros((M, M))
    for l in range(lmax + 1):
        D[l * l : (l + 1) ** 2, l * l : (l + 1) ** 2] = wigner_d_from_sh(
            l, rot
        )
    out = np.asarray(mod.apply(params, jnp.asarray(x), jnp.asarray(y)))
    out_rot = np.asarray(
        mod.apply(
            params, jnp.asarray(np.einsum("ij,bcj->bci", D, x)), jnp.asarray(y)
        )
    )
    np.testing.assert_allclose(
        np.einsum("ij,bcj->bci", D, out), out_rot, atol=1e-5
    )


# ----------------------------------------------------------------------


def _samples(rot=None, shift=None, n_graphs=2, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n_graphs):
        n = int(r.integers(5, 9))
        pos = r.uniform(0, 3.0, (n, 3)).astype(np.float32)
        if rot is not None:
            pos = (pos @ rot.T).astype(np.float32)
        if shift is not None:
            pos = pos + np.asarray(shift, np.float32)
        ei = radius_graph(pos, 2.5, max_neighbours=16)
        out.append(
            GraphSample(
                x=r.integers(1, 9, (n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei,
                y_graph=np.zeros(1, np.float32),
                y_node=np.zeros((n, 1), np.float32),
                energy=0.0,
                forces=np.zeros((n, 3), np.float32),
            )
        )
    return out


def _mace_cfg(heads="both", **kw):
    if heads == "both":
        hs = (HeadSpec("e", "graph", 1), HeadSpec("n", "node", 1))
        tw = (0.5, 0.5)
    else:
        hs = (HeadSpec("e", heads, 1),)
        tw = (1.0,)
    defaults = dict(
        mpnn_type="MACE",
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=hs,
        graph_branches=(BranchSpec(),),
        node_branches=(BranchSpec(),),
        task_weights=tw,
        radius=2.5,
        num_radial=6,
        max_ell=2,
        node_max_ell=2,
        correlation=2,
        avg_num_neighbors=4.0,
    )
    defaults.update(kw)
    return ModelConfig(**defaults)


def test_mace_rotation_translation_invariance():
    cfg = _mace_cfg()
    model = create_model(cfg)
    rot = _rotation(seed=21)
    base = collate(_samples())
    rotated = collate(_samples(rot=rot))
    shifted = collate(_samples(shift=[4.0, -2.0, 1.0]))
    params, bs = init_params(model, base)
    fwd = jax.jit(
        lambda p, b: model.apply(
            {"params": p, "batch_stats": bs}, b, train=False
        )
    )
    out0 = fwd(params, base)
    for other in (fwd(params, rotated), fwd(params, shifted)):
        for h0, h1 in zip(out0, other):
            np.testing.assert_allclose(
                np.asarray(h0), np.asarray(h1), rtol=1e-3, atol=1e-5
            )


@pytest.mark.parametrize("correlation", [1, 2, 3])
def test_mace_force_equivariance(correlation):
    from hydragnn_tpu.train.mlip import energy_and_forces

    cfg = _mace_cfg(
        heads="node",
        correlation=correlation,
        enable_interatomic_potential=True,
        force_weight=1.0,
    )
    model = create_model(cfg)
    rot = _rotation(seed=31)
    base = collate(_samples(n_graphs=1, seed=4))
    rotated = collate(_samples(rot=rot, n_graphs=1, seed=4))
    params, bs = init_params(model, base)
    variables = {"params": params, "batch_stats": bs}
    e0, f0, _ = energy_and_forces(model, variables, base, cfg)
    e1, f1, _ = energy_and_forces(model, variables, rotated, cfg)
    np.testing.assert_allclose(
        np.asarray(e0), np.asarray(e1), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(f0) @ rot.T, np.asarray(f1), rtol=1e-3, atol=1e-4
    )


def test_mace_training_reduces_loss():
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    cfg = _mace_cfg()
    model = create_model(cfg)
    r = np.random.default_rng(0)
    samples = []
    for _ in range(8):
        n = int(r.integers(5, 9))
        pos = r.uniform(0, 3.0, (n, 3)).astype(np.float32)
        x = r.integers(1, 5, (n, 1)).astype(np.float32)
        samples.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5, max_neighbours=16),
                y_graph=np.array([x.sum() / 10.0], np.float32),
                y_node=(x / 4.0).astype(np.float32),
            )
        )
    batch = collate(samples)
    params, bs = init_params(model, batch)
    tx = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    )
    state = create_train_state(params, tx, bs)
    step = make_train_step(model, tx, cfg)
    losses = []
    for _ in range(40):
        state, tot, _ = step(state, batch)
        losses.append(float(tot))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


def test_channelwise_tp_aggregate_matches_edge_space():
    """Node-space accumulation (channelwise_tp_aggregate) must equal
    segment_sum(channelwise_tp(...)) — same math, different traffic."""
    import jax.numpy as jnp

    from hydragnn_tpu.models.mace import (
        channelwise_tp,
        channelwise_tp_aggregate,
        tp_paths,
    )
    from hydragnn_tpu.ops import segment_sum

    rng = np.random.default_rng(0)
    E, C, N, lmax = 96, 4, 11, 2
    paths = tp_paths(lmax, lmax, lmax)
    x = jnp.asarray(rng.normal(size=(E, C, 9)), jnp.float32)
    sh = jnp.asarray(rng.normal(size=(E, 9)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(E, len(paths), C)), jnp.float32)
    rcv = jnp.asarray(rng.integers(0, N, E), jnp.int32)
    mask = jnp.asarray(rng.random(E) > 0.15)

    import types

    edge_space = segment_sum(
        channelwise_tp(x, sh, w, paths, lmax).reshape(E, -1),
        rcv,
        N,
        mask=mask,
    ).reshape(N, C, -1)
    batch = types.SimpleNamespace(
        receivers=rcv, num_nodes=N, edge_mask=mask, seg_window=None
    )
    fused = channelwise_tp_aggregate(x, sh, w, paths, lmax, batch)
    np.testing.assert_allclose(
        np.asarray(edge_space), np.asarray(fused), rtol=2e-5, atol=2e-5
    )
