"""Online serving subsystem (hydragnn_tpu/serve/, docs/SERVING.md):
the PackPlanner split under the epoch packer (bit-identity with the
former inline algorithm), deadline-driven dynamic batching, the
admission gate, the AOT-warmed engine (bitwise parity with
run_prediction at the matched shape, warm-up suppression pinned
through the compile observer), and the Serving config surface.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample, PackSpec


def _mols(n, lo, hi, seed=0, with_node_targets=False):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(rng.integers(lo, hi))
        pos = rng.uniform(0, 3.0, (k, 3)).astype(np.float32)
        ei = np.stack(
            [np.repeat(np.arange(k), 2), rng.integers(0, k, 2 * k)]
        )
        s = GraphSample(
            x=rng.normal(size=(k, 1)).astype(np.float32),
            pos=pos,
            edge_index=ei.astype(np.int64),
            y_graph=np.array([float(pos.sum())], np.float32),
        )
        if with_node_targets:
            s.y_node = rng.normal(size=(k, 1)).astype(np.float32)
        out.append(s)
    return out


# ----------------------------------------------------------------------
# The enabling refactor: PackPlanner under pack_epoch_ffd must be
# bit-identical to the former inline algorithm.
# ----------------------------------------------------------------------


def _reference_pack_epoch_ffd(
    order, node_sizes, edge_sizes, budgets, open_window=256
):
    """The PRE-REFACTOR pack_epoch_ffd, inlined verbatim — the frozen
    reference the PackPlanner-backed implementation is pinned
    against."""
    budgets = sorted(
        budgets, key=lambda b: (b.num_nodes, b.num_edges), reverse=True
    )
    big = budgets[0]
    order = np.asarray(order, dtype=np.int64)
    n_of = node_sizes[order]
    by_size = np.argsort(-n_of, kind="stable")
    bins, closed = [], []
    for pos in by_size:
        i = int(order[pos])
        n, e = int(node_sizes[i]), int(edge_sizes[i])
        placed = False
        for b in bins:
            if b[0] >= n and b[1] >= e and b[2] >= 1:
                b[0] -= n
                b[1] -= e
                b[2] -= 1
                b[3].append(int(pos))
                placed = True
                break
        if not placed:
            if not big.fits(n, e, 1):
                raise ValueError("oversize")
            bins.append(
                [
                    big.capacity_nodes - n,
                    big.capacity_edges - e,
                    big.capacity_graphs - 1,
                    [int(pos)],
                ]
            )
            if len(bins) > max(int(open_window), 1):
                full = min(range(len(bins)), key=lambda k: bins[k][0])
                closed.append(bins.pop(full))
    out = []
    for b in sorted(closed + bins, key=lambda b: min(b[3])):
        members = sorted(b[3])
        idx = order[members]
        tot_n = int(node_sizes[idx].sum())
        tot_e = int(edge_sizes[idx].sum())
        spec = big
        for cand in budgets:
            if cand.fits(tot_n, tot_e, len(idx)):
                spec = cand
        out.append((idx, spec))
    return out


@pytest.mark.parametrize("open_window", [2, 3, 256])
def test_pack_epoch_ffd_bit_identical_through_planner(open_window):
    """The queue-feedable PackPlanner reproduces the former inline
    packer EXACTLY — including the small-open-window freeze regime,
    where the fullest-bin pick depends on post-placement node rooms."""
    from hydragnn_tpu.data.padschedule import (
        fit_pack_budgets,
        pack_epoch_ffd,
    )

    rng = np.random.default_rng(7)
    for trial in range(4):
        nodes = rng.integers(4, 30, 80).astype(np.int64)
        edges = (nodes * 2 + rng.integers(0, 9, 80)).astype(np.int64)
        budgets = fit_pack_budgets(nodes, edges, 8, seed=trial)
        order = rng.permutation(80).astype(np.int64)
        got = pack_epoch_ffd(order, nodes, edges, budgets, open_window)
        ref = _reference_pack_epoch_ffd(
            order, nodes, edges, budgets, open_window
        )
        assert len(got) == len(ref)
        for (gi, gs), (ri, rs) in zip(got, ref):
            assert np.array_equal(gi, ri)
            assert gs == rs


def test_packed_loader_skip_to_suffix_after_refactor():
    """GraphLoader's packed epoch delivery and its skip_to cursor
    contract are unchanged through the planner split: a fast-forwarded
    iteration is exactly the uninterrupted epoch's suffix."""
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(40, 5, 14, seed=2)
    ld = GraphLoader(samples, 8, shuffle=True, seed=1, packing=True)
    ld.set_epoch(3)
    full = [np.asarray(b.x) for b in ld]
    ld.set_epoch(3)
    ld.skip_to(2)
    suffix = [np.asarray(b.x) for b in ld]
    assert len(suffix) == len(full) - 2
    for a, b in zip(full[2:], suffix):
        assert np.array_equal(a, b)


def test_epoch_plan_deterministic_after_refactor():
    """Two identically-constructed loaders plan identically (the
    determinism the dp/pipeline feeds build on — padschedule's
    epoch_plan contract, re-pinned across the planner split)."""
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(30, 5, 12, seed=4)
    a = GraphLoader(samples, 6, shuffle=True, seed=9, packing=True)
    b = GraphLoader(samples, 6, shuffle=True, seed=9, packing=True)
    for ep in (0, 1):
        pa = list(a.epoch_plan(ep))
        pb = list(b.epoch_plan(ep))
        assert len(pa) == len(pb)
        for (ia, sa), (ib, sb) in zip(pa, pb):
            assert np.array_equal(ia, ib) and sa == sb


# ----------------------------------------------------------------------
# DynamicBatcher: dispatch triggers under a fake clock.
# ----------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _budget(n=64, e=128, g=5):
    return PackSpec(num_nodes=n, num_edges=e, num_graphs=g)


def test_batcher_full_bin_dispatches_immediately():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    clock = _FakeClock()
    bat = DynamicBatcher(
        [_budget(g=3)], deadline_ms=1e6, clock=clock
    )  # capacity 2 graphs per bin
    s = _mols(4, 5, 6, seed=0)
    bat.submit(s[0])
    bat.submit(s[1])
    reason, b = bat.next_bin(timeout=0)
    assert reason == "full" and len(b.tags) == 2
    assert bat.next_bin(timeout=0) is None  # nothing else ready


def test_batcher_deadline_dispatches_partial_bin():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    clock = _FakeClock()
    bat = DynamicBatcher([_budget()], deadline_ms=20.0, clock=clock)
    s = _mols(1, 5, 6, seed=1)[0]
    req = bat.submit(s)
    assert bat.next_bin(timeout=0) is None  # deadline not reached
    clock.t = 0.021
    reason, b = bat.next_bin(timeout=0)
    assert reason == "deadline"
    assert bat.bin_requests(b) == [req]


def test_batcher_capacity_pressure_freezes_fullest():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    clock = _FakeClock()
    # tiny node capacity: each graph of ~8 nodes fills most of a bin,
    # so distinct bins open per request
    bat = DynamicBatcher(
        [_budget(n=16, e=64, g=5)],
        deadline_ms=1e6,
        max_open_bins=1,
        clock=clock,
    )
    s = _mols(3, 8, 9, seed=2)
    bat.submit(s[0])
    bat.submit(s[1])  # second bin opens -> pressure freezes one
    reason, b = bat.next_bin(timeout=0)
    assert reason == "pressure" and len(b.tags) == 1


def test_batcher_flush_on_close_preserves_arrival_order():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    clock = _FakeClock()
    bat = DynamicBatcher([_budget(g=9)], deadline_ms=1e6, clock=clock)
    s = _mols(3, 5, 6, seed=3)
    reqs = [bat.submit(x) for x in s]
    bat.close()
    reason, b = bat.next_bin(timeout=0)
    assert reason == "flush"
    assert bat.bin_requests(b) == reqs  # arrival order
    assert bat.next_bin(timeout=0) is None


def test_batcher_rejects_oversize_request_at_the_door():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    bat = DynamicBatcher([_budget(n=16, e=16, g=3)], deadline_ms=10)
    big = _mols(1, 20, 21, seed=4)[0]
    with pytest.raises(ValueError, match="exceeds the largest"):
        bat.submit(big)


def test_batcher_downshifts_to_smallest_fitting_budget():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    small, big = _budget(n=24, e=48, g=3), _budget(n=96, e=192, g=9)
    bat = DynamicBatcher([big, small], deadline_ms=20.0, clock=_FakeClock())
    s = _mols(1, 5, 6, seed=5)[0]
    bat.submit(s)
    bat.clock.t = 1.0
    _, b = bat.next_bin(timeout=0)
    assert bat.bin_spec(b) == small


# ----------------------------------------------------------------------
# Admission gate.
# ----------------------------------------------------------------------


def test_admission_refuses_nonfinite_and_names_the_leaf():
    from hydragnn_tpu.serve.admission import AdmissionError, admit_state

    good = {"params": {"w": jnp.ones((3, 3)), "b": jnp.zeros(3)}}
    info = admit_state(good)
    assert info["leaves"] == 2

    bad = {
        "params": {
            "w": jnp.ones((3, 3)),
            "b": jnp.array([0.0, np.nan, np.inf]),
        }
    }
    with pytest.raises(AdmissionError) as ei:
        admit_state(bad, source="unit snapshot")
    msg = str(ei.value)
    assert "'b'" in msg and "2/3 non-finite" in msg
    assert "unit snapshot" in msg


def test_checkpoint_writer_gate_shares_the_scan():
    from hydragnn_tpu.utils.checkpoint import (
        _state_is_finite,
        nonfinite_leaves,
    )

    host = {"a": np.ones(4, np.float32), "b": np.array([np.inf])}
    bad = nonfinite_leaves(host)
    assert len(bad) == 1 and bad[0][0] == "['b']"
    assert not _state_is_finite(host)
    assert _state_is_finite({"a": np.ones(4, np.float32)})


# ----------------------------------------------------------------------
# ServingEngine end-to-end.
# ----------------------------------------------------------------------


def _serving_model(samples):
    import optax

    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model, init_params
    from hydragnn_tpu.models.spec import (
        BranchSpec,
        HeadSpec,
        ModelConfig,
    )
    from hydragnn_tpu.train.state import create_train_state

    cfg = ModelConfig(
        mpnn_type="SchNet",
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("e", "graph", 1), HeadSpec("n", "node", 1)),
        graph_branches=(BranchSpec(),),
        node_branches=(
            BranchSpec(
                node_head_type="mlp",
                dim_headlayers=(8, 8),
                num_headlayers=2,
            ),
        ),
        task_weights=(1.0, 1.0),
        radius=3.0,
        num_gaussians=8,
        num_filters=8,
    )
    from hydragnn_tpu.models.create import create_model

    model = create_model(cfg)
    batch0 = next(iter(GraphLoader(samples, 4)))
    params, bs = init_params(model, batch0)
    state = create_train_state(params, optax.adam(1e-3), bs)
    return model, cfg, state


def test_served_outputs_bitwise_equal_run_prediction_matched_shape():
    """THE acceptance invariant: per-graph, mask-stripped served
    outputs are bitwise equal to run_prediction on the same graphs
    when the dispatch shape matches (one budget == the prediction
    loader's fixed batch spec, arrival order)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.serve.batcher import DynamicBatcher
    from hydragnn_tpu.serve.engine import ServingEngine, ServingSettings
    from hydragnn_tpu.train.loop import test as run_test

    samples = _mols(14, 5, 11, seed=0, with_node_targets=True)
    model, cfg, state = _serving_model(samples)
    loader = GraphLoader(samples, 4)
    _, _, _, preds = run_test(model, cfg, state, loader)

    fspec = loader._fixed_batch_spec()
    budget = PackSpec(
        num_nodes=fspec.num_nodes,
        num_edges=fspec.num_edges,
        num_graphs=fspec.num_graphs,
    )
    engine = ServingEngine(
        model,
        cfg,
        state,
        [budget],
        example=samples[0],
        settings=ServingSettings(enabled=True),
    )
    bat = DynamicBatcher([budget], deadline_ms=1e3, max_open_bins=1)
    reqs = [bat.submit(s) for s in samples]
    bat.close()
    engine.process(bat, timeout=0.05)
    g_served = np.stack([np.asarray(r.result[0]) for r in reqs])
    n_served = np.concatenate(
        [np.asarray(r.result[1]) for r in reqs], axis=0
    )
    np.testing.assert_array_equal(g_served, np.asarray(preds[0]))
    np.testing.assert_array_equal(n_served, np.asarray(preds[1]))


def test_engine_fitted_budgets_serve_within_ulp_parity():
    """At fitted (non-matched) budget shapes, pooled graph heads agree
    with the fixed-pad prediction pass to reduction-order ulps (the
    PACKING.md parity contract); node heads stay bit-exact
    (row-aligned compute)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.padschedule import dataset_size_arrays
    from hydragnn_tpu.serve.batcher import DynamicBatcher
    from hydragnn_tpu.serve.engine import (
        ServingEngine,
        ServingSettings,
        fit_serving_budgets,
    )
    from hydragnn_tpu.train.loop import test as run_test

    samples = _mols(20, 5, 11, seed=6, with_node_targets=True)
    model, cfg, state = _serving_model(samples)
    _, _, _, preds = run_test(
        model, cfg, state, GraphLoader(samples, 4)
    )
    ns, es = dataset_size_arrays(samples)
    st = ServingSettings(enabled=True, batch_size=4)
    budgets = fit_serving_budgets(ns, es, st)
    engine = ServingEngine(
        model, cfg, state, budgets, example=samples[0], settings=st
    )
    bat = DynamicBatcher(budgets, deadline_ms=1e3, max_open_bins=2)
    reqs = [bat.submit(s) for s in samples]
    bat.close()
    engine.process(bat, timeout=0.05)
    g_served = np.stack([np.asarray(r.result[0]) for r in reqs])
    n_served = np.concatenate(
        [np.asarray(r.result[1]) for r in reqs], axis=0
    )
    np.testing.assert_allclose(
        g_served, np.asarray(preds[0]), rtol=1e-6, atol=1e-7
    )
    np.testing.assert_array_equal(n_served, np.asarray(preds[1]))


def test_warmup_and_steady_serving_hidden_from_retrace_observer():
    """Satellite regression pin: the engine's warm-up AOT compiles are
    suppressed from the compile observer exactly like StepClock's cost
    capture, and steady-state dispatches only ever call warm
    executables — observer counts stay 0 through BOTH."""
    from hydragnn_tpu.data.padschedule import dataset_size_arrays
    from hydragnn_tpu.serve.batcher import DynamicBatcher
    from hydragnn_tpu.serve.engine import (
        ServingEngine,
        ServingSettings,
        fit_serving_budgets,
    )
    from hydragnn_tpu.utils import telemetry

    samples = _mols(12, 5, 10, seed=8)
    model, cfg, state = _serving_model(samples)
    ns, es = dataset_size_arrays(samples)
    st = ServingSettings(enabled=True, batch_size=4)
    budgets = fit_serving_budgets(ns, es, st)
    obs = telemetry.install_observer(warmup_phase=0)
    try:
        engine = ServingEngine(
            model, cfg, state, budgets, example=samples[0], settings=st
        )
        assert obs.compile_count == 0, (
            "warm-up compiles reached the observer — suppression "
            "regressed"
        )
        bat = DynamicBatcher(budgets, deadline_ms=1e3, max_open_bins=2)
        reqs = [bat.submit(s) for s in samples]
        bat.close()
        engine.process(bat, timeout=0.05)
        assert obs.compile_count == 0
        assert obs.post_warmup == []
        assert all(r.result is not None for r in reqs)
    finally:
        obs.close()


def test_install_executables_validates_budget_coverage():
    """An executable map missing a downshift-target shape must fail at
    install time, not as a KeyError on the first tail bin."""
    from hydragnn_tpu.serve.engine import ServingEngine, ServingSettings

    samples = _mols(6, 5, 9, seed=11)
    model, cfg, state = _serving_model(samples)
    small, big = _budget(n=24, e=48, g=3), _budget(n=96, e=192, g=9)
    engine = ServingEngine(
        model,
        cfg,
        state,
        [big, small],
        example=samples[0],
        settings=ServingSettings(enabled=True),
        warm=False,
    )
    with pytest.raises(ValueError, match="does not cover budget"):
        engine.install_executables(
            {(96, 192, 9): lambda batch: batch}
        )


def test_suppress_compile_events_restores_prior_state():
    from hydragnn_tpu.utils import telemetry

    assert not telemetry._SUPPRESS_COMPILE_EVENTS
    with telemetry.suppress_compile_events():
        assert telemetry._SUPPRESS_COMPILE_EVENTS
        with telemetry.suppress_compile_events():
            assert telemetry._SUPPRESS_COMPILE_EVENTS
        assert telemetry._SUPPRESS_COMPILE_EVENTS  # nesting-safe
    assert not telemetry._SUPPRESS_COMPILE_EVENTS


def test_serve_rows_render_through_graftboard(tmp_path):
    """The telemetry serve/serve_rollup rows round-trip into graftboard
    report's serving section (p50/p99, slot-waste, per-spec dispatch
    breakdown)."""
    import os
    import sys

    from hydragnn_tpu.data.padschedule import dataset_size_arrays
    from hydragnn_tpu.serve.batcher import DynamicBatcher
    from hydragnn_tpu.serve.engine import (
        ServingEngine,
        ServingSettings,
        fit_serving_budgets,
    )
    from hydragnn_tpu.utils import telemetry

    samples = _mols(12, 5, 10, seed=9)
    model, cfg, state = _serving_model(samples)
    ns, es = dataset_size_arrays(samples)
    st = ServingSettings(enabled=True, batch_size=4)
    budgets = fit_serving_budgets(ns, es, st)
    path = str(tmp_path / "telemetry.jsonl")
    stream = telemetry.TelemetryStream(path)
    telemetry.install(stream)
    try:
        engine = ServingEngine(
            model, cfg, state, budgets, example=samples[0], settings=st
        )
        bat = DynamicBatcher(budgets, deadline_ms=1e3, max_open_bins=2)
        for s in samples:
            bat.submit(s)
        bat.close()
        engine.process(bat, timeout=0.05)
        rollup = engine.rollup()
        assert rollup["requests"] == len(samples)
        assert 0.0 <= rollup["slot_waste"] < 1.0
        assert rollup["p99_ms"] >= rollup["p50_ms"]
    finally:
        telemetry.install(None)
        stream.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import graftboard

        rep = graftboard.build_report(path)
    finally:
        sys.path.remove(os.path.join(repo, "tools"))
    ss = rep["serve_summary"]
    assert ss["bins"] == len(engine._records)
    assert ss["rollup"]["requests"] == len(samples)
    rendered = graftboard.render_report(rep)
    assert "-- serving" in rendered
    assert "dispatch reasons" in rendered


# ----------------------------------------------------------------------
# Config surface.
# ----------------------------------------------------------------------


def test_serving_settings_resolution_and_validation():
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.serve.engine import serving_settings

    st = serving_settings({"Serving": True})
    assert st.enabled and st.deadline_ms == 25.0
    st = serving_settings(
        {"Serving": {"enabled": True, "deadline_ms": 5, "batch_size": 16}}
    )
    assert st.deadline_ms == 5.0 and st.batch_size == 16
    assert serving_settings({}).enabled is False

    cfg = {"NeuralNetwork": {}, "Serving": {"deadline_msec": 5}}
    with pytest.raises(ValueError, match="Serving: unknown keys"):
        update_config(cfg)
    update_config({"NeuralNetwork": {}, "Serving": {"deadline_ms": 5}})


def test_serving_keys_in_graftlint_config_vocabulary():
    """graftlint's config-schema rule harvests its accepted-key
    vocabulary from the real readers — the Serving block's keys must
    all be covered (a user config using them lints clean) now that
    serve/engine.serving_settings and update_config read them."""
    import os

    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules import DEFAULT_PATHS
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ctx = collect_files(
        repo, [p for p in DEFAULT_PATHS if os.path.exists(
            os.path.join(repo, p)
        )]
    )
    accepted = harvest_accepted_keys(ctx)
    for key in (
        "Serving",
        "deadline_ms",
        "max_open_bins",
        "batch_size",
        "max_budgets",
        "slack",
        "max_graphs",
        "validate_snapshot",
    ):
        assert key in accepted, f"Serving key {key!r} not harvested"


def test_loadgen_histograms_are_deterministic_and_sized():
    from hydragnn_tpu.serve.loadgen import synthetic_request_samples

    a = synthetic_request_samples("qm9", 32, seed=3)
    b = synthetic_request_samples("qm9", 32, seed=3)
    assert [s.num_nodes for s in a] == [s.num_nodes for s in b]
    assert all(4 <= s.num_nodes <= 29 for s in a)
    z = synthetic_request_samples("zinc", 32, seed=3)
    assert np.mean([s.num_nodes for s in z]) > np.mean(
        [s.num_nodes for s in a]
    )
    with pytest.raises(ValueError, match="unknown histogram"):
        synthetic_request_samples("pcqm", 4)
