"""Aux subsystems: tracer, visualizer, postprocess denormalize, HPO
helpers, atomic descriptors, LSMS enthalpy conversion (SURVEY.md §2.7/§5).
"""

import os
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401


def test_region_timer():
    from hydragnn_tpu.utils import tracer as tr

    tr.initialize(["RegionTimer"])
    tr.reset()
    tr.start("outer")
    time.sleep(0.01)
    tr.start("inner")
    time.sleep(0.01)
    tr.stop("inner")
    tr.stop("outer")
    timer = tr._TRACERS["RegionTimer"]
    assert timer.counts["outer"] == 1
    assert timer.counts["outer/inner"] == 1
    assert timer.totals["outer"] >= timer.totals["outer/inner"]


def test_profile_decorator_and_csv(tmp_path):
    from hydragnn_tpu.utils import tracer as tr

    tr.initialize(["RegionTimer"])
    tr.reset()

    @tr.profile("fn")
    def f(x):
        return x + 1

    for _ in range(3):
        f(1)
    timer = tr._TRACERS["RegionTimer"]
    assert timer.counts["fn"] == 3
    path = str(tmp_path / "timing.csv")
    timer.save_csv(path)
    content = open(path).read()
    assert "fn,3," in content


def test_device_metrics_tracer_counters_and_csv(tmp_path):
    """DeviceMetricsTracer accumulates per-region counter deltas/maxes
    from an injected reader (on TPU the default reader uses libtpu
    memory_stats) and its columns land in the timing CSV."""
    from hydragnn_tpu.utils.tracer import DeviceMetricsTracer, RegionTimer

    readings = iter(
        [
            {"hbm_bytes_in_use": 100.0},  # activation probe
            {"hbm_bytes_in_use": 100.0},  # start train
            {"hbm_bytes_in_use": 350.0},  # stop train
            {"hbm_bytes_in_use": 300.0},  # start train (2nd call)
            {"hbm_bytes_in_use": 400.0},  # stop train
        ]
    )
    dm = DeviceMetricsTracer(read_fn=lambda: next(readings, None))
    assert dm.active
    timer = RegionTimer()
    for _ in range(2):
        dm.start("train")
        timer.start("train")
        timer.stop("train")
        dm.stop("train")
    cols = dm.columns()
    assert cols["train"]["hbm_bytes_in_use_delta"] == 350.0  # 250+100
    assert cols["train"]["hbm_bytes_in_use_max"] == 400.0
    path = str(tmp_path / "timing.csv")
    timer.save_csv(path, device_columns=cols)
    content = open(path).read()
    assert "hbm_bytes_in_use_delta" in content
    assert "350.0" in content


def test_device_metrics_tracer_inert_without_counters():
    """A backend that publishes nothing (CPU) leaves the tracer inert:
    no snapshots, no columns, no crash."""
    from hydragnn_tpu.utils.tracer import DeviceMetricsTracer

    dm = DeviceMetricsTracer(read_fn=lambda: None)
    assert not dm.active
    dm.start("train")
    dm.stop("train")
    assert dm.columns() == {}


def test_output_denormalize():
    from hydragnn_tpu.postprocess import output_denormalize

    trues = [np.array([[0.0], [0.5], [1.0]])]
    preds = [np.array([[0.25], [0.5], [0.75]])]
    t, p = output_denormalize([(10.0, 20.0)], trues, preds)
    np.testing.assert_allclose(t[0].reshape(-1), [10.0, 15.0, 20.0])
    np.testing.assert_allclose(p[0].reshape(-1), [12.5, 15.0, 17.5])


def test_visualizer_writes_files(tmp_path, monkeypatch):
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.postprocess import Visualizer

    monkeypatch.chdir(tmp_path)
    viz = Visualizer("viztest", num_heads=1)
    t = [np.random.default_rng(0).normal(size=(50, 1))]
    p = [t[0] + 0.1]
    viz.create_scatter_plots(t, p, output_names=["energy"])
    viz.plot_history([1.0, 0.5, 0.2], [1.1, 0.6, 0.3], [1.2, 0.7, 0.4])
    ds = [
        [GraphSample(x=np.zeros((n, 1), np.float32)) for n in (3, 4, 5)]
    ]
    viz.num_nodes_plot(ds, ["train"])
    rng = np.random.default_rng(1)
    viz.create_error_histograms(t, p, output_names=["energy"])
    viz.create_plot_global(t, p, output_names=["energy"])
    viz.create_parity_plot_vector(
        rng.normal(size=(40, 3)), rng.normal(size=(40, 3)), name="forces"
    )
    viz.plot_task_history(
        [np.array([1.0, 0.5]), np.array([0.8, 0.4]), np.array([0.6, 0.3])],
        task_names=["energy", "forces"],
    )
    out = tmp_path / "logs" / "viztest"
    assert (out / "scatter_energy.png").exists()
    assert (out / "history.png").exists()
    assert (out / "num_nodes.png").exists()
    assert (out / "error_hist_energy.png").exists()
    assert (out / "global_analysis.png").exists()
    assert (out / "parity_forces.png").exists()
    assert (out / "task_history.png").exists()


def test_hpo_random_search():
    from hydragnn_tpu.utils.hpo import apply_trial, random_search

    config = {"NeuralNetwork": {"Architecture": {"hidden_dim": 8}}}
    c2 = apply_trial(
        config, {"NeuralNetwork.Architecture.hidden_dim": 32}
    )
    assert c2["NeuralNetwork"]["Architecture"]["hidden_dim"] == 32
    assert config["NeuralNetwork"]["Architecture"]["hidden_dim"] == 8

    # objective: parabola over the space — search must find the minimum
    def obj(cfg, params):
        h = params["NeuralNetwork.Architecture.hidden_dim"]
        return (h - 16) ** 2

    best_p, best_v, trials = random_search(
        config,
        {"NeuralNetwork.Architecture.hidden_dim": [4, 8, 16, 32]},
        n_trials=20,
        objective=obj,
    )
    assert best_p["NeuralNetwork.Architecture.hidden_dim"] == 16
    assert best_v == 0


def test_atomic_descriptors():
    from hydragnn_tpu.utils.descriptors import atomicdescriptors

    d = atomicdescriptors(element_types=["C", "H", "O"])
    fc = d.get_atom_features("C")
    fh = d.get_atom_features(1)
    assert fc.shape == fh.shape == (7,)
    assert not np.array_equal(fc, fh)
    assert np.all(fc >= 0) and np.all(fc <= 1)

    d1 = atomicdescriptors(element_types=["C", "H", "O"], one_hot=True)
    assert d1.get_atom_features("C").shape == (10,)  # 3 one-hot + 7


def test_smiles_entrypoint_without_rdkit():
    """Without rdkit the descriptors entry point routes through the
    native parser (utils/smiles.py) instead of raising — SMILES
    ingestion works on this rdkit-less image."""
    from hydragnn_tpu.utils.descriptors import (
        generate_graphdata_from_smilestr,
        get_node_attribute_name,
    )

    names, dims = get_node_attribute_name(["C", "H"])
    assert names[0] == "atomC" and len(names) == 8 and dims == [1] * 8
    s = generate_graphdata_from_smilestr(
        "CO", [0.25], {"C": 0, "O": 1, "H": 2}
    )
    assert s.x.shape == (6, 3 + 6)  # CH3OH: 2 heavy + 4 H
    assert s.edge_index.shape == (2, 10)  # 5 bonds, both directions
    np.testing.assert_allclose(s.y_graph, [0.25])


def test_lsms_gibbs_conversion(tmp_path):
    from hydragnn_tpu.utils.lsms import convert_raw_data_energy_to_gibbs

    # Two pure configs + one mixed 50/50 binary.
    d = tmp_path / "lsms"
    d.mkdir()

    def write(name, rows, energy):
        lines = [f"{energy}"]
        for r in rows:
            lines.append(" ".join(str(v) for v in r))
        (d / name).write_text("\n".join(lines) + "\n")

    # columns: type idx x y z ...
    write("pure0.txt", [[0, 0, 0, 0, 0], [0, 1, 0.5, 0.5, 0.5]], -2.0)
    write("pure1.txt", [[1, 0, 0, 0, 0], [1, 1, 0.5, 0.5, 0.5]], -4.0)
    write("mix.txt", [[0, 0, 0, 0, 0], [1, 1, 0.5, 0.5, 0.5]], -3.5)
    out = convert_raw_data_energy_to_gibbs(str(d), [0.0, 1.0])
    assert os.path.isdir(out)
    # mixed config: linear mixing = 0.5*(-1) + 0.5*(-2) per atom * 2
    # atoms = -3.0; enthalpy = -3.5 - (-3.0) = -0.5 (T=0 -> Gibbs).
    gibbs = float(open(os.path.join(out, "mix.txt")).readline().split()[0])
    np.testing.assert_allclose(gibbs, -0.5, atol=1e-10)
    # pure configs have zero formation enthalpy
    g0 = float(open(os.path.join(out, "pure0.txt")).readline().split()[0])
    np.testing.assert_allclose(g0, 0.0, atol=1e-10)


@pytest.fixture
def fake_tpu_info(tmp_path, monkeypatch):
    """A `tpu-info` PATH shim emitting a canned duty-cycle table and
    counting its own invocations, plus a fresh duty cache."""
    from hydragnn_tpu.utils import tracer

    count_file = tmp_path / "calls"
    count_file.write_text("0")
    shim = tmp_path / "tpu-info"
    shim.write_text(
        "#!/bin/sh\n"
        f"echo $(( $(cat {count_file}) + 1 )) > {count_file}\n"
        "if [ \"$1\" = --metric ]; then\n"
        "  echo 'unknown flag: --metric' >&2; exit 2\n"
        "fi\n"
        "echo 'Chip  Duty cycle'\n"
        "echo '0     83.5%'\n"
    )
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setattr(
        tracer, "_DUTY_CACHE", {"exe": False, "t": 0.0, "value": None}
    )
    return count_file


def test_default_device_counters_with_fake_tpu_info(
    fake_tpu_info, monkeypatch, tmp_path
):
    """The DEFAULT reader path end-to-end without hardware: libtpu-style
    memory_stats (monkeypatched) + the tpu-info CLI (PATH shim) feed
    _default_device_counters; the duty-cycle parse survives an unknown
    --metric flag (nonzero exit) by falling back to the table, the
    subprocess is rate-limited, and the columns land in the timing CSV
    (round-4 verdict, weak #3)."""
    import jax

    from hydragnn_tpu.utils import tracer
    from hydragnn_tpu.utils.tracer import DeviceMetricsTracer, RegionTimer

    class _Dev:
        def memory_stats(self):
            return {"bytes_in_use": 512.0, "peak_bytes_in_use": 2048.0}

    monkeypatch.setattr(jax, "local_devices", lambda: [_Dev()])
    out = tracer._default_device_counters()
    assert out["hbm_bytes_in_use"] == 512.0
    assert out["hbm_peak_bytes"] == 2048.0
    # --metric failed (exit 2) -> table fallback; chip index 0 is NOT
    # mistaken for the duty cycle, the %-suffixed value wins.
    assert out["duty_cycle_pct"] == 83.5
    # Rate limit: a second read within the window reuses the cache —
    # the shim ran twice for the first read (flag try + table), and not
    # again for the second.
    calls_after_first = int(fake_tpu_info.read_text())
    assert calls_after_first == 2
    tracer._default_device_counters()
    assert int(fake_tpu_info.read_text()) == calls_after_first

    # Wired as the DEFAULT reader (read_fn=None): active, records
    # per-region columns, merges into the CSV.
    dm = DeviceMetricsTracer()
    assert dm.active
    timer = RegionTimer()
    dm.start("train")
    timer.start("train")
    timer.stop("train")
    dm.stop("train")
    cols = dm.columns()
    assert cols["train"]["duty_cycle_pct_max"] == 83.5
    assert cols["train"]["hbm_peak_bytes_max"] == 2048.0
    path = str(tmp_path / "timing.csv")
    timer.save_csv(path, device_columns=cols)
    assert "duty_cycle_pct_max" in open(path).read()


def test_duty_cycle_rejects_error_banner(tmp_path, monkeypatch):
    """A failing tpu-info (nonzero exit with numbers in its output)
    must yield None, not log an arbitrary number as the duty cycle
    (round-4 advisor)."""
    from hydragnn_tpu.utils import tracer

    shim = tmp_path / "tpu-info"
    shim.write_text(
        "#!/bin/sh\necho 'error 404: libtpu not found'; exit 1\n"
    )
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setattr(
        tracer, "_DUTY_CACHE", {"exe": False, "t": 0.0, "value": None}
    )
    assert tracer._read_tpu_duty_cycle() is None


def test_device_metrics_stop_desync_tolerated():
    """An out-of-order stop (or a stop whose start never recorded a
    snapshot) must not permanently desynchronize the region stack
    (round-4 advisor)."""
    from hydragnn_tpu.utils.tracer import DeviceMetricsTracer

    vals = {"c": 0.0}

    def read():
        vals["c"] += 1.0
        return dict(vals)

    dm = DeviceMetricsTracer(read_fn=read)
    dm.stop("never-started")  # no-op, stack intact
    dm.start("epoch")
    dm.start("orphan")  # started, never stopped
    dm.stop("epoch")  # truncates through the orphan
    assert dm._stack == []
    # Later regions key correctly.
    dm.start("train")
    dm.stop("train")
    assert "train" in dm.columns()
    assert "epoch/orphan/train" not in dm.columns()
