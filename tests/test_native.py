"""Native C++ host components: differential tests against the numpy
reference implementations (cell-list neighbor builder replacing vesin,
sample store replacing DDStore/Adios-shmem — SURVEY.md §2.8).
"""

import os

import numpy as np
import pytest

from hydragnn_tpu.native import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native library could not be built"
)


def _canon(ei, sh=None):
    keys = (ei[1], ei[0]) if sh is None else (
        sh[:, 2], sh[:, 1], sh[:, 0], ei[1], ei[0]
    )
    idx = np.lexsort(keys)
    return ei[:, idx], (None if sh is None else sh[idx])


def test_radius_graph_matches_numpy():
    from hydragnn_tpu.native import radius_graph_native
    from hydragnn_tpu.ops.neighbors import _cell_list_pairs

    rng = np.random.default_rng(3)
    for n in (1, 2, 17, 300):
        pos = rng.uniform(0, 5.0, (n, 3))
        ei_n, _ = _canon(radius_graph_native(pos, 1.4))
        s, r, _ = _cell_list_pairs(pos, 1.4, loop=False)
        ei_p, _ = _canon(np.stack([s, r]).astype(np.int64))
        assert np.array_equal(ei_n, ei_p), n


def test_radius_graph_pbc_matches_numpy():
    from hydragnn_tpu.native import radius_graph_pbc_native

    os.environ["HYDRAGNN_TPU_NO_NATIVE"] = "1"
    try:
        from hydragnn_tpu.ops.neighbors import radius_graph_pbc

        rng = np.random.default_rng(5)
        cell = np.array([[5.0, 0, 0], [0.7, 4.5, 0], [0.1, 0.4, 5.5]])
        for pbc in [(True, True, True), (True, False, True), (False,) * 3]:
            pos = rng.uniform(-3, 8.0, (40, 3))
            ein, shn = radius_graph_pbc_native(pos, cell, 1.6, pbc)
            eip, shp = radius_graph_pbc(pos, cell, 1.6, pbc=pbc)
            ein, shn = _canon(ein, shn)
            eip, shp = _canon(eip, shp)
            assert np.array_equal(ein, eip), pbc
            np.testing.assert_allclose(shn, shp, atol=1e-9)
    finally:
        os.environ.pop("HYDRAGNN_TPU_NO_NATIVE", None)


def test_dispatch_through_public_api():
    """ops.neighbors.radius_graph must give identical results with the
    native path on and off (including max_neighbours capping)."""
    from hydragnn_tpu.ops import neighbors

    rng = np.random.default_rng(7)
    pos = rng.uniform(0, 4.0, (80, 3))
    ei_native = neighbors.radius_graph(pos, 1.5, max_neighbours=6)
    os.environ["HYDRAGNN_TPU_NO_NATIVE"] = "1"
    try:
        ei_numpy = neighbors.radius_graph(pos, 1.5, max_neighbours=6)
    finally:
        os.environ.pop("HYDRAGNN_TPU_NO_NATIVE", None)
    a, _ = _canon(ei_native)
    b, _ = _canon(ei_numpy)
    assert np.array_equal(a, b)


def test_sample_store_roundtrip():
    from hydragnn_tpu.native import SampleStore

    recs = [os.urandom(int(k)) for k in (1, 100, 0, 4096)]
    st = SampleStore([len(r) for r in recs])
    for i, r in enumerate(recs):
        st.put(i, r)
    assert len(st) == len(recs)
    for i, r in enumerate(recs):
        assert st.get(i) == r
    with pytest.raises(IndexError):
        st.get(99)
    st.close()


def test_store_dataset_roundtrip():
    from hydragnn_tpu.data.diststore import (
        StoreDataset,
        pack_sample,
        shard_for_process,
        unpack_sample,
    )
    from hydragnn_tpu.data.graph import GraphSample

    rng = np.random.default_rng(0)
    samples = []
    for i in range(5):
        n = int(rng.integers(3, 7))
        samples.append(
            GraphSample(
                x=rng.normal(size=(n, 2)).astype(np.float32),
                pos=rng.normal(size=(n, 3)).astype(np.float32),
                edge_index=np.stack(
                    [np.arange(n - 1), np.arange(1, n)]
                ).astype(np.int64),
                y_graph=np.array([float(i)], np.float32),
                energy=-float(i),
                dataset_id=i % 2,
            )
        )
    # pack/unpack identity
    s2 = unpack_sample(pack_sample(samples[0]))
    np.testing.assert_array_equal(s2.x, samples[0].x)
    assert s2.energy == samples[0].energy
    assert s2.edge_attr is None
    # store-backed dataset
    ds = StoreDataset.build(samples)
    assert len(ds) == 5
    for i in range(5):
        np.testing.assert_array_equal(ds[i].pos, samples[i].pos)
        assert ds[i].dataset_id == samples[i].dataset_id
    ds.close()
    # host shard partition covers everything exactly once
    parts = [list(shard_for_process(11, p, 4)) for p in range(4)]
    assert sorted(sum(parts, [])) == list(range(11))


def test_sample_store_shared_memory():
    from hydragnn_tpu.native import SampleStore

    name = f"/hgtpu_pytest_{os.getpid()}"
    st = SampleStore([8, 8], shm_name=name)
    st.put(0, b"abcdefgh")
    st.put(1, b"01234567")
    reader = SampleStore.attach(name)
    assert reader.get(0) == b"abcdefgh"
    assert reader.get(1) == b"01234567"
    reader.close()
    st.close()
    # after the owner closes, the shm name must be gone
    with pytest.raises(RuntimeError):
        SampleStore.attach(name)
