"""Test configuration: force an 8-device virtual CPU platform so sharding
paths are exercised without TPU hardware (SURVEY.md §4: the TPU analog of
the reference's 2-rank MPI CI is multi-device pjit on CPU).

The actual pinning dance lives in tests/_cpu.py so ad-hoc scripts can
reuse it (``import tests._cpu``); it must run before any test builds an
array.
"""

import jax

import tests._cpu  # noqa: F401  (side effect: pin CPU platform)

assert jax.devices()[0].platform == "cpu"
assert len(jax.devices()) == 8, (
    "expected 8 virtual CPU devices; XLA_FLAGS was read too late"
)
