"""Test configuration: force an 8-device virtual CPU platform so sharding
paths are exercised without TPU hardware (SURVEY.md §4: the TPU analog of
the reference's 2-rank MPI CI is multi-device pjit on CPU).

The environment may pre-register an accelerator PJRT plugin at interpreter
start (sitecustomize) and pin jax_platforms to it; we re-point JAX at CPU
and clear any initialized backends before any test builds an array.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:
    pass

assert jax.devices()[0].platform == "cpu"
assert len(jax.devices()) == 8, (
    "expected 8 virtual CPU devices; XLA_FLAGS was read too late"
)
