"""Radial bases, cutoffs, and distance transforms (reference
tests/test_radial_transforms.py + mace_utils/modules/radial.py).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax.numpy as jnp

from hydragnn_tpu.ops.rbf import (
    agnesi_transform,
    bessel_basis,
    chebyshev_basis,
    cosine_cutoff,
    envelope,
    gaussian_smearing,
    polynomial_cutoff,
    sinc_basis,
    soft_transform,
)

R_MAX = 5.0
D = jnp.linspace(0.05, 6.0, 200)


def test_bessel_shape_and_cutoff_zero():
    b = bessel_basis(D, R_MAX, 8)
    assert b.shape == (200, 8)
    # first basis function is sqrt(2/c) sin(pi d/c)/d -> 0 at d = c
    at_c = bessel_basis(jnp.asarray([R_MAX]), R_MAX, 8)
    np.testing.assert_allclose(np.asarray(at_c)[0], 0.0, atol=1e-6)


def test_gaussian_smearing_peaks():
    g = gaussian_smearing(jnp.asarray([0.0, 2.5, 5.0]), 0.0, 5.0, 11)
    # each input at a center hits 1.0 on that center
    assert np.isclose(float(g[0, 0]), 1.0)
    assert np.isclose(float(g[1, 5]), 1.0)
    assert np.isclose(float(g[2, 10]), 1.0)


def test_chebyshev_bounded():
    c = chebyshev_basis(D, R_MAX, 6)
    assert float(jnp.abs(c).max()) <= 1.0 + 1e-6


def test_sinc_basis_finite_at_zero():
    s = sinc_basis(jnp.asarray([0.0, 1.0]), R_MAX, 4)
    assert np.isfinite(np.asarray(s)).all()


@pytest.mark.parametrize(
    "fn", [cosine_cutoff, lambda d, c: polynomial_cutoff(d, c, 6)]
)
def test_cutoffs_smoothly_vanish(fn):
    c = np.asarray(fn(D, R_MAX))
    assert np.isclose(float(fn(jnp.asarray([0.0]), R_MAX)[0]), 1.0, atol=1e-6)
    # zero beyond the cutoff, monotonically decreasing before it
    beyond = np.asarray(fn(jnp.asarray([R_MAX + 0.1, 2 * R_MAX]), R_MAX))
    np.testing.assert_allclose(beyond, 0.0, atol=1e-8)
    inside = c[np.asarray(D) < R_MAX]
    assert np.all(np.diff(inside) <= 1e-6)


def test_envelope_vanishes_at_one():
    e = np.asarray(envelope(jnp.asarray([0.999, 1.0, 1.5]), 5))
    assert abs(e[1]) < 1e-6 and e[2] == 0.0


def test_agnesi_transform_shape():
    """Reference AgnesiTransform (radial.py:151-196): value in (0, 1],
    decreasing with distance, -> 1 as d -> 0."""
    r0 = jnp.asarray(1.0)
    d = jnp.linspace(0.01, 10.0, 100)
    t = np.asarray(agnesi_transform(d, r0))
    assert np.all(t > 0) and np.all(t <= 1.0 + 1e-6)
    assert np.all(np.diff(t) < 1e-9)
    assert t[0] > 0.95


def test_soft_transform_shape():
    """Reference SoftTransform (radial.py:204-248): ~d + 0.5 shape —
    approaches d + 0.5 for large d, small positive near zero, and
    monotonic."""
    r0 = jnp.asarray(0.5)
    d = jnp.linspace(0.0, 8.0, 100)
    t = np.asarray(soft_transform(d, r0))
    assert np.all(np.diff(t) > -1e-9)
    # large d: tanh term saturates at -1, so t -> d
    np.testing.assert_allclose(t[-1], float(d[-1]), atol=1e-3)
    assert 0.0 <= t[0] <= 0.6
