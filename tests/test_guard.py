"""Divergence guard (ISSUE 10, docs/DURABILITY.md "Divergence
recovery"): on-device detection + containment, the host-side policy
ladder, fault-injection grammar, and the healthy-run bitwise-identity
contract.

The load-bearing invariants:

- guard ENABLED vs DISABLED on a healthy run is BITWISE identical —
  losses AND params — through serial, pipeline, and superstep feeds;
- an injected-NaN step under the skip policy ends bitwise equal to a
  run trained without the poisoned step (params and loss history),
  even when the poison lands INSIDE a ``[K, ...]`` superstep macro;
- the policy ladder escalates skip → rollback (restore + LR backoff +
  fast-forward past the poison) → halt with an actionable report.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample
from hydragnn_tpu.ops.neighbors import radius_graph


def _mols(n, lo=5, hi=11, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(lo, hi))
        pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.integers(0, 3, (k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                y_graph=np.array([r.normal()], np.float32),
            )
        )
    return out


def _config(batch_size=4, num_epoch=2, workers=0, steps=1):
    return {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.2,
                "max_neighbours": 16,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "num_epoch": num_epoch,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
                "Parallelism": {
                    "scheme": "single",
                    "pipeline": {"workers": workers},
                    "superstep": {"steps": steps},
                },
            },
        }
    }


@pytest.fixture(scope="module")
def tiny_model():
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.train.optimizer import select_optimizer

    samples = _mols(32, seed=3)
    cfgd = update_config(_config(), samples)
    model, cfg = create_model_config(cfgd)
    params, bs = init_params(model, next(iter(GraphLoader(samples, 4))))
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )
    return samples, model, cfg, tx, params, bs


def _fresh_state(tiny_model):
    from hydragnn_tpu.train.state import create_train_state

    _, _, _, tx, params, bs = tiny_model
    return create_train_state(
        jax.tree_util.tree_map(jnp.array, params),
        tx,
        jax.tree_util.tree_map(jnp.array, bs),
    )


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _monitor(**overrides):
    from hydragnn_tpu.train.guard import GuardMonitor, guard_settings

    block = {"enabled": True}
    block.update(overrides)
    return GuardMonitor(guard_settings({"Guard": block}))


@pytest.fixture(autouse=True)
def _disarm_faults():
    from hydragnn_tpu.utils import faults

    faults.reset()
    yield
    faults.reset()


# ----------------------------------------------------------------------
# Grammar / settings
# ----------------------------------------------------------------------


def test_guard_settings_grammar():
    from hydragnn_tpu.train.guard import guard_settings

    s = guard_settings({})
    assert not s.enabled and s.policy == "skip"
    s = guard_settings({"Guard": True})
    assert s.enabled and s.check_interval_steps == 0
    s = guard_settings(
        {
            "Guard": {
                "enabled": True,
                "policy": "rollback",
                "max_bad_steps": 1,
                "window_steps": 10,
                "check_interval_steps": 2,
                "lr_backoff": 0.25,
                "max_rollbacks": 5,
            }
        }
    )
    assert s.policy == "rollback" and s.max_rollbacks == 5
    with pytest.raises(ValueError, match="policy"):
        guard_settings({"Guard": {"policy": "panic"}})
    # lr_backoff must SHRINK the LR: > 1 would re-walk the poisoned
    # region hotter on every rollback, <= 0 yields a broken LR
    with pytest.raises(ValueError, match="lr_backoff"):
        guard_settings({"Guard": {"lr_backoff": 1.5}})
    with pytest.raises(ValueError, match="lr_backoff"):
        guard_settings({"Guard": {"lr_backoff": 0.0}})
    assert guard_settings({"Guard": {"lr_backoff": 1.0}}).lr_backoff == 1.0


def test_update_config_rejects_unknown_guard_keys():
    from hydragnn_tpu.config import update_config

    cfg = _config()
    cfg["NeuralNetwork"]["Training"]["Guard"] = {"enabled": True}
    update_config(cfg, _mols(2))  # known keys pass
    cfg["NeuralNetwork"]["Training"]["Guard"] = {"max_bad_stepz": 3}
    with pytest.raises(ValueError, match="max_bad_stepz"):
        update_config(cfg, _mols(2))


def test_nan_fault_grammar():
    from hydragnn_tpu.utils import faults

    faults.install("nan:loss@5;nan:loss@7;nan:grad@2;nan:batch@0")
    assert faults.nan_rules() == {
        "loss": [5, 7],
        "grad": [2],
        "batch": [0],
    }
    assert faults.plan_spec() == "nan:loss@5;nan:loss@7;nan:grad@2;nan:batch@0"
    faults.reset()
    assert faults.nan_rules() == {} and faults.plan_spec() is None
    with pytest.raises(ValueError, match="site"):
        faults.install("nan:params@3")


# ----------------------------------------------------------------------
# Healthy-run bitwise identity (the acceptance contract): guard on vs
# off through serial, pipeline, and superstep feeds.
# ----------------------------------------------------------------------


def _run_feed(tiny_model, feed, guard_on):
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader
    from hydragnn_tpu.train.loop import (
        _run_epoch,
        make_superstep_fn,
        make_train_step,
        superstep_task_count,
    )

    samples, model, cfg, tx, _, _ = tiny_model
    step = make_train_step(model, tx, cfg, donate=False, guard=guard_on)
    sstep = make_superstep_fn(
        model, tx, cfg, train=True, donate=False, guard=guard_on
    )
    monitor = _monitor() if guard_on else None
    state = _fresh_state(tiny_model)
    losses = []
    for ep in range(2):
        base = GraphLoader(samples, 4)
        base.set_epoch(ep)
        if feed == "superstep":
            loader = SuperstepLoader(base, 4)
        elif feed == "pipeline":
            loader = ParallelPipelineLoader(base, workers=2)
        else:
            loader = base
        if monitor is not None:
            monitor.note_epoch(ep)
        state, loss, _ = _run_epoch(
            step, state, loader, train=True,
            superstep_fn=sstep,
            n_tasks=superstep_task_count(cfg), guard=monitor,
        )
        losses.append(loss)
    if monitor is not None:
        assert monitor.skipped_total == 0
    return state, losses


@pytest.mark.parametrize("feed", ["serial", "pipeline", "superstep"])
def test_healthy_run_guard_identity(tiny_model, feed):
    """Guard enabled vs disabled on a healthy run: identical losses
    AND params, bitwise — through every single-scheme feed."""
    s_off, l_off = _run_feed(tiny_model, feed, False)
    s_on, l_on = _run_feed(tiny_model, feed, True)
    assert l_off == l_on
    assert _leaves_equal(s_off.params, s_on.params)
    assert _leaves_equal(s_off.batch_stats, s_on.batch_stats)


# ----------------------------------------------------------------------
# Injected-NaN containment: skip == poisoned-step-excluded baseline.
# ----------------------------------------------------------------------


def _baseline_without_step(tiny_model, skip_step, epochs=1):
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_train_step

    samples, model, cfg, tx, _, _ = tiny_model
    step = make_train_step(model, tx, cfg, donate=False)
    state = _fresh_state(tiny_model)
    losses = []
    g = 0
    for ep in range(epochs):
        loader = GraphLoader(samples, 4)
        loader.set_epoch(ep)
        loss_sum = n_graphs = None
        for batch in loader:
            if g == skip_step:
                state = state.replace(step=state.step + 1)
                g += 1
                continue
            state, loss, _ = step(state, batch)
            ng = jnp.sum(batch.graph_mask).astype(jnp.float32)
            if loss_sum is None:
                loss_sum, n_graphs = loss * ng, ng
            else:
                loss_sum = loss_sum + loss * ng
                n_graphs = n_graphs + ng
            g += 1
        ls, ngs = jax.device_get((loss_sum, n_graphs))
        losses.append(float(ls) / max(float(ngs), 1.0))
    return state, losses


@pytest.mark.parametrize("site", ["loss", "batch"])
@pytest.mark.parametrize("feed", ["serial", "superstep"])
def test_injected_nan_skip_matches_baseline(tiny_model, site, feed):
    """The drill contract in tier-1: a guarded run with nan:<site>@3
    armed ends bitwise equal (loss AND params) to a run that never saw
    step 3 — serially and with the poison INSIDE a K=4 macro."""
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader
    from hydragnn_tpu.train.loop import (
        _run_epoch,
        make_superstep_fn,
        make_train_step,
        superstep_task_count,
    )
    from hydragnn_tpu.utils import faults

    samples, model, cfg, tx, _, _ = tiny_model
    faults.install(f"nan:{site}@3")
    step = make_train_step(model, tx, cfg, donate=False, guard=True)
    sstep = make_superstep_fn(
        model, tx, cfg, train=True, donate=False, guard=True
    )
    monitor = _monitor()
    base = GraphLoader(samples, 4)
    loader = SuperstepLoader(base, 4) if feed == "superstep" else base
    state, loss, _ = _run_epoch(
        step, _fresh_state(tiny_model), loader, train=True,
        superstep_fn=sstep, n_tasks=superstep_task_count(cfg),
        guard=monitor,
    )
    faults.reset()
    assert monitor.bad_steps_all == [(0, 3)]
    assert monitor.skipped_total == 1
    b_state, b_losses = _baseline_without_step(tiny_model, 3)
    assert loss == b_losses[0]
    assert _leaves_equal(state.params, b_state.params)
    assert _leaves_equal(state.batch_stats, b_state.batch_stats)


def test_grad_site_predicate_and_containment(tiny_model):
    """The grad injection site exercises the grad-norm half of the
    predicate: loss stays finite, grads go NaN, the update is
    suppressed (state bitwise unchanged vs pre-dispatch) and the step
    counter still ticks."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.utils import faults

    samples, model, cfg, tx, _, _ = tiny_model
    faults.install("nan:grad@0")
    step = make_train_step(model, tx, cfg, donate=False, guard=True)
    st0 = _fresh_state(tiny_model)
    batch = next(iter(GraphLoader(samples, 4)))
    st1, tot, tasks, ng, ok, gnorm = step(st0, batch)
    faults.reset()
    assert not bool(ok)
    assert not np.isfinite(float(gnorm))
    assert float(tot) == 0.0 and float(ng) == 0.0
    assert np.all(np.asarray(tasks) == 0.0)
    assert _leaves_equal(st0.params, st1.params)
    assert _leaves_equal(st0.opt_state, st1.opt_state)
    assert int(st1.step) == int(st0.step) + 1


def test_unguarded_control_diverges(tiny_model):
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import _run_epoch, make_train_step
    from hydragnn_tpu.utils import faults

    samples, model, cfg, tx, _, _ = tiny_model
    faults.install("nan:loss@2")
    step = make_train_step(model, tx, cfg, donate=False)
    _, loss, _ = _run_epoch(
        step, _fresh_state(tiny_model), GraphLoader(samples, 4),
        train=True,
    )
    faults.reset()
    assert not np.isfinite(loss)


# ----------------------------------------------------------------------
# Policy ladder (monitor unit level).
# ----------------------------------------------------------------------


def _observe_steps(monitor, flags, start=0):
    for i, ok in enumerate(flags):
        monitor.observe(
            step=start + i + 1,
            k=1,
            ok_ref=jnp.asarray(ok),
            gnorm_ref=jnp.asarray(1.0, jnp.float32),
        )


def test_monitor_skip_policy_never_escalates():
    m = _monitor(policy="skip", max_bad_steps=0)
    _observe_steps(m, [False] * 5)
    m.epoch_end()  # resolves; skip policy records only
    assert m.skipped_total == 5
    assert m.rollbacks == 0


def test_monitor_rollback_then_halt_ladder():
    from hydragnn_tpu.train.guard import GuardHalt, GuardRollback

    m = _monitor(
        policy="rollback", max_bad_steps=1, window_steps=100,
        max_rollbacks=1,
    )
    _observe_steps(m, [True, False, True, False])
    with pytest.raises(GuardRollback) as ri:
        m.epoch_end()
    assert ri.value.bad_steps == [1, 3]
    m.note_rollback(4, 5e-4)
    assert m.rollbacks == 1
    # the replayed region hits bad steps again: rollbacks exhausted
    _observe_steps(m, [False, False], start=4)
    with pytest.raises(GuardHalt) as hi:
        m.epoch_end()
    assert "HALTED" in str(hi.value)
    assert "last-known-good" in str(hi.value)


def test_monitor_halt_policy_is_immediate():
    from hydragnn_tpu.train.guard import GuardHalt

    m = _monitor(policy="halt", max_bad_steps=0)
    _observe_steps(m, [False])
    with pytest.raises(GuardHalt):
        m.epoch_end()


def test_monitor_window_expires_old_bad_steps():
    m = _monitor(policy="rollback", max_bad_steps=1, window_steps=5)
    _observe_steps(m, [False])  # bad at step 1
    m.check()
    # 30 healthy steps push the bad step out of the 5-step window
    _observe_steps(m, [True] * 30, start=1)
    _observe_steps(m, [False], start=31)  # one bad in-window: tolerated
    m.epoch_end()
    assert m.skipped_total == 2 and m.rollbacks == 0


def test_monitor_window_is_run_global_across_epochs():
    """The epoch loop numbers steps per epoch; the window must live in
    RUN-GLOBAL coordinates or a bad step in a short epoch would never
    age out (epoch-local `last_step` never exceeds the epoch length)."""
    from hydragnn_tpu.train.guard import GuardRollback

    m = _monitor(policy="rollback", max_bad_steps=1, window_steps=8)
    m.note_epoch(0)
    _observe_steps(m, [False] + [True] * 5)  # bad at e0 step 0, len 6
    m.epoch_end()
    m.note_epoch(1)
    # e1 step 3 is global step 9 — the e0 bad (global 0) has aged out
    # of the 8-step window by resolution time (a per-epoch basis
    # would keep it in-window forever and escalate here)
    _observe_steps(m, [True, True, True, False, True, True])
    m.epoch_end()
    assert m.skipped_total == 2 and m.rollbacks == 0
    # but two bads CLOSE together across the epoch boundary escalate,
    # with the rollback cursor carrying only CURRENT-epoch steps
    m2 = _monitor(policy="rollback", max_bad_steps=1, window_steps=8)
    m2.note_epoch(0)
    _observe_steps(m2, [True] * 5 + [False])  # bad at e0 step 5, len 6
    m2.epoch_end()
    m2.note_epoch(1)
    _observe_steps(m2, [True, False])  # bad at e1 step 1 == global 7
    with pytest.raises(GuardRollback) as ri:
        m2.epoch_end()
    assert ri.value.bad_steps == [1]  # e1-local only


def test_monitor_sampled_cadence_resolves_mid_epoch():
    from hydragnn_tpu.train.guard import GuardRollback

    m = _monitor(
        policy="rollback", max_bad_steps=0, check_interval_steps=2
    )
    m.observe(
        step=1, k=1, ok_ref=jnp.asarray(True),
        gnorm_ref=jnp.asarray(1.0),
    )
    with pytest.raises(GuardRollback):
        m.observe(
            step=2, k=1, ok_ref=jnp.asarray(False),
            gnorm_ref=jnp.asarray(np.nan),
        )


# ----------------------------------------------------------------------
# Rollback end-to-end through run_training.
# ----------------------------------------------------------------------


def test_rollback_end_to_end(tmp_path, monkeypatch):
    """Two poisoned steps over a max_bad_steps=1 window escalate to a
    rollback: the run completes with the LR backed off and the
    restored trajectory intact (losses finite, history full-length)."""
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.train.optimizer import get_learning_rate
    from hydragnn_tpu.utils import checkpoint as ck
    from hydragnn_tpu.utils import faults

    monkeypatch.setattr(ck, "CHECKPOINT_DIR", str(tmp_path))
    samples = _mols(60, seed=9)
    tr, va, te = split_dataset(samples, 0.8)
    cfg = _config(num_epoch=2)
    cfg["Dataset"] = {"name": "guard_rb"}
    t = cfg["NeuralNetwork"]["Training"]
    t["Checkpoint"] = {
        "enabled": True, "async": True, "interval_steps": 3,
    }
    t["Guard"] = {
        "enabled": True,
        "policy": "rollback",
        "max_bad_steps": 1,
        "window_steps": 50,
        "lr_backoff": 0.5,
        "max_rollbacks": 2,
    }
    faults.install("nan:loss@4;nan:loss@6")
    try:
        state, _, _, hist, _ = run_training(
            cfg, datasets=(tr, va, te), seed=0
        )
    finally:
        faults.reset()
    assert len(hist.train_loss) == 2
    assert all(np.isfinite(hist.train_loss))
    assert get_learning_rate(state.opt_state) == pytest.approx(5e-4)


def test_halt_end_to_end_without_checkpointing(tmp_path, monkeypatch):
    """policy=rollback with NO writer artifacts must halt with the
    actionable report, not limp on."""
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.train.guard import GuardHalt
    from hydragnn_tpu.utils import checkpoint as ck
    from hydragnn_tpu.utils import faults

    monkeypatch.setattr(ck, "CHECKPOINT_DIR", str(tmp_path))
    samples = _mols(60, seed=9)
    tr, va, te = split_dataset(samples, 0.8)
    cfg = _config(num_epoch=2)
    cfg["Dataset"] = {"name": "guard_halt"}
    cfg["NeuralNetwork"]["Training"]["Guard"] = {
        "enabled": True,
        "policy": "rollback",
        "max_bad_steps": 0,
    }
    faults.install("nan:loss@4")
    try:
        with pytest.raises(GuardHalt, match="no restorable checkpoint"):
            run_training(cfg, datasets=(tr, va, te), seed=0)
    finally:
        faults.reset()


def test_guard_universal_no_scheme_carveout():
    """ISSUE 13: the PR-10 scheme exclusion is gone — the loop never
    prints the old loud-ignore, and every branch of build_steps
    threads the guard flag into its step builder."""
    import inspect

    from hydragnn_tpu.train import loop as L

    src = inspect.getsource(L.train_validate_test)
    assert "Training.Guard ignored" not in src
    build = inspect.getsource(L.build_steps)
    # single, multibranch and dp builders all receive guard=
    assert build.count("guard=guard") >= 3


# ----------------------------------------------------------------------
# Guard under dp (ISSUE 13 leg a): replicated-predicate containment in
# the dp step and the [K, D, ...] superstep scan body, on the fake
# 8-device CPU mesh.
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def dp_model():
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.optimizer import select_optimizer

    mesh = make_mesh({"data": 8})
    samples = _mols(96, seed=3)  # 6 dp steps/epoch at batch 2 x D=8
    cfgd = update_config(_config(batch_size=2), samples)
    model, cfg = create_model_config(cfgd)
    params, bs = init_params(
        model, next(iter(GraphLoader(samples, 2, fixed_pad=True)))
    )
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )
    return samples, model, cfg, tx, params, bs, mesh


def _fresh_dp_state(dp_model):
    from hydragnn_tpu.parallel.dp import replicate_state
    from hydragnn_tpu.train.state import create_train_state

    _, _, _, tx, params, bs, mesh = dp_model
    st = create_train_state(
        jax.tree_util.tree_map(jnp.array, params),
        tx,
        jax.tree_util.tree_map(jnp.array, bs),
    )
    return replicate_state(st, mesh)


def _dp_feed(dp_model, feed, epoch):
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader
    from hydragnn_tpu.parallel.dp import DPLoader

    samples, *_, mesh = dp_model
    base = GraphLoader(samples, 2, fixed_pad=True)
    base.set_epoch(epoch)
    if feed == "superstep":
        return DPLoader(base, mesh, superstep_k=3)
    if feed == "pipeline":
        inner = ParallelPipelineLoader(
            base, workers=2, to_device=False,
            hold=DPLoader.required_hold(mesh),
        )
        return DPLoader(inner, mesh)
    return DPLoader(base, mesh)


def _run_dp_feed(dp_model, feed, guard_on):
    from hydragnn_tpu.parallel.dp import (
        make_dp_superstep_fn,
        make_dp_train_step,
    )
    from hydragnn_tpu.train.loop import _run_epoch, superstep_task_count

    _, model, cfg, tx, _, _, mesh = dp_model
    step = make_dp_train_step(model, tx, cfg, mesh, guard=guard_on)
    sstep = make_dp_superstep_fn(
        model, tx, cfg, mesh, train=True, guard=guard_on
    )
    monitor = _monitor() if guard_on else None
    state = _fresh_dp_state(dp_model)
    losses = []
    for ep in range(2):
        if monitor is not None:
            monitor.note_epoch(ep)
        state, loss, _ = _run_epoch(
            step, state, _dp_feed(dp_model, feed, ep), train=True,
            superstep_fn=sstep,
            n_tasks=superstep_task_count(cfg), guard=monitor,
        )
        losses.append(loss)
    if monitor is not None:
        assert monitor.skipped_total == 0
    return state, losses


@pytest.mark.parametrize("feed", ["serial", "pipeline", "superstep"])
def test_dp_healthy_run_guard_identity(dp_model, feed):
    """Guard enabled vs disabled on a healthy dp run: identical losses
    AND params, bitwise — through the serial, pipeline and superstep
    dp feeds (the ISSUE 13 acceptance contract)."""
    s_off, l_off = _run_dp_feed(dp_model, feed, False)
    s_on, l_on = _run_dp_feed(dp_model, feed, True)
    assert l_off == l_on
    assert _leaves_equal(s_off.params, s_on.params)
    assert _leaves_equal(s_off.batch_stats, s_on.batch_stats)


def _dp_baseline_without_step(dp_model, skip_step, epochs=1):
    from hydragnn_tpu.parallel.dp import make_dp_train_step

    _, model, cfg, tx, _, _, mesh = dp_model
    step = make_dp_train_step(model, tx, cfg, mesh)
    state = _fresh_dp_state(dp_model)
    losses = []
    g = 0
    for ep in range(epochs):
        loss_sum = n_graphs = None
        for batch in _dp_feed(dp_model, "serial", ep):
            if g == skip_step:
                state = state.replace(step=state.step + 1)
                g += 1
                continue
            state, loss, _ = step(state, batch)
            ng = jnp.sum(batch.graph_mask).astype(jnp.float32)
            if loss_sum is None:
                loss_sum, n_graphs = loss * ng, ng
            else:
                loss_sum = loss_sum + loss * ng
                n_graphs = n_graphs + ng
            g += 1
        ls, ngs = jax.device_get((loss_sum, n_graphs))
        losses.append(float(ls) / max(float(ngs), 1.0))
    return state, losses


@pytest.mark.parametrize("feed", ["serial", "superstep"])
def test_dp_injected_nan_skip_matches_baseline(dp_model, feed):
    """A guarded dp run with nan:loss@2 armed ends bitwise equal (loss
    AND params) to a dp run that never saw step 2 — plain [D, ...]
    delivery and with the poison INSIDE a [K, D, ...] macro."""
    from hydragnn_tpu.parallel.dp import (
        make_dp_superstep_fn,
        make_dp_train_step,
    )
    from hydragnn_tpu.train.loop import _run_epoch, superstep_task_count
    from hydragnn_tpu.utils import faults

    _, model, cfg, tx, _, _, mesh = dp_model
    faults.install("nan:loss@2")
    step = make_dp_train_step(model, tx, cfg, mesh, guard=True)
    sstep = make_dp_superstep_fn(
        model, tx, cfg, mesh, train=True, guard=True
    )
    monitor = _monitor()
    state, loss, _ = _run_epoch(
        step, _fresh_dp_state(dp_model),
        _dp_feed(dp_model, feed, 0), train=True,
        superstep_fn=sstep, n_tasks=superstep_task_count(cfg),
        guard=monitor,
    )
    faults.reset()
    assert monitor.bad_steps_all == [(0, 2)]
    assert monitor.skipped_total == 1
    b_state, b_losses = _dp_baseline_without_step(dp_model, 2)
    assert loss == b_losses[0]
    assert _leaves_equal(state.params, b_state.params)
    assert _leaves_equal(state.batch_stats, b_state.batch_stats)


def test_dp_unguarded_control_diverges(dp_model):
    """The same armed fault without the guard must poison the dp epoch
    accumulator — proof the injection lands in the dp build too."""
    from hydragnn_tpu.parallel.dp import make_dp_train_step
    from hydragnn_tpu.train.loop import _run_epoch
    from hydragnn_tpu.utils import faults

    _, model, cfg, tx, _, _, mesh = dp_model
    faults.install("nan:loss@2")
    step = make_dp_train_step(model, tx, cfg, mesh)
    _, loss, _ = _run_epoch(
        step, _fresh_dp_state(dp_model), _dp_feed(dp_model, "serial", 0),
        train=True,
    )
    faults.reset()
    assert not np.isfinite(loss)


def test_dp_rollback_end_to_end(tmp_path, monkeypatch):
    """GuardRollback under dp through run_training on the 8-device
    mesh: rollback restores the last-known-good container, backs the
    LR off, and the skip_to fast-forward lands PAST the poisoned
    region of the packed [K, D, ...] superstep feed — the run
    completes with finite losses and the backed-off LR."""
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.train.optimizer import get_learning_rate
    from hydragnn_tpu.utils import checkpoint as ck
    from hydragnn_tpu.utils import faults

    monkeypatch.setattr(ck, "CHECKPOINT_DIR", str(tmp_path))
    samples = _mols(400, seed=9)
    tr, va, te = split_dataset(samples, 0.8)
    cfg = _config(num_epoch=2, batch_size=4)
    cfg["Dataset"] = {"name": "guard_rb_dp"}
    t = cfg["NeuralNetwork"]["Training"]
    t["Parallelism"] = {
        "scheme": "dp",
        "data": 8,
        "pipeline": {"workers": 0},
        "packing": {"enabled": True},
        "superstep": {"steps": 4},
    }
    t["Checkpoint"] = {
        "enabled": True, "async": True, "interval_steps": 2,
    }
    t["Guard"] = {
        "enabled": True,
        "policy": "rollback",
        "max_bad_steps": 1,
        "window_steps": 50,
        "lr_backoff": 0.5,
        "max_rollbacks": 2,
    }
    faults.install("nan:loss@4;nan:loss@6")
    try:
        state, _, _, hist, _ = run_training(
            cfg, datasets=(tr, va, te), seed=0
        )
    finally:
        faults.reset()
    assert len(hist.train_loss) == 2
    assert all(np.isfinite(hist.train_loss))
    assert get_learning_rate(state.opt_state) == pytest.approx(5e-4)


# ----------------------------------------------------------------------
# Guard under multibranch (ISSUE 13 leg b): per-branch containment in
# the task-parallel step + per-branch monitor windows.
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def mb_model():
    """2-branch multibranch setup on the 8-device mesh (6+2 split)."""
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.parallel.multibranch import (
        MultiBranchLoader,
        dual_optimizer,
        proportional_branch_split,
    )

    mesh = make_mesh({"data": 8})
    branch_sets = [_mols(48, seed=b) for b in range(2)]
    cfgd = _config(batch_size=2)
    cfgd["NeuralNetwork"]["Architecture"]["output_heads"] = {
        "graph": [
            {
                "type": f"branch-{i}",
                "architecture": {
                    "num_sharedlayers": 1,
                    "dim_sharedlayers": 8,
                    "num_headlayers": 1,
                    "dim_headlayers": [8],
                },
            }
            for i in range(2)
        ]
    }
    cfgd = update_config(cfgd, [s for b in branch_sets for s in b])
    model, cfg = create_model_config(cfgd)
    dpb = proportional_branch_split([len(b) for b in branch_sets], 8)
    loader = MultiBranchLoader(
        branch_sets, dpb, batch_size=2, mesh=mesh, seed=0
    )
    # init from a SLOT loader's plain (un-stacked) batch — the model
    # sees per-device batches under vmap, never the [D, ...] stack
    batch0 = next(iter(loader.loaders[0]))
    params, bs = init_params(model, batch0)
    tx = dual_optimizer(cfgd["NeuralNetwork"]["Training"])
    params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )
    return branch_sets, model, cfg, tx, params, bs, mesh, dpb


def _fresh_mb_state(mb_model):
    from hydragnn_tpu.parallel.dp import replicate_state
    from hydragnn_tpu.train.state import create_train_state

    _, _, _, tx, params, bs, mesh, _ = mb_model
    st = create_train_state(
        jax.tree_util.tree_map(jnp.array, params),
        tx,
        jax.tree_util.tree_map(jnp.array, bs),
    )
    return replicate_state(st, mesh)


def _mb_loader(mb_model, epoch=0):
    from hydragnn_tpu.parallel.multibranch import MultiBranchLoader

    branch_sets, _, _, _, _, _, mesh, dpb = mb_model
    loader = MultiBranchLoader(
        branch_sets, dpb, batch_size=2, mesh=mesh, seed=0
    )
    loader.set_epoch(epoch)
    return loader


def test_multibranch_healthy_run_guard_identity(mb_model):
    """Guard on vs off over healthy multibranch steps: bitwise
    identical params, batch_stats and losses."""
    from hydragnn_tpu.parallel.multibranch import (
        make_multibranch_train_step,
    )

    _, model, cfg, tx, _, _, mesh, dpb = mb_model
    runs = {}
    for guard_on in (False, True):
        step = make_multibranch_train_step(
            model, tx, cfg, mesh, dpb, guard=guard_on
        )
        st = _fresh_mb_state(mb_model)
        losses = []
        for batch in _mb_loader(mb_model):
            out = step(st, batch)
            st, loss = out[0], out[1]
            losses.append(float(loss))
            if guard_on:
                ok = np.asarray(out[4])
                assert ok.shape == (3,) and ok.all()
        runs[guard_on] = (st, losses)
    assert runs[False][1] == runs[True][1]
    assert _leaves_equal(runs[False][0].params, runs[True][0].params)
    assert _leaves_equal(
        runs[False][0].batch_stats, runs[True][0].batch_stats
    )


def _branch_param_leaves(state, cfg, dpb, branch):
    """Leaves of ``state`` belonging to ``branch``'s decoder (or the
    encoder slot for branch == len(dpb)), via the step's own path
    resolution."""
    from hydragnn_tpu.parallel.multibranch import (
        _branch_name_index,
        _decoder_branch_of_path,
    )

    name_index = _branch_name_index(cfg)
    names_by_len = sorted(name_index, key=len, reverse=True)
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        jax.device_get(state)
    )[0]:
        bi = _decoder_branch_of_path(path, names_by_len, name_index)
        slot = len(dpb) if bi is None else bi
        if slot == branch:
            out.append((jax.tree_util.keystr(path), np.asarray(leaf)))
    return out


def test_multibranch_per_branch_containment(mb_model):
    """One branch's poison never suppresses another branch's healthy
    update (the ISSUE 13 leg-b contract): NaN'ing branch 0's LABELS
    (its own head's y column) on one step must (a) flag slots
    [branch-0, encoder] bad and branch-1 ok, (b) keep branch-0 decoder
    + encoder leaves bitwise at their pre-step values, and (c) commit
    branch-1's decoder leaves bitwise equal to the CLEAN step's —
    branch-1's gradients flow only through its own devices' loss
    terms, so its update is untouched by the poison. (A NaN in the
    INPUTS instead reaches every decoder numerically — 0·NaN through
    the masked head terms — and correctly reads all-slot-bad.)"""
    from hydragnn_tpu.parallel.multibranch import (
        branch_of_device,
        make_multibranch_train_step,
    )

    _, model, cfg, tx, _, _, mesh, dpb = mb_model
    step = make_multibranch_train_step(
        model, tx, cfg, mesh, dpb, guard=True
    )
    batch = next(iter(_mb_loader(mb_model)))
    # Poison branch-0 devices' y column for branch-0's OWN head only:
    # the corruption enters through branch-0's loss term; branch-1's
    # zero-weighted term on those devices reads its own (zero-filled)
    # column and stays finite.
    bids = branch_of_device(dpb)
    y = np.array(jax.device_get(batch.y_graph), copy=True)
    y[np.flatnonzero(bids == 0), :, 0] = np.nan
    poisoned = batch.replace(y_graph=jnp.asarray(y))

    st_clean = step(_fresh_mb_state(mb_model), batch)[0]
    st0 = _fresh_mb_state(mb_model)
    pre = jax.tree_util.tree_map(
        lambda v: np.array(v, copy=True), jax.device_get(st0)
    )
    st_p, tot, tasks, ng, ok, gnorm = step(st0, poisoned)
    ok = np.asarray(ok)
    assert ok.tolist() == [False, True, False]  # b0 bad, b1 ok, enc bad
    # Metrics are globally masked: the poisoned step contributes 0.
    assert float(tot) == 0.0 and float(ng) == 0.0
    # Branch-0 decoder and encoder slots: bitwise pre-step.
    for slot in (0, 2):
        got = _branch_param_leaves(st_p, cfg, dpb, slot)
        want = _branch_param_leaves(pre, cfg, dpb, slot)
        assert [k for k, _ in got] == [k for k, _ in want]
        for (k, a), (_, b) in zip(got, want):
            # the step counter always ticks
            if k.endswith(".step") or k == ".step":
                continue
            assert np.array_equal(a, b), k
    # Branch-1 decoder slot: bitwise the CLEAN step's update.
    got = _branch_param_leaves(st_p, cfg, dpb, 1)
    want = _branch_param_leaves(st_clean, cfg, dpb, 1)
    assert [k for k, _ in got] == [k for k, _ in want]
    changed = False
    for (k, a), (_, b) in zip(got, want):
        assert np.array_equal(a, b), k
        pre_leaf = dict(_branch_param_leaves(pre, cfg, dpb, 1))[k]
        changed = changed or not np.array_equal(a, pre_leaf)
    assert changed  # branch 1 actually updated


def test_monitor_per_branch_window_isolation():
    """Per-slot windows: two different branches' single bad steps must
    NOT sum into one escalation (max_bad_steps=1 tolerates one bad per
    slot), while two bad steps on the SAME slot escalate."""
    from hydragnn_tpu.train.guard import GuardMonitor, GuardRollback, guard_settings

    def mk():
        return GuardMonitor(
            guard_settings(
                {
                    "Guard": {
                        "enabled": True,
                        "policy": "rollback",
                        "max_bad_steps": 1,
                        "window_steps": 100,
                    }
                }
            ),
            branches=["branch-0", "branch-1", "encoder"],
        )

    def obs(m, step, ok_vec):
        m.observe(
            step=step, k=1,
            ok_ref=jnp.asarray(ok_vec),
            gnorm_ref=jnp.asarray([1.0, 1.0, 1.0], jnp.float32),
        )

    # Branch 0 bad once, branch 1 bad once (encoder rides along once):
    # per-slot counts are all <= 1 ... except encoder, which went bad
    # BOTH times — use encoder-ok vectors to isolate the branch slots.
    m = mk()
    obs(m, 1, [False, True, True])
    obs(m, 2, [True, False, True])
    m.epoch_end()  # no escalation: no slot exceeded 1 in-window
    assert m.skipped_total == 2 and m.rollbacks == 0
    # Same slot twice: escalates.
    m2 = mk()
    obs(m2, 1, [False, True, True])
    obs(m2, 2, [False, True, True])
    with pytest.raises(GuardRollback):
        m2.epoch_end()


# ----------------------------------------------------------------------
# Health telemetry rows + graftboard.
# ----------------------------------------------------------------------


def _graftboard():
    import importlib
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return importlib.import_module("graftboard")
    finally:
        sys.path.pop(0)


def test_health_rows_and_graftboard(tiny_model, tmp_path):
    """A guarded run with an injected fault emits `health` rows the
    stream carries and graftboard renders; `diff` flags a run whose
    guard history differs from a clean one."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import (
        _run_epoch,
        make_train_step,
    )
    from hydragnn_tpu.utils import faults, telemetry

    samples, model, cfg, tx, _, _ = tiny_model

    def run(tag, fault):
        stream = telemetry.TelemetryStream(str(tmp_path / f"{tag}.jsonl"))
        telemetry.install(stream)
        if fault:
            faults.install(fault)
        try:
            step = make_train_step(
                model, tx, cfg, donate=False, guard=True
            )
            monitor = _monitor()
            monitor.note_epoch(0)
            _run_epoch(
                step, _fresh_state(tiny_model),
                GraphLoader(samples, 4), train=True, guard=monitor,
            )
        finally:
            faults.reset()
            telemetry.install(None)
            stream.close()
        return str(tmp_path / f"{tag}.jsonl")

    bad_path = run("bad", "nan:loss@2")
    clean_path = run("clean", None)
    gb = _graftboard()
    rep_bad = gb.build_report(bad_path)
    rep_clean = gb.build_report(clean_path)
    hs = rep_bad["health_summary"]
    assert hs["skipped_total"] == 1
    assert hs["bad_steps"] == [[0, 2]]
    assert hs["fault_plans"] == ["nan:loss@2"]
    assert hs["gnorm_steps"] > 0 and hs["gnorm_max"] >= hs["gnorm_min"]
    rendered = gb.render_report(rep_bad)
    assert "health (divergence guard)" in rendered
    assert "bad optimizer steps: ['e0:s2']" in rendered
    # clean run: a health row per epoch, zero bad
    assert rep_clean["health_summary"]["skipped_total"] == 0
    d = gb.build_diff(rep_clean, rep_bad)
    assert d["health"]["differs"] is True
    assert "HEALTH DIVERGENCE" in gb.render_diff(d)
    d_same = gb.build_diff(rep_clean, rep_clean)
    assert d_same["health"]["differs"] is False


def test_health_summary_dedups_cumulative_rows():
    """Health rows are cumulative within an epoch and an escalation
    row duplicates the epoch row's running grad-norm stats — the
    summary must take one row per epoch, not sum them; and bad steps
    are epoch-local, so the summary must keep epoch context (e0:s3 vs
    e1:s3 are different skipped batches — `diff` must see them
    differ)."""
    gb = _graftboard()
    rollback_row = {
        "t": "health", "action": "rollback", "epoch": 0,
        "bad_steps": [3], "skipped_total": 1, "rollbacks": 0,
        "gnorm_min": 1.0, "gnorm_max": 2.0, "gnorm_mean": 1.5,
        "gnorm_steps": 10,
    }
    epoch_row = {
        "t": "health", "action": "epoch", "epoch": 0,
        "bad_steps": [3], "skipped_total": 1, "rollbacks": 1,
        "gnorm_min": 1.0, "gnorm_max": 3.0, "gnorm_mean": 2.0,
        "gnorm_steps": 16,  # cumulative superset of the rollback row
    }
    e1_row = {
        "t": "health", "action": "epoch", "epoch": 1,
        "bad_steps": [3], "skipped_total": 2, "rollbacks": 1,
        "gnorm_min": 0.5, "gnorm_max": 1.0, "gnorm_mean": 0.75,
        "gnorm_steps": 12,
    }
    hs = gb._health_summary([rollback_row, epoch_row, e1_row], [])
    assert hs["gnorm_steps"] == 16 + 12  # NOT 10 + 16 + 12
    assert hs["gnorm_mean"] == pytest.approx(
        (2.0 * 16 + 0.75 * 12) / 28
    )
    assert hs["gnorm_min"] == 0.5 and hs["gnorm_max"] == 3.0
    assert hs["bad_steps"] == [[0, 3], [1, 3]]
    # two runs skipping "step 3" in DIFFERENT epochs are not the same
    # trajectory
    a = gb._health_summary([epoch_row], [])
    b = gb._health_summary([e1_row], [])
    assert a["bad_steps"] != b["bad_steps"]


# ----------------------------------------------------------------------
# Satellite: Optimizer.clip_grad_norm.
# ----------------------------------------------------------------------


def test_clip_grad_norm_matches_hand_scaling():
    """clip_grad_norm=c scales a gradient of global norm g > c by
    exactly c/g before the optimizer sees it (SGD lr=1 makes the
    update the negated clipped gradient)."""
    from hydragnn_tpu.train.optimizer import select_optimizer

    tx = select_optimizer(
        {"Optimizer": {"type": "SGD", "learning_rate": 1.0,
                       "clip_grad_norm": 1.0}}
    )
    params = {"w": jnp.zeros((3,)), "b": jnp.zeros((2,))}
    grads = {
        "w": jnp.asarray([3.0, 0.0, 0.0]),
        "b": jnp.asarray([0.0, 4.0]),
    }  # global norm 5
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    np.testing.assert_allclose(
        np.asarray(updates["w"]), [-3.0 / 5.0, 0.0, 0.0], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(updates["b"]), [0.0, -4.0 / 5.0], rtol=1e-6
    )
    # under the threshold the update passes through untouched (optax
    # selects the unclipped branch — bitwise)
    small = {"w": jnp.asarray([0.3, 0.0, 0.0]), "b": jnp.asarray([0.0, 0.4])}
    updates, _ = tx.update(small, tx.init(params), params)
    assert np.array_equal(
        np.asarray(updates["w"]), -np.asarray(small["w"])
    )


def test_clip_grad_norm_default_off_and_lr_scheduler_compat():
    """Absent key -> the bare optimizer object (bitwise no-op); with
    clipping the LR scheduler still finds/sets the injected rate
    through the chain."""
    from hydragnn_tpu.train.optimizer import (
        get_learning_rate,
        select_optimizer,
        set_learning_rate,
    )

    base = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    params = {"w": jnp.ones((2,))}
    s = base.init(params)
    assert get_learning_rate(s) == pytest.approx(1e-3)
    clipped = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3,
                       "clip_grad_norm": 0.5}}
    )
    s2 = clipped.init(params)
    assert get_learning_rate(s2) == pytest.approx(1e-3)
    s2 = set_learning_rate(s2, 5e-4)
    assert get_learning_rate(s2) == pytest.approx(5e-4)
    # explicit 0 / None also mean off
    off = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3,
                       "clip_grad_norm": 0}}
    )
    assert get_learning_rate(off.init(params)) == pytest.approx(1e-3)


# ----------------------------------------------------------------------
# Satellite: bf16 overflow on the fused edge pipeline is caught.
# ----------------------------------------------------------------------


def test_bf16_fused_pipeline_overflow_guard(tiny_model, monkeypatch):
    """Adversarial activation scales through the PR-9 fused edge
    pipeline (pallas_fused, interpret mode on CPU) blow bf16 up to a
    non-finite loss on the unguarded step; the guarded step catches it
    on-device, skips the update, and reports ok=False."""
    import hydragnn_tpu.ops.pallas_segment as ps
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_train_step

    samples, model, cfg, tx, _, _ = tiny_model
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    calls = {"fused": 0}
    real = ps.edge_pipeline_planned

    def counting(a, b, w, *rest, **kw):
        calls["fused"] += 1
        return real(a, b, w, *rest, **kw)

    monkeypatch.setattr(ps, "edge_pipeline_planned", counting)
    loader = GraphLoader(samples, 4, with_segment_plan=True)
    batch = next(iter(loader))
    assert batch.seg_window is not None  # the plan actually attached
    # adversarial scale: bf16 max is ~3.39e38; products of
    # ~1e30-magnitude activations inside the conv stack overflow to inf
    hot = batch.replace(x=batch.x * jnp.float32(1e30) + jnp.float32(1e30))
    unguarded = make_train_step(
        model, tx, cfg, compute_dtype=jnp.bfloat16, donate=False
    )
    st = _fresh_state(tiny_model)
    _, tot_u, _ = unguarded(st, hot)
    assert not np.isfinite(float(tot_u)), (
        "adversarial scale failed to overflow the unguarded bf16 path"
    )
    guarded = make_train_step(
        model, tx, cfg, compute_dtype=jnp.bfloat16, donate=False,
        guard=True,
    )
    st0 = _fresh_state(tiny_model)
    st1, tot, tasks, ng, ok, gnorm = guarded(st0, hot)
    assert calls["fused"] > 0, "the fused kernel was never dispatched"
    assert not bool(ok)
    assert float(tot) == 0.0 and float(ng) == 0.0
    assert _leaves_equal(st0.params, st1.params)
    # and a sane batch through the same guarded build commits normally
    st2, tot2, _, ng2, ok2, _ = guarded(st1, batch)
    assert bool(ok2) and float(ng2) > 0 and np.isfinite(float(tot2))
    assert not _leaves_equal(st1.params, st2.params)


def test_multibranch_guard_and_autosave_wiring_end_to_end(
    tmp_path, monkeypatch
):
    """The full wiring, not just the builders: run_training under the
    multibranch scheme with Guard enabled and mid-epoch autosaves must
    (a) run the guarded step + per-branch monitor without tripping on
    healthy data, and (b) write mid-epoch resume containers whose
    manifest carries the per-branch cursors (the old multibranch
    autosave exclusion is gone)."""
    import glob
    import struct
    import json as _json

    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.utils import checkpoint as ck

    monkeypatch.setattr(ck, "CHECKPOINT_DIR", str(tmp_path))
    branch_sets = [_mols(24, seed=b) for b in range(2)]

    def split(s):
        n = len(s)
        return s[: n - 8], s[n - 8 : n - 4], s[n - 4 :]

    cfg = _config(batch_size=2, num_epoch=1)
    cfg["Dataset"] = {"name": "mb_guard"}
    cfg["NeuralNetwork"]["Architecture"]["output_heads"] = {
        "graph": [
            {
                "type": f"branch-{i}",
                "architecture": {
                    "num_sharedlayers": 1,
                    "dim_sharedlayers": 8,
                    "num_headlayers": 1,
                    "dim_headlayers": [8],
                },
            }
            for i in range(2)
        ]
    }
    t = cfg["NeuralNetwork"]["Training"]
    t["Parallelism"] = {"scheme": "multibranch"}
    t["Guard"] = True
    t["Checkpoint"] = {
        "enabled": True, "async": True, "interval_steps": 2,
    }
    state, _, _, hist, _ = run_training(
        cfg, datasets=[split(b) for b in branch_sets], seed=0
    )
    assert len(hist.train_loss) == 1
    assert np.isfinite(hist.train_loss[0])
    # the rolling container's manifest carries per-branch cursors
    paths = glob.glob(str(tmp_path / "*" / "resume.msgpack"))
    assert paths, "no resume container written"
    with open(paths[0], "rb") as f:
        head = f.read(len(ck._RESUME_MAGIC) + 8)
        (mlen,) = struct.unpack("<Q", head[len(ck._RESUME_MAGIC):])
        manifest = _json.loads(f.read(mlen).decode())
    assert manifest["branch_steps"] is not None
    assert len(manifest["branch_steps"]) == 2
    assert all(
        int(b) == int(manifest["step"])
        for b in manifest["branch_steps"]
    )
