"""Importable CPU-pinning preamble for ad-hoc scripts (same dance as
tests/conftest.py): force a virtual 8-device CPU platform even when
sitecustomize pre-registered an accelerator plugin."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:
    pass
