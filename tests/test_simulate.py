"""MD rollout engine (hydragnn_tpu/simulate/, docs/SIMULATION.md):
conservation on the NVE path, the bitwise K-macro == serial replay
contract (with neighbor rebuilds and the Langevin thermostat in the
loop), containment of injected overflow/non-finite events through the
policy ladder, interrupt/resume through the PR-6 writer, rollout
telemetry rows, and the config surface."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.simulate import (
    RolloutEngine,
    RolloutHalt,
    md_template_batch,
    run_simulation,
    simulation_settings,
    total_momentum,
)
from hydragnn_tpu.utils import faults
from tests.test_interatomic_potential import _mlip_config

N_ATOMS = 10
CUTOFF = 2.5


@pytest.fixture(scope="module")
def potential():
    """One tiny SchNet MLIP shared by every rollout test (random-init
    weights are a perfectly smooth potential — conservation and replay
    are properties of the ENGINE, not of training quality)."""
    rng = np.random.default_rng(0)
    pos = rng.uniform(0, 3.0, (N_ATOMS, 3)).astype(np.float32)
    x = np.ones((N_ATOMS, 1), np.float32)
    cfg = _mlip_config("node")
    model = create_model(cfg)
    ei = radius_graph(pos, CUTOFF)
    sample = GraphSample(
        x=x,
        pos=pos,
        edge_index=ei,
        energy=0.0,
        forces=np.zeros((N_ATOMS, 3), np.float32),
    )
    params, bs = init_params(model, collate([sample]))
    variables = {"params": params, "batch_stats": bs}
    return model, variables, cfg, sample


def _engine(potential, *, k=8, steps=24, max_edges=256, **sim):
    model, variables, cfg, sample = potential
    block = {
        "steps": steps,
        "dt": 2e-3,
        "superstep_k": k,
        "temperature_k": 0.2,
        "kb": 1.0,
        "seed": 3,
        "neighbor": {"skin": 0.2, "max_edges": max_edges},
    }
    block.update(sim)
    s = simulation_settings({"Simulation": block})
    tmpl = md_template_batch(
        np.asarray(sample.x), np.asarray(sample.pos), s.neighbor.max_edges
    )
    return RolloutEngine(model, variables, cfg, tmpl, s)


def test_nve_conservation_and_momentum(potential):
    """NVE velocity-Verlet over the MLIP: total energy drift stays
    bounded at this dt, and total momentum is conserved to fp
    tolerance (SchNet is translation-invariant, so forces sum to ~0)."""
    eng = _engine(potential, k=8, steps=40, dt=1e-3)
    res = eng.run(eng.init_state())
    assert res.stats["steps"] == 40
    total = res.energies + res.kinetic
    scale = max(abs(float(total[0])), float(res.kinetic[0]), 1e-3)
    drift = float(np.max(np.abs(total - total[0])))
    assert drift < 1e-3 * scale, (drift, scale)
    p = np.asarray(
        total_momentum(
            jnp.asarray(res.state.vel), eng.masses, eng.template.node_mask
        )
    )
    assert np.max(np.abs(p)) < 1e-4, p


def test_macro_bitwise_equals_serial(potential):
    """Same seed + same initial state ⇒ BITWISE-identical trajectory
    across serial (K=1) and K-macro dispatch, with the Langevin
    thermostat AND mid-run neighbor rebuilds in the loop (skin small
    enough that the displacement check fires)."""
    kw = dict(
        steps=32,
        thermostat="langevin",
        friction=0.5,
        neighbor={"skin": 0.02, "max_edges": 256},
    )
    e1 = _engine(potential, k=1, **kw)
    r1 = e1.run(e1.init_state(), record=True)
    e8 = _engine(potential, k=8, **kw)
    r8 = e8.run(e8.init_state(), record=True)
    assert r1.stats["rebuilds"] == r8.stats["rebuilds"] > 0
    assert np.array_equal(r1.trajectory, r8.trajectory)
    assert np.array_equal(r1.velocities, r8.velocities)
    assert np.array_equal(r1.energies, r8.energies)


def test_tail_macro_shorter_than_k(potential):
    """steps not divisible by K: the tail compiles a shorter trip
    count of the same body and stays bitwise on the serial curve."""
    e1 = _engine(potential, k=1, steps=11)
    r1 = e1.run(e1.init_state(), record=True)
    e4 = _engine(potential, k=4, steps=11)
    r4 = e4.run(e4.init_state(), record=True)
    assert r4.stats["steps"] == 11
    assert np.array_equal(r1.trajectory, r4.trajectory)


def test_overflow_containment_and_capacity_growth(potential):
    """An undersized neighbor capacity is a contained event: the
    overflow is detected on-device, the state never sees a truncated
    list, the ladder grows the capacity, and the completed trajectory
    is the same physics the roomy engine produces."""
    clean = _engine(potential, k=8, steps=24)
    res_clean = clean.run(clean.init_state(), record=True)
    tiny = _engine(potential, k=8, steps=24, max_edges=32)
    st = tiny.init_state()
    assert bool(jax.device_get(st.poisoned))  # t=0 overflow flagged
    res = tiny.run(st, record=True)
    assert res.stats["steps"] == 24
    assert res.stats["capacity_growths"] >= 1
    assert res.stats["capacity"] > 32
    assert [e["action"] for e in res.stats["events"]] == ["rebuild"] * res.stats[
        "capacity_growths"
    ]
    assert np.array_equal(res.trajectory, res_clean.trajectory)


def test_overflow_growths_exhausted_halts(potential):
    eng = _engine(
        potential,
        k=8,
        max_edges=32,
        guard={"max_capacity_growths": 0},
    )
    with pytest.raises(RolloutHalt, match="capacity growths exhausted"):
        eng.run(eng.init_state())


def test_injected_nonfinite_force_dt_halve(potential):
    """faults.py ``nan:force@10``: the poisoned step is a no-op, the
    state at the last good step is bit-preserved (trajectory prefix
    bitwise equals the clean run), dt halves, and the rollout still
    delivers every committed step."""
    clean = _engine(potential, k=8, steps=24)
    res_clean = clean.run(clean.init_state(), record=True)
    faults.install("nan:force@10")
    try:
        eng = _engine(potential, k=8, steps=24)
        res = eng.run(eng.init_state(), record=True)
    finally:
        faults.reset()
    assert res.stats["steps"] == 24
    assert res.stats["dt_halvings"] == 1
    assert res.stats["dt"] == pytest.approx(1e-3)
    assert [e["action"] for e in res.stats["events"]] == ["dt_halve"]
    # Steps 0..9 ran at the original dt before the injection landed:
    # bit-identical to the clean run; the post-policy suffix continues
    # at dt/2 from the PRESERVED step-9 state.
    assert np.array_equal(res.trajectory[:10], res_clean.trajectory[:10])
    assert not np.array_equal(
        res.trajectory[10:], res_clean.trajectory[10:]
    )
    assert np.all(np.isfinite(res.trajectory))


def test_injected_nonfinite_halt_policy(potential):
    faults.install("nan:force@5")
    try:
        eng = _engine(
            potential, k=8, guard={"on_nonfinite": "halt"}
        )
        with pytest.raises(RolloutHalt, match="non-finite"):
            eng.run(eng.init_state())
    finally:
        faults.reset()


def test_dt_halvings_exhausted_halts(potential):
    faults.install("nan:force@5")
    try:
        eng = _engine(
            potential, k=8, guard={"max_dt_halvings": 0}
        )
        with pytest.raises(RolloutHalt, match="halvings exhausted"):
            eng.run(eng.init_state())
    finally:
        faults.reset()


def test_checkpoint_interrupt_resume_bitwise(potential, tmp_path):
    """Trajectory checkpoint through the PR-6 CheckpointWriter: a
    rollout interrupted at step 16 and resumed from the container
    continues BITWISE on the uninterrupted trajectory."""
    from hydragnn_tpu.utils.checkpoint import (
        CheckpointWriter,
        load_resume_checkpoint,
    )

    kw = dict(steps=32, thermostat="langevin", friction=0.5)
    full = _engine(potential, k=8, **kw)
    res_full = full.run(full.init_state(), record=True)

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        w = CheckpointWriter("md_resume_test")
        first = _engine(potential, k=8, **kw)
        res_half = first.run(first.init_state(), 16, record=True)
        w.save(res_half.state, kind="auto", epoch=0, step=16)
        w.close()
        second = _engine(potential, k=8, **kw)
        template_state = second.init_state()
        restored, manifest = load_resume_checkpoint(
            "md_resume_test", template_state
        )
        assert manifest is not None and manifest["step"] == 16
        res_rest = second.run(restored, 16, record=True)
    finally:
        os.chdir(cwd)
    whole = np.concatenate([res_half.trajectory, res_rest.trajectory])
    assert np.array_equal(whole, res_full.trajectory)


def test_resume_adopts_policy_ladder(potential, tmp_path):
    """A resumed rollout must continue at the rungs the interrupted
    run had REACHED, not the config's starting rungs: the checkpoint
    manifest persists the ladder (dt, halvings, capacity, growths),
    and run_simulation adopts it before the restored state is used —
    otherwise the grown edge arrays trace at the wrong static shape
    and the trajectory silently integrates at the wrong dt."""
    model, variables, cfg, sample = potential
    config = {
        "Simulation": {
            "steps": 16,
            "dt": 2e-3,
            "superstep_k": 8,
            "temperature_k": 0.2,
            "kb": 1.0,
            "seed": 3,
            "log_name": "md_ladder_resume",
            "checkpoint": {"enabled": True, "interval_steps": 8},
            # Undersized: t=0 overflow forces a capacity growth.
            "neighbor": {"skin": 0.2, "max_edges": 32},
        }
    }
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        faults.install("nan:force@4")  # forces one dt halving too
        try:
            first = run_simulation(
                config,
                sample=sample,
                model=model,
                cfg=cfg,
                variables=variables,
            )
        finally:
            faults.reset()
        assert first.stats["capacity_growths"] >= 1
        assert first.stats["dt_halvings"] == 1
        grown = first.stats["capacity"]
        halved_dt = first.stats["dt"]

        config["Simulation"]["steps"] = 32
        second = run_simulation(
            config,
            sample=sample,
            model=model,
            cfg=cfg,
            variables=variables,
            resume=True,
        )
    finally:
        os.chdir(cwd)
    # Adopted, not reset: the continuation ran at the reached rungs
    # (a non-adopted engine would trace-fail on the grown [E'] edge
    # arrays, or silently integrate at the config dt).
    assert second.stats["dt"] == pytest.approx(halved_dt)
    assert second.stats["capacity"] == grown
    assert second.stats["steps"] == 16  # the remaining half only
    assert second.stats["events"] == []  # no re-escalation on resume
    assert np.all(np.isfinite(second.energies))


def test_rollout_telemetry_rows(potential, tmp_path):
    """Every macro emits a ``rollout`` row (docs/OBSERVABILITY.md);
    the rows carry the documented fields and graftboard aggregates
    them into the simulation section."""
    from hydragnn_tpu.utils import telemetry

    stream_path = str(tmp_path / "telemetry.jsonl")
    stream = telemetry.configure(
        {"Telemetry": {"enabled": True, "stream_path": stream_path}},
        "md_rows",
    )
    try:
        eng = _engine(potential, k=8, steps=24)
        eng.run(eng.init_state())
    finally:
        telemetry.close_run(stream)
    rows = [
        json.loads(line) for line in open(stream_path) if line.strip()
    ]
    rollout = [r for r in rows if r.get("t") == "rollout"]
    assert len(rollout) == 3  # 24 steps / K=8
    required = {
        "macro",
        "step",
        "k",
        "committed",
        "dt",
        "spec",
        "energy",
        "drift",
        "rebuilds",
        "overflow",
        "nonfinite",
        "dispatch_ms",
        "steps_per_sec",
        "ns_per_day",
    }
    for r in rollout:
        assert required <= set(r), sorted(required - set(r))
    assert rollout[-1]["step"] == 24
    assert all(r["overflow"] == 0 and not r["nonfinite"] for r in rollout)

    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    try:
        import graftboard

        rep = graftboard.build_report(stream_path)
    finally:
        sys.path.pop(0)
    rs = rep["rollout_summary"]
    assert rs["macros"] == 3
    assert rs["steps"] == 24
    assert rs["halts"] == 0 and rs["overflow_events"] == 0


def test_run_simulation_api(potential):
    """The public entry: config-driven rollout from a GraphSample over
    supplied variables."""
    model, variables, cfg, sample = potential
    config = {
        "Simulation": {
            "steps": 8,
            "dt": 1e-3,
            "superstep_k": 4,
            "temperature_k": 0.1,
            "kb": 1.0,
            "seed": 1,
            "record_trajectory": True,
            "neighbor": {"skin": 0.3, "max_edges": 256},
        }
    }
    res = run_simulation(
        config, sample=sample, model=model, cfg=cfg, variables=variables
    )
    assert res.stats["steps"] == 8
    assert res.trajectory.shape[0] == 8
    assert np.all(np.isfinite(res.energies))


def test_simulation_settings_validation():
    with pytest.raises(ValueError, match="thermostat"):
        simulation_settings({"Simulation": {"thermostat": "nose"}})
    with pytest.raises(ValueError, match="rebuild_policy"):
        simulation_settings(
            {"Simulation": {"neighbor": {"rebuild_policy": "sometimes"}}}
        )
    with pytest.raises(ValueError, match="on_nonfinite"):
        simulation_settings(
            {"Simulation": {"guard": {"on_nonfinite": "retry"}}}
        )
    with pytest.raises(ValueError, match="must be positive"):
        simulation_settings({"Simulation": {"steps": 0}})
    with pytest.raises(ValueError, match="capacity_growth"):
        simulation_settings(
            {"Simulation": {"guard": {"capacity_growth": 1.0}}}
        )


def test_update_config_rejects_unknown_simulation_keys():
    from hydragnn_tpu.config import update_config

    cfg = {"Simulation": {"steps": 4, "dtt": 1e-3}}
    with pytest.raises(ValueError, match="Simulation: unknown keys"):
        update_config(cfg)
    cfg = {"Simulation": {"neighbor": {"max_edge": 64}}}
    with pytest.raises(ValueError, match="Simulation.neighbor"):
        update_config(cfg)
    cfg = {"Simulation": {"guard": {"on_nonfinit": "halt"}}}
    with pytest.raises(ValueError, match="Simulation.guard"):
        update_config(cfg)
    # A well-formed block passes.
    update_config(
        {
            "Simulation": {
                "steps": 4,
                "dt": 1e-3,
                "neighbor": {"skin": 0.2, "max_edges": 64},
                "guard": {"on_nonfinite": "halt"},
                "checkpoint": {"enabled": True, "interval_steps": 8},
            }
        }
    )
