"""Optimizer registry (reference tests/test_optimizer.py), precision
control (tests/test_precision_control.py), and the loss/activation
registries (tests/test_loss_and_activation_functions.py).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp
import optax

from hydragnn_tpu.models.layers import activation
from hydragnn_tpu.train.losses import elementwise_loss, head_loss
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.state import cast_batch, resolve_precision

OPTIMIZERS = [
    "SGD",
    "Adam",
    "Adadelta",
    "Adagrad",
    "Adamax",
    "AdamW",
    "RMSprop",
    "LAMB",
]


@pytest.mark.parametrize("name", OPTIMIZERS)
def test_optimizer_steps(name):
    tx = select_optimizer(
        {"Optimizer": {"type": name, "learning_rate": 1e-2}}
    )
    params = {"w": jnp.ones(4)}
    st = tx.init(params)
    g = {"w": jnp.ones(4)}
    updates, st = tx.update(g, st, params)
    new = optax.apply_updates(params, updates)
    assert np.all(np.asarray(new["w"]) < 1.0)  # moved against gradient


def test_unknown_optimizer_raises():
    with pytest.raises(ValueError, match="ptimizer"):
        select_optimizer({"Optimizer": {"type": "Nope"}})


@pytest.mark.parametrize(
    "precision,param_dt,compute_dt",
    [
        ("bf16", jnp.float32, jnp.bfloat16),
        ("fp32", jnp.float32, jnp.float32),
    ],
)
def test_resolve_precision(precision, param_dt, compute_dt):
    p, c = resolve_precision(precision)
    assert p == param_dt and c == compute_dt


def test_resolve_precision_invalid():
    with pytest.raises(ValueError, match="precision"):
        resolve_precision("fp8")


def test_cast_batch_dtypes():
    from hydragnn_tpu.data.graph import GraphSample, collate
    from hydragnn_tpu.ops.neighbors import radius_graph

    r = np.random.default_rng(0)
    pos = r.uniform(0, 2.0, (5, 3)).astype(np.float32)
    s = GraphSample(
        x=r.normal(size=(5, 2)).astype(np.float32),
        pos=pos,
        edge_index=radius_graph(pos, 2.0),
        y_graph=np.zeros(1, np.float32),
    )
    b = collate([s])
    cb = cast_batch(b, jnp.bfloat16)
    assert cb.x.dtype == jnp.bfloat16
    assert cb.pos.dtype == jnp.bfloat16
    # integer index arrays and masks must not be cast
    assert cb.senders.dtype == jnp.int32
    assert cb.node_mask.dtype == jnp.bool_
    # targets stay full precision for the loss
    assert cb.y_graph.dtype == jnp.float32


ACTIVATIONS = [
    "relu",
    "selu",
    "prelu",
    "elu",
    "lrelu_01",
    "lrelu_025",
    "lrelu_05",
    "sigmoid",
    "shifted_softplus",
    "silu",
    "tanh",
]


@pytest.mark.parametrize("name", ACTIVATIONS)
def test_activation_registry(name):
    fn = activation(name)
    x = jnp.asarray([-1.0, 0.0, 2.0])
    y = np.asarray(fn(x))
    assert y.shape == (3,) and np.isfinite(y).all()


def test_unknown_activation_raises():
    with pytest.raises(ValueError, match="activation"):
        activation("swoosh")


def test_elementwise_losses():
    p = jnp.asarray([1.0, 2.0, 3.0])
    t = jnp.asarray([1.5, 2.0, 1.0])
    np.testing.assert_allclose(
        np.asarray(elementwise_loss("mse", p, t)), [0.25, 0.0, 4.0]
    )
    np.testing.assert_allclose(
        np.asarray(elementwise_loss("mae", p, t)), [0.5, 0.0, 2.0]
    )
    sl1 = np.asarray(elementwise_loss("smooth_l1", p, t))
    np.testing.assert_allclose(sl1, [0.125, 0.0, 1.5])
    with pytest.raises(ValueError):
        elementwise_loss("hinge", p, t)


def test_head_loss_rmse_and_gaussian_nll():
    p = jnp.asarray([[1.0], [3.0]])
    t = jnp.asarray([[2.0], [5.0]])
    mask = jnp.asarray([True, True])
    rmse = float(head_loss("rmse", p, t, mask))
    np.testing.assert_allclose(rmse, np.sqrt((1 + 4) / 2), rtol=1e-6)
    var = jnp.asarray([[1.0], [1.0]])
    nll = float(head_loss("GaussianNLLLoss", p, t, mask, var))
    np.testing.assert_allclose(nll, 0.5 * (1 + 4) / 2, rtol=1e-6)


def test_masked_loss_ignores_padding():
    p = jnp.asarray([[1.0], [100.0]])
    t = jnp.asarray([[2.0], [0.0]])
    mask = jnp.asarray([True, False])
    v = float(head_loss("mse", p, t, mask))
    np.testing.assert_allclose(v, 1.0, rtol=1e-6)


# ----------------------------------------------------------------------
# bf16 end-to-end converged-loss parity (ISSUE 9). TOLERANCE CONTRACT:
# bf16 training (fp32 master weights via optax, bf16 compute through
# resolve_precision/cast_batch — no loss scaling) must CONVERGE (train
# loss < 0.15 from ~1.3 at init after 25 epochs) and land within 25%
# relative (+0.02 absolute floor) of the fp32 converged loss on the
# same seed, and the same under the fused Pallas edge pipeline
# (HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused, interpret mode on CPU).
# Bitwise identity is explicitly NOT the contract (docs/ROOFLINE.md
# "Fused edge pipeline"); measured gap on this problem is ~15% at the
# 25-epoch point (0.078 vs 0.092 — late-training losses are small so
# relative noise is wide), while any real precision break leaves the
# run orders of magnitude off the convergence gate.
# ----------------------------------------------------------------------


def _schnet_samples(n=24, seed=0):
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.ops.neighbors import radius_graph

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        na = int(rng.integers(6, 12))
        pos = rng.uniform(0, 2.0 * na ** (1 / 3), size=(na, 3))
        x = rng.integers(0, 4, size=(na, 1)).astype(np.float32)
        ei = radius_graph(pos, 3.0, max_neighbours=12)
        # Learnable structural target: mean feature + size term.
        y = float(x.mean() + 0.05 * na)
        out.append(
            GraphSample(
                x=x,
                pos=pos.astype(np.float32),
                edge_index=ei,
                y_graph=np.array([y], np.float32),
            )
        )
    return out


def _train_tiny_schnet(precision, epochs=25, seed=0, with_plan=False):
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.train.loop import _run_epoch, make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    samples = _schnet_samples()
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 3.0,
                "max_neighbours": 12,
                "num_gaussians": 8,
                "num_filters": 16,
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": 8,
                "precision": precision,
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
            },
        }
    }
    config = update_config(config, samples)
    _, compute_dtype = resolve_precision(
        config["NeuralNetwork"]["Training"]["precision"]
    )
    loader = GraphLoader(
        samples, 8, shuffle=True, seed=seed, with_segment_plan=with_plan
    )
    model, cfg = create_model_config(config)
    params, bs = init_params(model, next(iter(loader)))
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    step = make_train_step(
        model, tx, cfg, compute_dtype=compute_dtype, donate=False
    )
    state = create_train_state(params, tx, bs)
    loss = float("nan")
    for ep in range(epochs):
        loader.set_epoch(ep)
        state, loss, _ = _run_epoch(step, state, loader, train=True)
    return loss


@pytest.mark.parametrize(
    "variant", ["bf16", "bf16_fused", "bf16_fused_vjp"]
)
def test_bf16_converged_loss_parity(variant, monkeypatch):
    """bf16 (and bf16 + fused Pallas edge pipeline) converges, and
    lands within the documented 25%-relative/+0.02 tolerance of the
    fp32 converged loss. The ``bf16_fused_vjp`` leg attaches segment
    plans to every batch, so with pallas_fused forced the symmetric
    Pallas BACKWARD carries every gradient of the whole 25-epoch run
    (ISSUE 18) — the end-to-end complement of the fixed-cotangent
    parity tests in test_pallas_segment.py."""
    if variant.startswith("bf16_fused"):
        monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    else:
        monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    loss16 = _train_tiny_schnet(
        "bf16", with_plan=variant == "bf16_fused_vjp"
    )
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    loss32 = _train_tiny_schnet("fp32")
    assert np.isfinite(loss16) and np.isfinite(loss32)
    # both converged (the synthetic target starts at loss ~1.3)
    assert loss32 < 0.15, loss32
    assert loss16 < 0.15, loss16
    assert abs(loss16 - loss32) <= 0.25 * abs(loss32) + 0.02, (
        loss16,
        loss32,
    )
