"""Optimizer registry (reference tests/test_optimizer.py), precision
control (tests/test_precision_control.py), and the loss/activation
registries (tests/test_loss_and_activation_functions.py).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp
import optax

from hydragnn_tpu.models.layers import activation
from hydragnn_tpu.train.losses import elementwise_loss, head_loss
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.state import cast_batch, resolve_precision

OPTIMIZERS = [
    "SGD",
    "Adam",
    "Adadelta",
    "Adagrad",
    "Adamax",
    "AdamW",
    "RMSprop",
    "LAMB",
]


@pytest.mark.parametrize("name", OPTIMIZERS)
def test_optimizer_steps(name):
    tx = select_optimizer(
        {"Optimizer": {"type": name, "learning_rate": 1e-2}}
    )
    params = {"w": jnp.ones(4)}
    st = tx.init(params)
    g = {"w": jnp.ones(4)}
    updates, st = tx.update(g, st, params)
    new = optax.apply_updates(params, updates)
    assert np.all(np.asarray(new["w"]) < 1.0)  # moved against gradient


def test_unknown_optimizer_raises():
    with pytest.raises(ValueError, match="ptimizer"):
        select_optimizer({"Optimizer": {"type": "Nope"}})


@pytest.mark.parametrize(
    "precision,param_dt,compute_dt",
    [
        ("bf16", jnp.float32, jnp.bfloat16),
        ("fp32", jnp.float32, jnp.float32),
    ],
)
def test_resolve_precision(precision, param_dt, compute_dt):
    p, c = resolve_precision(precision)
    assert p == param_dt and c == compute_dt


def test_resolve_precision_invalid():
    with pytest.raises(ValueError, match="precision"):
        resolve_precision("fp8")


def test_cast_batch_dtypes():
    from hydragnn_tpu.data.graph import GraphSample, collate
    from hydragnn_tpu.ops.neighbors import radius_graph

    r = np.random.default_rng(0)
    pos = r.uniform(0, 2.0, (5, 3)).astype(np.float32)
    s = GraphSample(
        x=r.normal(size=(5, 2)).astype(np.float32),
        pos=pos,
        edge_index=radius_graph(pos, 2.0),
        y_graph=np.zeros(1, np.float32),
    )
    b = collate([s])
    cb = cast_batch(b, jnp.bfloat16)
    assert cb.x.dtype == jnp.bfloat16
    assert cb.pos.dtype == jnp.bfloat16
    # integer index arrays and masks must not be cast
    assert cb.senders.dtype == jnp.int32
    assert cb.node_mask.dtype == jnp.bool_
    # targets stay full precision for the loss
    assert cb.y_graph.dtype == jnp.float32


ACTIVATIONS = [
    "relu",
    "selu",
    "prelu",
    "elu",
    "lrelu_01",
    "lrelu_025",
    "lrelu_05",
    "sigmoid",
    "shifted_softplus",
    "silu",
    "tanh",
]


@pytest.mark.parametrize("name", ACTIVATIONS)
def test_activation_registry(name):
    fn = activation(name)
    x = jnp.asarray([-1.0, 0.0, 2.0])
    y = np.asarray(fn(x))
    assert y.shape == (3,) and np.isfinite(y).all()


def test_unknown_activation_raises():
    with pytest.raises(ValueError, match="activation"):
        activation("swoosh")


def test_elementwise_losses():
    p = jnp.asarray([1.0, 2.0, 3.0])
    t = jnp.asarray([1.5, 2.0, 1.0])
    np.testing.assert_allclose(
        np.asarray(elementwise_loss("mse", p, t)), [0.25, 0.0, 4.0]
    )
    np.testing.assert_allclose(
        np.asarray(elementwise_loss("mae", p, t)), [0.5, 0.0, 2.0]
    )
    sl1 = np.asarray(elementwise_loss("smooth_l1", p, t))
    np.testing.assert_allclose(sl1, [0.125, 0.0, 1.5])
    with pytest.raises(ValueError):
        elementwise_loss("hinge", p, t)


def test_head_loss_rmse_and_gaussian_nll():
    p = jnp.asarray([[1.0], [3.0]])
    t = jnp.asarray([[2.0], [5.0]])
    mask = jnp.asarray([True, True])
    rmse = float(head_loss("rmse", p, t, mask))
    np.testing.assert_allclose(rmse, np.sqrt((1 + 4) / 2), rtol=1e-6)
    var = jnp.asarray([[1.0], [1.0]])
    nll = float(head_loss("GaussianNLLLoss", p, t, mask, var))
    np.testing.assert_allclose(nll, 0.5 * (1 + 4) / 2, rtol=1e-6)


def test_masked_loss_ignores_padding():
    p = jnp.asarray([[1.0], [100.0]])
    t = jnp.asarray([[2.0], [0.0]])
    mask = jnp.asarray([True, False])
    v = float(head_loss("mse", p, t, mask))
    np.testing.assert_allclose(v, 1.0, rtol=1e-6)
