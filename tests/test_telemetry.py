"""Run-telemetry subsystem (ISSUE 7, docs/OBSERVABILITY.md): the
bounded non-blocking stream writer (incl. fault posture via
utils/faults.py), the step clock across every feed/scheme combination
(serial, pipeline, superstep, dp), per-epoch rollups bit-equal to the
loop's History, live MFU consistent with bench.py's flop arithmetic to
1e-9 relative, the compile/retrace observer, graftboard parsing (incl.
the truncated-tail tolerance), and the RegionTimer.reset regression.

Training runs use a uniform-size dataset so the packed plan is a
single budget spec — epoch 0 warms every executable and the
zero-post-warmup-recompiles assertions are deterministic.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401  (side effect: pin 8-device CPU platform)

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample
from hydragnn_tpu.data.loader import split_dataset
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import graftboard  # noqa: E402

sys.path.remove(os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    """No cross-test leakage: detach any active stream/observer and
    disarm faults before AND after every test."""
    telemetry.install(None)
    obs = telemetry.observer()
    if obs is not None:
        obs.close()
    faults.reset()
    yield
    telemetry.install(None)
    obs = telemetry.observer()
    if obs is not None:
        obs.close()
    faults.reset()


def _uniform_samples(n, seed=11, n_nodes=6):
    rng = np.random.default_rng(seed)
    pos = rng.uniform(0, 3.0, size=(n_nodes, 3))
    x = rng.integers(0, 3, size=(n_nodes, 1)).astype(np.float32)
    ei = radius_graph(pos, 2.5, max_neighbours=16)
    return [
        GraphSample(
            x=x.copy(),
            pos=pos.astype(np.float32),
            edge_index=ei.copy(),
            y_graph=np.array([rng.normal()], dtype=np.float32),
        )
        for _ in range(n)
    ]


def _tiny_config(batch_size=4, num_epoch=2, **parallelism):
    cfg = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "max_neighbours": 16,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["energy"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "num_epoch": num_epoch,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            },
        }
    }
    if parallelism:
        cfg["NeuralNetwork"]["Training"]["Parallelism"] = parallelism
    return cfg


def _run(tmp_path, config, n_samples=48, seed=0, sync_interval=0):
    from hydragnn_tpu.runner import run_training

    stream_path = str(tmp_path / "telemetry.jsonl")
    config["NeuralNetwork"]["Training"]["Telemetry"] = {
        "enabled": True,
        "stream_path": stream_path,
        "sync_interval_steps": sync_interval,
    }
    samples = _uniform_samples(n_samples)
    tr, va, te = split_dataset(samples, 0.8)
    state, model, cfg, hist, _ = run_training(
        config, datasets=(tr, va, te), seed=seed
    )
    rows = [json.loads(line) for line in open(stream_path)]
    return rows, hist, cfg, stream_path


# ---------------------------------------------------------------------------
# RegionTimer.reset regression (satellite 1)


def test_region_timer_reset_preserves_enabled():
    """reset() used to re-run __init__, silently re-enabling a tracer
    that was explicitly disabled."""
    from hydragnn_tpu.utils.tracer import RegionTimer

    t = RegionTimer()
    t.start("r")
    t.stop("r")
    t.disable()
    t.reset()
    assert t.enabled is False, "reset() re-enabled a disabled tracer"
    t.start("r")
    t.stop("r")
    assert t.totals == {}, "disabled tracer recorded after reset()"
    t.enable()
    t.reset()
    assert t.enabled is True  # and reset keeps an enabled one enabled
    t.start("r")
    t.stop("r")
    assert "r" in t.totals


# ---------------------------------------------------------------------------
# Stream writer + fault posture (satellite 2)


def test_stream_roundtrip_header_first_and_close_accounting(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p, meta={"log_name": "x"})
    for i in range(20):
        assert s.emit({"t": "step", "i": i})
    s.close()
    rows = [json.loads(line) for line in open(p)]
    assert rows[0]["t"] == "header"
    assert rows[0]["schema"] == telemetry.SCHEMA_VERSION
    assert rows[0]["log_name"] == "x"
    assert [r["i"] for r in rows if r["t"] == "step"] == list(range(20))
    close = rows[-1]
    assert close["t"] == "close"
    assert close["dropped"] == 0 and close["write_errors"] == 0
    # closed stream refuses quietly
    assert s.emit({"t": "late"}) is False


def test_stream_overflow_drops_with_counter_never_blocks(tmp_path):
    """A stalled writer (slow_write fault on the stream path) must
    never stall emit(): rows drop with a counter instead."""
    p = str(tmp_path / "slow" / "t.jsonl")
    faults.install("slow_write:slow:5.0:100")
    s = telemetry.TelemetryStream(p, queue_depth=64)
    t0 = time.perf_counter()
    for i in range(500):
        s.emit({"t": "step", "i": i})
    emit_s = time.perf_counter() - t0
    assert emit_s < 1.0, f"emit() stalled the caller: {emit_s:.2f}s"
    assert s.dropped > 0, "queue overflow did not count drops"
    faults.reset()
    s.close()


def test_stream_write_failure_never_crashes_or_stalls(tmp_path):
    """All writes failing: training-side emit stays fast, the stream
    surfaces on write_errors/last_error, close() does not raise."""
    p = str(tmp_path / "fail" / "t.jsonl")
    faults.install("write_fail:fail:9999")
    s = telemetry.TelemetryStream(p, queue_depth=256)
    for i in range(100):
        s.emit({"t": "step", "i": i})
    s.flush(10.0)
    s.close()
    assert s.write_errors > 0
    assert s.last_error is not None
    assert s.lost_rows > 0
    # accounting invariant: every emitted row is written XOR lost,
    # never double-counted (flush()'s drained test depends on it)
    assert s.written + s.lost_rows <= s.emitted
    faults.reset()


def test_stream_recovers_after_transient_write_failure(tmp_path):
    p = str(tmp_path / "flaky" / "t.jsonl")
    s = telemetry.TelemetryStream(p, queue_depth=256)
    s.emit({"t": "a"})
    assert s.flush(10.0)
    faults.install("write_fail:flaky:1")
    s.emit({"t": "b"})
    s.flush(10.0)
    faults.reset()
    s.emit({"t": "c"})
    s.close()
    kinds = [json.loads(line)["t"] for line in open(p)]
    assert "a" in kinds and "c" in kinds  # 'b' was the injected loss
    assert s.write_errors >= 1


def test_graftboard_skips_truncated_tail_line(tmp_path):
    """A SIGKILL mid-write leaves a truncated tail line; graftboard
    must skip-and-count it, never die."""
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    s.emit({"t": "epoch", "epoch": 0, "train_loss": 1.5})
    s.close()
    with open(p, "a") as f:
        f.write('{"t":"step","epoch":1,"trunc')  # no newline, cut mid-key
    rep = graftboard.build_report(p)
    assert rep["skipped_lines"] == 1
    assert rep["train_loss_by_epoch"] == [1.5]


# ---------------------------------------------------------------------------
# Config grammar


def test_telemetry_settings_block_and_envs(monkeypatch):
    st = telemetry.telemetry_settings(
        {"Telemetry": {"enabled": True, "sync_interval_steps": 7}}
    )
    assert st.enabled and st.sync_interval_steps == 7
    assert telemetry.telemetry_settings({"Telemetry": True}).enabled
    assert not telemetry.telemetry_settings({}).enabled
    monkeypatch.setenv("HYDRAGNN_TPU_TELEMETRY", "1")
    monkeypatch.setenv("HYDRAGNN_TPU_TELEMETRY_STREAM", "/tmp/x.jsonl")
    monkeypatch.setenv("HYDRAGNN_TPU_TELEMETRY_SYNC", "5")
    st = telemetry.telemetry_settings({})
    assert st.enabled and st.stream_path == "/tmp/x.jsonl"
    assert st.sync_interval_steps == 5
    monkeypatch.setenv("HYDRAGNN_TPU_TELEMETRY", "0")
    assert not telemetry.telemetry_settings(
        {"Telemetry": {"enabled": True}}
    ).enabled  # env wins both ways


def test_update_config_rejects_unknown_telemetry_key():
    from hydragnn_tpu.config import update_config

    cfg = _tiny_config()
    cfg["NeuralNetwork"]["Training"]["Telemetry"] = {
        "enabled": True,
        "sync_interval": 5,  # misspelled: must fail EAGERLY
    }
    with pytest.raises(ValueError, match="Telemetry"):
        update_config(cfg, _uniform_samples(8))


# ---------------------------------------------------------------------------
# The step clock across feeds/schemes + bit-equal rollups + MFU


def _breakdown_keys(rows):
    return {
        (r["region"], r["feed"], r["scheme"])
        for r in rows
        if r["t"] == "step"
    }


def _assert_losses_bit_equal(rows, hist):
    ep = sorted(
        (r for r in rows if r["t"] == "epoch"),
        key=lambda r: r["epoch"],
    )
    assert [r["train_loss"] for r in ep] == hist.train_loss
    assert [r["val_loss"] for r in ep] == hist.val_loss
    assert [r["test_loss"] for r in ep] == hist.test_loss


def _assert_mfu_consistent(rows, cfg):
    """The acceptance contract: per-spec MFU in the stream reproduces
    bench.py's flop arithmetic (the SAME utils/flops function over the
    row's own emitted fields) to 1e-9 relative."""
    from hydragnn_tpu.utils.flops import model_flops_per_graph

    mfu_rows = [
        r for r in rows if r["t"] == "spec_rollup" and "mfu" in r
    ]
    assert mfu_rows, "no MFU rows in the stream"
    for r in mfu_rows:
        mf = model_flops_per_graph(cfg, r["mean_nodes"], r["mean_edges"])
        expect = mf * r["graphs"] / (r["wall_ms"] / 1e3) / r["peak_flops"]
        assert abs(r["mfu"] - expect) <= 1e-9 * abs(expect), (
            r["spec"],
            r["mfu"],
            expect,
        )
        assert r["model_flops_per_graph"] == mf


def test_serial_feed_stream(tmp_path):
    rows, hist, cfg, path = _run(
        tmp_path,
        _tiny_config(
            scheme="single",
            pipeline={"workers": 0},
            packing={"enabled": True},
        ),
        sync_interval=3,
    )
    keys = _breakdown_keys(rows)
    assert ("train", "prefetch", "single") in keys or (
        "train",
        "serial",
        "single",
    ) in keys
    _assert_losses_bit_equal(rows, hist)
    _assert_mfu_consistent(rows, cfg)
    # sampled device fences appeared (sync_interval=3) but ONLY there
    fenced = [
        r
        for r in rows
        if r["t"] == "step" and "device_complete_ms" in r
    ]
    assert fenced, "sync_interval_steps=3 produced no fence samples"
    # per-step rows carry spec + plan-domain real sizes + loss + lr
    st = [r for r in rows if r["t"] == "step" and r["region"] == "train"]
    assert all("spec" in r and "loss" in r and "lr" in r for r in st)
    assert all(
        r["nodes"] <= r["nodes_pad"] and r["graphs_plan"] <= r["graphs_pad"]
        for r in st
        if "nodes" in r
    )
    # zero post-warmup recompiles on the stable packed run
    rep = graftboard.build_report(path)
    assert rep["post_warmup_compiles"] == 0
    assert rep["drops"] == 0


def test_pipeline_feed_stream(tmp_path):
    rows, hist, _, _ = _run(
        tmp_path,
        _tiny_config(
            scheme="single",
            pipeline={"workers": 2, "depth": 2},
            packing={"enabled": True},
        ),
    )
    keys = _breakdown_keys(rows)
    assert any(
        k[0] == "train" and "pipeline" in k[1] for k in keys
    ), keys
    _assert_losses_bit_equal(rows, hist)
    # pipeline counters routed into the same stream
    assert any(r["t"] == "pipeline" for r in rows)


def test_superstep_feed_stream(tmp_path):
    rows, hist, _, _ = _run(
        tmp_path,
        _tiny_config(
            scheme="single",
            pipeline={"workers": 0},
            packing={"enabled": True},
            superstep={"steps": 4},
        ),
    )
    st = [r for r in rows if r["t"] == "step" and r["region"] == "train"]
    macro = [r for r in st if r["k"] > 1]
    assert macro, "superstep run emitted no K>1 dispatch rows"
    assert all(r["k"] == 4 for r in macro)
    assert all("loss_sum" in r for r in macro), (
        "macro rows must carry the cumulative loss_sum ref"
    )
    assert any("superstep" in k[1] for k in _breakdown_keys(rows))
    # K steps per dispatch: plan sizes aggregate k*d entries
    assert all(
        r["graphs_plan"] >= r["k"] for r in macro if "graphs_plan" in r
    )
    _assert_losses_bit_equal(rows, hist)


def test_dp_feed_stream(tmp_path):
    assert len(jax.devices()) >= 8
    rows, hist, cfg, _ = _run(
        tmp_path,
        _tiny_config(
            batch_size=2,
            scheme="dp",
            data=8,
            pipeline={"workers": 0},
            packing={"enabled": True},
        ),
        n_samples=160,
    )
    st = [r for r in rows if r["t"] == "step" and r["region"] == "train"]
    assert st and all(r["lanes"] == 8 for r in st)
    assert all(r["scheme"] == "dp" for r in st)
    assert any("dp" in k[1] for k in _breakdown_keys(rows))
    _assert_losses_bit_equal(rows, hist)
    _assert_mfu_consistent(rows, cfg)


def test_telemetry_off_is_inert(tmp_path):
    """No active stream: epoch_clock returns None and the loop runs
    the pre-telemetry path (no stream file, no context mutation)."""
    from hydragnn_tpu.data.loader import GraphLoader

    telemetry.install(None)
    assert telemetry.epoch_clock(
        GraphLoader(_uniform_samples(8), 4), "train"
    ) is None
    assert telemetry.emit({"t": "x"}) is False


# ---------------------------------------------------------------------------
# Compile observer (satellite 3)


def test_compile_observer_flags_shape_unstable_fn():
    obs = telemetry.install_observer()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones((3,)))  # warmup phase 0
    n_warm = obs.compile_count
    assert n_warm > 0
    obs.set_phase(1)
    f(jnp.ones((3,)))  # cache hit: no compile
    assert obs.compile_count == n_warm
    assert obs.post_warmup == []
    f(jnp.ones((9,)))  # NEW shape after warmup = retrace leak
    assert obs.compile_count > n_warm
    assert obs.post_warmup, "shape-unstable fn not flagged"
    assert all(ev["epoch"] == 1 for ev in obs.post_warmup)
    obs.close()


def test_compile_observer_stable_run_is_clean():
    obs = telemetry.install_observer()
    g = jax.jit(lambda x: x - 1)
    g(jnp.ones((4,)))
    obs.set_phase(1)
    for _ in range(3):
        g(jnp.ones((4,)))  # stable spec: replayed executable
    assert obs.post_warmup == []
    obs.close()


def test_compile_observer_idempotent_install_and_clean_close():
    obs1 = telemetry.install_observer()
    obs1.install()  # double install: no double counting
    h = jax.jit(lambda x: x + 3)
    h(jnp.ones((5,)))
    count1 = obs1.compile_count
    assert count1 >= 1
    obs1.close()
    # a closed observer receives nothing (no cross-test leakage)
    h(jnp.ones((6,)))
    assert obs1.compile_count == count1
    # and a NEW observer takes over cleanly
    obs2 = telemetry.install_observer()
    h(jnp.ones((7,)))
    assert obs2.compile_count >= 1
    assert obs1.compile_count == count1
    obs2.close()
    assert telemetry.observer() is None


def test_compile_observer_emits_rows_and_summary(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    obs = telemetry.CompileObserver(s, warmup_phase=1).install()
    f = jax.jit(lambda x: x * 5)
    f(jnp.ones((3,)))
    obs.set_phase(2)
    f(jnp.ones((4,)))
    obs.close()
    s.close()
    rows = [json.loads(line) for line in open(p)]
    compiles = [r for r in rows if r["t"] == "compile"]
    assert compiles
    assert any(r["retrace_leak"] and r["epoch"] == 2 for r in compiles)
    summary = [r for r in rows if r["t"] == "compile_summary"]
    assert summary and summary[0]["post_warmup_compiles"] >= 1


# ---------------------------------------------------------------------------
# graftboard report + diff


def test_graftboard_report_and_diff_cli(tmp_path, capsys):
    cfg_a = _tiny_config(
        scheme="single",
        pipeline={"workers": 0},
        packing={"enabled": True},
    )
    rows_a, hist_a, _, path_a = _run(tmp_path / "a", cfg_a)
    cfg_b = _tiny_config(
        scheme="single",
        pipeline={"workers": 0},
        packing={"enabled": True},
    )
    rows_b, hist_b, _, path_b = _run(tmp_path / "b", cfg_b)
    assert graftboard.main(["report", path_a]) == 0
    out = capsys.readouterr().out
    assert "step-time breakdown" in out and "compiles:" in out
    # identical config+seed => identical loss curves in the diff
    assert graftboard.main(["diff", path_a, path_b, "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["loss_identical"] is True
    assert d["train_loss_a"] == hist_a.train_loss
    assert d["post_warmup_compiles"]["a"] == 0
    # directory resolution: logs/<name>/telemetry.jsonl layout
    run_dir = tmp_path / "dir"
    run_dir.mkdir()
    os.rename(path_a, run_dir / "telemetry.jsonl")
    assert graftboard.build_report(str(run_dir))["rows"] > 0
    assert graftboard.main(["report", str(tmp_path / "missing")]) == 2


def test_checkpoint_rows_routed_into_stream(tmp_path):
    cfg = _tiny_config(
        scheme="single",
        pipeline={"workers": 0},
        packing={"enabled": True},
    )
    cfg["NeuralNetwork"]["Training"]["Checkpoint"] = {
        "enabled": True,
        "async": True,
        "interval_steps": 3,
    }
    os.chdir(tmp_path)  # checkpoints land under ./logs
    try:
        rows, _, _, _ = _run(tmp_path, cfg)
    finally:
        os.chdir(REPO)
    ck = [r for r in rows if r["t"] == "checkpoint"]
    saves = [r for r in ck if r["event"] == "save"]
    writes = [r for r in ck if r["event"] == "write"]
    assert saves and writes
    assert all("snapshot_block_ms" in r for r in saves)
    assert all("serialize_write_ms" in r for r in writes)
    assert not any(r.get("failed") for r in writes)


# ---------------------------------------------------------------------------
# Roofline attribution (ISSUE 8): header self-description, executable
# cost/memory rows, hw rollups, memory rows, profiler alignment,
# graftboard roofline/diff


def test_header_self_description(tmp_path):
    """graftboard roofline/diff resolve their peak basis from the
    header instead of guessing: device/jax/host facts + both peaks."""
    jax.devices()  # ensure the backend is live (order-independence)
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    s.close()
    hdr = json.loads(open(p).readline())
    assert hdr["t"] == "header"
    assert hdr["device_kind"] == "cpu" and hdr["platform"] == "cpu"
    assert hdr["jax_version"] == jax.__version__
    assert hdr["hostname"] and hdr["device_count"] >= 1
    assert hdr["process_count"] == 1
    # CPU host: both peaks fall back to the ROOFLINE anchor, flagged
    assert hdr["peak_flops"] > 0 and hdr["peak_basis"] == "roofline_anchor"
    assert hdr["peak_hbm_bytes_per_sec"] > 0
    assert hdr["peak_hbm_basis"] == "roofline_anchor"


def test_compiled_cost_stats_matches_raw_cost_analysis():
    """The shared parse (bench dedupe satellite): flops/bytes equal the
    raw Compiled.cost_analysis values bench.py used to parse inline."""
    from hydragnn_tpu.utils.flops import (
        compiled_cost_stats,
        compiled_memory_stats,
    )

    f = jax.jit(lambda x: jnp.tanh(x @ x.T).sum())
    compiled = f.lower(jnp.ones((16, 16))).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    cost = compiled_cost_stats(compiled)
    assert cost["flops"] == float(ca["flops"]) > 0
    assert cost["bytes_accessed"] == float(ca["bytes accessed"]) > 0
    mem = compiled_memory_stats(compiled)
    ma = compiled.memory_analysis()
    assert mem["argument_bytes"] == int(ma.argument_size_in_bytes)
    assert mem["temp_bytes"] == int(ma.temp_size_in_bytes)
    # unavailable backends degrade to {} (never fabricate)
    class _NoCost:
        def cost_analysis(self):
            raise NotImplementedError

        def memory_analysis(self):
            return None

    assert compiled_cost_stats(_NoCost()) == {}
    assert compiled_memory_stats(_NoCost()) == {}


def test_resolve_peak_bandwidth_anchor_and_device():
    from hydragnn_tpu.utils.flops import (
        PEAK_HBM_BYTES_PER_SEC,
        resolve_peak_bandwidth,
    )

    bw, basis = resolve_peak_bandwidth("TPU v4")
    assert bw == PEAK_HBM_BYTES_PER_SEC["TPU v4"] and basis == "device"
    # unknown kind -> ROOFLINE_TPU.txt anchor (its measured header)
    bw, basis = resolve_peak_bandwidth("cpu")
    assert basis == "roofline_anchor" and bw == 819.0e9


def _exec_rows(rows):
    return [r for r in rows if r["t"] == "executable"]


def test_executable_rows_hw_rollups_and_roofline_cli(tmp_path, capsys):
    """One end-to-end packed run: every compiled spec gets ONE
    executable row with counted flops/bytes/memory footprint; rollups
    gain hw-MFU + intensity reproducible from their own emitted fields
    to 1e-9; graftboard roofline renders a bound-ness verdict per spec
    (anchor what-if flagged), and diff-against-self reports zero
    intensity/ceiling deltas."""
    rows, hist, cfg, path = _run(
        tmp_path,
        _tiny_config(
            scheme="single",
            pipeline={"workers": 0},
            packing={"enabled": True},
        ),
    )
    ex = _exec_rows(rows)
    assert ex, "no executable rows in the stream"
    # counted flops/bytes > 0 and the memory footprint fields landed
    for r in ex:
        assert r["flops"] > 0 and r["bytes_accessed"] > 0, r
        assert r["temp_bytes"] >= 0 and r["argument_bytes"] > 0, r
        assert "capture_ms" in r and not r.get("post_warmup"), r
    # exactly ONE capture per (region, spec, k, lanes) across epochs
    keys = [(r["region"], r["spec"], r["k"], r["lanes"]) for r in ex]
    assert len(keys) == len(set(keys))
    # every rollup spec is attributed (uniform dataset: stable specs)
    rollups = [r for r in rows if r["t"] == "spec_rollup"]
    assert rollups
    exec_specs = {(r["region"], r["spec"]) for r in ex}
    for r in rollups:
        assert (r["region"], r["spec"]) in exec_specs
        assert r["hw_dispatches"] > 0 and "hw_missing_dispatches" not in r
        # reader-reproducibility contract (1e-9 relative), hw side
        hw_mfu = r["hw_flops"] / (r["wall_ms"] / 1e3) / r["peak_flops"]
        assert abs(r["hw_mfu"] - hw_mfu) <= 1e-9 * abs(hw_mfu)
        intensity = r["hw_flops"] / r["hw_bytes_accessed"]
        assert abs(r["intensity"] - intensity) <= 1e-9 * abs(intensity)
        assert r["peak_hbm_bytes_per_sec"] > 0
        if "model_flops_per_graph" in r:
            ratio = r["hw_flops"] / (
                r["model_flops_per_graph"] * r["graphs"]
            )
            assert abs(r["hw_over_model_flops"] - ratio) <= 1e-9 * ratio
    # close row accounts for the captures
    close = [r for r in rows if r["t"] == "close"][-1]
    assert close["executables"] == len(ex)
    assert close["exec_capture_failures"] == 0
    # graftboard roofline: verdict per spec + anchor what-if note
    assert graftboard.main(["roofline", path]) == 0
    out = capsys.readouterr().out
    assert "memory-bound" in out or "compute-bound" in out
    assert "WHAT-IF" in out
    rl = graftboard.build_roofline(graftboard.build_report(path))
    assert rl["what_if"] is True
    assert rl["specs"] and all(
        e["verdict"] in ("memory-bound", "compute-bound")
        for e in rl["specs"]
    )
    for e in rl["specs"]:
        assert e["roofline_ceiling_flops_per_sec"] == min(
            e["peak_flops"],
            e["intensity"] * e["peak_hbm_bytes_per_sec"],
        )
        assert 0 < e["ceiling_frac"] < 1
    # diff against self: zero deltas, stable verdicts
    assert graftboard.main(["diff", path, path, "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    roof = d["roofline_delta_by_spec"]
    assert roof
    for spec, v in roof.items():
        assert v["intensity"]["delta"] == 0.0
        assert v["ceiling_frac"]["delta"] == 0.0
        assert v["verdict_a"] == v["verdict_b"]


def test_cost_analysis_off_emits_no_executable_rows(tmp_path):
    cfg = _tiny_config(
        scheme="single",
        pipeline={"workers": 0},
        packing={"enabled": True},
    )
    from hydragnn_tpu.runner import run_training
    from hydragnn_tpu.data.loader import split_dataset as _split

    stream_path = str(tmp_path / "telemetry.jsonl")
    cfg["NeuralNetwork"]["Training"]["Telemetry"] = {
        "enabled": True,
        "stream_path": stream_path,
        "cost_analysis": False,
    }
    tr, va, te = _split(_uniform_samples(48), 0.8)
    run_training(cfg, datasets=(tr, va, te), seed=0)
    rows = [json.loads(line) for line in open(stream_path)]
    assert not _exec_rows(rows)
    rollups = [r for r in rows if r["t"] == "spec_rollup"]
    assert rollups and all("hw_mfu" not in r for r in rollups)
    assert all("hw_missing_dispatches" not in r for r in rollups)
    # roofline degrades honestly: rows render, verdict is None
    rl = graftboard.build_roofline(
        graftboard.build_report(stream_path)
    )
    assert rl["specs"] and all(e["verdict"] is None for e in rl["specs"])


def test_capture_failure_degrades_and_never_retries(tmp_path):
    """A step fn without a working AOT path: ONE capture_error row per
    key, the failure counter moves, rollups carry the miss count and
    OMIT hw-MFU/intensity — and record() never raises."""
    from hydragnn_tpu.data.loader import GraphLoader

    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    batch = next(iter(GraphLoader(_uniform_samples(8), 4)))

    class _Unlowerable:
        def lower(self, *a):
            raise RuntimeError("no AOT for you")

    clock = telemetry.StepClock(s, region="train", epoch=0)
    for step in (1, 2, 3):
        t = time.perf_counter()
        clock.record(
            step=step,
            k=1,
            batch=batch,
            is_macro=False,
            t_fetch_start=t,
            t_fetch_end=t,
            t_dispatch_start=t,
            t_dispatch_end=t + 1e-4,
            capture_fn=_Unlowerable(),
            capture_args=(None, batch),
        )
    clock.finish()
    s.close()
    rows = [json.loads(line) for line in open(p)]
    errs = [r for r in _exec_rows(rows) if "capture_error" in r]
    assert len(errs) == 1, "failed capture must not retry per step"
    assert s.exec_capture_failures == 1
    roll = [r for r in rows if r["t"] == "spec_rollup"]
    assert roll and roll[0]["hw_missing_dispatches"] == 3
    assert "hw_mfu" not in roll[0] and "intensity" not in roll[0]
    # graftboard: no fabricated verdict for the unattributed spec
    rl = graftboard.build_roofline(graftboard.build_report(p))
    assert all(e["verdict"] is None for e in rl["specs"])


def test_memory_rows_epoch_boundaries_and_compiles(tmp_path):
    """CPU run: memory rows at run start + every epoch boundary +
    after compiles, carrying host RSS (device allocator fields absent
    on CPU — partial, never fabricated)."""
    rows, _, _, _ = _run(
        tmp_path,
        _tiny_config(
            scheme="single",
            pipeline={"workers": 0},
            packing={"enabled": True},
        ),
    )
    mem = [r for r in rows if r["t"] == "memory"]
    assert {r.get("epoch") for r in mem if r["tag"] == "epoch"} == {0, 1}
    assert any(r["tag"] == "run_start" for r in mem)
    assert any(r["tag"] == "compile" for r in mem)
    for r in mem:
        assert r["host_rss_bytes"] > 1 << 20
        assert "bytes_in_use" not in r  # CPU: no allocator stats
    # off-path: emit_memory is inert
    telemetry.install(None)
    assert telemetry.emit_memory("x") is False


def test_profiling_window_and_step_annotations(tmp_path):
    """Training.Profiling {epoch, steps}: the capture starts at the
    target epoch, stops after the step budget, both ends land in the
    stream, and the trace dir materializes."""
    cfg = _tiny_config(
        scheme="single",
        pipeline={"workers": 0},
        packing={"enabled": True},
    )
    trace_dir = str(tmp_path / "trace")
    cfg["NeuralNetwork"]["Training"]["Profiling"] = {
        "enabled": True,
        "epoch": 1,
        "steps": 2,
        "trace_dir": trace_dir,
    }
    rows, _, _, path = _run(tmp_path, cfg)
    prof = [r for r in rows if r["t"] == "profile"]
    assert [r["event"] for r in prof] == ["start", "stop"]
    assert prof[0]["epoch"] == 1 and prof[0]["steps"] == 2
    assert prof[0]["trace_dir"] == trace_dir
    assert prof[1]["reason"] == "step_budget"
    assert os.path.isdir(trace_dir)
    # profiling a steady epoch must not retrace (annotation is outside
    # the jit key) — the stable packed run stays recompile-free
    rep = graftboard.build_report(path)
    assert rep["post_warmup_compiles"] == 0
    from hydragnn_tpu.utils import tracer as tr

    assert tr.jax_trace_active() is False  # window closed cleanly


def test_update_config_rejects_unknown_profiling_key():
    from hydragnn_tpu.config import update_config

    cfg = _tiny_config()
    cfg["NeuralNetwork"]["Training"]["Profiling"] = {
        "enabled": True,
        "target_epoch": 1,  # legacy name: must fail EAGERLY
    }
    with pytest.raises(ValueError, match="Profiling"):
        update_config(cfg, _uniform_samples(8))


def test_header_omits_device_fields_when_backend_uninitialized(
    tmp_path, monkeypatch
):
    """Constructing a stream must NEVER initialize a jax backend:
    with no backend live, the header skips the device fields (peaks
    still resolve from the ROOFLINE anchor) instead of calling
    jax.devices()."""
    from jax._src import xla_bridge

    monkeypatch.setattr(xla_bridge, "_backends", {})
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    s.close()
    hdr = json.loads(open(p).readline())
    assert "device_kind" not in hdr and "device_count" not in hdr
    assert hdr["hostname"]
    assert hdr["peak_basis"] == "roofline_anchor"  # anchor-only peaks


def test_capture_compile_not_counted_by_observer(tmp_path):
    """The capture's OWN AOT compile must not reach the compile
    observer: one real post-warmup retrace reads as ONE leak (not
    two), and the capture's cost lands on the row's capture_ms."""
    from hydragnn_tpu.data.loader import GraphLoader

    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    obs = telemetry.CompileObserver(s, warmup_phase=1).install()
    batch = next(iter(GraphLoader(_uniform_samples(8), 4)))
    f = jax.jit(lambda st, b: (st, jnp.sum(b.x), jnp.zeros((1,))))
    f(0.0, batch)  # warmup compile at phase 0
    obs.set_phase(2)
    state, loss, _ = f(1.0, batch)  # cache hit: no compile
    n_before = obs.compile_count
    assert obs.post_warmup == []
    clock = telemetry.StepClock(s, region="train", epoch=2)
    t = time.perf_counter()
    clock.record(
        step=1,
        k=1,
        batch=batch,
        is_macro=False,
        t_fetch_start=t,
        t_fetch_end=t,
        t_dispatch_start=t,
        t_dispatch_end=t + 1e-4,
        loss_ref=loss,
        capture_fn=f,
        capture_args=(1.0, batch),
    )
    clock.finish()
    obs.close()
    s.close()
    # the AOT capture compiled (flops landed) but the observer saw
    # nothing: no new compiles, no fabricated retrace leak
    rows = [json.loads(line) for line in open(p)]
    ex = [r for r in rows if r["t"] == "executable"]
    assert ex and ex[0]["flops"] > 0 and ex[0]["post_warmup"] is True
    assert obs.compile_count == n_before
    assert obs.post_warmup == []
