"""Fleet serving tier (hydragnn_tpu/serve/fleet.py + router.py,
docs/SERVING.md "Fleet tier"): routing policies over fake replica
handles, deadline-class load shedding and its conservation accounting,
dead-replica re-route, rollover atomicity (failed admission AND
warm-up failure leave the old generation serving bitwise-untouched),
the skewed loadgen histogram, the graftboard serving section, the
Serving.Fleet config surface, and the graftlint seed registrations.
"""

import os
import threading
import types

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.graph import GraphSample, PackSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# Router unit surface: fake replicas implementing the handle protocol
# (serve/router.py Router docstring) so policy/shed arithmetic is
# tested without engines or threads.
# ----------------------------------------------------------------------


class _FakeInner:
    def __init__(self):
        self.result = None
        self.t_done = None


class _FakeReplica:
    def __init__(self, index, depth=0, anchor_age=0.0, deadline_s=0.04):
        self.index = index
        self.alive = True
        self.depth = depth
        self.anchor_age = anchor_age
        self.deadline_s = deadline_s
        self.routed = []
        self.tracked = []
        self.pending = []

    def qsize(self):
        return self.depth

    def oldest_anchor_age_s(self):
        return self.anchor_age

    def submit_inner(self, sample, deadline_class):
        self.routed.append((sample, deadline_class))
        self.depth += 1
        return _FakeInner()

    def track(self, fr):
        self.tracked.append(fr)

    def recover_pending(self):
        out, self.pending = self.pending, []
        return out


def _sample(n=20, e=40):
    return types.SimpleNamespace(num_nodes=n, num_edges=e)


_BUDGETS = [
    PackSpec(num_nodes=208, num_edges=456, num_graphs=13),
    PackSpec(num_nodes=104, num_edges=224, num_graphs=7),
]


def _router(replicas, **kw):
    from hydragnn_tpu.serve.router import Router

    kw.setdefault("budgets", _BUDGETS)
    budgets = kw.pop("budgets")
    rows = []
    r = Router(replicas, budgets, emit=rows.append, **kw)
    return r, rows


def test_router_least_loaded_min_queue_lowest_index_tie():
    reps = [_FakeReplica(0, depth=3), _FakeReplica(1, depth=1),
            _FakeReplica(2, depth=1)]
    router, _ = _router(reps, policy="least_loaded")
    fr = router.submit(_sample())
    assert fr.replica == 1 and not fr.shed
    assert reps[1].routed and reps[1].tracked == [fr]


def test_router_budget_rank_half_capacity_share_rule():
    """The spec-affinity key: rank = smallest budget the request can
    SHARE (<= half node/edge capacity). Giants that would monopolize
    the small budget rank 0 (the big shape's home); oversize requests
    rank 0 too."""
    router, _ = _router([_FakeReplica(0)], policy="spec_affinity")
    assert router.budget_rank(_sample(20, 40)) == 1   # shares small
    assert router.budget_rank(_sample(60, 150)) == 0  # 2n > 104
    assert router.budget_rank(_sample(52, 115)) == 0  # 2e > 224
    assert router.budget_rank(_sample(500, 900)) == 0  # oversize


def test_router_spec_affinity_homes_then_falls_back():
    reps = [_FakeReplica(0), _FakeReplica(1)]
    router, _ = _router(reps, policy="spec_affinity", queue_bound=4)
    small, big = _sample(20, 40), _sample(60, 150)
    assert router.submit(small).replica == 1  # rank 1 % 2 live
    assert router.submit(big).replica == 0    # rank 0
    # Saturate the small-budget home: affinity degrades to balance.
    reps[1].depth = 4
    fr = router.submit(small)
    assert fr.replica == 0 and not fr.shed


def test_router_pressure_levels_depth_and_anchor():
    r = _FakeReplica(0)
    router, _ = _router([r], queue_bound=8)
    assert router.pressure(r) == 0
    r.depth = 8
    assert router.pressure(r) == 1
    r.depth = 16
    assert router.pressure(r) == 2
    r.depth = 32
    assert router.pressure(r) == 3
    # Deadline-anchor path: depth nominal but the oldest open bin has
    # aged past 2x the dispatch deadline.
    r.depth = 0
    r.anchor_age = 0.09  # > 2 * 0.04
    assert router.pressure(r) == 1


def test_router_sheds_lowest_class_first_counts_and_rows():
    r = _FakeReplica(0, depth=8)
    router, rows = _router([r], queue_bound=8)  # pressure 1
    shed0 = router.submit(_sample(), deadline_class=0)
    kept1 = router.submit(_sample(), deadline_class=1)
    assert shed0.shed and shed0.shed_reason == "overload"
    assert shed0.result is None and shed0.done
    assert not kept1.shed
    r.depth = 16  # pressure 2: class 1 sheds, interactive survives
    shed1 = router.submit(_sample(), deadline_class=1)
    kept2 = router.submit(_sample(), deadline_class=2)
    assert shed1.shed and not kept2.shed
    r.depth = 32  # the hard wall sheds everything
    assert router.submit(_sample(), deadline_class=2).shed
    rep = router.shed_report()
    assert rep["submitted"] == 5
    assert rep["shed_total"] == 3
    # Conservation: every submit either routed first-time or shed.
    assert rep["submitted"] == rep["routed_first"] + rep["shed_total"]
    assert rep["shed_by_reason"] == {"overload": 3}
    assert rep["shed_by_class"] == {"0": 1, "1": 1, "2": 1}
    shed_rows = [x for x in rows if x["t"] == "shed"]
    assert len(shed_rows) == 3
    assert set(shed_rows[0]) == {
        "t", "reason", "class", "fleet_id", "replica", "queue_depth"
    }


def test_router_shed_escape_hatch_prefers_least_loaded_alt():
    """An overloaded affinity home degrades to the globally
    least-loaded replica BEFORE shedding — affinity buys locality,
    never drops. Home pressure comes from the deadline-anchor signal
    (depth nominal), so only the escape hatch can route this."""
    reps = [_FakeReplica(0), _FakeReplica(1, anchor_age=0.09)]
    router, rows = _router(reps, policy="spec_affinity", queue_bound=8)
    fr = router.submit(_sample(20, 40), deadline_class=0)  # home = 1
    assert not fr.shed and fr.replica == 0
    assert rows == []


def test_router_reroute_moves_pending_and_sheds_expired():
    from hydragnn_tpu.serve.router import FleetRequest

    clk = [100.0]
    dead = _FakeReplica(0)
    dead.alive = False
    live = _FakeReplica(1)
    router, rows = _router(
        [dead, live],
        class_budgets_ms=(None, None, 50.0),
        clock=lambda: clk[0],
    )
    # One interactive request submitted 1s ago (budget 50ms: expired
    # inside the corpse) and one batch request (no budget: moved).
    stale = FleetRequest(_sample(), 0, 2, t_submit=99.0)
    fresh = FleetRequest(_sample(), 1, 0, t_submit=99.99)
    dead.pending = [stale, fresh]
    row = router.reroute(dead)
    assert row == {
        "t": "reroute", "from_replica": 0, "recovered": 2,
        "moved": 1, "shed_expired": 1,
    }
    assert stale.shed and stale.shed_reason == "expired"
    assert fresh.replica == 1 and fresh.reroutes == 1
    assert router.shed_report()["reroutes"] == 1
    # All replicas down: recovery sheds loudly, never silently drops.
    live.alive = False
    dead.pending = [FleetRequest(_sample(), 2, 0, t_submit=clk[0])]
    row2 = router.reroute(dead)
    assert row2["moved"] == 0
    assert router.shed_report()["shed_by_reason"]["no_live_replica"] == 1


def test_router_no_live_replicas_raises_and_bad_policy_rejected():
    from hydragnn_tpu.serve.router import Router

    r = _FakeReplica(0)
    r.alive = False
    router, _ = _router([r])
    with pytest.raises(RuntimeError, match="no live replicas"):
        router.submit(_sample())
    with pytest.raises(ValueError, match="unknown routing policy"):
        Router([_FakeReplica(0)], _BUDGETS, policy="round_robin")


def test_batcher_oldest_anchor_age_reads_oldest_open_bin():
    from hydragnn_tpu.serve.batcher import DynamicBatcher

    clk = [0.0]
    bat = DynamicBatcher(
        _BUDGETS, deadline_ms=1e6, clock=lambda: clk[0]
    )
    assert bat.oldest_anchor_age_s() == 0.0
    rng = np.random.default_rng(0)
    k = 6
    bat.submit(GraphSample(
        x=rng.normal(size=(k, 1)).astype(np.float32),
        pos=rng.uniform(0, 3, (k, 3)).astype(np.float32),
        edge_index=np.stack(
            [np.arange(k), (np.arange(k) + 1) % k]
        ).astype(np.int64),
        y_graph=np.zeros(1, np.float32),
    ))
    # The anchor is stamped at PLACEMENT (dispatch side): one empty
    # next_bin poll pulls the queue into an open bin with t0 = the
    # enqueue stamp, exactly what the dispatch loop does.
    assert bat.next_bin(timeout=0.0) is None
    clk[0] = 1.25
    assert bat.oldest_anchor_age_s() == pytest.approx(1.25)
    bat.close()


# ----------------------------------------------------------------------
# Serving.Fleet config surface.
# ----------------------------------------------------------------------


def test_fleet_settings_resolution_defaults_and_validation():
    from hydragnn_tpu.serve.fleet import FleetSettings, fleet_settings

    assert fleet_settings({}) == FleetSettings()
    assert fleet_settings({"Serving": True}) == FleetSettings()
    fs = fleet_settings({"Serving": {"Fleet": {
        "replicas": 3, "policy": "spec_affinity", "queue_bound": 16,
        "heartbeat_interval_s": 0.1, "heartbeat_timeout_s": 0.5,
        "class_budgets_ms": [250.0, None, 80],
    }}})
    assert fs.replicas == 3 and fs.policy == "spec_affinity"
    assert fs.queue_bound == 16
    assert fs.class_budgets_ms == (250.0, None, 80.0)
    # Floors: a zero-replica or sub-resolution-heartbeat tier is a
    # config bug, clamped loudly at the floor rather than deadlocked.
    floored = fleet_settings({"Serving": {"Fleet": {
        "replicas": 0, "queue_bound": 0, "heartbeat_timeout_s": 0.0,
    }}})
    assert floored.replicas == 1 and floored.queue_bound == 1
    assert floored.heartbeat_timeout_s == 0.05
    with pytest.raises(ValueError, match="policy"):
        fleet_settings({"Serving": {"Fleet": {"policy": "nearest"}}})
    with pytest.raises(ValueError, match="must be an object"):
        fleet_settings({"Serving": {"Fleet": [3]}})


def test_update_config_validates_fleet_block_eagerly():
    from hydragnn_tpu.config import update_config

    update_config({"NeuralNetwork": {}, "Serving": {
        "Fleet": {"replicas": 2, "policy": "least_loaded"},
    }})
    with pytest.raises(ValueError, match="Serving.Fleet: unknown keys"):
        update_config({"NeuralNetwork": {}, "Serving": {
            "Fleet": {"que_bound": 8},
        }})
    with pytest.raises(ValueError, match="Serving.Fleet.policy"):
        update_config({"NeuralNetwork": {}, "Serving": {
            "Fleet": {"policy": "hash_ring"},
        }})


def test_fleet_keys_in_graftlint_config_vocabulary():
    """Injection-verification (ISSUE 16 satellite): the config-schema
    rule's harvested vocabulary must cover every Serving.Fleet key —
    a user config using them lints clean."""
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules import DEFAULT_PATHS
    from hydragnn_tpu.analysis.rules.config_schema import (
        harvest_accepted_keys,
    )

    ctx = collect_files(
        REPO, [p for p in DEFAULT_PATHS if os.path.exists(
            os.path.join(REPO, p)
        )]
    )
    accepted = harvest_accepted_keys(ctx)
    for key in (
        "Fleet",
        "replicas",
        "policy",
        "queue_bound",
        "heartbeat_interval_s",
        "heartbeat_timeout_s",
        "class_budgets_ms",
    ):
        assert key in accepted, f"Fleet key {key!r} not harvested"


def test_fleet_hot_path_seeds_resolve_and_files_lint_clean():
    """The routing front's never-block/host-sync seed registrations
    must RESOLVE in the real callgraph (a renamed method silently
    un-linting the hot path is the failure mode), and the real files
    must be clean under both rules."""
    from hydragnn_tpu.analysis.callgraph import build_callgraph
    from hydragnn_tpu.analysis.engine import collect_files
    from hydragnn_tpu.analysis.rules.host_sync import (
        HOT_SEEDS,
        HostSyncRule,
    )
    from hydragnn_tpu.analysis.rules.thread_discipline import (
        NEVER_BLOCK_SEEDS,
        ThreadDisciplineRule,
    )
    from tests.test_lint import findings_of

    files = [
        "hydragnn_tpu/serve/router.py",
        "hydragnn_tpu/serve/fleet.py",
    ]
    ctx = collect_files(REPO, files)
    graph = build_callgraph(ctx)
    for path, qual in (
        ("serve/router.py", "Router.submit"),
        ("serve/router.py", "Router._route"),
        ("serve/router.py", "Router._shed"),
        ("serve/fleet.py", "ServingTier.submit"),
        ("serve/fleet.py", "ReplicaHandle.submit_inner"),
        ("serve/fleet.py", "ReplicaHandle.swap"),
    ):
        assert (path, qual) in NEVER_BLOCK_SEEDS
        assert any(
            graph.find(p, q) for p, q in NEVER_BLOCK_SEEDS
            if q == qual
        ), f"{qual} not resolvable among never-block seeds"
    for qual in (
        "Router.submit",
        "ServingTier.submit",
        "ReplicaHandle.submit_inner",
        "ReplicaHandle.swap",
    ):
        assert any(
            graph.find(p, q) for p, q in HOT_SEEDS if q == qual
        ), f"{qual} not resolvable among host-sync hot seeds"
    sources = {f: pf.text for f, pf in zip(files, ctx.py_files)}
    f = findings_of(sources, [ThreadDisciplineRule(), HostSyncRule()])
    assert f == [], [x.message for x in f]


# ----------------------------------------------------------------------
# Loadgen: the skewed histogram and deadline-class stamping.
# ----------------------------------------------------------------------


def test_loadgen_zinc_skew_deterministic_with_heavy_tail():
    from hydragnn_tpu.serve.loadgen import synthetic_request_samples

    a = synthetic_request_samples("zinc_skew", 200, seed=7)
    b = synthetic_request_samples("zinc_skew", 200, seed=7)
    assert [s.num_nodes for s in a] == [s.num_nodes for s in b]
    sizes = np.array([s.num_nodes for s in a])
    assert sizes.max() <= 104 and sizes.min() >= 8
    # The tail exists and is a MINORITY: ~12% giants at 2-3.5x the
    # body mean, the mix spec-affinity homing exists for.
    giants = (sizes >= 40).sum()
    assert 5 <= giants <= 60
    body = np.median(sizes)
    assert 18 <= body <= 28


def test_loadgen_class_mix_deterministic_and_content_invariant():
    from hydragnn_tpu.serve.loadgen import synthetic_request_samples

    plain = synthetic_request_samples("zinc_skew", 64, seed=3)
    mixed = synthetic_request_samples(
        "zinc_skew", 64, seed=3, class_mix=(0.25, 0.5, 0.25)
    )
    mixed2 = synthetic_request_samples(
        "zinc_skew", 64, seed=3, class_mix=(0.25, 0.5, 0.25)
    )
    # Class draw happens AFTER content draws: payloads stay bitwise
    # identical whatever the mix.
    for p, m in zip(plain, mixed):
        np.testing.assert_array_equal(p.x, m.x)
        np.testing.assert_array_equal(p.edge_index, m.edge_index)
    assert all(s.deadline_class == 1 for s in plain)
    cls = [s.deadline_class for s in mixed]
    assert cls == [s.deadline_class for s in mixed2]
    assert set(cls) <= {0, 1, 2} and len(set(cls)) >= 2
    with pytest.raises(ValueError, match="class_mix"):
        synthetic_request_samples("qm9", 4, class_mix=(1.0, -1.0, 0.0))


# ----------------------------------------------------------------------
# graftboard: the fleet serving section over synthetic shard rows.
# ----------------------------------------------------------------------


def test_graftboard_fleet_serving_section_merges_and_verdicts():
    import tools.graftboard as gb

    rows_by_proc = {
        0: [
            {"t": "serve", "replica": 0, "queue_depth": 2},
            {"t": "serve_rollup", "replica": 0, "requests": 40,
             "dispatches": 9, "p50_ms": 8.0, "p99_ms": 20.0},
            {"t": "shed", "reason": "overload", "class": 0},
            {"t": "shed", "reason": "expired", "class": 2},
            {"t": "reroute", "from_replica": 1, "recovered": 3,
             "moved": 2, "shed_expired": 1},
            {"t": "rollover", "phase": "done"},
            {"t": "rollover", "phase": "refused"},
        ],
        1: [
            {"t": "serve", "replica": 1, "queue_depth": 11},
            {"t": "serve_rollup", "replica": 1, "requests": 12,
             "dispatches": 4, "p50_ms": 9.0, "p99_ms": 60.0},
        ],
        2: [
            {"t": "serve", "replica": 2, "queue_depth": 1},
            {"t": "serve_rollup", "replica": 2, "requests": 30,
             "dispatches": 8, "p50_ms": 8.5, "p99_ms": 30.0},
        ],
    }
    s = gb._fleet_serving(rows_by_proc, {"dead": [1]})
    assert s["per_replica"]["0"]["requests"] == 40
    assert s["per_replica"]["1"]["queue_depth_max"] == 11
    assert s["p99_skew"] == pytest.approx(3.0)
    assert "straggler" in s["queue_verdict"]
    assert s["sheds_by_reason"] == {"overload": 1, "expired": 1}
    assert s["sheds_by_class"] == {"0": 1, "2": 1}
    assert s["shed_total"] == 2
    assert s["rollovers"] == {"done": 1, "refused": 1}
    # Replica 1 died but its pending requests were re-routed: covered.
    assert s["dead_replicas"] == [1]
    assert s["dead_without_reroute"] == []
    # Without the reroute row the same death is a LOST-requests flag.
    rows_by_proc[0] = [
        r for r in rows_by_proc[0] if r["t"] != "reroute"
    ]
    s2 = gb._fleet_serving(rows_by_proc, {"dead": [1]})
    assert s2["dead_without_reroute"] == [1]
    # A training-only fleet has no serving section at all.
    assert gb._fleet_serving(
        {0: [{"t": "step", "loss": 1.0}]}, {}
    ) is None


# ----------------------------------------------------------------------
# Tier integration: rollover atomicity + lifecycle over a real tiny
# model (the satellite-3 contract: failed admission mid-rollover and
# death during warm-up both leave the OLD generation serving,
# bitwise).
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def _tier_fixture():
    from hydragnn_tpu.data.padschedule import dataset_size_arrays
    from hydragnn_tpu.serve.engine import (
        ServingSettings,
        fit_serving_budgets,
    )
    from tests.test_serving import _mols, _serving_model

    samples = _mols(24, 6, 12, seed=11)
    model, cfg, state = _serving_model(samples)
    ns, es = dataset_size_arrays(samples)
    st = ServingSettings(
        enabled=True, batch_size=4, deadline_ms=10.0, max_open_bins=2
    )
    budgets = fit_serving_budgets(ns, es, st)
    return samples, model, cfg, state, st, budgets


def _mk_tier(fix, **kw):
    from hydragnn_tpu.serve.fleet import FleetSettings, ServingTier

    samples, model, cfg, state, st, budgets = fix
    kw.setdefault("fleet", FleetSettings(
        replicas=2, heartbeat_interval_s=0.05, heartbeat_timeout_s=0.4
    ))
    kw.setdefault("monitor", False)
    return ServingTier(
        model, cfg, state, budgets,
        example=samples[0], settings=st, **kw
    )


def _probe(tier, samples):
    frs = [tier.submit(s) for s in samples]
    deadline = threading.Event()
    import time as _t
    t0 = _t.monotonic()
    while not all(fr.done for fr in frs):
        assert _t.monotonic() - t0 < 30.0, "probe requests stalled"
        deadline.wait(0.01)
    assert not any(fr.shed for fr in frs)
    return [np.asarray(fr.result[0]).copy() for fr in frs]


def test_tier_rollover_refusals_leave_old_engine_bitwise(_tier_fixture):
    """Satellite 3: (a) a snapshot failing the admission gate
    mid-rollover leaves the old engine serving bitwise-untouched;
    (b) a warm-up crash never leaves the router pointing at a
    half-warmed engine; (c) a clean rollover swaps with zero requests
    lost and bitwise-equal outputs (same snapshot)."""
    import jax.numpy as jnp

    from hydragnn_tpu.serve.admission import AdmissionError

    samples, model, cfg, state, st, budgets = _tier_fixture
    tier = _mk_tier(_tier_fixture)
    try:
        probe = samples[:6]
        before = _probe(tier, probe)
        old_engines = [h.engine for h in tier.replicas]

        # (a) ADMIT refusal: poison one leaf. The tier must re-raise,
        # count nothing, and keep serving the old snapshot bitwise.
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        bad_leaves = list(leaves)
        bad_leaves[0] = bad_leaves[0].at[(0,) * bad_leaves[0].ndim].set(
            jnp.nan
        )
        bad_state = state.replace(
            params=jax.tree_util.tree_unflatten(treedef, bad_leaves)
        )
        with pytest.raises(AdmissionError):
            tier.rollover(bad_state)
        assert tier.rollovers == 0
        assert [h.engine for h in tier.replicas] == old_engines
        for a, b in zip(before, _probe(tier, probe)):
            np.testing.assert_array_equal(a, b)

        # (b) WARM crash: the shadow build explodes after admission.
        # Swap never happens; the router still points at the old
        # generation and it still serves bitwise.
        real_build = tier._build_engine
        tier._build_engine = lambda s, h: (_ for _ in ()).throw(
            RuntimeError("warm-up crashed")
        )
        with pytest.raises(RuntimeError, match="warm-up crashed"):
            tier.rollover(state)
        tier._build_engine = real_build
        assert tier.rollovers == 0
        assert [h.engine for h in tier.replicas] == old_engines
        for a, b in zip(before, _probe(tier, probe)):
            np.testing.assert_array_equal(a, b)

        # (c) Clean rollover with the SAME snapshot: drained to zero
        # in-flight, every replica swapped, outputs bitwise across the
        # swap, old engines torn down.
        row = tier.rollover(state, drain_timeout_s=30.0)
        assert row["phase"] == "done" and row["drained"]
        assert sorted(row["replicas"]) == [0, 1]
        assert tier.rollovers == 1
        new_engines = [h.engine for h in tier.replicas]
        assert all(
            n is not o for n, o in zip(new_engines, old_engines)
        )
        assert all(o.closed for o in old_engines)
        for a, b in zip(before, _probe(tier, probe)):
            np.testing.assert_array_equal(a, b)
        rep = tier.report()
        assert rep["rollovers"] == 1
        assert rep["router"]["shed_total"] == 0
    finally:
        tier.close(timeout_s=30.0)


def test_tier_kill_detect_reroute_and_close_contract(_tier_fixture):
    """A killed replica is declared dead by one health sweep, its pump
    joined, its requests recovered through the router; close() is
    idempotent and post-close submits are rejected loudly (the
    lifecycle satellite)."""
    samples, model, cfg, state, st, budgets = _tier_fixture
    tier = _mk_tier(_tier_fixture)
    try:
        _probe(tier, samples[:4])
        tier.kill_replica(0)
        assert tier.check_health() == [0]
        h = tier.replicas[0]
        assert not h.alive and h.killed and h.t_dead is not None
        assert not h.pump_alive()
        # Second sweep is a no-op: death is edge-triggered.
        assert tier.check_health() == []
        rows = tier.router.shed_report()
        assert rows["submitted"] == 4
        # Everything already served before the kill: recovery found
        # nothing to move, nothing was shed.
        assert rows["shed_total"] == 0
        # The survivor still serves.
        import time as _t

        fr = tier.submit(samples[5])
        t0 = _t.monotonic()
        while not fr.done:
            assert _t.monotonic() - t0 < 30.0, "survivor stalled"
            _t.sleep(0.01)
        assert fr.replica == 1 and fr.result is not None
    finally:
        tier.close(timeout_s=30.0)
        tier.close(timeout_s=30.0)  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        tier.submit(samples[0])
    with pytest.raises(RuntimeError, match="closed"):
        tier.rollover(state)
    # The engine lifecycle contract on the torn-down survivor.
    eng = tier.replicas[1].engine
    assert eng.closed
    with pytest.raises(RuntimeError, match="closed"):
        eng.install_executables({})
