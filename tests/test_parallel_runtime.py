"""Parallel runtime: plan resolution, DP loader padding, DP step
equivalence against the single-device step, and run_training E2E over
the 8-device virtual CPU mesh (the TPU analog of the reference's
DDP-wrapped run_training, run_training.py:105 + distributed.py:396-481).
"""

import os

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample
from hydragnn_tpu.data.loader import GraphLoader, split_dataset
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.parallel import runtime
from hydragnn_tpu.parallel.dp import (
    DPLoader,
    make_dp_eval_step,
    make_dp_train_step,
    replicate_state,
)
from hydragnn_tpu.parallel.mesh import make_mesh


def _samples(n, seed=0, target_rule=1.7):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(5, 10))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        x = r.normal(size=(k, 1)).astype(np.float32)
        out.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5, max_neighbours=12),
                y_graph=np.array([target_rule * float(x.mean())], np.float32),
            )
        )
    return out


def _config(batch_size=4, **training):
    cfg = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "max_neighbours": 12,
                "num_gaussians": 8,
                "num_filters": 16,
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
                **training,
            },
        }
    }
    return cfg


def test_plan_auto_resolves_dp():
    plan = runtime.plan_from_config(_config())
    assert plan.scheme == "dp"
    assert plan.mesh is not None
    assert plan.data_parallel_size == 8
    assert not plan.fsdp


def test_plan_single_and_fsdp():
    plan = runtime.plan_from_config(
        _config(Parallelism={"scheme": "single"})
    )
    assert plan.scheme == "single" and plan.mesh is None
    plan = runtime.plan_from_config(
        _config(Parallelism={"scheme": "dp", "data": 4, "fsdp": 2})
    )
    assert plan.fsdp
    assert dict(plan.mesh.shape) == {"data": 4, "fsdp": 2}
    with pytest.raises(ValueError):
        runtime.plan_from_config(
            _config(Parallelism={"scheme": "dp", "data": 16})
        )


def test_plan_env_override(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TPU_MESH", "data=2,fsdp=4")
    plan = runtime.plan_from_config(_config())
    assert dict(plan.mesh.shape) == {"data": 2, "fsdp": 4}


def test_shard_dataset_for_process_single():
    xs = list(range(10))
    assert runtime.shard_dataset_for_process(xs) == xs


def test_dploader_pads_short_epochs():
    """A val set smaller than the device group must still produce a
    step (DistributedSampler-style padding by repetition)."""
    mesh = make_mesh({"data": 8})
    samples = _samples(12, seed=3)
    loader = GraphLoader(samples, 4)  # 3 batches < 8 devices
    dp = DPLoader(loader, mesh)
    batches = list(dp)
    assert len(batches) == 1 == len(dp)
    # All 12 real graphs present at least once; 8*5 slots padded.
    total_real = float(jnp.sum(batches[0].graph_mask))
    assert total_real >= 12


def _build_model_state(config, samples, lr=5e-3):
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    config = update_config(config, samples)
    model, cfg = create_model_config(config)
    loader = GraphLoader(samples, 4)
    batch = next(iter(loader))
    params, bs = init_params(model, batch)
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(params, tx, bs)
    return model, cfg, tx, state, loader


def test_dp_eval_matches_weighted_single():
    """DP eval loss over stacked batches == graph-count-weighted mean of
    per-batch single-device eval losses."""
    from hydragnn_tpu.parallel.mesh import shard_stacked_batch, stack_batches
    from hydragnn_tpu.train.loop import make_eval_step

    samples = _samples(32, seed=1)
    model, cfg, tx, state, loader = _build_model_state(_config(), samples)
    mesh = make_mesh({"data": 8})

    batches = list(loader)[:8]
    single_eval = make_eval_step(model, cfg)
    losses, ngs = [], []
    for b in batches:
        loss, _ = single_eval(state, b)
        losses.append(float(loss))
        ngs.append(float(np.asarray(b.graph_mask).sum()))
    expected = float(np.sum(np.array(losses) * np.array(ngs)) / np.sum(ngs))

    dp_state = replicate_state(state, mesh)
    dp_eval = make_dp_eval_step(model, cfg, mesh)
    stacked = shard_stacked_batch(stack_batches(batches), mesh)
    dp_loss, _ = dp_eval(dp_state, stacked)
    np.testing.assert_allclose(float(dp_loss), expected, rtol=1e-5)


def test_dp_train_step_matches_single_on_one_device_mesh():
    """On a {data:1} mesh the DP step must reproduce the single-device
    step bit-for-bit (same loss, same updated params)."""
    from hydragnn_tpu.train.loop import make_train_step

    samples = _samples(16, seed=2)
    model, cfg, tx, state, loader = _build_model_state(_config(), samples)
    batch = next(iter(loader))

    single_step = make_train_step(model, tx, cfg, donate=False)
    s1, loss1, _ = single_step(state, batch)

    mesh = make_mesh({"data": 1}, jax.devices()[:1])
    from hydragnn_tpu.parallel.mesh import shard_stacked_batch, stack_batches

    dp_state = replicate_state(state, mesh)
    dp_step = make_dp_train_step(model, tx, cfg, mesh)
    stacked = shard_stacked_batch(stack_batches([batch]), mesh)
    s2, loss2, _ = dp_step(dp_state, stacked)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    p1 = jax.device_get(s1.params)
    p2 = jax.device_get(s2.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        p1,
        p2,
    )


def test_run_training_dp_e2e_learns():
    """run_training with the default (auto->dp) plan on the 8-device
    mesh: loss must drop and the full (ingest->mesh->train->ckpt) path
    must hold together."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(160, seed=5)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=6)
    state, model, cfg, hist, out_config = run_training(
        config, datasets=(tr, va, te), seed=0
    )
    assert len(hist.train_loss) == 6
    assert hist.train_loss[-1] < hist.train_loss[0] * 0.7
    assert hist.val_loss[-1] > 0.0  # padded short epochs still measure


def test_run_training_dp_matches_single_trajectory():
    """dp over a {data:1} mesh must track the single-device trajectory
    exactly — the parallel path adds no math. The batch FORMER is
    pinned to the ladder on both sides: bin packing (docs/PACKING.md)
    applies on the single scheme only, so the cross-scheme comparison
    must disable it to compare identical batch sequences."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(48, seed=7)
    tr, va, te = split_dataset(samples, 0.7)
    losses = {}
    for scheme, data in (("single", None), ("dp", 1)):
        cfg = _config(batch_size=4, num_epoch=3)
        p = {"scheme": scheme, "packing": {"enabled": False}}
        if data:
            p["data"] = data
        cfg["NeuralNetwork"]["Training"]["Parallelism"] = p
        _, _, _, hist, _ = run_training(cfg, datasets=(tr, va, te), seed=0)
        losses[scheme] = hist.train_loss
    np.testing.assert_allclose(
        losses["single"], losses["dp"], rtol=1e-5, atol=1e-7
    )


def test_run_training_fsdp_e2e():
    """FSDP param sharding through the public API."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(96, seed=9)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=2)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {
        "scheme": "dp",
        "data": 4,
        "fsdp": 2,
    }
    state, model, cfg, hist, _ = run_training(
        config, datasets=(tr, va, te), seed=0
    )
    assert len(hist.train_loss) == 2
    assert np.isfinite(hist.train_loss).all()


def test_run_training_multibranch_from_config():
    """Multibranch task parallelism reachable from the public API."""
    from hydragnn_tpu.runner import run_training

    branch_data = []
    for bi in range(2):
        s = _samples(96, seed=10 + bi, target_rule=1.0 + bi)
        branch_data.append(split_dataset(s, 0.7))
    config = _config(batch_size=4, num_epoch=10)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {
        "scheme": "multibranch"
    }
    config["NeuralNetwork"]["Architecture"]["output_heads"] = {
        "graph": [
            {
                "type": f"branch-{i}",
                "architecture": {
                    "num_sharedlayers": 1,
                    "dim_sharedlayers": 16,
                    "num_headlayers": 1,
                    "dim_headlayers": [16],
                },
            }
            for i in range(2)
        ]
    }
    state, model, cfg, hist, _ = run_training(
        config, datasets=branch_data, seed=0
    )
    assert len(hist.train_loss) == 10
    assert hist.train_loss[-1] < hist.train_loss[0] * 0.8


def test_zero_fsdp_over_data_axis(monkeypatch):
    """HYDRAGNN_TPU_USE_FSDP / Parallelism.zero shards params over the
    data axis itself (ZeRO-3 / torch FULL_SHARD layout)."""
    monkeypatch.setenv("HYDRAGNN_TPU_USE_FSDP", "1")
    plan = runtime.plan_from_config(_config())
    assert plan.fsdp and plan.fsdp_axis == "data"
    samples = _samples(32, seed=4)
    model, cfg, tx, state, loader = _build_model_state(_config(), samples)
    state = runtime.prepare_state(plan, state)
    sharded = [
        p
        for p in jax.tree_util.tree_leaves(state.params)
        if len(p.sharding.device_set) == 8 and not p.sharding.is_fully_replicated
    ]
    assert sharded, "no parameter was ZeRO-sharded over the data axis"
    from hydragnn_tpu.parallel.dp import make_dp_train_step
    from hydragnn_tpu.parallel.mesh import shard_stacked_batch, stack_batches

    step = make_dp_train_step(model, tx, cfg, plan.mesh)
    stacked = shard_stacked_batch(
        stack_batches(list(loader)[:8]), plan.mesh
    )
    state, loss, _ = step(state, stacked)
    assert np.isfinite(float(loss))


def test_valtest_and_max_batch_env_flags(monkeypatch):
    """HYDRAGNN_TPU_VALTEST=0 skips eval epochs;
    HYDRAGNN_TPU_MAX_NUM_BATCH caps per-epoch batches (reference
    HYDRAGNN_VALTEST / HYDRAGNN_MAX_NUM_BATCH throughput-mode flags)."""
    from hydragnn_tpu.runner import run_training

    monkeypatch.setenv("HYDRAGNN_TPU_VALTEST", "0")
    monkeypatch.setenv("HYDRAGNN_TPU_MAX_NUM_BATCH", "1")
    samples = _samples(64, seed=11)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=2)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    _, _, _, hist, _ = run_training(config, datasets=(tr, va, te), seed=0)
    assert hist.val_loss == hist.train_loss  # val skipped, mirrors train


def test_variable_graph_size_env(monkeypatch):
    """HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE: unset -> AUTO bucket
    ladder on every scheme (single: the loader buckets independently;
    dp/multibranch: a shared per-step spec schedule), "1"/"0" force
    the ladder / the worst-case shape."""
    from hydragnn_tpu.runner import _resolve_fixed_pad, run_training

    # Default (clear any shell-inherited value first): auto.
    monkeypatch.delenv(
        "HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", raising=False
    )
    assert _resolve_fixed_pad("single") == "auto"
    assert _resolve_fixed_pad("dp") == "auto"
    monkeypatch.setenv("HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", "0")
    assert _resolve_fixed_pad("single") is True
    assert _resolve_fixed_pad("dp") is True
    monkeypatch.setenv("HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", "1")
    assert _resolve_fixed_pad("single") is False
    assert _resolve_fixed_pad("dp") is False

    samples = _samples(48, seed=13)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=2)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    _, _, _, hist, _ = run_training(config, datasets=(tr, va, te), seed=0)
    assert np.isfinite(hist.train_loss).all()


def test_use_segment_plan_config():
    """Training.use_segment_plan attaches sorted-block plans to batches
    through the public API (Pallas aggregation path on TPU; XLA
    fallback elsewhere gives identical results)."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(48, seed=15)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=2)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    config["NeuralNetwork"]["Training"]["use_segment_plan"] = True
    _, _, _, hist, _ = run_training(config, datasets=(tr, va, te), seed=0)
    assert np.isfinite(hist.train_loss).all()

    # Differential: same run without plans must give the same losses
    # (plan only changes the aggregation lowering, not the math).
    config2 = _config(batch_size=4, num_epoch=2)
    config2["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    _, _, _, hist2, _ = run_training(config2, datasets=(tr, va, te), seed=0)
    np.testing.assert_allclose(
        hist.train_loss, hist2.train_loss, rtol=1e-4
    )


def test_segment_impl_env_forces_pallas_interpret(monkeypatch):
    """HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused] routes run_training's
    aggregation through the planned Pallas kernel even off-TPU
    (interpret mode) — the full wiring, same losses as the XLA path.
    Kernel entry points are counted so a silent routing regression to
    the XLA path cannot keep this test green vacuously."""
    import hydragnn_tpu.ops.pallas_segment as ps
    from hydragnn_tpu.runner import run_training

    samples = _samples(48, seed=15)
    tr, va, te = split_dataset(samples, 0.75)

    calls = {"plain": 0, "fused": 0}
    real_plain = ps.segment_sum_planned
    real_pipeline = ps.edge_pipeline_planned

    def counting_plain(*a, **k):
        calls["plain"] += 1
        return real_plain(*a, **k)

    def counting_pipeline(a_, b_, w_, *rest, **k):
        # every planned entry funnels through edge_pipeline_planned;
        # a filter/weight operand means the FUSED pipeline was taken
        if b_ is not None or w_ is not None:
            calls["fused"] += 1
        return real_pipeline(a_, b_, w_, *rest, **k)

    monkeypatch.setattr(ps, "segment_sum_planned", counting_plain)
    monkeypatch.setattr(ps, "edge_pipeline_planned", counting_pipeline)

    def _run(impl):
        if impl is None:
            monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
        else:
            monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", impl)
        config = _config(batch_size=4, num_epoch=2)
        config["NeuralNetwork"]["Training"]["Parallelism"] = {
            "scheme": "single"
        }
        config["NeuralNetwork"]["Training"]["use_segment_plan"] = True
        _, _, _, hist, _ = run_training(
            config, datasets=(tr, va, te), seed=0
        )
        return np.asarray(hist.train_loss)

    base = _run(None)  # XLA scatter path (CPU backend ignores plans)
    assert calls == {"plain": 0, "fused": 0}
    pallas = _run("pallas")  # planned kernel, interpret mode
    assert calls["plain"] > 0 and calls["fused"] == 0
    fused = _run("pallas_fused")  # in-kernel multiply variant
    assert calls["fused"] > 0
    np.testing.assert_allclose(base, pallas, rtol=1e-4)
    np.testing.assert_allclose(base, fused, rtol=1e-4)


from tests.test_equivariance import _rotation_matrix  # noqa: E402


def _host_predict(state, model, samples, rotation=None):
    """Apply the trained (possibly mesh-sharded) state on the host to a
    fresh batch, optionally with rigidly rotated positions."""
    import dataclasses

    from hydragnn_tpu.data.graph import PadSpec, collate

    if rotation is not None:
        samples = [
            dataclasses.replace(s, pos=s.pos @ rotation.T) for s in samples
        ]
    batch = collate(samples, PadSpec.for_samples(samples))
    params = jax.device_get(state.params)
    bs = jax.device_get(state.batch_stats)
    out = model.apply(
        {"params": params, "batch_stats": bs}, batch, train=False
    )
    return np.asarray(out[0])


def test_run_training_dp_painn_learns_and_stays_equivariant():
    """PaiNN (vector-channel equivariant stack) end to end under the dp
    mesh: loss drops AND the sharded-trained parameters still give
    rotation-invariant scalar predictions — a sharding bug in the
    vector channels would break either (reference FSDP2 force-grad
    regression test, tests/test_fsdp2_force_grad_regression.py)."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(128, seed=21)
    tr, va, te = split_dataset(samples, 0.75)
    config = _config(batch_size=4, num_epoch=5)
    arch = config["NeuralNetwork"]["Architecture"]
    arch.update(mpnn_type="PAINN", num_radial=8)
    config["NeuralNetwork"]["Training"]["Parallelism"] = {
        "scheme": "dp", "data": 8,
    }
    state, model, cfg, hist, _ = run_training(
        config, datasets=(tr, va, te), seed=0
    )
    assert hist.train_loss[-1] < hist.train_loss[0] * 0.8

    probe = _samples(6, seed=99)
    base = _host_predict(state, model, probe)
    rot = _host_predict(state, model, probe, rotation=_rotation_matrix())
    np.testing.assert_allclose(base, rot, rtol=1e-4, atol=1e-5)


def test_run_training_fsdp_mace_learns_and_stays_equivariant():
    """MACE (small lmax) under dp+fsdp param sharding: the irreps path
    (spherical harmonics, CG contractions) must survive GSPMD param
    sharding — loss drops and predictions stay rotation invariant."""
    from hydragnn_tpu.runner import run_training

    # MACE reads x[:, 0] as integer atomic numbers (clamped to 1..118,
    # config.py element embedding) — integer species, target derived
    # from them so the signal survives the embedding.
    def _species_samples(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            k = int(r.integers(5, 10))
            pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
            x = r.integers(1, 9, size=(k, 1)).astype(np.float32)
            out.append(
                GraphSample(
                    x=x,
                    pos=pos,
                    edge_index=radius_graph(pos, 2.5, max_neighbours=12),
                    y_graph=np.array([0.3 * float(x.mean())], np.float32),
                )
            )
        return out

    tr, va, te = split_dataset(_species_samples(96, seed=23), 0.75)
    config = _config(batch_size=4, num_epoch=4)
    arch = config["NeuralNetwork"]["Architecture"]
    arch.update(
        mpnn_type="MACE",
        hidden_dim=8,
        num_radial=6,
        max_ell=1,
        node_max_ell=1,
        correlation=2,
    )
    config["NeuralNetwork"]["Training"]["Parallelism"] = {
        "scheme": "dp", "data": 4, "fsdp": 2,
    }
    state, model, cfg, hist, _ = run_training(
        config, datasets=(tr, va, te), seed=0
    )
    assert np.isfinite(hist.train_loss).all()
    assert hist.train_loss[-1] < hist.train_loss[0]

    probe = _species_samples(6, seed=101)
    base = _host_predict(state, model, probe)
    rot = _host_predict(state, model, probe, rotation=_rotation_matrix())
    np.testing.assert_allclose(base, rot, rtol=1e-4, atol=1e-5)


def test_variable_pad_matches_fixed_pad_losses(monkeypatch):
    """Padding is masked everywhere, so the forced bucket ladder AND
    the auto default must reproduce the fixed-pad loss trajectory
    exactly — same data, same seed, different padded shapes. Any op
    that leaks padding into the math diverges here."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(64, seed=31)
    tr, va, te = split_dataset(samples, 0.75)
    # Vacuity guard: the forced ladder genuinely produces several
    # bucketed shapes on this split — otherwise the "1" run would be
    # byte-identical to "0" and prove nothing. (On THIS heterogeneous
    # split auto resolves to fixed — the spec count exceeds the bucket
    # budget, which is the designed behavior; the auto-takes-ladder
    # case is unit-tested in test_loader_auto_pad_selects_ladder...)
    probe = GraphLoader(tr, 4, shuffle=True, fixed_pad=False)
    assert len(probe.planned_spec_keys()) > 1

    losses = {}
    for mode in ("0", "1", "auto"):
        if mode == "auto":
            monkeypatch.delenv(
                "HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", raising=False
            )
        else:
            monkeypatch.setenv(
                "HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", mode
            )
        config = _config(batch_size=4, num_epoch=3)
        config["NeuralNetwork"]["Training"]["Parallelism"] = {
            "scheme": "single"
        }
        _, _, _, hist, _ = run_training(
            config, datasets=(tr, va, te), seed=0
        )
        losses[mode] = np.asarray(hist.train_loss)
    np.testing.assert_allclose(losses["0"], losses["1"], rtol=2e-4)
    np.testing.assert_allclose(losses["0"], losses["auto"], rtol=2e-4)


def test_dp_variable_pad_matches_fixed_pad_losses(monkeypatch):
    """The dp scheme's per-step spec schedule (data/padschedule.py) must
    reproduce the fixed-pad loss trajectory exactly on the 8-vdev mesh —
    same data, same seed, different padded shapes per step. Any padding
    leak into the vmapped device loss, the graph-weighted mean, or the
    masked remainder group diverges here."""
    from hydragnn_tpu.runner import run_training

    samples = _samples(96, seed=47)
    tr, va, te = split_dataset(samples, 0.75)
    losses = {}
    specs_seen = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", mode)
        config = _config(batch_size=4, num_epoch=3)
        config["NeuralNetwork"]["Training"]["Parallelism"] = {
            "scheme": "dp"
        }
        _, _, _, hist, _ = run_training(
            config, datasets=(tr, va, te), seed=0
        )
        losses[mode] = np.asarray(hist.train_loss)
    np.testing.assert_allclose(losses["0"], losses["1"], rtol=2e-4)

    # Vacuity guard: the schedule genuinely varies specs across steps
    # on this split (otherwise "1" is byte-identical to "0").
    from hydragnn_tpu.data.padschedule import (
        dataset_size_arrays,
        dp_spec_schedule,
    )

    ns, es = dataset_size_arrays(tr)
    sched = dp_spec_schedule(
        ns, es, batch_size=4, n_procs=1, steps_group=8, seed=0,
        shuffle=True,
    )
    assert len(sched.distinct_keys(3)) > 1


def test_dp_spec_schedule_covers_process_shards():
    """Cross-process consistency contract: the schedule built from the
    FULL dataset must cover every process's actual local batches (each
    process builds the same schedule object from the same metadata, so
    equality across processes is by construction; coverage of the real
    sharded loaders is what needs proof)."""
    from hydragnn_tpu.data.diststore import shard_for_process
    from hydragnn_tpu.data.padschedule import (
        dataset_size_arrays,
        dp_spec_schedule,
    )

    samples = _samples(70, seed=11)  # 70 % 2 = 0 shards, ragged batches
    n_procs, steps_group, bs = 2, 2, 4
    ns, es = dataset_size_arrays(samples)
    sched = dp_spec_schedule(
        ns, es, batch_size=bs, n_procs=n_procs,
        steps_group=steps_group, seed=3, shuffle=True,
    )
    equal = len(samples) // n_procs
    for p in range(n_procs):
        block = list(shard_for_process(len(samples), p, n_procs))[:equal]
        shard = [samples[i] for i in block]
        loader = GraphLoader(
            shard, bs, shuffle=True, seed=3, spec_schedule=sched
        )
        for epoch in range(3):
            loader.set_epoch(epoch)
            # _iter_collate raises if any batch exceeds its spec.
            batches = list(loader)
            # Within a step group every batch shares one padded shape.
            for t0 in range(0, len(batches), steps_group):
                group = batches[t0 : t0 + steps_group]
                shapes = {b.x.shape for b in group}
                assert len(shapes) == 1


def test_multibranch_variable_pad_matches_fixed(monkeypatch):
    """Multibranch slot loaders under the shared per-step schedule must
    reproduce the fixed worst-case-pad loss trajectory exactly."""
    from hydragnn_tpu.runner import run_training

    b0 = _samples(40, seed=5, target_rule=1.7)
    b1 = _samples(56, seed=6, target_rule=-0.9)
    sets = [split_dataset(b0, 0.7), split_dataset(b1, 0.7)]
    losses = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("HYDRAGNN_TPU_USE_VARIABLE_GRAPH_SIZE", mode)
        config = _config(batch_size=4, num_epoch=2)
        config["NeuralNetwork"]["Training"]["Parallelism"] = {
            "scheme": "multibranch"
        }
        _, _, _, hist, _ = run_training(config, datasets=sets, seed=0)
        losses[mode] = np.asarray(hist.train_loss)
    np.testing.assert_allclose(losses["0"], losses["1"], rtol=2e-4)
