"""Round-5 advisor satellites: pin the generation-time spherical
harmonics to the runtime basis, and lock the post-b015722 MACE
construction path (host-float64 Wigner D fit) end-to-end through
``models/create.py``.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401


@pytest.mark.parametrize("l", [0, 1, 2, 3])
def test_sh_basis_np_matches_runtime_sh_basis(l):
    """_sh_basis_np (generation-time, host numpy float64) and sh_basis
    (runtime, JAX) evaluate the SAME constants; a normalization or
    ordering change to one must fail here before it silently
    desynchronizes Wigner-D/3j generation from runtime harmonics
    (ADVICE.md round 5, e3.py:290)."""
    import jax

    from hydragnn_tpu.ops.e3 import _sh_basis_np, sh_basis

    rng = np.random.default_rng(11)
    v = rng.normal(size=(64, 3))
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    want = _sh_basis_np(v, l)
    with jax.experimental.enable_x64():
        got = np.asarray(
            sh_basis(np.asarray(v, np.float64), l, normalize=False)
        )[:, l * l : (l + 1) * (l + 1)]
    assert got.dtype == np.float64
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_mace_constructs_and_trains_through_create():
    """CPU regression lock for the live-TPU round-5 failure "Wigner D
    fit failed for l=1" (fixed in b015722 by evaluating the fit
    harmonics in host float64): build MACE end-to-end through the JSON
    config path (models/create.py) and take one finite train step —
    the path that generates every Wigner/3j constant."""
    import jax

    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    rng = np.random.default_rng(3)
    samples = []
    for _ in range(6):
        n = int(rng.integers(6, 10))
        pos = rng.uniform(0, 3.5, (n, 3)).astype(np.float32)
        samples.append(
            GraphSample(
                x=rng.integers(1, 9, size=(n, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 3.0, max_neighbours=12),
                y_graph=np.array([rng.normal()], np.float32),
            )
        )
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "MACE",
                "radius": 3.0,
                "max_neighbours": 12,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "num_radial": 4,
                "max_ell": 2,
                "node_max_ell": 2,
                "correlation": 2,
                "avg_num_neighbors": 8.0,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["energy"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": 6,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            },
        }
    }
    config = update_config(config, samples)
    model, cfg = create_model_config(config)
    assert cfg.mpnn_type == "MACE"
    loader = GraphLoader(samples, 6)
    batch = next(iter(loader))
    params, bs = init_params(model, batch)
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(params, tx, bs)
    step = make_train_step(model, tx, cfg)
    state, tot, tasks = step(state, batch)
    assert np.isfinite(float(tot))


@pytest.mark.parametrize("l", [1, 2])
def test_wigner_d_fit_is_fp64_regardless_of_rot_dtype(l):
    """Regression for the BENCH_TPU ``Wigner D fit failed for l=1: err
    0.00599`` failure: a float32 — or jax-array under default x64-off —
    rotation matrix must not drag the lstsq fit to fp32 (numpy defers
    ``v @ rot.T`` to ``jax.Array.__rmatmul__``), where the 1e-6 fp64
    verification tolerance is unreachable. The fit now coerces to
    float64 numpy up front; the fitted D must be identical whatever the
    input container/dtype, under BOTH x64 settings."""
    import jax

    from hydragnn_tpu.ops.e3 import _rotation_samples, wigner_d_from_sh

    rot64 = _rotation_samples()[0]
    want = wigner_d_from_sh(l, rot64)
    # orthogonal representation sanity
    assert np.allclose(want @ want.T, np.eye(2 * l + 1), atol=1e-8)

    import jax.numpy as jnp

    for cast in (
        lambda r: np.asarray(r, np.float32),
        lambda r: jnp.asarray(r, jnp.float32),  # x64-off default: f32
    ):
        got = wigner_d_from_sh(l, cast(rot64))
        # float32 only rounds the INPUT rotation (~1e-7 per entry); the
        # fit itself stays fp64, so the result matches to that level.
        assert np.abs(got - want).max() < 1e-5

    with jax.experimental.enable_x64():
        got = wigner_d_from_sh(l, jnp.asarray(rot64))
        assert np.array_equal(got, want)  # fp64 in, bitwise-equal fit
