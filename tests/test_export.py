"""AOT inference export (hydragnn_tpu/export.py): serialized-artifact
roundtrip against the live model, file save/load, and the MLIP
energy+forces serving form. The reference analog is its fused-inference
deployment (run-scripts/SC26_fused_inference*.sh).
"""

import numpy as np

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.train.state import create_train_state


def _setup(enable_mlip=False):
    import optax

    rng = np.random.default_rng(0)
    samples = []
    for _ in range(6):
        n = int(rng.integers(5, 9))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        ei = np.stack(
            [np.repeat(np.arange(n), 2), rng.integers(0, n, 2 * n)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei.astype(np.int64),
                y_graph=np.array([float(pos.sum())], np.float32),
                energy=float(pos.sum()),
                forces=rng.normal(size=(n, 3)).astype(np.float32),
            )
        )
    cfg = ModelConfig(
        mpnn_type="SchNet",
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("e", "graph", 1),),
        graph_branches=(BranchSpec(),),
        node_branches=(),
        task_weights=(1.0,),
        radius=3.0,
        num_gaussians=8,
        num_filters=8,
        graph_pooling="add",
        enable_interatomic_potential=enable_mlip,
    )
    model = create_model(cfg)
    spec = PadSpec.for_samples(samples)
    batch = collate(samples[:4], spec)
    params, batch_stats = init_params(model, batch)
    state = create_train_state(params, optax.adam(1e-3), batch_stats)
    batch2 = collate(samples[2:6], spec)  # same bucket shapes
    return model, cfg, state, batch, batch2


def test_export_roundtrip_matches_live_model(tmp_path):
    from hydragnn_tpu.export import export_inference, load_exported

    model, cfg, state, batch, batch2 = _setup()
    path = str(tmp_path / "model.hlo")
    blob = export_inference(model, cfg, state, batch, path=path)
    assert len(blob) > 100
    # cross-backend serving: the artifact must record both platforms
    from jax import export as jax_export

    assert set(jax_export.deserialize(blob).platforms) >= {"cpu", "tpu"}
    fn = load_exported(path)

    live = model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        batch2,
        train=False,
    )
    exported = fn(batch2)
    assert len(exported) == len(live)
    np.testing.assert_allclose(
        np.asarray(exported[0]), np.asarray(live[0]), rtol=1e-5, atol=1e-6
    )


def test_export_bytes_source():
    from hydragnn_tpu.export import export_inference, load_exported

    model, cfg, state, batch, _ = _setup()
    blob = export_inference(model, cfg, state, batch)
    fn = load_exported(blob)
    out = fn(batch)
    assert np.isfinite(np.asarray(out[0])).all()


def test_export_mlip_energy_forces():
    """with_forces bakes the grad-of-energy path into the artifact."""
    from hydragnn_tpu.export import export_inference, load_exported
    from hydragnn_tpu.train.mlip import energy_and_forces

    model, cfg, state, batch, batch2 = _setup(enable_mlip=True)
    blob = export_inference(
        model, cfg, state, batch, with_forces=True
    )
    fn = load_exported(blob)
    ge, forces = fn(batch2)
    ge_live, forces_live, _ = energy_and_forces(
        model,
        {"params": state.params, "batch_stats": state.batch_stats},
        batch2,
        cfg,
        train=False,
    )
    np.testing.assert_allclose(
        np.asarray(ge), np.asarray(ge_live), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(forces), np.asarray(forces_live), rtol=1e-4, atol=1e-5
    )


def test_export_roundtrip_packed_shape_bit_equal():
    """Packed-shape coverage (ISSUE 11): on a bin-packed budget-shaped
    GraphBatch the exported artifact is BIT-EQUAL to the live jitted
    forward — the serving engine AOT-compiles the same make_forward
    program, so this is the exported-forward contract the serving path
    rides (docs/SERVING.md)."""
    from hydragnn_tpu.data.graph import PackSpec
    from hydragnn_tpu.export import (
        export_inference,
        load_exported,
        make_forward,
    )

    model, cfg, state, batch, _ = _setup()
    # a packed budget spec: lane-rounded, NOT a ladder point, with
    # generous slack slots like real FFD tail bins
    rng = np.random.default_rng(5)
    samples = []
    for _ in range(5):
        n = int(rng.integers(5, 9))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        ei = np.stack(
            [np.repeat(np.arange(n), 2), rng.integers(0, n, 2 * n)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei.astype(np.int64),
                y_graph=np.array([float(pos.sum())], np.float32),
                energy=float(pos.sum()),
                forces=rng.normal(size=(n, 3)).astype(np.float32),
            )
        )
    budget = PackSpec(num_nodes=56, num_edges=96, num_graphs=7)
    packed = collate(samples, budget.pad_spec())
    blob = export_inference(model, cfg, state, packed)
    fn = load_exported(blob)

    variables = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
    }
    live = jax.jit(make_forward(model, cfg, variables))(packed)
    exported = fn(packed)
    assert len(exported) == len(live)
    for a, b in zip(exported, live):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_packed_edge_mask_slots_are_inert():
    """The artifact's masking contract on packed shapes: rewriting the
    PADDED edge slots (redirecting them from the padding node onto
    real nodes, edge_mask still False) must not move a single output
    bit — masked contributions are exact zeros, so real graphs cannot
    see them. A failure here means a model consumed padding edges
    through the point-at-padding-node convention instead of the
    mask."""
    import dataclasses

    from hydragnn_tpu.data.graph import PackSpec
    from hydragnn_tpu.export import export_inference, load_exported

    model, cfg, state, _, _ = _setup()
    rng = np.random.default_rng(7)
    samples = []
    for _ in range(4):
        n = int(rng.integers(5, 9))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        ei = np.stack(
            [np.repeat(np.arange(n), 2), rng.integers(0, n, 2 * n)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(n, 1)).astype(np.float32),
                pos=pos,
                edge_index=ei.astype(np.int64),
                y_graph=np.array([float(pos.sum())], np.float32),
                energy=float(pos.sum()),
                forces=rng.normal(size=(n, 3)).astype(np.float32),
            )
        )
    budget = PackSpec(num_nodes=48, num_edges=80, num_graphs=6)
    packed = collate(samples, budget.pad_spec())
    blob = export_inference(model, cfg, state, packed)
    fn = load_exported(blob)
    base = fn(packed)

    e_real = sum(s.num_edges for s in samples)
    senders = np.array(packed.senders)
    receivers = np.array(packed.receivers)
    n_pad_edges = senders.shape[0] - e_real
    assert n_pad_edges > 0, "fixture must exercise padded edge slots"
    senders[e_real:] = rng.integers(0, 5, n_pad_edges)
    receivers[e_real:] = rng.integers(0, 5, n_pad_edges)
    poked = dataclasses.replace(
        packed,
        senders=jnp.asarray(senders),
        receivers=jnp.asarray(receivers),
    )
    out = fn(poked)
    for a, b in zip(out, base):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_cli_from_checkpoint(tmp_path):
    """python -m hydragnn_tpu.export <config> <out>: restores the run's
    checkpoint and writes a servable artifact (the checkpoint-to-
    deployment workflow, no retraining)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = f"""
import json, sys; sys.path.insert(0, {repo!r})
import hydragnn_tpu
from hydragnn_tpu.data.synthetic import deterministic_graph_data
deterministic_graph_data("dataset/demo", number_configurations=40, seed=1)
config = json.load(open({repo!r} + "/tests/inputs/ci.json"))
config["Dataset"]["path"] = {{"total": "dataset/demo"}}
config["NeuralNetwork"]["Training"]["num_epoch"] = 2
hydragnn_tpu.run_training(config)
json.dump(config, open("cfg.json", "w"))
"""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PYTHONPATH=repo,
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=420,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    r = subprocess.run(
        [sys.executable, "-m", "hydragnn_tpu.export", "cfg.json",
         "model.hlo"],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=420,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    info = json.loads(r.stdout.strip().splitlines()[-1])
    assert info["artifact"] == "model.hlo"
    assert (tmp_path / "model.hlo").stat().st_size == info["bytes"] > 100
