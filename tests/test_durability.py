"""Durability subsystem tests (ISSUE 6, docs/DURABILITY.md).

Crash-safety is proved, not claimed: fault injection (utils/faults.py)
lands a simulated kill or transient I/O error at the exact instruction a
real one would strike, and these tests assert the on-disk contract — a
kill at ANY point during a save leaves a restorable checkpoint (msgpack
and orbax), loads validate before trusting, the async writer retries
transients and surfaces exhaustion without ever crashing training, and
the ``skip_to`` fast-forward delivers a bit-identical batch suffix
versus a fresh iterator on every feed (serial, packed, pipeline,
superstep-grouped, dp ``[D, ...]``). The end-to-end SIGKILL+resume
bitwise-identity proof lives in ``__graft_entry__.preemption_drill``.
"""

import os
import threading
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils import checkpoint as ck


@pytest.fixture(autouse=True)
def _fault_free(tmp_path, monkeypatch):
    """Every test starts disarmed in its own checkpoint root."""
    monkeypatch.chdir(tmp_path)
    faults.reset()
    yield
    faults.reset()


def _state(seed=0):
    r = np.random.default_rng(seed)
    return {
        "params": {
            "w": r.normal(size=(4, 3)).astype(np.float32),
            "b": r.normal(size=(3,)).astype(np.float32),
        },
        "step": np.asarray(seed, np.int32),
    }


def _jstate(seed=0):
    return jax.tree_util.tree_map(jnp.asarray, _state(seed))


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(u), np.asarray(v))
        for u, v in zip(la, lb)
    )


# ----------------------------------------------------------------------
# Fault grammar
# ----------------------------------------------------------------------


def test_fault_spec_grammar_rejects_garbage():
    with pytest.raises(ValueError):
        faults.install("write_fail:only_two_parts")
    with pytest.raises(ValueError):
        faults.install("no_such_kind:a:1")
    faults.install(
        "write_fail:resume:1;slow_write:epoch:0.01:2;crash:write_tmp:3"
    )
    assert faults.active()
    faults.reset()
    assert not faults.active()


def test_write_fail_counts_down_and_disarms():
    faults.install("write_fail:target:2")
    for _ in range(2):
        with pytest.raises(OSError):
            faults.on_write("/some/target/path")
    faults.on_write("/some/target/path")  # budget spent: no raise
    faults.on_write("/other/path")  # never matched


# ----------------------------------------------------------------------
# Kill-mid-save restorability: msgpack
# ----------------------------------------------------------------------


def test_kill_mid_write_leaves_previous_msgpack_restorable():
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=0)
    # A kill lands mid tmp write of BOTH artifacts of the next save
    # (per-epoch file first): the previous 'latest' and epoch files
    # must stay restorable and the truncated tmp must never be
    # trusted.
    faults.install("crash:write_tmp:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint("run", b, epoch=1)
    faults.reset()
    restored = ck.load_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    # The interrupted epoch-1 artifact either never appeared or is
    # fully restorable — never a truncated file at the final path.
    p1 = os.path.join("./logs", "run", "checkpoint_epoch1.msgpack")
    if os.path.exists(p1):
        assert _leaves_equal(ck.load_checkpoint("run", _state(9), epoch=1), b)


def test_kill_between_epoch_and_latest_write_keeps_both_restorable():
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=0)
    # Crash on the SECOND artifact (the 'latest' refresh, a hard-link
    # publish of the epoch file) — epoch file already durable, latest
    # still the old bytes.
    faults.install("crash:publish_link:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint("run", b, epoch=1)
    faults.reset()
    assert _leaves_equal(
        ck.load_checkpoint("run", _state(9), epoch=1), b
    )
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), a)


def test_load_falls_back_from_corrupt_latest(capsys):
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=2)
    ck.save_checkpoint("run", b, epoch=3)
    # In-place truncation (a pre-durability writer or partial in-place
    # copy — our own writers only ever tmp+replace). 'latest' hard-
    # links the newest epoch file, so the shared inode takes epoch3
    # down with it; the fallback chain must recover from the newest
    # INDEPENDENT artifact (epoch2).
    latest = os.path.join("./logs", "run", "checkpoint.msgpack")
    blob = open(latest, "rb").read()
    open(latest, "wb").write(blob[: len(blob) // 3])
    restored = ck.load_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    out = capsys.readouterr().out
    assert "not restorable" in out and "falling back" in out


def test_load_raises_when_nothing_restorable():
    os.makedirs("./logs/run", exist_ok=True)
    open("./logs/run/checkpoint.msgpack", "wb").write(b"junk")
    open("./logs/run/checkpoint_epoch0.msgpack", "wb").write(b"junk")
    with pytest.raises(FileNotFoundError):
        ck.load_checkpoint("run", _state(9))


# ----------------------------------------------------------------------
# Kill-mid-save restorability: orbax
# ----------------------------------------------------------------------


def test_orbax_crash_between_replaces_falls_back_to_old(capsys):
    a, b = _jstate(1), _jstate(2)
    ck.save_checkpoint_sharded("run", a)
    # The two-rename window: 'final' was renamed aside, the new dir
    # not yet in place — exactly where a kill leaves no 'final'.
    faults.install("crash:orbax_between_replaces:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint_sharded("run", b)
    faults.reset()
    base = os.path.join("./logs", "run", "orbax")
    assert not os.path.isdir(os.path.join(base, "final"))
    assert os.path.isdir(os.path.join(base, "final.old"))
    restored = ck.load_checkpoint_sharded("run", _jstate(9))
    assert _leaves_equal(restored, a)
    assert "falling back" in capsys.readouterr().out
    # The next successful save sweeps the crash leftovers.
    ck.save_checkpoint_sharded("run", b)
    assert not os.path.isdir(os.path.join(base, "final.old"))
    assert _leaves_equal(
        ck.load_checkpoint_sharded("run", _jstate(9)), b
    )


def test_orbax_stale_latest_pointer_falls_back(capsys):
    a = _jstate(1)
    ck.save_checkpoint_sharded("run", a, epoch=2)
    base = os.path.join("./logs", "run", "orbax")
    ck._write_pointer(base, "LATEST", "epoch_99")  # crashed before dir
    restored = ck.load_checkpoint_sharded("run", _jstate(9))
    assert _leaves_equal(restored, a)
    assert "LATEST pointer targets missing dir" in capsys.readouterr().out


# ----------------------------------------------------------------------
# Resume manifest + container
# ----------------------------------------------------------------------


def test_encode_acc_round_trip_is_bit_exact():
    # Values chosen to be unrepresentable in short decimal — a decimal
    # round-trip would be off by an ulp; the uint32-bit encoding must
    # not be.
    loss = np.float32(0.1) + np.float32(1e-7)
    tasks = np.asarray([np.float32(1.0) / 3, np.float32(2.0) / 7], np.float32)
    n = np.float32(96.0)
    dec = ck.decode_acc(ck.encode_acc((loss, tasks, n)))
    assert dec[0].tobytes() == loss.tobytes()
    assert dec[1].tobytes() == tasks.tobytes()
    assert dec[2].tobytes() == n.tobytes()
    assert ck.encode_acc(None) is None
    assert ck.decode_acc(None) is None


def test_resume_container_round_trip_and_fallback(capsys):
    a = _state(1)
    w = ck.CheckpointWriter(
        "run", async_enabled=False, plan_seed=7, fingerprint="abc"
    )
    w.save(a, kind="auto", epoch=2, step=5)
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    assert (manifest["epoch"], manifest["step"]) == (2, 5)
    assert manifest["plan_seed"] == 7
    assert manifest["config_fingerprint"] == "abc"
    # Corrupt container + a good plain checkpoint: loud epoch-boundary
    # fallback, never a crash mid-restart.
    ck.save_checkpoint("run", a, epoch=0)
    path = os.path.join("./logs", "run", ck._RESUME_FILE)
    open(path, "wb").write(b"HGTPUCK1garbage")
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest is None
    assert _leaves_equal(restored, a)
    assert "falling back" in capsys.readouterr().out


def test_config_fingerprint_volatile_keys():
    cfg = {
        "NeuralNetwork": {"Training": {"batch_size": 8, "num_epoch": 3}},
        "Dataset": {"name": "x"},
    }
    f0 = ck.config_fingerprint(cfg)
    cfg2 = {
        "NeuralNetwork": {
            "Training": {
                "batch_size": 8,
                "num_epoch": 30,  # extending a run keeps the cursor
                "continue": 1,
                "Checkpoint": {"interval_steps": 5},
            }
        },
        "Dataset": {"name": "x"},
    }
    assert ck.config_fingerprint(cfg2) == f0
    cfg3 = {
        "NeuralNetwork": {"Training": {"batch_size": 16, "num_epoch": 3}},
        "Dataset": {"name": "x"},
    }
    assert ck.config_fingerprint(cfg3) != f0


# ----------------------------------------------------------------------
# Async writer: retry/backoff, exhaustion, backpressure, crash safety
# ----------------------------------------------------------------------


def test_writer_retries_transient_failures_then_succeeds():
    faults.install("write_fail:resume:2")
    w = ck.CheckpointWriter("run", retries=3, backoff_s=0.01)
    w.save(_state(1), kind="auto", epoch=0, step=3)
    w.close()
    assert w.last_error is None
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 3
    assert _leaves_equal(restored, _state(1))


def test_writer_exhausts_retries_surfaces_and_training_continues():
    faults.install("write_fail:resume:10")
    w = ck.CheckpointWriter("run", retries=1, backoff_s=0.01)
    w.save(_state(1), kind="auto", epoch=0, step=1)  # must NOT raise
    w.wait()
    assert isinstance(w.last_error, OSError)
    # The writer (and "training") is still alive: the next save, with
    # the fault budget spent, lands durably.
    faults.reset()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    w.close()
    assert w.last_error is None
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2
    assert _leaves_equal(restored, _state(2))


def test_writer_serialization_failure_surfaces_never_raises(monkeypatch):
    # A to_bytes failure (e.g. MemoryError building the full in-memory
    # msgpack copy) rides the same contract as a write failure: save()
    # never raises into the train loop (sync mode runs on the caller
    # thread), the error surfaces on last_error, and the writer — and
    # its worker thread — survive to land the next save.
    w = ck.CheckpointWriter("run", async_enabled=False)

    def boom(_):
        raise MemoryError("no room for the serialized copy")

    monkeypatch.setattr(ck.serialization, "to_bytes", boom)
    w.save(_state(1), kind="auto", epoch=0, step=1)  # must NOT raise
    assert isinstance(w.last_error, MemoryError)
    monkeypatch.undo()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    w.close()
    assert w.last_error is None
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2


def test_writer_single_writer_backpressure_blocks_next_save_only():
    faults.install("slow_write:resume:0.25:1")
    w = ck.CheckpointWriter("run", retries=0)
    t0 = time.perf_counter()
    w.save(_state(1), kind="auto", epoch=0, step=1)
    first = time.perf_counter() - t0
    # The first save returns while the slow write is still in flight —
    # the train step between saves is never blocked by serialization.
    assert first < 0.2, f"snapshot phase blocked {first:.3f}s"
    t1 = time.perf_counter()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    waited = time.perf_counter() - t1
    assert waited >= 0.15, "second save must wait out the in-flight write"
    w.close()
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2


def test_writer_crash_mid_container_write_keeps_previous_container():
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="auto", epoch=1, step=4)
    # InjectedCrash models the kill: the sync writer records it (a real
    # kill ends the process; what matters is the on-disk state).
    faults.install("crash:write_tmp:1")
    w.save(_state(2), kind="auto", epoch=1, step=8)
    assert isinstance(w.last_error, faults.InjectedCrash)
    faults.reset()
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 4
    assert _leaves_equal(restored, _state(1))


def test_writer_orbax_format_autosave_and_resume_pointer():
    a = _jstate(1)
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=False)
    w.save(a, kind="auto", epoch=3, step=2)
    w.close()
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(9)
    )
    assert (manifest["epoch"], manifest["step"]) == (3, 2)
    assert _leaves_equal(restored, a)


def test_writer_epoch_kind_prunes_and_updates_latest():
    w = ck.CheckpointWriter("run", keep=2, async_enabled=False)
    for e in range(4):
        w.save(_state(e), kind="epoch", epoch=e + 1, step=0, label_epoch=e)
    w.close()
    d = os.path.join("./logs", "run")
    eps = sorted(
        f for f in os.listdir(d) if f.startswith("checkpoint_epoch")
    )
    assert eps == ["checkpoint_epoch2.msgpack", "checkpoint_epoch3.msgpack"]
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), _state(3))


# ----------------------------------------------------------------------
# Validate-finite gate (ISSUE 10, "Divergence recovery"): a non-finite
# state is NEVER published as 'latest' (or any artifact) — the
# divergence guard's rollback target is guaranteed good.
# ----------------------------------------------------------------------


def _poisoned_state(seed=0):
    s = _state(seed)
    s["params"]["w"][1, 1] = np.nan
    return s


def test_writer_rejects_non_finite_state(capsys):
    """A NaN'd state must leave EVERY artifact — 'latest', the epoch
    file, the resume container — at its previous good bytes, counted
    on rejected_saves and without touching last_error (a rejection is
    the gate working, not a failure)."""
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="epoch", epoch=1, step=0, label_epoch=0)
    d = os.path.join("./logs", "run")
    before = {
        f: open(os.path.join(d, f), "rb").read() for f in os.listdir(d)
    }
    w.save(_poisoned_state(2), kind="epoch", epoch=2, step=0, label_epoch=1)
    w.save(_poisoned_state(2), kind="auto", epoch=2, step=7)
    w.save(_poisoned_state(2), kind="final", epoch=2, step=0)
    assert w.rejected_saves == 3
    assert w.last_error is None
    assert "REJECTED" in capsys.readouterr().out
    after = {
        f: open(os.path.join(d, f), "rb").read() for f in os.listdir(d)
    }
    assert after == before  # no new files, no byte changed
    # a good save after the rejections writes normally
    w.save(_state(3), kind="epoch", epoch=3, step=0, label_epoch=2)
    w.close()
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), _state(3))
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["epoch"] == 3
    assert _leaves_equal(restored, _state(3))


def test_writer_async_rejection_never_blocks_or_raises():
    """The gate runs on the background phase: the caller's save()
    returns promptly and the rejection surfaces on the counter after
    the drain."""
    w = ck.CheckpointWriter("run")
    w.save(_state(1), kind="auto", epoch=0, step=1)
    w.save(_poisoned_state(2), kind="auto", epoch=0, step=2)
    w.wait()
    assert w.rejected_saves == 1 and w.last_error is None
    w.close()
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 1  # the good cursor survived


def test_writer_validate_finite_opt_out():
    """Training.Checkpoint.validate_finite: false disables the gate
    (and checkpoint_settings carries the knob)."""
    assert ck.checkpoint_settings(
        {"Checkpoint": {"enabled": True}}
    ).validate_finite
    assert not ck.checkpoint_settings(
        {"Checkpoint": {"enabled": True, "validate_finite": False}}
    ).validate_finite
    w = ck.CheckpointWriter(
        "run", async_enabled=False, validate_finite=False
    )
    w.save(_poisoned_state(1), kind="final", epoch=0, step=0)
    w.close()
    assert w.rejected_saves == 0
    restored = ck.load_checkpoint("run", _state(9))
    assert np.isnan(np.asarray(restored["params"]["w"])[1, 1])


def test_writer_rejects_non_finite_orbax_state():
    """Same gate on the orbax path: the RESUME/LATEST pointers keep
    targeting the good artifact."""
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=False)
    w.save(_jstate(1), kind="auto", epoch=0, step=2)
    bad = jax.tree_util.tree_map(jnp.asarray, _poisoned_state(2))
    w.save(bad, kind="final", epoch=1, step=0)
    assert w.rejected_saves == 1
    w.close()
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(9)
    )
    assert (manifest["epoch"], manifest["step"]) == (0, 2)
    assert _leaves_equal(restored, _jstate(1))


def test_writer_kill_then_rejected_save_keeps_previous_container():
    """Compose with the crash tests: a kill mid-write followed by a
    diverged (rejected) save still leaves the ORIGINAL container as
    the resume point — the gate never 'recovers' a crash by writing
    corruption over it."""
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="auto", epoch=1, step=4)
    faults.install("crash:write_tmp:1")
    w.save(_state(2), kind="auto", epoch=1, step=8)  # killed mid-write
    assert isinstance(w.last_error, faults.InjectedCrash)
    faults.reset()
    w.save(_poisoned_state(3), kind="auto", epoch=1, step=12)  # rejected
    assert w.rejected_saves == 1
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 4
    assert _leaves_equal(restored, _state(1))


# ----------------------------------------------------------------------
# skip_to: bit-identical batch suffix on every feed
# ----------------------------------------------------------------------

from hydragnn_tpu.data.graph import GraphSample, MacroBatch  # noqa: E402
from hydragnn_tpu.ops.neighbors import radius_graph  # noqa: E402


def _mols(n, lo=5, hi=11, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(lo, hi))
        pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.integers(0, 3, (k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                y_graph=np.array([r.normal()], np.float32),
            )
        )
    return out


def _host(item):
    # np.array COPIES: these tests hold every delivered batch past the
    # pipeline's buffer-hold window, so a view of a pooled host buffer
    # would be recycled under us (the loop's consumers finish a batch
    # before fetching that deep — holding an epoch is test-only usage).
    if isinstance(item, MacroBatch):
        return (item.k, jax.tree_util.tree_map(np.array, item.batch))
    return (1, jax.tree_util.tree_map(np.array, item))


def _suffix_matches(full, resumed, skip):
    assert len(resumed) == len(full) - skip, (
        f"suffix length {len(resumed)} != {len(full) - skip}"
    )
    for a, b in zip(full[skip:], resumed):
        assert a[0] == b[0]
        assert _leaves_equal(a[1], b[1])


@pytest.mark.parametrize("packing", [False, True])
def test_skip_to_serial_suffix_bit_identical(packing):
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(60, seed=3)

    def _mk():
        return GraphLoader(
            samples, 5, shuffle=True, seed=1, packing=packing
        )

    for epoch in (0, 2):
        base = _mk()
        base.set_epoch(epoch)
        full = [_host(b) for b in base]
        for skip in (1, len(full) // 2, len(full) - 1):
            lo = _mk()
            lo.set_epoch(epoch)
            lo.skip_to(skip)
            _suffix_matches(full, [_host(b) for b in lo], skip)
            # One-shot: the NEXT epoch iterates in full again.
            assert len([_host(b) for b in lo]) == len(full)


def test_skip_to_pipeline_suffix_bit_identical():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _mols(60, seed=4)
    serial = GraphLoader(samples, 5, shuffle=True, seed=2, packing=True)
    serial.set_epoch(1)
    full = [_host(b) for b in serial]
    skip = len(full) // 2
    pipe = ParallelPipelineLoader(
        GraphLoader(samples, 5, shuffle=True, seed=2, packing=True),
        workers=2,
        depth=2,
        to_device=False,
    )
    pipe.set_epoch(1)
    pipe.skip_to(skip)
    _suffix_matches(full, [_host(b) for b in pipe], skip)


def test_skip_to_superstep_groups_cut_from_full_plan():
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader

    samples = _mols(64, seed=5)

    def _flat():
        lo = GraphLoader(samples, 4, shuffle=True, seed=3, packing=True)
        lo.set_epoch(0)
        return [_host(b) for b in lo]

    flat = _flat()
    k = 2
    grouped = SuperstepLoader(
        GraphLoader(samples, 4, shuffle=True, seed=3, packing=True),
        k=k,
        to_device=False,
    )
    grouped.loader.set_epoch(0)
    full_groups = [_host(b) for b in grouped]
    # cursor on a delivery boundary: resumed macros are the exact
    # delivery suffix of the uninterrupted run
    steps_per = [g[0] for g in full_groups]
    skip_deliveries = len(full_groups) // 2
    skip_steps = sum(steps_per[:skip_deliveries])
    grouped.loader.set_epoch(0)
    grouped.skip_to(skip_steps)
    resumed = [_host(b) for b in grouped]
    _suffix_matches(full_groups, resumed, skip_deliveries)
    # flat content sanity: the resumed steps are the flat plan suffix
    n_steps = sum(g[0] for g in resumed)
    assert n_steps == len(flat) - skip_steps


def test_skip_to_cursor_inside_group_degrades_to_singles(capsys):
    from hydragnn_tpu.data.loader import drop_consumed_groups

    groups = [[("a", 1), ("b", 1)], [("c", 1), ("d", 1)], [("e", 1)]]
    out = drop_consumed_groups(groups, 3)
    # group 1 fully consumed; cursor inside group 2 -> remainder
    # delivered as singles, then the tail group untouched
    assert out == [[("d", 1)], [("e", 1)]]
    assert "lands inside a superstep group" in capsys.readouterr().out
    assert drop_consumed_groups(groups, 0) == groups
    assert drop_consumed_groups(groups, 5) == []


def test_skip_to_dp_stacked_suffix_bit_identical():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(160, seed=6)
    mesh = make_mesh({"data": 8})

    def _mk():
        return DPLoader(
            GraphLoader(
                samples, 4, shuffle=True, seed=0, packing=True,
                pack_dp_shards=8,
            ),
            mesh,
        )

    base = _mk()
    base.set_epoch(0)
    full = [_host(b) for b in base]
    skip = len(full) // 2
    lo = _mk()
    lo.set_epoch(0)
    lo.skip_to(skip)
    _suffix_matches(full, [_host(b) for b in lo], skip)


def test_skip_to_prefetch_delegates():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.prefetch import PrefetchLoader

    samples = _mols(40, seed=7)
    serial = GraphLoader(samples, 5, shuffle=True, seed=4)
    serial.set_epoch(0)
    full = [_host(b) for b in serial]
    skip = 3
    pf = PrefetchLoader(
        GraphLoader(samples, 5, shuffle=True, seed=4), to_device=False
    )
    pf.set_epoch(0)
    pf.skip_to(skip)
    # the worker thread winds down with its iterator — no explicit
    # shutdown (stop-aware queue put; see prefetch.py)
    _suffix_matches(full, [_host(b) for b in pf], skip)


def test_skip_to_never_seeds_the_replay_cache():
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(30, seed=8)
    lo = GraphLoader(samples, 5, cache_batches=True)
    lo.skip_to(2)
    partial = list(lo)
    assert lo._batch_cache is None, (
        "a fast-forwarded (partial) epoch must not become the cache"
    )
    full = list(lo)
    assert len(full) == len(partial) + 2
    assert lo._batch_cache is not None
    # and a cached loader fast-forwards by slicing the cache
    lo.skip_to(2)
    again = [_host(b) for b in lo]
    _suffix_matches([_host(b) for b in full], again, 2)


def test_find_continue_log_name_resolves_num_epoch_drift():
    from hydragnn_tpu.utils.checkpoint import find_continue_log_name

    # Extending num_epoch is the resume-after-completion flow, but the
    # derived log name encodes it — the continue must still find the
    # run it is continuing (and prefer an exact or in-flight name).
    ck.save_checkpoint("run_SchNet_hd16_l2_e2", _state(1), epoch=1)
    assert (
        find_continue_log_name("run_SchNet_hd16_l2_e4")
        == "run_SchNet_hd16_l2_e2"
    )
    assert (
        find_continue_log_name("run_SchNet_hd16_l2_e2")
        == "run_SchNet_hd16_l2_e2"
    )
    assert (
        find_continue_log_name(
            "other_e4", preferred="run_SchNet_hd16_l2_e2"
        )
        == "run_SchNet_hd16_l2_e2"
    )
    # nothing restorable anywhere: the derived name passes through
    assert find_continue_log_name("fresh_run_e8") == "fresh_run_e8"


def test_find_continue_log_name_rejects_foreign_fingerprint(capsys):
    from hydragnn_tpu.utils.checkpoint import find_continue_log_name

    w = ck.CheckpointWriter(
        "run_GIN_hd8_l2_e2", async_enabled=False, fingerprint="aaaa"
    )
    w.save(_state(1), kind="final", epoch=2, step=0)
    w.close()
    # Same stored fingerprint: the num_epoch-drifted sibling is adopted.
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4", fingerprint="aaaa")
        == "run_GIN_hd8_l2_e2"
    )
    # Different config (fingerprint mismatch): the sibling must NOT
    # become this run's WRITE target — save_config/checkpoint saves/
    # pruning would clobber the other run's artifacts.
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4", fingerprint="bbbb")
        == "run_GIN_hd8_l2_e4"
    )
    assert "not adopting" in capsys.readouterr().out
    # No fingerprint given: legacy behavior (restore-side guard only).
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4")
        == "run_GIN_hd8_l2_e2"
    )
