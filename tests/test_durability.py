"""Durability subsystem tests (ISSUE 6, docs/DURABILITY.md).

Crash-safety is proved, not claimed: fault injection (utils/faults.py)
lands a simulated kill or transient I/O error at the exact instruction a
real one would strike, and these tests assert the on-disk contract — a
kill at ANY point during a save leaves a restorable checkpoint (msgpack
and orbax), loads validate before trusting, the async writer retries
transients and surfaces exhaustion without ever crashing training, and
the ``skip_to`` fast-forward delivers a bit-identical batch suffix
versus a fresh iterator on every feed (serial, packed, pipeline,
superstep-grouped, dp ``[D, ...]``). The end-to-end SIGKILL+resume
bitwise-identity proof lives in ``__graft_entry__.preemption_drill``.
"""

import os
import threading
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils import checkpoint as ck


@pytest.fixture(autouse=True)
def _fault_free(tmp_path, monkeypatch):
    """Every test starts disarmed in its own checkpoint root."""
    monkeypatch.chdir(tmp_path)
    faults.reset()
    yield
    faults.reset()


def _state(seed=0):
    r = np.random.default_rng(seed)
    return {
        "params": {
            "w": r.normal(size=(4, 3)).astype(np.float32),
            "b": r.normal(size=(3,)).astype(np.float32),
        },
        "step": np.asarray(seed, np.int32),
    }


def _jstate(seed=0):
    return jax.tree_util.tree_map(jnp.asarray, _state(seed))


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(u), np.asarray(v))
        for u, v in zip(la, lb)
    )


# ----------------------------------------------------------------------
# Fault grammar
# ----------------------------------------------------------------------


def test_fault_spec_grammar_rejects_garbage():
    with pytest.raises(ValueError):
        faults.install("write_fail:only_two_parts")
    with pytest.raises(ValueError):
        faults.install("no_such_kind:a:1")
    faults.install(
        "write_fail:resume:1;slow_write:epoch:0.01:2;crash:write_tmp:3"
    )
    assert faults.active()
    faults.reset()
    assert not faults.active()


def test_write_fail_counts_down_and_disarms():
    faults.install("write_fail:target:2")
    for _ in range(2):
        with pytest.raises(OSError):
            faults.on_write("/some/target/path")
    faults.on_write("/some/target/path")  # budget spent: no raise
    faults.on_write("/other/path")  # never matched


# ----------------------------------------------------------------------
# Kill-mid-save restorability: msgpack
# ----------------------------------------------------------------------


def test_kill_mid_write_leaves_previous_msgpack_restorable():
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=0)
    # A kill lands mid tmp write of BOTH artifacts of the next save
    # (per-epoch file first): the previous 'latest' and epoch files
    # must stay restorable and the truncated tmp must never be
    # trusted.
    faults.install("crash:write_tmp:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint("run", b, epoch=1)
    faults.reset()
    restored = ck.load_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    # The interrupted epoch-1 artifact either never appeared or is
    # fully restorable — never a truncated file at the final path.
    p1 = os.path.join("./logs", "run", "checkpoint_epoch1.msgpack")
    if os.path.exists(p1):
        assert _leaves_equal(ck.load_checkpoint("run", _state(9), epoch=1), b)


def test_kill_between_epoch_and_latest_write_keeps_both_restorable():
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=0)
    # Crash on the SECOND artifact (the 'latest' refresh, a hard-link
    # publish of the epoch file) — epoch file already durable, latest
    # still the old bytes.
    faults.install("crash:publish_link:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint("run", b, epoch=1)
    faults.reset()
    assert _leaves_equal(
        ck.load_checkpoint("run", _state(9), epoch=1), b
    )
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), a)


def test_load_falls_back_from_corrupt_latest(capsys):
    a, b = _state(1), _state(2)
    ck.save_checkpoint("run", a, epoch=2)
    ck.save_checkpoint("run", b, epoch=3)
    # In-place truncation (a pre-durability writer or partial in-place
    # copy — our own writers only ever tmp+replace). 'latest' hard-
    # links the newest epoch file, so the shared inode takes epoch3
    # down with it; the fallback chain must recover from the newest
    # INDEPENDENT artifact (epoch2).
    latest = os.path.join("./logs", "run", "checkpoint.msgpack")
    blob = open(latest, "rb").read()
    open(latest, "wb").write(blob[: len(blob) // 3])
    restored = ck.load_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    out = capsys.readouterr().out
    assert "not restorable" in out and "falling back" in out


def test_load_raises_when_nothing_restorable():
    os.makedirs("./logs/run", exist_ok=True)
    open("./logs/run/checkpoint.msgpack", "wb").write(b"junk")
    open("./logs/run/checkpoint_epoch0.msgpack", "wb").write(b"junk")
    with pytest.raises(FileNotFoundError):
        ck.load_checkpoint("run", _state(9))


# ----------------------------------------------------------------------
# Kill-mid-save restorability: orbax
# ----------------------------------------------------------------------


def test_orbax_crash_between_replaces_falls_back_to_old(capsys):
    a, b = _jstate(1), _jstate(2)
    ck.save_checkpoint_sharded("run", a)
    # The two-rename window: 'final' was renamed aside, the new dir
    # not yet in place — exactly where a kill leaves no 'final'.
    faults.install("crash:orbax_between_replaces:1")
    with pytest.raises(faults.InjectedCrash):
        ck.save_checkpoint_sharded("run", b)
    faults.reset()
    base = os.path.join("./logs", "run", "orbax")
    assert not os.path.isdir(os.path.join(base, "final"))
    assert os.path.isdir(os.path.join(base, "final.old"))
    restored = ck.load_checkpoint_sharded("run", _jstate(9))
    assert _leaves_equal(restored, a)
    assert "falling back" in capsys.readouterr().out
    # The next successful save sweeps the crash leftovers.
    ck.save_checkpoint_sharded("run", b)
    assert not os.path.isdir(os.path.join(base, "final.old"))
    assert _leaves_equal(
        ck.load_checkpoint_sharded("run", _jstate(9)), b
    )


def test_orbax_stale_latest_pointer_falls_back(capsys):
    a = _jstate(1)
    ck.save_checkpoint_sharded("run", a, epoch=2)
    base = os.path.join("./logs", "run", "orbax")
    ck._write_pointer(base, "LATEST", "epoch_99")  # crashed before dir
    restored = ck.load_checkpoint_sharded("run", _jstate(9))
    assert _leaves_equal(restored, a)
    assert "LATEST pointer targets missing dir" in capsys.readouterr().out


# ----------------------------------------------------------------------
# Resume manifest + container
# ----------------------------------------------------------------------


def test_encode_acc_round_trip_is_bit_exact():
    # Values chosen to be unrepresentable in short decimal — a decimal
    # round-trip would be off by an ulp; the uint32-bit encoding must
    # not be.
    loss = np.float32(0.1) + np.float32(1e-7)
    tasks = np.asarray([np.float32(1.0) / 3, np.float32(2.0) / 7], np.float32)
    n = np.float32(96.0)
    dec = ck.decode_acc(ck.encode_acc((loss, tasks, n)))
    assert dec[0].tobytes() == loss.tobytes()
    assert dec[1].tobytes() == tasks.tobytes()
    assert dec[2].tobytes() == n.tobytes()
    assert ck.encode_acc(None) is None
    assert ck.decode_acc(None) is None


def test_resume_container_round_trip_and_fallback(capsys):
    a = _state(1)
    w = ck.CheckpointWriter(
        "run", async_enabled=False, plan_seed=7, fingerprint="abc"
    )
    w.save(a, kind="auto", epoch=2, step=5)
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert _leaves_equal(restored, a)
    assert (manifest["epoch"], manifest["step"]) == (2, 5)
    assert manifest["plan_seed"] == 7
    assert manifest["config_fingerprint"] == "abc"
    # Corrupt container + a good plain checkpoint: loud epoch-boundary
    # fallback, never a crash mid-restart.
    ck.save_checkpoint("run", a, epoch=0)
    path = os.path.join("./logs", "run", ck._RESUME_FILE)
    open(path, "wb").write(b"HGTPUCK1garbage")
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest is None
    assert _leaves_equal(restored, a)
    assert "falling back" in capsys.readouterr().out


def test_config_fingerprint_volatile_keys():
    cfg = {
        "NeuralNetwork": {"Training": {"batch_size": 8, "num_epoch": 3}},
        "Dataset": {"name": "x"},
    }
    f0 = ck.config_fingerprint(cfg)
    cfg2 = {
        "NeuralNetwork": {
            "Training": {
                "batch_size": 8,
                "num_epoch": 30,  # extending a run keeps the cursor
                "continue": 1,
                "Checkpoint": {"interval_steps": 5},
            }
        },
        "Dataset": {"name": "x"},
    }
    assert ck.config_fingerprint(cfg2) == f0
    cfg3 = {
        "NeuralNetwork": {"Training": {"batch_size": 16, "num_epoch": 3}},
        "Dataset": {"name": "x"},
    }
    assert ck.config_fingerprint(cfg3) != f0


# ----------------------------------------------------------------------
# Async writer: retry/backoff, exhaustion, backpressure, crash safety
# ----------------------------------------------------------------------


def test_writer_retries_transient_failures_then_succeeds():
    faults.install("write_fail:resume:2")
    w = ck.CheckpointWriter("run", retries=3, backoff_s=0.01)
    w.save(_state(1), kind="auto", epoch=0, step=3)
    w.close()
    assert w.last_error is None
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 3
    assert _leaves_equal(restored, _state(1))


def test_writer_exhausts_retries_surfaces_and_training_continues():
    faults.install("write_fail:resume:10")
    w = ck.CheckpointWriter("run", retries=1, backoff_s=0.01)
    w.save(_state(1), kind="auto", epoch=0, step=1)  # must NOT raise
    w.wait()
    assert isinstance(w.last_error, OSError)
    # The writer (and "training") is still alive: the next save, with
    # the fault budget spent, lands durably.
    faults.reset()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    w.close()
    assert w.last_error is None
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2
    assert _leaves_equal(restored, _state(2))


def test_writer_serialization_failure_surfaces_never_raises(monkeypatch):
    # A to_bytes failure (e.g. MemoryError building the full in-memory
    # msgpack copy) rides the same contract as a write failure: save()
    # never raises into the train loop (sync mode runs on the caller
    # thread), the error surfaces on last_error, and the writer — and
    # its worker thread — survive to land the next save.
    w = ck.CheckpointWriter("run", async_enabled=False)

    def boom(_):
        raise MemoryError("no room for the serialized copy")

    monkeypatch.setattr(ck.serialization, "to_bytes", boom)
    w.save(_state(1), kind="auto", epoch=0, step=1)  # must NOT raise
    assert isinstance(w.last_error, MemoryError)
    monkeypatch.undo()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    w.close()
    assert w.last_error is None
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2


def test_writer_single_writer_backpressure_blocks_next_save_only():
    faults.install("slow_write:resume:0.25:1")
    w = ck.CheckpointWriter("run", retries=0)
    t0 = time.perf_counter()
    w.save(_state(1), kind="auto", epoch=0, step=1)
    first = time.perf_counter() - t0
    # The first save returns while the slow write is still in flight —
    # the train step between saves is never blocked by serialization.
    assert first < 0.2, f"snapshot phase blocked {first:.3f}s"
    t1 = time.perf_counter()
    w.save(_state(2), kind="auto", epoch=0, step=2)
    waited = time.perf_counter() - t1
    assert waited >= 0.15, "second save must wait out the in-flight write"
    w.close()
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 2


def test_writer_crash_mid_container_write_keeps_previous_container():
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="auto", epoch=1, step=4)
    # InjectedCrash models the kill: the sync writer records it (a real
    # kill ends the process; what matters is the on-disk state).
    faults.install("crash:write_tmp:1")
    w.save(_state(2), kind="auto", epoch=1, step=8)
    assert isinstance(w.last_error, faults.InjectedCrash)
    faults.reset()
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 4
    assert _leaves_equal(restored, _state(1))


def test_writer_orbax_format_autosave_and_resume_pointer():
    a = _jstate(1)
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=False)
    w.save(a, kind="auto", epoch=3, step=2)
    w.close()
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(9)
    )
    assert (manifest["epoch"], manifest["step"]) == (3, 2)
    assert _leaves_equal(restored, a)


def test_writer_epoch_kind_prunes_and_updates_latest():
    w = ck.CheckpointWriter("run", keep=2, async_enabled=False)
    for e in range(4):
        w.save(_state(e), kind="epoch", epoch=e + 1, step=0, label_epoch=e)
    w.close()
    d = os.path.join("./logs", "run")
    eps = sorted(
        f for f in os.listdir(d) if f.startswith("checkpoint_epoch")
    )
    assert eps == ["checkpoint_epoch2.msgpack", "checkpoint_epoch3.msgpack"]
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), _state(3))


# ----------------------------------------------------------------------
# Validate-finite gate (ISSUE 10, "Divergence recovery"): a non-finite
# state is NEVER published as 'latest' (or any artifact) — the
# divergence guard's rollback target is guaranteed good.
# ----------------------------------------------------------------------


def _poisoned_state(seed=0):
    s = _state(seed)
    s["params"]["w"][1, 1] = np.nan
    return s


def test_writer_rejects_non_finite_state(capsys):
    """A NaN'd state must leave EVERY artifact — 'latest', the epoch
    file, the resume container — at its previous good bytes, counted
    on rejected_saves and without touching last_error (a rejection is
    the gate working, not a failure)."""
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="epoch", epoch=1, step=0, label_epoch=0)
    d = os.path.join("./logs", "run")
    before = {
        f: open(os.path.join(d, f), "rb").read() for f in os.listdir(d)
    }
    w.save(_poisoned_state(2), kind="epoch", epoch=2, step=0, label_epoch=1)
    w.save(_poisoned_state(2), kind="auto", epoch=2, step=7)
    w.save(_poisoned_state(2), kind="final", epoch=2, step=0)
    assert w.rejected_saves == 3
    assert w.last_error is None
    assert "REJECTED" in capsys.readouterr().out
    after = {
        f: open(os.path.join(d, f), "rb").read() for f in os.listdir(d)
    }
    assert after == before  # no new files, no byte changed
    # a good save after the rejections writes normally
    w.save(_state(3), kind="epoch", epoch=3, step=0, label_epoch=2)
    w.close()
    assert _leaves_equal(ck.load_checkpoint("run", _state(9)), _state(3))
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["epoch"] == 3
    assert _leaves_equal(restored, _state(3))


def test_writer_async_rejection_never_blocks_or_raises():
    """The gate runs on the background phase: the caller's save()
    returns promptly and the rejection surfaces on the counter after
    the drain."""
    w = ck.CheckpointWriter("run")
    w.save(_state(1), kind="auto", epoch=0, step=1)
    w.save(_poisoned_state(2), kind="auto", epoch=0, step=2)
    w.wait()
    assert w.rejected_saves == 1 and w.last_error is None
    w.close()
    _, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 1  # the good cursor survived


def test_writer_validate_finite_opt_out():
    """Training.Checkpoint.validate_finite: false disables the gate
    (and checkpoint_settings carries the knob)."""
    assert ck.checkpoint_settings(
        {"Checkpoint": {"enabled": True}}
    ).validate_finite
    assert not ck.checkpoint_settings(
        {"Checkpoint": {"enabled": True, "validate_finite": False}}
    ).validate_finite
    w = ck.CheckpointWriter(
        "run", async_enabled=False, validate_finite=False
    )
    w.save(_poisoned_state(1), kind="final", epoch=0, step=0)
    w.close()
    assert w.rejected_saves == 0
    restored = ck.load_checkpoint("run", _state(9))
    assert np.isnan(np.asarray(restored["params"]["w"])[1, 1])


def test_writer_rejects_non_finite_orbax_state():
    """Same gate on the orbax path: the RESUME/LATEST pointers keep
    targeting the good artifact."""
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=False)
    w.save(_jstate(1), kind="auto", epoch=0, step=2)
    bad = jax.tree_util.tree_map(jnp.asarray, _poisoned_state(2))
    w.save(bad, kind="final", epoch=1, step=0)
    assert w.rejected_saves == 1
    w.close()
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(9)
    )
    assert (manifest["epoch"], manifest["step"]) == (0, 2)
    assert _leaves_equal(restored, _jstate(1))


def test_writer_kill_then_rejected_save_keeps_previous_container():
    """Compose with the crash tests: a kill mid-write followed by a
    diverged (rejected) save still leaves the ORIGINAL container as
    the resume point — the gate never 'recovers' a crash by writing
    corruption over it."""
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(_state(1), kind="auto", epoch=1, step=4)
    faults.install("crash:write_tmp:1")
    w.save(_state(2), kind="auto", epoch=1, step=8)  # killed mid-write
    assert isinstance(w.last_error, faults.InjectedCrash)
    faults.reset()
    w.save(_poisoned_state(3), kind="auto", epoch=1, step=12)  # rejected
    assert w.rejected_saves == 1
    w.close()
    restored, manifest = ck.load_resume_checkpoint("run", _state(9))
    assert manifest["step"] == 4
    assert _leaves_equal(restored, _state(1))


# ----------------------------------------------------------------------
# skip_to: bit-identical batch suffix on every feed
# ----------------------------------------------------------------------

from hydragnn_tpu.data.graph import GraphSample, MacroBatch  # noqa: E402
from hydragnn_tpu.ops.neighbors import radius_graph  # noqa: E402


def _mols(n, lo=5, hi=11, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(lo, hi))
        pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.integers(0, 3, (k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                y_graph=np.array([r.normal()], np.float32),
            )
        )
    return out


def _host(item):
    # np.array COPIES: these tests hold every delivered batch past the
    # pipeline's buffer-hold window, so a view of a pooled host buffer
    # would be recycled under us (the loop's consumers finish a batch
    # before fetching that deep — holding an epoch is test-only usage).
    if isinstance(item, MacroBatch):
        return (item.k, jax.tree_util.tree_map(np.array, item.batch))
    return (1, jax.tree_util.tree_map(np.array, item))


def _suffix_matches(full, resumed, skip):
    assert len(resumed) == len(full) - skip, (
        f"suffix length {len(resumed)} != {len(full) - skip}"
    )
    for a, b in zip(full[skip:], resumed):
        assert a[0] == b[0]
        assert _leaves_equal(a[1], b[1])


@pytest.mark.parametrize("packing", [False, True])
def test_skip_to_serial_suffix_bit_identical(packing):
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(60, seed=3)

    def _mk():
        return GraphLoader(
            samples, 5, shuffle=True, seed=1, packing=packing
        )

    for epoch in (0, 2):
        base = _mk()
        base.set_epoch(epoch)
        full = [_host(b) for b in base]
        for skip in (1, len(full) // 2, len(full) - 1):
            lo = _mk()
            lo.set_epoch(epoch)
            lo.skip_to(skip)
            _suffix_matches(full, [_host(b) for b in lo], skip)
            # One-shot: the NEXT epoch iterates in full again.
            assert len([_host(b) for b in lo]) == len(full)


def test_skip_to_pipeline_suffix_bit_identical():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _mols(60, seed=4)
    serial = GraphLoader(samples, 5, shuffle=True, seed=2, packing=True)
    serial.set_epoch(1)
    full = [_host(b) for b in serial]
    skip = len(full) // 2
    pipe = ParallelPipelineLoader(
        GraphLoader(samples, 5, shuffle=True, seed=2, packing=True),
        workers=2,
        depth=2,
        to_device=False,
    )
    pipe.set_epoch(1)
    pipe.skip_to(skip)
    _suffix_matches(full, [_host(b) for b in pipe], skip)


def test_skip_to_superstep_groups_cut_from_full_plan():
    from hydragnn_tpu.data.loader import GraphLoader, SuperstepLoader

    samples = _mols(64, seed=5)

    def _flat():
        lo = GraphLoader(samples, 4, shuffle=True, seed=3, packing=True)
        lo.set_epoch(0)
        return [_host(b) for b in lo]

    flat = _flat()
    k = 2
    grouped = SuperstepLoader(
        GraphLoader(samples, 4, shuffle=True, seed=3, packing=True),
        k=k,
        to_device=False,
    )
    grouped.loader.set_epoch(0)
    full_groups = [_host(b) for b in grouped]
    # cursor on a delivery boundary: resumed macros are the exact
    # delivery suffix of the uninterrupted run
    steps_per = [g[0] for g in full_groups]
    skip_deliveries = len(full_groups) // 2
    skip_steps = sum(steps_per[:skip_deliveries])
    grouped.loader.set_epoch(0)
    grouped.skip_to(skip_steps)
    resumed = [_host(b) for b in grouped]
    _suffix_matches(full_groups, resumed, skip_deliveries)
    # flat content sanity: the resumed steps are the flat plan suffix
    n_steps = sum(g[0] for g in resumed)
    assert n_steps == len(flat) - skip_steps


def test_skip_to_cursor_inside_group_degrades_to_singles(capsys):
    from hydragnn_tpu.data.loader import drop_consumed_groups

    groups = [[("a", 1), ("b", 1)], [("c", 1), ("d", 1)], [("e", 1)]]
    out = drop_consumed_groups(groups, 3)
    # group 1 fully consumed; cursor inside group 2 -> remainder
    # delivered as singles, then the tail group untouched
    assert out == [[("d", 1)], [("e", 1)]]
    assert "lands inside a superstep group" in capsys.readouterr().out
    assert drop_consumed_groups(groups, 0) == groups
    assert drop_consumed_groups(groups, 5) == []


def test_skip_to_dp_stacked_suffix_bit_identical():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(160, seed=6)
    mesh = make_mesh({"data": 8})

    def _mk():
        return DPLoader(
            GraphLoader(
                samples, 4, shuffle=True, seed=0, packing=True,
                pack_dp_shards=8,
            ),
            mesh,
        )

    base = _mk()
    base.set_epoch(0)
    full = [_host(b) for b in base]
    skip = len(full) // 2
    lo = _mk()
    lo.set_epoch(0)
    lo.skip_to(skip)
    _suffix_matches(full, [_host(b) for b in lo], skip)


def test_skip_to_prefetch_delegates():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.prefetch import PrefetchLoader

    samples = _mols(40, seed=7)
    serial = GraphLoader(samples, 5, shuffle=True, seed=4)
    serial.set_epoch(0)
    full = [_host(b) for b in serial]
    skip = 3
    pf = PrefetchLoader(
        GraphLoader(samples, 5, shuffle=True, seed=4), to_device=False
    )
    pf.set_epoch(0)
    pf.skip_to(skip)
    # the worker thread winds down with its iterator — no explicit
    # shutdown (stop-aware queue put; see prefetch.py)
    _suffix_matches(full, [_host(b) for b in pf], skip)


def test_skip_to_never_seeds_the_replay_cache():
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(30, seed=8)
    lo = GraphLoader(samples, 5, cache_batches=True)
    lo.skip_to(2)
    partial = list(lo)
    assert lo._batch_cache is None, (
        "a fast-forwarded (partial) epoch must not become the cache"
    )
    full = list(lo)
    assert len(full) == len(partial) + 2
    assert lo._batch_cache is not None
    # and a cached loader fast-forwards by slicing the cache
    lo.skip_to(2)
    again = [_host(b) for b in lo]
    _suffix_matches([_host(b) for b in full], again, 2)


def test_find_continue_log_name_resolves_num_epoch_drift():
    from hydragnn_tpu.utils.checkpoint import find_continue_log_name

    # Extending num_epoch is the resume-after-completion flow, but the
    # derived log name encodes it — the continue must still find the
    # run it is continuing (and prefer an exact or in-flight name).
    ck.save_checkpoint("run_SchNet_hd16_l2_e2", _state(1), epoch=1)
    assert (
        find_continue_log_name("run_SchNet_hd16_l2_e4")
        == "run_SchNet_hd16_l2_e2"
    )
    assert (
        find_continue_log_name("run_SchNet_hd16_l2_e2")
        == "run_SchNet_hd16_l2_e2"
    )
    assert (
        find_continue_log_name(
            "other_e4", preferred="run_SchNet_hd16_l2_e2"
        )
        == "run_SchNet_hd16_l2_e2"
    )
    # nothing restorable anywhere: the derived name passes through
    assert find_continue_log_name("fresh_run_e8") == "fresh_run_e8"


def test_find_continue_log_name_rejects_foreign_fingerprint(capsys):
    from hydragnn_tpu.utils.checkpoint import find_continue_log_name

    w = ck.CheckpointWriter(
        "run_GIN_hd8_l2_e2", async_enabled=False, fingerprint="aaaa"
    )
    w.save(_state(1), kind="final", epoch=2, step=0)
    w.close()
    # Same stored fingerprint: the num_epoch-drifted sibling is adopted.
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4", fingerprint="aaaa")
        == "run_GIN_hd8_l2_e2"
    )
    # Different config (fingerprint mismatch): the sibling must NOT
    # become this run's WRITE target — save_config/checkpoint saves/
    # pruning would clobber the other run's artifacts.
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4", fingerprint="bbbb")
        == "run_GIN_hd8_l2_e4"
    )
    assert "not adopting" in capsys.readouterr().out
    # No fingerprint given: legacy behavior (restore-side guard only).
    assert (
        find_continue_log_name("run_GIN_hd8_l2_e4")
        == "run_GIN_hd8_l2_e2"
    )


# ----------------------------------------------------------------------
# Process-scoped fault sites + the stall family (ISSUE 13): a kill
# threshold must name the same global optimizer step on every process
# (per-process counters at SPMD loop points), with @proc<i> selecting
# which process acts on it; stall:barrier models a late process at the
# writer's cross-process rendezvous.
# ----------------------------------------------------------------------


def test_proc_scoped_fault_grammar():
    faults.install("kill:train_step@proc1:34")
    plan = faults._plan()
    assert plan.kills == [
        {"site": "train_step", "at": 34, "proc": 1}
    ]
    faults.install("stall:barrier@3")
    assert faults._plan().stalls == [
        {"site": "barrier", "at": 3, "proc": None, "seconds": 1.0}
    ]
    faults.install("stall:barrier@3@proc0:0.25")
    assert faults._plan().stalls == [
        {"site": "barrier", "at": 3, "proc": 0, "seconds": 0.25}
    ]
    # proc segment order-insensitive
    faults.install("stall:barrier@proc1@2")
    assert faults._plan().stalls == [
        {"site": "barrier", "at": 2, "proc": 1, "seconds": 1.0}
    ]


@pytest.mark.parametrize(
    "bad",
    [
        "kill:train_step@procX:3",  # malformed proc index
        "kill:@proc1:3",  # empty site
        "stall:barrier",  # no @<at>
        "stall:barrier@x",  # non-integer at
        "stall:barrier@1@proc0@2",  # duplicate at segment
        "stall:barrier@proc0@proc1@1",  # duplicate proc segment
    ],
)
def test_proc_scoped_fault_grammar_rejects_malformed(bad):
    with pytest.raises(ValueError):
        faults.install(bad)


def test_kill_rule_scoped_to_other_process_never_fires(monkeypatch):
    """A @proc-scoped kill on a process that is NOT the named one must
    tick straight through — the drill arms the SAME spec on every
    process and only the named one dies."""
    monkeypatch.setenv("HYDRAGNN_TPU_PROCESS_ID", "0")
    faults.install("kill:train_step@proc1:2")
    for _ in range(4):  # crosses the threshold; process 0 survives
        faults.tick("train_step")
    # counters advanced (same global step numbering on every process)
    assert faults._plan()._counters["train_step"] == 4


def test_stall_rule_delays_the_named_tick(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TPU_PROCESS_ID", "1")
    faults.install("stall:barrier@2:0.3;stall:barrier@3@proc0:9.9")
    t0 = time.perf_counter()
    faults.tick("barrier")  # arrival 1: no stall
    fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    faults.tick("barrier")  # arrival 2: 0.3s stall
    stalled = time.perf_counter() - t0
    t0 = time.perf_counter()
    faults.tick("barrier")  # arrival 3: scoped to proc 0, we are 1
    other = time.perf_counter() - t0
    assert stalled >= 0.28
    assert fast < 0.25 and other < 0.25


# ----------------------------------------------------------------------
# Async collective orbax (ISSUE 13): the publish barrier rides the
# worker; a kill between barrier phases leaves the previous artifacts
# restorable; a stalled barrier never blocks the train step.
# ----------------------------------------------------------------------


def test_orbax_async_kill_between_barrier_phases_restorable():
    """InjectedCrash at the writer's publish barrier (the boundary
    between the rename phase and the cross-process rendezvous): the
    worker's never-crash guard records it, the just-published artifacts
    are already durable, and the next save recovers cleanly."""
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=True)
    w.save(_jstate(1), kind="auto", epoch=0, step=1)
    w.wait()
    assert w.last_error is None
    # the SECOND publish-barrier arrival crashes (mid-job, post-rename)
    # the next publish-barrier arrival (save 2's, post-rename) crashes
    faults.install("crash:barrier:1")
    w.save(_jstate(2), kind="auto", epoch=0, step=2)
    w.wait()
    assert isinstance(w.last_error, faults.InjectedCrash)
    faults.reset()
    # the step-2 artifacts were already renamed into place before the
    # barrier: the newest container must carry cursor step 2
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(0)
    )
    assert manifest is not None and manifest["step"] == 2
    assert _leaves_equal(restored, _jstate(2))
    # and the writer recovers on the next save
    w.save(_jstate(3), kind="final", epoch=1, step=0)
    w.close()
    assert w.last_error is None
    restored, manifest = ck.load_resume_checkpoint_sharded(
        "run", _jstate(0)
    )
    assert manifest is not None and manifest["epoch"] == 1
    assert _leaves_equal(restored, _jstate(3))


def test_orbax_async_stalled_barrier_never_blocks_save():
    """stall:barrier@1 parks the WORKER at the publish rendezvous; the
    caller-thread save() must stay snapshot-cheap (the stall lands on
    the background thread; only the NEXT save's backpressure would
    wait for it)."""
    faults.install("stall:barrier@1:1.0")
    w = ck.CheckpointWriter("run", fmt="orbax", async_enabled=True)
    t0 = time.perf_counter()
    w.save(_jstate(1), kind="auto", epoch=0, step=1)
    call_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    w.wait()  # rides out the stalled barrier
    waited_s = time.perf_counter() - t0
    w.close()
    faults.reset()
    assert w.last_error is None
    assert call_s < 0.8, f"save() blocked {call_s:.2f}s on the barrier"
    assert waited_s >= 0.5  # the stall really landed on the worker


def test_manifest_branch_steps_roundtrip():
    """Multibranch manifests carry per-branch cursors; they round-trip
    through the msgpack container bit-exactly and default to None
    elsewhere."""
    w = ck.CheckpointWriter("run", async_enabled=False)
    w.save(
        _state(1), kind="auto", epoch=2, step=7,
        branch_steps=[7, 7, 7],
    )
    w.close()
    _, manifest = ck.load_resume_checkpoint("run", _state(0))
    assert manifest["step"] == 7
    assert manifest["branch_steps"] == [7, 7, 7]
    w2 = ck.CheckpointWriter("run2", async_enabled=False)
    w2.save(_state(1), kind="auto", epoch=0, step=3)
    w2.close()
    _, manifest = ck.load_resume_checkpoint("run2", _state(0))
    assert manifest["branch_steps"] is None


def test_sharded_host_leaf_snapshot_rebuild_roundtrip():
    """The multi-process orbax snapshot path: capturing a sharded
    array's shards to host and rebuilding it on the worker must be
    bit-exact and preserve the sharding (exercised here on a
    single-process 8-device mesh array, forced through the sharded
    path)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    gx = jax.device_put(x, NamedSharding(mesh, P("data")))
    leaf = ck._ShardedHostLeaf(gx)
    assert len(leaf.shards) == 8 and len(leaf.data) == 8
    rebuilt = ck._rebuild_sharded({"w": leaf})["w"]
    assert rebuilt.sharding == gx.sharding
    assert np.array_equal(np.asarray(rebuilt), np.asarray(gx))
    # REPLICATED leaves deduplicate: one host copy, 8 device slots —
    # dp params/opt state replicate over every local device, and a
    # per-replica capture would multiply snapshot RAM and D2H by the
    # local device count.
    gr = jax.device_put(x, NamedSharding(mesh, P()))
    rleaf = ck._ShardedHostLeaf(gr)
    assert len(rleaf.shards) == 8 and len(rleaf.data) == 1
    rrebuilt = ck._rebuild_sharded({"w": rleaf})["w"]
    assert rrebuilt.sharding == gr.sharding
    assert np.array_equal(np.asarray(rrebuilt), np.asarray(gr))
    # the finite scan sees shard data (each NaN counted ONCE)
    bad = np.asarray(gx).copy()
    bad[3, 4] = np.nan
    gbad = jax.device_put(jnp.asarray(bad), NamedSharding(mesh, P("data")))
    found = ck.nonfinite_leaves({"w": ck._ShardedHostLeaf(gbad)})
    assert len(found) == 1 and found[0][1] == 1
    rbad = jax.device_put(jnp.asarray(bad), NamedSharding(mesh, P()))
    found = ck.nonfinite_leaves({"w": ck._ShardedHostLeaf(rbad)})
    assert len(found) == 1 and found[0][1] == 1 and found[0][2] == 64


def test_processes_agree_finite_single_process_identity():
    assert ck._processes_agree_finite(True, "t", 1) is True
    assert ck._processes_agree_finite(False, "t", 2) is False


# ----------------------------------------------------------------------
# Multibranch plan-domain resume (ISSUE 13 leg c): per-branch skip_to
# suffix identity, lockstep validation, and mid-epoch resume
# equivalence through train_validate_test on the 8-device mesh.
# ----------------------------------------------------------------------


def _mb_setup(n_per_branch=32, batch_size=2):
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.parallel.multibranch import (
        MultiBranchLoader,
        dual_optimizer,
        proportional_branch_split,
    )

    def mols(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            k = int(r.integers(5, 11))
            pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(
                np.float32
            )
            out.append(
                GraphSample(
                    x=r.integers(0, 3, (k, 1)).astype(np.float32),
                    pos=pos,
                    edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                    y_graph=np.array([r.normal()], np.float32),
                )
            )
        return out

    mesh = make_mesh({"data": 8})
    branch_sets = [mols(n_per_branch, seed=b) for b in range(2)]
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.2,
                "max_neighbours": 16,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": [
                        {
                            "type": f"branch-{i}",
                            "architecture": {
                                "num_sharedlayers": 1,
                                "dim_sharedlayers": 8,
                                "num_headlayers": 1,
                                "dim_headlayers": [8],
                            },
                        }
                        for i in range(2)
                    ]
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "num_epoch": 1,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            },
        }
    }
    config = update_config(
        config, [s for b in branch_sets for s in b]
    )
    model, cfg = create_model_config(config)
    dpb = proportional_branch_split(
        [len(b) for b in branch_sets], 8
    )

    def loader(epoch=0, shuffle=True):
        ld = MultiBranchLoader(
            branch_sets, dpb, batch_size=batch_size, mesh=mesh,
            shuffle=shuffle, seed=0,
        )
        ld.set_epoch(epoch)
        return ld

    # init from a SLOT loader's plain (un-stacked) batch — the model
    # sees per-device batches under vmap, never the [D, ...] stack
    batch0 = next(iter(loader().loaders[0]))
    params, bs = init_params(model, batch0)
    tx = dual_optimizer(config["NeuralNetwork"]["Training"])
    host_p = jax.tree_util.tree_map(
        lambda v: np.array(v, copy=True), jax.device_get(params)
    )
    host_b = jax.tree_util.tree_map(
        lambda v: np.array(v, copy=True), jax.device_get(bs)
    )
    return (
        config, model, cfg, tx, host_p, host_b, mesh, dpb, loader,
    )


def _mb_fresh(tx, host_p, host_b, mesh):
    from hydragnn_tpu.parallel.dp import replicate_state
    from hydragnn_tpu.train.state import create_train_state

    st = create_train_state(
        jax.tree_util.tree_map(jnp.asarray, host_p),
        tx,
        jax.tree_util.tree_map(jnp.asarray, host_b),
    )
    return replicate_state(st, mesh)


def test_multibranch_skip_to_suffix_bit_identical():
    """MultiBranchLoader.skip_to(s) delivers exactly the stacked batch
    suffix a fresh iterator delivers from step s on — every branch
    slot fast-forwards its own plan replay."""
    *_, loader = _mb_setup()
    full = [
        jax.tree_util.tree_map(np.asarray, b) for b in loader(epoch=1)
    ]
    ld = loader(epoch=1)
    ld.skip_to(3)
    resumed = [jax.tree_util.tree_map(np.asarray, b) for b in ld]
    assert len(resumed) == len(full) - 3
    for a, b in zip(full[3:], resumed):
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        assert len(la) == len(lb)
        assert all(np.array_equal(u, v) for u, v in zip(la, lb))
    # one-shot: the NEXT epoch iterates in full
    ld.set_epoch(2)
    assert len(list(ld)) == len(full)


def test_multibranch_skip_to_accepts_lockstep_list_rejects_drift():
    *_, loader = _mb_setup()
    ld = loader()
    ld.skip_to([2, 2])  # the manifest's per-branch cursor form
    assert ld._skip_next == 2
    with pytest.raises(ValueError, match="lockstep"):
        ld.skip_to([2, 3])
    # set_epoch clears an armed cursor
    ld.skip_to(4)
    ld.set_epoch(1)
    assert ld._skip_next == 0


def test_multibranch_mid_epoch_resume_bitwise(monkeypatch):
    """Leg-c acceptance at loop level: a multibranch run resumed from
    a mid-epoch manifest (cursor + bit-exact acc + per-branch steps)
    ends bitwise equal — params AND history — to the uninterrupted
    run. The 'same as single' row of the per-scheme resume table."""
    from hydragnn_tpu.parallel.multibranch import (
        make_multibranch_train_step,
    )
    from hydragnn_tpu.parallel.runtime import ParallelPlan
    from hydragnn_tpu.train.loop import train_validate_test

    monkeypatch.setenv("HYDRAGNN_TPU_VALTEST", "0")  # train region only
    (
        config, model, cfg, tx, host_p, host_b, mesh, dpb, loader,
    ) = _mb_setup()
    plan = ParallelPlan(
        scheme="multibranch", mesh=mesh,
        devices_per_branch=tuple(dpb), prefetch=0,
    )

    # Uninterrupted baseline.
    st_full, hist_full = train_validate_test(
        model, cfg, _mb_fresh(tx, host_p, host_b, mesh), tx,
        loader(), loader(shuffle=False), loader(shuffle=False),
        config, plan=plan,
    )

    # Manual prefix: s steps of epoch 0 with the loop's own step
    # builder and accumulator arithmetic, encoded as a manifest.
    S = 3
    step = make_multibranch_train_step(model, tx, cfg, mesh, dpb)
    st = _mb_fresh(tx, host_p, host_b, mesh)
    loss_sum = tasks_sum = n_graphs = None
    it = iter(loader())
    for _ in range(S):
        batch = next(it)
        ng = jnp.sum(batch.graph_mask).astype(jnp.float32)
        st, loss, tasks = step(st, batch)
        if loss_sum is None:
            loss_sum, tasks_sum, n_graphs = loss * ng, tasks * ng, ng
        else:
            loss_sum = loss_sum + loss * ng
            tasks_sum = tasks_sum + tasks * ng
            n_graphs = n_graphs + ng
    manifest = ck.build_manifest(
        epoch=0, step=S,
        acc=ck.encode_acc((loss_sum, tasks_sum, n_graphs)),
        branch_steps=[S] * len(dpb),
    )
    st_res, hist_res = train_validate_test(
        model, cfg, st, tx,
        loader(), loader(shuffle=False), loader(shuffle=False),
        config, plan=plan, resume=manifest,
    )
    assert hist_res.train_loss == hist_full.train_loss
    assert _leaves_equal(
        jax.device_get(st_res.params), jax.device_get(st_full.params)
    )
