"""Example-driver smoke tests (reference tests/test_examples.py runs the
actual examples/ scripts): each driver must run end to end with tiny
settings.
"""

import os
import subprocess
import sys

import pytest

import tests._cpu  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            # Emptying PALLAS_AXON_POOL_IPS is what actually disables the
            # image's axon TPU plugin (sitecustomize reads it); without
            # this, JAX_PLATFORMS=cpu alone is overridden.
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
    )
    return subprocess.run(
        [sys.executable, os.path.join(REPO, script), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )


def test_lennard_jones_example():
    r = _run(
        "examples/LennardJones/LennardJones.py",
        "--configs",
        "40",
        "--epochs",
        "4",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "force MAE" in r.stdout


def test_qm9_example_synthetic():
    r = _run(
        "examples/qm9/qm9.py",
        "--synthetic",
        "--mols",
        "60",
        "--epochs",
        "3",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Test MAE" in r.stdout


def test_multibranch_example():
    r = _run(
        "examples/multibranch/train.py",
        "--epochs",
        "2",
        "--sizes",
        "60",
        "30",
        "--hidden_dim",
        "8",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "devices per branch" in r.stdout
    assert "epoch   1" in r.stdout


def test_md17_example():
    r = _run(
        "examples/md17/md17.py", "--frames", "60", "--epochs", "3"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "test force loss" in r.stdout


def test_zinc_example_gps():
    r = _run(
        "examples/zinc/zinc.py", "--mols", "80", "--epochs", "3"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_oc20_example():
    r = _run(
        "examples/open_catalyst_2020/oc20.py",
        "--systems", "48", "--epochs", "2",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "test force loss" in r.stdout


def test_lsms_example_raw_ingest():
    """Drives the full Dataset.path raw-LSMS ingestion inside
    run_training (format detect -> read -> normalize -> split)."""
    r = _run("examples/lsms/lsms.py", "--configs", "60", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_ising_example_multihead():
    r = _run(
        "examples/ising_model/ising.py", "--configs", "60", "--epochs", "2"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "field" in r.stdout


def test_qm9_hpo_example():
    r = _run(
        "examples/qm9_hpo/qm9_hpo.py",
        "--trials", "2", "--epochs", "1", "--mols", "40",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "best:" in r.stdout


def test_giant_graph_example_ring_attention():
    """One sharded structure trained end-to-end over the 8-device mesh
    with ring attention (the long-context path as a user workflow)."""
    r = _run(
        "examples/giant_graph/giant.py",
        "--atoms", "125", "--configs", "8", "--epochs", "3",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "giant-graph training done" in r.stdout


def test_giant_graph_example_halo_mode():
    """The --halo path (ppermute boundary exchange, no full gather) as
    a user workflow, incl. the printed memory-model comparison."""
    r = _run(
        "examples/giant_graph/giant.py",
        "--atoms", "125", "--configs", "6", "--epochs", "2", "--halo",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "giant-graph training done" in r.stdout
    assert "memory model" in r.stdout


def test_uv_spectrum_example_multidim_head():
    """50-dim graph-output (full-spectrum) regression driver."""
    r = _run(
        "examples/dftb_uv_spectrum/uv_spectrum.py",
        "--mols", "80", "--epochs", "3",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "spectrum head" in r.stdout


def test_ani1x_example_mlip():
    r = _run(
        "examples/ani1_x/train.py", "--frames", "60", "--epochs", "2",
        "--mlip",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "test force loss" in r.stdout


def test_qm7x_train_then_inference():
    """train.py writes the checkpoint; inference.py reloads it through
    run_prediction (the reference qm7x_mlip_inference.py workflow)."""
    r = _run("examples/qm7x/train.py", "--frames", "60", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    r = _run("examples/qm7x/inference.py", "--frames", "40", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "inference error" in r.stdout


def test_transition1x_example():
    r = _run(
        "examples/transition1x/train.py",
        "--reactions", "8", "--epochs", "2",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_mptrj_example_periodic():
    r = _run(
        "examples/mptrj/train.py", "--structures", "60", "--epochs", "2"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_alexandria_example_energy_baseline():
    """Exercises fit/subtract_energy_baseline in a user workflow."""
    r = _run(
        "examples/alexandria/train.py",
        "--structures", "60", "--epochs", "2",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "element coefficients fitted" in r.stdout


def test_eam_example_multitask():
    r = _run(
        "examples/eam/eam.py",
        "--structures", "60", "--epochs", "2", "--multitask",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "atomic_energy" in r.stdout


def test_ogb_example_smiles_edge_features():
    """ogb driver: SMILES ingestion (native parser) feeding an
    edge-featured PNA — one-hot bond classes on the edges."""
    r = _run("examples/ogb/train_gap.py", "--mols", "80", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_open_catalyst_2025_mixed_pbc_example():
    """oc25 driver: periodic slabs + gas-phase frames in ONE MLIP run
    (mixed cell/edge_shifts presence through the field union)."""
    r = _run(
        "examples/open_catalyst_2025/train.py",
        "--systems", "40", "--epochs", "2",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_sc26_multi_model_hpo_example():
    """SC26 campaign: the HPO space includes mpnn_type itself."""
    r = _run(
        "examples/multidataset_hpo_sc26/train_hpo.py",
        "--trials", "2", "--epochs", "1", "--frames", "64",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "best: val" in r.stdout


def test_sc26_structure_optimization_example():
    """SC26 campaign: relaxation by gradient descent on positions with
    the trained MLIP's -grad(E, pos) forces must lower the energy."""
    r = _run(
        "examples/multidataset_hpo_sc26/structure_optimization.py",
        "--epochs", "2", "--frames", "64", "--blocks", "2",
        "--steps", "20",
        timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "relaxed: E" in r.stdout


def test_csce_example_smiles_ingestion():
    """csce driver end-to-end on synthetic SMILES strings through the
    rdkit-free parser (hydragnn_tpu/utils/smiles.py)."""
    r = _run("examples/csce/train_gap.py", "--mols", "80", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_multibranch_hpo_example():
    """HPO x task parallelism: every random-search trial trains under
    the multibranch scheme through the public run_training API."""
    r = _run(
        "examples/multibranch_hpo/train.py",
        "--trials", "2", "--epochs", "2", "--sizes", "80", "40",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "best: val" in r.stdout


def test_multidataset_example_branch_routing():
    """One encoder, three per-family decoder branches routed by
    dataset_id inside a single-process run."""
    r = _run(
        "examples/multidataset/train.py",
        "--per_family", "40", "--epochs", "2",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "3 decoder branches" in r.stdout


def test_open_family_examples():
    """OC22 / OMat24 / OMol25 / nabla2DFT thin drivers."""
    for script, args in [
        ("examples/open_catalyst_2022/train.py", ["--systems", "40"]),
        ("examples/open_materials_2024/train.py", ["--structures", "50"]),
        ("examples/open_molecules_2025/train.py", ["--frames", "50"]),
        ("examples/nabla2_dft/train.py", ["--frames", "50"]),
    ]:
        r = _run(script, *args, "--epochs", "2", timeout=540)
        assert r.returncode == 0, f"{script}: {r.stderr[-2000:]}"
        assert "final:" in r.stdout, script


def test_qcml_example_mace():
    r = _run(
        "examples/qcml/train.py", "--frames", "48", "--epochs", "1",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_multidataset_hpo_example():
    """Random-search HPO over the two-family GFM setup."""
    r = _run(
        "examples/multidataset_hpo/train.py",
        "--per_family", "30", "--trials", "2", "--epochs", "1",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "best:" in r.stdout


def test_odac23_example_film_conditioning():
    """Graph-attr FiLM conditioning end-to-end (otherwise untested)."""
    r = _run(
        "examples/open_direct_air_capture_2023/train.py",
        "--systems", "48", "--epochs", "2",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FiLM-conditioned" in r.stdout


def test_polymers_example_conv_node_head():
    """Long-chain graphs with a conv-type node decoder head."""
    r = _run(
        "examples/open_polymers_2026/train.py",
        "--chains", "60", "--epochs", "2",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "conv head" in r.stdout
