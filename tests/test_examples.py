"""Example-driver smoke tests (reference tests/test_examples.py runs the
actual examples/ scripts): each driver must run end to end with tiny
settings.
"""

import os
import subprocess
import sys

import pytest

import tests._cpu  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            # Emptying PALLAS_AXON_POOL_IPS is what actually disables the
            # image's axon TPU plugin (sitecustomize reads it); without
            # this, JAX_PLATFORMS=cpu alone is overridden.
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
    )
    return subprocess.run(
        [sys.executable, os.path.join(REPO, script), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )


def test_lennard_jones_example():
    r = _run(
        "examples/LennardJones/LennardJones.py",
        "--configs",
        "40",
        "--epochs",
        "4",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "force MAE" in r.stdout


def test_qm9_example_synthetic():
    r = _run(
        "examples/qm9/qm9.py",
        "--synthetic",
        "--mols",
        "60",
        "--epochs",
        "3",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Test MAE" in r.stdout


def test_multibranch_example():
    r = _run(
        "examples/multibranch/train.py",
        "--epochs",
        "2",
        "--sizes",
        "60",
        "30",
        "--hidden_dim",
        "8",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "devices per branch" in r.stdout
    assert "epoch   1" in r.stdout


def test_md17_example():
    r = _run(
        "examples/md17/md17.py", "--frames", "60", "--epochs", "3"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "test force loss" in r.stdout


def test_zinc_example_gps():
    r = _run(
        "examples/zinc/zinc.py", "--mols", "80", "--epochs", "3"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_oc20_example():
    r = _run(
        "examples/open_catalyst_2020/oc20.py",
        "--systems", "48", "--epochs", "2",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "test force loss" in r.stdout


def test_lsms_example_raw_ingest():
    """Drives the full Dataset.path raw-LSMS ingestion inside
    run_training (format detect -> read -> normalize -> split)."""
    r = _run("examples/lsms/lsms.py", "--configs", "60", "--epochs", "2")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final:" in r.stdout


def test_ising_example_multihead():
    r = _run(
        "examples/ising_model/ising.py", "--configs", "60", "--epochs", "2"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "field" in r.stdout


def test_qm9_hpo_example():
    r = _run(
        "examples/qm9_hpo/qm9_hpo.py",
        "--trials", "2", "--epochs", "1", "--mols", "40",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "best:" in r.stdout


def test_giant_graph_example_ring_attention():
    """One sharded structure trained end-to-end over the 8-device mesh
    with ring attention (the long-context path as a user workflow)."""
    r = _run(
        "examples/giant_graph/giant.py",
        "--atoms", "125", "--configs", "8", "--epochs", "3",
        timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "giant-graph training done" in r.stdout


def test_uv_spectrum_example_multidim_head():
    """50-dim graph-output (full-spectrum) regression driver."""
    r = _run(
        "examples/dftb_uv_spectrum/uv_spectrum.py",
        "--mols", "80", "--epochs", "3",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "spectrum head" in r.stdout
