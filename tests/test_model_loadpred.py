"""Checkpoint save -> load -> predict (reference
tests/test_model_loadpred.py): a fresh process-equivalent state restored
from disk must reproduce the trained model's predictions exactly; resume
via Training.continue must keep training from the stored state.
"""

import os

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import hydragnn_tpu
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.config import load_config


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("loadpred")
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        data = str(tmp / "dataset" / "unit_test")
        deterministic_graph_data(data, number_configurations=80, seed=5)
        here = os.path.dirname(os.path.abspath(__file__))
        config = load_config(os.path.join(here, "inputs", "ci.json"))
        config["Dataset"]["path"] = {"total": data}
        config["NeuralNetwork"]["Training"]["num_epoch"] = 6
        config["NeuralNetwork"]["Training"]["Checkpoint"] = True
        state, model, cfg, hist, full = hydragnn_tpu.run_training(config)
        yield tmp, state, model, cfg, full
    finally:
        os.chdir(cwd)


def test_checkpoint_roundtrip_exact(trained):
    tmp, state, model, cfg, full = trained
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        # predict with the in-memory state
        err0, tasks0, trues0, preds0 = hydragnn_tpu.run_prediction(
            full, state=state, model=model, cfg=cfg
        )
        # predict loading the checkpoint from disk (state=None)
        err1, tasks1, trues1, preds1 = hydragnn_tpu.run_prediction(full)
        np.testing.assert_allclose(err0, err1, rtol=1e-6)
        for p0, p1 in zip(preds0, preds1):
            np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)
    finally:
        os.chdir(cwd)


def test_resume_continues_training(trained):
    tmp, state, model, cfg, full = trained
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        # Same config (the log name encodes it) with continue=1: training
        # must restart from the stored state, not a fresh init.
        import copy

        cfg2 = copy.deepcopy(full)
        cfg2["NeuralNetwork"]["Training"]["continue"] = 1
        state2, _, _, hist2, _ = hydragnn_tpu.run_training(cfg2)
        # resumed training starts from the trained loss level, not from
        # a fresh initialization
        assert hist2.train_loss[0] < 0.5
        assert int(np.asarray(state2.step)) > int(np.asarray(state.step)) - 1
    finally:
        os.chdir(cwd)


def test_missing_checkpoint_raises(tmp_path):
    from hydragnn_tpu.utils.checkpoint import load_checkpoint

    with pytest.raises(FileNotFoundError):
        load_checkpoint("no_such_run_name", state=None)


def test_checkpoint_retention_prunes(tmp_path, monkeypatch):
    """Per-epoch checkpoints are pruned to the newest ``keep`` files
    (the reference writes unbounded per-epoch files, model.py:161-187)."""
    import jax.numpy as jnp

    from hydragnn_tpu.utils import checkpoint as ck

    monkeypatch.chdir(tmp_path)
    state = {"w": jnp.ones((3,))}
    for epoch in range(8):
        ck.save_checkpoint("runx", state, epoch=epoch, keep=3)
    import glob

    files = sorted(glob.glob("logs/runx/checkpoint_epoch*.msgpack"))
    assert len(files) == 3
    assert files[-1].endswith("checkpoint_epoch7.msgpack")
    assert ck.checkpoint_exists("runx")  # latest link retained
