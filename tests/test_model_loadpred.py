"""Checkpoint save -> load -> predict (reference
tests/test_model_loadpred.py): a fresh process-equivalent state restored
from disk must reproduce the trained model's predictions exactly; resume
via Training.continue must keep training from the stored state.
"""

import os

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import hydragnn_tpu
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.config import load_config


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("loadpred")
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        data = str(tmp / "dataset" / "unit_test")
        deterministic_graph_data(data, number_configurations=80, seed=5)
        here = os.path.dirname(os.path.abspath(__file__))
        config = load_config(os.path.join(here, "inputs", "ci.json"))
        config["Dataset"]["path"] = {"total": data}
        config["NeuralNetwork"]["Training"]["num_epoch"] = 6
        config["NeuralNetwork"]["Training"]["Checkpoint"] = True
        state, model, cfg, hist, full = hydragnn_tpu.run_training(config)
        yield tmp, state, model, cfg, full
    finally:
        os.chdir(cwd)


def test_checkpoint_roundtrip_exact(trained):
    tmp, state, model, cfg, full = trained
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        # predict with the in-memory state
        err0, tasks0, trues0, preds0 = hydragnn_tpu.run_prediction(
            full, state=state, model=model, cfg=cfg
        )
        # predict loading the checkpoint from disk (state=None)
        err1, tasks1, trues1, preds1 = hydragnn_tpu.run_prediction(full)
        np.testing.assert_allclose(err0, err1, rtol=1e-6)
        for p0, p1 in zip(preds0, preds1):
            np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)
    finally:
        os.chdir(cwd)


def test_resume_continues_training(trained):
    tmp, state, model, cfg, full = trained
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        # Same config (the log name encodes it) with continue=1: training
        # must restart from the stored state, not a fresh init.
        import copy

        cfg2 = copy.deepcopy(full)
        cfg2["NeuralNetwork"]["Training"]["continue"] = 1
        state2, _, _, hist2, _ = hydragnn_tpu.run_training(cfg2)
        # resumed training starts from the trained loss level, not from
        # a fresh initialization
        assert hist2.train_loss[0] < 0.5
        assert int(np.asarray(state2.step)) > int(np.asarray(state.step)) - 1
    finally:
        os.chdir(cwd)


def test_missing_checkpoint_raises(tmp_path):
    from hydragnn_tpu.utils.checkpoint import load_checkpoint

    with pytest.raises(FileNotFoundError):
        load_checkpoint("no_such_run_name", state=None)


def test_checkpoint_retention_prunes(tmp_path, monkeypatch):
    """Per-epoch checkpoints are pruned to the newest ``keep`` files
    (the reference writes unbounded per-epoch files, model.py:161-187)."""
    import jax.numpy as jnp

    from hydragnn_tpu.utils import checkpoint as ck

    monkeypatch.chdir(tmp_path)
    state = {"w": jnp.ones((3,))}
    for epoch in range(8):
        ck.save_checkpoint("runx", state, epoch=epoch, keep=3)
    import glob

    files = sorted(glob.glob("logs/runx/checkpoint_epoch*.msgpack"))
    assert len(files) == 3
    assert files[-1].endswith("checkpoint_epoch7.msgpack")
    assert ck.checkpoint_exists("runx")  # latest link retained


def test_orbax_sharded_checkpoint_roundtrip(tmp_path, monkeypatch):
    """Orbax path: FSDP-sharded state saved per-shard (no gather) and
    restored onto the same sharding layout, bit-exact."""
    import jax
    import numpy as np

    import tests._cpu  # noqa: F401

    from hydragnn_tpu.utils import checkpoint as ck

    monkeypatch.chdir(tmp_path)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"fsdp": 8})
    w = jax.device_put(
        jnp.arange(64.0).reshape(8, 8), NamedSharding(mesh, P("fsdp"))
    )
    state = {"params": {"w": w}, "step": jnp.asarray(7)}
    ck.save_checkpoint_sharded("orbx", state, epoch=1, keep=2)
    zeros = jax.device_put(
        jnp.zeros((8, 8)), NamedSharding(mesh, P("fsdp"))
    )
    restored = ck.load_checkpoint_sharded(
        "orbx", {"params": {"w": zeros}, "step": jnp.asarray(0)}
    )
    np.testing.assert_allclose(
        np.asarray(restored["params"]["w"]), np.arange(64.0).reshape(8, 8)
    )
    assert int(restored["step"]) == 7
    assert restored["params"]["w"].sharding.spec == P("fsdp")


def test_run_training_orbax_resume(tmp_path, monkeypatch):
    """run_training with checkpoint_format=orbax writes sharded
    checkpoints and resumes from them through the public API."""
    import numpy as np

    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.runner import run_training

    monkeypatch.chdir(tmp_path)
    r = np.random.default_rng(0)
    samples = []
    for _ in range(64):
        k = int(r.integers(5, 9))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        x = r.normal(size=(k, 1)).astype(np.float32)
        samples.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5, max_neighbours=12),
                y_graph=np.array([1.5 * float(x.mean())], np.float32),
            )
        )
    datasets = split_dataset(samples, 0.75)
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "max_neighbours": 12,
                "num_gaussians": 8,
                "num_filters": 16,
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": 4,
                "num_epoch": 2,
                "checkpoint_format": "orbax",
                "Parallelism": {"scheme": "dp", "data": 4, "fsdp": 2},
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
            },
        }
    }
    _, _, _, hist1, _ = run_training(config, datasets=datasets, seed=0)
    # Resume-manifest semantics (docs/DURABILITY.md): ``continue``
    # picks up the saved (epoch, step) cursor AND the loss history —
    # the finished 2-epoch run has nothing left to train, so training
    # longer means extending num_epoch (a resume-volatile key: the
    # cursor stays valid). The resumed run must append epochs 2..3 to
    # the carried history, starting from the trained weights.
    config["NeuralNetwork"]["Training"]["continue"] = 1
    config["NeuralNetwork"]["Training"]["num_epoch"] = 4
    _, _, _, hist2, _ = run_training(config, datasets=datasets, seed=0)
    assert np.isfinite(hist2.train_loss).all()
    assert len(hist2.train_loss) == 4
    # carried history: the first run's epochs ride the manifest intact
    np.testing.assert_array_equal(
        np.asarray(hist2.train_loss[:2]), np.asarray(hist1.train_loss)
    )
    # resumed epochs continue from the trained loss level, not init
    assert hist2.train_loss[2] < hist1.train_loss[0]
    # run_prediction loads the orbax checkpoint from disk (state=None)
    from hydragnn_tpu.runner import run_prediction

    err, tasks, trues, preds = run_prediction(config, datasets=datasets)
    assert np.isfinite(err)
