"""Fleet observability (ISSUE 14, docs/OBSERVABILITY.md "Fleet
observability"): per-process stream shards, barrier-wait attribution
(incl. the fault-injected single-process stall contract), the
heartbeat liveness beacon, and graftboard's fleet merge — last-arriver
attribution, straggler verdicts, heartbeat-gap dead detection, and the
LOUD (never fatal) degradation on partial/malformed shard sets.
"""

import json
import os
import sys
import time

import pytest

import tests._cpu  # noqa: F401  (side effect: pin 8-device CPU platform)

from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import graftboard  # noqa: E402

sys.path.remove(os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    telemetry.install(None)
    obs = telemetry.observer()
    if obs is not None:
        obs.close()
    faults.reset()
    yield
    telemetry.install(None)
    obs = telemetry.observer()
    if obs is not None:
        obs.close()
    faults.reset()


# ---------------------------------------------------------------------------
# Shard naming + process identity


def test_shard_path_naming():
    assert telemetry.shard_path("logs/r/telemetry.jsonl", 0) == (
        "logs/r/telemetry.jsonl"
    )
    assert telemetry.shard_path("logs/r/telemetry.jsonl", 1) == (
        "logs/r/telemetry.proc1.jsonl"
    )
    assert telemetry.shard_path("logs/r/telemetry.jsonl", 12) == (
        "logs/r/telemetry.proc12.jsonl"
    )


def test_process_identity_env_wins(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TPU_PROCESS_ID", "3")
    monkeypatch.setenv("HYDRAGNN_TPU_NUM_PROCESSES", "8")
    assert telemetry.process_identity() == (3, 8)


def test_configure_shards_per_process(tmp_path, monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TPU_PROCESS_ID", "2")
    monkeypatch.setenv("HYDRAGNN_TPU_NUM_PROCESSES", "3")
    base = str(tmp_path / "telemetry.jsonl")
    stream = telemetry.configure(
        {
            "Telemetry": {
                "enabled": True,
                "stream_path": base,
                "heartbeat_interval_s": 0,
            }
        }
    )
    try:
        assert stream is not None
        assert stream.path == str(tmp_path / "telemetry.proc2.jsonl")
        assert stream.process_index == 2
    finally:
        telemetry.close_run(stream)
    rows = [json.loads(line) for line in open(stream.path)]
    assert rows[0]["t"] == "header"
    assert rows[0]["process_index"] == 2
    assert rows[0]["process_count"] == 3


def test_rows_tagged_with_process_index_on_worker(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p, process_index=5)
    row = {"t": "step", "i": 0}
    s.emit(row)
    s.close()
    # the caller's dict is never mutated (tagging is a worker-side copy)
    assert "process_index" not in row
    rows = [json.loads(line) for line in open(p)]
    assert all(r["process_index"] == 5 for r in rows), rows


# ---------------------------------------------------------------------------
# Barrier rows + the single-process stall-attribution contract


def test_process_barrier_emits_row_and_stall_lands_in_wait(tmp_path):
    """ISSUE 14 satellite: a fault-injected single-process barrier
    stall (the `_process_barrier` single-process tick from PR 13)
    must produce a ``barrier`` row whose wait_ms >= the injected
    delay, at the crossing the fault spec armed — and the un-stalled
    crossing next to it must stay fast."""
    from hydragnn_tpu.utils.checkpoint import _process_barrier

    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    telemetry.install(s)
    faults.install("stall:barrier@2:0.3")
    t0 = time.perf_counter()
    _process_barrier("alpha")
    _process_barrier("beta")  # 2nd tick: the armed crossing
    assert time.perf_counter() - t0 >= 0.3
    faults.reset()
    telemetry.install(None)
    s.close()
    rows = [json.loads(line) for line in open(p)]
    barriers = {r["site"]: r for r in rows if r["t"] == "barrier"}
    assert set(barriers) == {"alpha", "beta"}
    assert barriers["beta"]["wait_ms"] >= 300.0, barriers["beta"]
    assert barriers["alpha"]["wait_ms"] < 300.0, barriers["alpha"]
    assert barriers["beta"]["barrier_ms"] == 0.0  # single-process
    assert barriers["beta"]["seq"] >= 1


def test_emit_barrier_carries_context_epoch_and_counts(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    telemetry.install(s)
    telemetry.set_context(epoch=4)
    assert telemetry.emit_barrier("x", 7, 0.25, 0.2)
    telemetry.install(None)
    s.close()
    rows = [json.loads(line) for line in open(p)]
    (b,) = [r for r in rows if r["t"] == "barrier"]
    assert b["epoch"] == 4 and b["seq"] == 7
    assert b["wait_ms"] == 250.0 and b["barrier_ms"] == 200.0


def test_emit_barrier_off_stream_is_inert():
    assert telemetry.emit_barrier("x", 1, 1.0) is False


# ---------------------------------------------------------------------------
# Heartbeats


def test_heartbeat_rows_phase_and_counters(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p, heartbeat_interval_s=0.05)
    telemetry.install(s)  # install() resets phase/counters (new run)
    telemetry.note_phase("test_phase")
    telemetry.bump("dp_batches", 3)
    time.sleep(0.35)
    telemetry.install(None)
    s.close()
    rows = [json.loads(line) for line in open(p)]
    hb = [r for r in rows if r["t"] == "heartbeat"]
    assert len(hb) >= 2, "expected periodic beats at 0.05s over 0.35s"
    assert hb[0]["seq"] == 1
    assert hb[-1]["phase"] == "test_phase"
    assert hb[-1]["interval_s"] == 0.05
    assert hb[-1].get("counters", {}).get("dp_batches", 0) >= 3
    # the close row is still the stream's last word
    assert rows[-1]["t"] == "close"


def test_waiting_on_marks_heartbeats(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p, heartbeat_interval_s=0.05)
    telemetry.install(s)
    with telemetry.waiting_on("barrier:test"):
        time.sleep(0.2)
    telemetry.install(None)
    s.close()
    hb = [
        json.loads(line)
        for line in open(p)
        if '"heartbeat"' in line
    ]
    waiting = [r for r in hb if r.get("waiting_on") == "barrier:test"]
    assert waiting, hb
    assert all("wait_age_s" in r for r in waiting)


def test_bump_is_inert_without_a_stream():
    before = telemetry.counters()
    telemetry.bump("never_counted")
    assert telemetry.counters() == before


def test_install_resets_counters_and_phase_per_run(tmp_path):
    """A second in-process run (HPO trials, bench reps) must not
    inherit the previous run's counters/phase — a counter the new run
    never bumps must be ABSENT, not frozen at the old total (the
    frozen-counter signature diagnoses a wedged feed)."""
    s1 = telemetry.TelemetryStream(str(tmp_path / "a.jsonl"))
    telemetry.install(s1)
    telemetry.bump("dp_batches", 7)
    telemetry.note_phase("train")
    telemetry.install(None)
    s1.close()
    s2 = telemetry.TelemetryStream(str(tmp_path / "b.jsonl"))
    telemetry.install(s2)
    try:
        assert telemetry.counters() == {}
        assert telemetry.get_phase() == "startup"
    finally:
        telemetry.install(None)
        s2.close()


def test_waiting_on_is_per_thread():
    """Concurrent waits (checkpoint worker parked at a barrier while
    the caller thread broadcasts walltime) must not clobber each
    other: the heartbeat reports the OLDEST active wait, and one
    thread's exit never erases or resurrects another's site."""
    import threading

    entered = threading.Event()
    release = threading.Event()

    def worker():
        with telemetry.waiting_on("barrier:publish:x"):
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert entered.wait(5.0)
    with telemetry.waiting_on("walltime"):
        row = telemetry.heartbeat_row(1, 0.5)
        # the worker's wait is older -> it wins the beat
        assert row["waiting_on"] == "barrier:publish:x"
    # the caller's exit must NOT have erased the worker's active wait
    row = telemetry.heartbeat_row(2, 0.5)
    assert row["waiting_on"] == "barrier:publish:x"
    release.set()
    t.join(5.0)
    assert "waiting_on" not in telemetry.heartbeat_row(3, 0.5)


def test_broadcast_waits_reported_but_never_attributed(tmp_path):
    """The walltime KV broadcast is ASYMMETRIC (only processes that
    arrive before proc 0's set wait; late arrivers read instantly),
    so min-barrier_ms last-arriver attribution would blame an
    innocent late reader: broadcast events report their waits but
    produce no last arriver and no straggler charge."""
    _write_shard(
        str(tmp_path / "telemetry.jsonl"),
        [
            {"t": "header", "schema": 1, "process_index": 0,
             "process_count": 3},
            {"t": "barrier", "site": "walltime", "seq": 1, "ts": 10.0,
             "wait_ms": 20.0, "broadcast": True, "epoch": 0},
            {"t": "step", "region": "train", "epoch": 0, "step": 1,
             "k": 1, "input_wait_ms": 1.0, "dispatch_ms": 1.0,
             "wall_ms": 100.0, "spec": "s"},
            {"t": "close", "dropped": 0, "write_errors": 0},
        ],
    )
    # proc 1 arrived AFTER the set: ~0 wait. proc 2 blocked 5s
    # waiting for proc 0's set — a wait proc 0 caused.
    for pidx, wait in ((1, 5.0), (2, 5000.0)):
        _write_shard(
            str(tmp_path / f"telemetry.proc{pidx}.jsonl"),
            [
                {"t": "header", "schema": 1, "process_index": pidx,
                 "process_count": 3},
                {"t": "barrier", "site": "walltime", "seq": 1,
                 "ts": 10.0, "wait_ms": wait, "broadcast": True,
                 "epoch": 0},
                {"t": "step", "region": "train", "epoch": 0, "step": 1,
                 "k": 1, "input_wait_ms": 1.0, "dispatch_ms": 1.0,
                 "wall_ms": 100.0, "spec": "s"},
                {"t": "close", "dropped": 0, "write_errors": 0},
            ],
        )
    fl = graftboard.build_fleet(str(tmp_path))
    (ev,) = fl["barrier_events"]
    assert ev["broadcast"] is True
    assert ev["last_arriver"] is None and ev["peer_wait_ms"] == 0.0
    # the wait itself is still visible, on the right process
    assert ev["max_wait_proc"] == 2
    # and nobody gets convicted for it
    (v,) = fl["stragglers"]
    assert v["straggler"] is None and v["cause"] == "balanced"


def test_emit_barrier_timed_out_flag(tmp_path):
    """A coordination wait that RAISED (dead peer, timeout) still
    reaches the shard, marked timed_out — graftboard's decomposition
    must be able to show the wait that wedged the fleet."""
    p = str(tmp_path / "t.jsonl")
    s = telemetry.TelemetryStream(p)
    telemetry.install(s)
    telemetry.emit_barrier("publish:x", 3, 600.0, 600.0, timed_out=True)
    telemetry.install(None)
    s.close()
    (b,) = [
        json.loads(line)
        for line in open(p)
        if '"barrier"' in line
    ]
    assert b["timed_out"] is True and b["wait_ms"] == 600000.0


# ---------------------------------------------------------------------------
# Fleet merge (synthetic shards — the unit-level contract; the real
# 2-process run is fleet_observability_drill)


def _write_shard(path, rows, truncated_tail=False):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        if truncated_tail:
            f.write('{"t":"step","trunc')


def _mk_fleet(tmp_path, stall_ms=3000.0):
    """Two shards: proc 1 stalls before a publish barrier (its own
    wait_ms carries the stall, barrier_ms ~0; proc 0 parks ~the same
    time AT the barrier)."""
    base = str(tmp_path / "telemetry.jsonl")
    _write_shard(
        base,
        [
            {"t": "header", "schema": 1, "process_index": 0,
             "process_count": 2, "log_name": "x"},
            {"t": "step", "region": "train", "epoch": 0, "step": 1,
             "k": 1, "input_wait_ms": 5.0, "dispatch_ms": 1.0,
             "wall_ms": 4000.0, "spec": "s"},
            {"t": "barrier", "site": "publish:x", "seq": 1, "ts": 100.0,
             "wait_ms": stall_ms, "barrier_ms": stall_ms - 10.0,
             "epoch": 0},
            {"t": "heartbeat", "seq": 1, "ts": 97.0, "interval_s": 0.25,
             "phase": "train"},
            {"t": "heartbeat", "seq": 2, "ts": 103.0,
             "interval_s": 0.25, "phase": "train"},
            {"t": "close", "dropped": 0, "write_errors": 0},
        ],
    )
    _write_shard(
        str(tmp_path / "telemetry.proc1.jsonl"),
        [
            {"t": "header", "schema": 1, "process_index": 1,
             "process_count": 2, "log_name": "x"},
            {"t": "step", "region": "train", "epoch": 0, "step": 1,
             "k": 1, "input_wait_ms": 6.0, "dispatch_ms": 1.0,
             "wall_ms": 4010.0, "spec": "s"},
            {"t": "barrier", "site": "publish:x", "seq": 1, "ts": 100.1,
             "wait_ms": stall_ms + 5.0, "barrier_ms": 4.0, "epoch": 0},
            {"t": "heartbeat", "seq": 1, "ts": 97.1, "interval_s": 0.25,
             "phase": "train"},
        ],
        truncated_tail=True,
    )
    return base


def test_fleet_attributes_stall_and_convicts_straggler(tmp_path):
    base = _mk_fleet(tmp_path)
    fl = graftboard.build_fleet(str(tmp_path))
    assert fl["present"] == [0, 1] and not fl["missing"]
    (ev,) = fl["barrier_events"]
    # last arriver = min barrier_ms (proc 1 stalled BEFORE the
    # rendezvous: it barely parks, proc 0 absorbed the wait)
    assert ev["last_arriver"] == 1
    assert ev["peer_wait_ms"] == pytest.approx(2990.0)
    assert ev["max_wait_proc"] == 1  # its own crossing carried the stall
    (v,) = fl["stragglers"]
    assert v["straggler"] == 1
    assert v["cause"] == "barrier:publish:x"
    # same answer when pointed at a non-0 shard path
    fl2 = graftboard.build_fleet(
        str(tmp_path / "telemetry.proc1.jsonl")
    )
    assert fl2["present"] == [0, 1]
    assert base in fl2["shards"]["0"]


def test_fleet_truncated_tail_and_aborted_shard_degrade_loudly(tmp_path):
    _mk_fleet(tmp_path)
    fl = graftboard.build_fleet(str(tmp_path))
    assert any("truncated tail" in w for w in fl["warnings"])
    assert any("no close row" in w for w in fl["warnings"])
    assert fl["processes"]["1"]["clean_exit"] is False
    assert fl["processes"]["0"]["clean_exit"] is True
    # loud, not fatal: the render carries the warnings
    text = graftboard.render_fleet(fl)
    assert "WARNING" in text and "STRAGGLER proc1" in text


def test_fleet_missing_shard_is_loud_lower_bound(tmp_path):
    base = str(tmp_path / "telemetry.jsonl")
    _write_shard(
        base,
        [
            {"t": "header", "schema": 1, "process_index": 0,
             "process_count": 3, "log_name": "x"},
            {"t": "close", "dropped": 0, "write_errors": 0},
        ],
    )
    fl = graftboard.build_fleet(str(tmp_path))
    assert fl["process_count"] == 3
    assert fl["missing"] == [1, 2]
    assert any("missing shard" in w.lower() for w in fl["warnings"])
    json.dumps(fl)  # --json stays serializable


def test_fleet_heartbeat_gap_detects_dead_not_clean_exit(tmp_path):
    base = str(tmp_path / "telemetry.jsonl")
    # proc 0: clean exit, old last beat -> "exited", NOT dead.
    _write_shard(
        base,
        [
            {"t": "header", "schema": 1, "process_index": 0,
             "process_count": 2},
            {"t": "heartbeat", "seq": 1, "ts": 10.0, "interval_s": 0.5},
            {"t": "close", "dropped": 0, "write_errors": 0},
        ],
    )
    # proc 1: no close row, beats stop 8s before the fleet's last.
    _write_shard(
        str(tmp_path / "telemetry.proc1.jsonl"),
        [
            {"t": "header", "schema": 1, "process_index": 1,
             "process_count": 2},
            {"t": "heartbeat", "seq": 1, "ts": 10.0, "interval_s": 0.5,
             "phase": "train"},
            {"t": "heartbeat", "seq": 2, "ts": 12.0, "interval_s": 0.5,
             "phase": "train", "waiting_on": "barrier:publish:x"},
        ],
    )
    # proc 2: no close row but beating until the end -> alive-at-end.
    _write_shard(
        str(tmp_path / "telemetry.proc2.jsonl"),
        [
            {"t": "header", "schema": 1, "process_index": 2,
             "process_count": 2},
            {"t": "heartbeat", "seq": 1, "ts": 20.0, "interval_s": 0.5},
        ],
    )
    fl = graftboard.build_fleet(str(tmp_path))
    hb = fl["heartbeats"]
    assert hb["dead"] == [1]
    assert hb["per_process"]["0"]["exited"] is True
    assert hb["per_process"]["1"]["last_waiting_on"] == (
        "barrier:publish:x"
    )
    assert hb["per_process"]["2"]["dead"] is False
    assert any("DEAD" in w for w in fl["warnings"])


def test_fleet_cli_json_and_report_barrier_section(tmp_path, capsys):
    _mk_fleet(tmp_path)
    rc = graftboard.main(["fleet", str(tmp_path), "--json"])
    assert rc == 0
    fl = json.loads(capsys.readouterr().out)
    assert fl["barrier_sites"]["publish:x"]["events"] == 1
    assert fl["stragglers"][0]["straggler"] == 1
    # the single-shard report grows the barrier/heartbeat sections
    rc = graftboard.main(
        ["report", str(tmp_path / "telemetry.jsonl")]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "-- barriers" in out and "publish:x" in out
    assert "heartbeats" in out


def test_fleet_no_shards_raises_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError):
        graftboard.build_fleet(str(tmp_path / "nope"))
    rc = graftboard.main(["fleet", str(tmp_path / "nope")])
    assert rc == 2  # the CLI's usage-error path, not a crash


# ---------------------------------------------------------------------------
# Config grammar


def test_telemetry_settings_heartbeat_interval():
    st = telemetry.telemetry_settings(
        {"Telemetry": {"enabled": True, "heartbeat_interval_s": 2.5}}
    )
    assert st.heartbeat_interval_s == 2.5
    assert telemetry.telemetry_settings(
        {"Telemetry": True}
    ).heartbeat_interval_s == 10.0
    assert telemetry.telemetry_settings(
        {"Telemetry": {"enabled": True, "heartbeat_interval_s": -1}}
    ).heartbeat_interval_s == 0.0
