"""PBC correctness (reference tests/test_periodic_boundary_conditions.py):
minimum-image displacements, wrap invariance (moving an atom by a full
lattice vector changes nothing), mixed-PBC axes, and model-output
invariance under wrapping.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.neighbors import radius_graph_pbc
from hydragnn_tpu.ops.rbf import edge_vectors_and_lengths


def _canon(ei, sh):
    idx = np.lexsort((sh[:, 2], sh[:, 1], sh[:, 0], ei[1], ei[0]))
    return ei[:, idx], sh[idx]


def test_minimum_image_distance():
    """Two atoms near opposite faces are neighbors through the wall."""
    cell = np.eye(3) * 10.0
    pos = np.array([[0.5, 5.0, 5.0], [9.5, 5.0, 5.0]])
    ei, sh = radius_graph_pbc(pos, cell, 1.5)
    assert ei.shape[1] == 2  # both directions
    vec = pos[ei[0]] + sh - pos[ei[1]]
    d = np.linalg.norm(vec, axis=1)
    np.testing.assert_allclose(d, [1.0, 1.0], atol=1e-10)


def test_wrap_invariance():
    """Translating an atom by a lattice vector must not change the edge
    set or the displacement vectors."""
    rng = np.random.default_rng(0)
    cell = np.array([[6.0, 0, 0], [1.0, 5.0, 0], [0, 0.5, 7.0]])
    pos = rng.uniform(0, 5.0, (20, 3))
    ei0, sh0 = radius_graph_pbc(pos, cell, 2.0)

    pos2 = pos.copy()
    pos2[3] += cell[0]  # + one lattice vector
    pos2[7] -= 2 * cell[2]
    ei1, sh1 = radius_graph_pbc(pos2, cell, 2.0)

    assert ei0.shape == ei1.shape
    v0 = pos[ei0[0]] + sh0 - pos[ei0[1]]
    v1 = pos2[ei1[0]] + sh1 - pos2[ei1[1]]
    a0, _ = _canon(ei0, np.round(v0, 9))
    a1, _ = _canon(ei1, np.round(v1, 9))
    assert np.array_equal(a0, a1)
    d0 = np.sort(np.linalg.norm(v0, axis=1))
    d1 = np.sort(np.linalg.norm(v1, axis=1))
    np.testing.assert_allclose(d0, d1, atol=1e-9)


def test_mixed_pbc():
    """Non-periodic axes must not produce through-wall edges."""
    cell = np.eye(3) * 10.0
    pos = np.array([[0.5, 5.0, 5.0], [9.5, 5.0, 5.0]])
    ei, sh = radius_graph_pbc(pos, cell, 1.5, pbc=(False, True, True))
    assert ei.shape[1] == 0


def test_self_image_edges():
    """A single atom in a small cell sees its own periodic images."""
    cell = np.eye(3) * 2.0
    pos = np.array([[1.0, 1.0, 1.0]])
    ei, sh = radius_graph_pbc(pos, cell, 2.1)
    assert ei.shape[1] == 6  # +-x, +-y, +-z images at distance 2.0
    d = np.linalg.norm(pos[ei[0]] + sh - pos[ei[1]], axis=1)
    np.testing.assert_allclose(d, 2.0, atol=1e-10)


def test_model_invariant_under_wrapping():
    """End-to-end: a geometric model fed PBC edges + shifts produces
    identical outputs for wrapped and unwrapped coordinates."""
    rng = np.random.default_rng(2)
    cell = np.eye(3).astype(np.float32) * 5.0
    n = 10
    pos = rng.uniform(0, 5.0, (n, 3)).astype(np.float32)
    pos_wrapped = pos.copy()
    pos_wrapped[4] += cell[1]

    cfg = ModelConfig(
        mpnn_type="SchNet",
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("e", "graph", 1),),
        graph_branches=(BranchSpec(),),
        node_branches=(),
        task_weights=(1.0,),
        radius=2.0,
        num_gaussians=8,
        num_filters=8,
        periodic_boundary_conditions=True,
    )
    model = create_model(cfg)

    x_shared = (
        np.random.default_rng(5).normal(size=(n, 1)).astype(np.float32)
    )

    def run(p):
        ei, sh = radius_graph_pbc(np.asarray(p, np.float64), cell, 2.0)
        return GraphSample(
            x=x_shared,
            pos=p,
            edge_index=ei,
            edge_shifts=sh.astype(np.float32),
            y_graph=np.zeros(1, np.float32),
            cell=cell,
        )

    b0, b1 = collate([run(pos)]), collate([run(pos_wrapped)])
    params, bs = init_params(model, b0)
    fwd = jax.jit(
        lambda p, b: model.apply(
            {"params": p, "batch_stats": bs}, b, train=False
        )
    )
    o0 = fwd(params, b0)
    o1 = fwd(params, b1)
    for h0, h1 in zip(o0, o1):
        np.testing.assert_allclose(
            np.asarray(h0), np.asarray(h1), rtol=1e-4, atol=1e-5
        )
