"""Parallel input-pipeline subsystem (hydragnn_tpu/data/pipeline.py):
in-order delivery equivalence, packed collation parity, worker-error
propagation, buffer-reuse isolation, shutdown hygiene, and the
PrefetchLoader shutdown-leak fix.
"""

import threading
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401


def _molecule(rng, n, i, rich=False, forces=False):
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.ops.neighbors import radius_graph

    pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
    ei = radius_graph(pos, 2.5)
    kw = dict(
        x=rng.normal(size=(n, 2)).astype(np.float32),
        pos=pos,
        edge_index=ei,
        y_graph=np.array([float(i), 2.0 * i], np.float32),
    )
    if rich:
        e = ei.shape[1]
        kw.update(
            edge_attr=rng.normal(size=(e, 4)).astype(np.float32),
            pe=rng.normal(size=(n, 8)).astype(np.float32),
            rel_pe=rng.normal(size=(e, 8)).astype(np.float32),
            cell=np.eye(3, dtype=np.float32) * float(n),
            y_node=rng.normal(size=(n, 3)).astype(np.float32),
            graph_attr=rng.normal(size=(5,)).astype(np.float32),
            dataset_id=i % 3,
        )
    if forces:
        kw.update(
            energy=float(rng.normal()),
            forces=rng.normal(size=(n, 3)).astype(np.float32),
        )
    return GraphSample(**kw)


def _samples(k, rich=False, forces=False, seed=7):
    rng = np.random.default_rng(seed)
    return [
        _molecule(rng, int(rng.integers(4, 9)), i, rich=rich, forces=forces)
        for i in range(k)
    ]


def _assert_batches_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for u, v in zip(la, lb):
        u, v = np.asarray(u), np.asarray(v)
        assert u.dtype == v.dtype and u.shape == v.shape
        np.testing.assert_array_equal(u, v)


@pytest.mark.parametrize(
    "loader_kwargs",
    [
        {},  # fixed worst-case pad
        {"fixed_pad": False},  # bucket ladder
        {"with_segment_plan": True},
        {"with_triplets": True},
        {"with_triplets": True, "fixed_pad": False},
    ],
)
def test_pipeline_bit_identical_to_serial(loader_kwargs):
    """Seeded-shuffle epochs through the multi-worker pipeline must be
    bit-identical to serial iteration of the same loader (the dp /
    spec-schedule paths rely on the deterministic per-step PadSpec
    order)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _samples(23)
    serial = GraphLoader(
        samples, 5, shuffle=True, seed=1, **loader_kwargs
    )
    pipe = ParallelPipelineLoader(
        GraphLoader(samples, 5, shuffle=True, seed=1, **loader_kwargs),
        workers=3,
        depth=3,
        packed=True,
        chunk=2,
    )
    for epoch in (0, 1):
        serial.set_epoch(epoch)
        pipe.set_epoch(epoch)
        n = 0
        for a, b in zip(serial, pipe):
            n += 1
            _assert_batches_equal(a, b)
        assert n == len(serial)


def test_pipeline_bit_identical_rich_fields_per_sample_path():
    """Optional-field-heavy samples, forced down the per-sample packed
    path (PackedStore disabled) — collate_packed parity under threads."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _samples(17, rich=True)
    serial = GraphLoader(samples, 4, shuffle=True, seed=2)
    pipe = ParallelPipelineLoader(
        GraphLoader(samples, 4, shuffle=True, seed=2),
        workers=2,
        depth=2,
        packed=True,
    )
    pipe._store_tried = True  # keep _store None -> collate_packed path
    serial.set_epoch(0)
    pipe.set_epoch(0)
    for a, b in zip(serial, pipe):
        _assert_batches_equal(a, b)
    assert pipe._store is None


def test_pipeline_mlip_fields_and_store():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _samples(19, forces=True)
    serial = GraphLoader(samples, 4, shuffle=True, seed=5)
    pipe = ParallelPipelineLoader(
        GraphLoader(samples, 4, shuffle=True, seed=5), workers=2, depth=2
    )
    serial.set_epoch(3)
    pipe.set_epoch(3)
    for a, b in zip(serial, pipe):
        _assert_batches_equal(a, b)
    assert pipe._store is not None  # list dataset, uniform fields


def test_collate_packed_matches_collate_mixed_presence():
    """Within-batch mixed presence (reachable via explicit
    ensure_fields) keeps collate's zero-fill semantics bit-for-bit."""
    from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
    from hydragnn_tpu.data.pipeline import collate_packed
    from hydragnn_tpu.ops.neighbors import radius_graph

    rng = np.random.default_rng(0)
    samples = []
    for i in range(6):
        n = int(rng.integers(4, 8))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        kw = dict(
            x=rng.normal(size=(n, 1)).astype(np.float32),
            edge_index=radius_graph(pos, 2.5),
            y_graph=np.array([float(i)], np.float32),
        )
        if i % 2 == 0:
            kw["pos"] = pos
            kw["pe"] = rng.normal(size=(n, 4)).astype(np.float32)
        samples.append(GraphSample(**kw))
    spec = PadSpec.for_samples(samples)
    a = collate(samples, spec, ensure_fields={"pe": 4, "graph_attr": 3})
    b = collate_packed(
        samples, spec, ensure_fields={"pe": 4, "graph_attr": 3}
    )
    _assert_batches_equal(a, b)


def test_pipeline_propagates_worker_exception():
    """A sample-decode error in a worker surfaces at the consumer, in
    order (lazy container path: the packed store cannot be built, so
    workers decode per sample)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    class Boom(Exception):
        pass

    items = _samples(12)

    class BadDS:
        def __len__(self):
            return len(items)

        def field_widths(self):
            return {}

        def sample_sizes(self):
            return (
                [s.num_nodes for s in items],
                [s.num_edges for s in items],
            )

        def __getitem__(self, i):
            if i == 7:
                raise Boom("bad sample")
            return items[i]

    pipe = ParallelPipelineLoader(
        GraphLoader(BadDS(), 4), workers=2, depth=2, packed=True
    )
    with pytest.raises(Boom):
        list(pipe)
    assert pipe._store is None  # container dataset: per-sample path


def test_packed_buffers_do_not_alias_across_yields():
    """Mutating a yielded host batch must not corrupt the next one
    (hold-window buffer recycling)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    pipe = ParallelPipelineLoader(
        GraphLoader(_samples(40), 4, shuffle=True, seed=0),
        workers=2,
        depth=2,
        packed=True,
        to_device=False,
        hold=2,
    )
    it = iter(pipe)
    b0 = next(it)
    np.asarray(b0.x)[:] = -999.0
    b1 = next(it)
    assert not np.any(np.asarray(b1.x) == -999.0)
    it.close()


def test_pipeline_threads_exit_on_early_close():
    pre = {
        t.name for t in threading.enumerate()
        if t.name.startswith("hgtpu-pipeline")
    }
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    pipe = ParallelPipelineLoader(
        GraphLoader(_samples(64), 4), workers=3, depth=2, packed=True
    )
    it = iter(pipe)
    next(it)
    next(it)
    it.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [
            t.name
            for t in threading.enumerate()
            if t.name.startswith("hgtpu-pipeline") and t.name not in pre
        ]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, f"pipeline workers leaked: {alive}"


def test_pipeline_populates_and_replays_batch_cache():
    """cache_batches loaders get their cache filled by the pipeline
    (host copies — later epochs replay identically even though packed
    buffers are recycled)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    base = GraphLoader(_samples(12), 4, cache_batches=True)
    pipe = ParallelPipelineLoader(base, workers=2, depth=2, packed=True)
    first = [np.asarray(b.y_graph).copy() for b in pipe]
    assert base._batch_cache is not None
    second = [np.asarray(b.y_graph).copy() for b in pipe]
    for u, v in zip(first, second):
        np.testing.assert_array_equal(u, v)


def test_dp_wrap_loader_with_pipeline_matches_workers0():
    """dp scheme: pipeline-fed DPLoader stacks must equal the
    single-thread path (shared spec schedule preserved under parallel
    collation)."""
    import dataclasses

    import jax

    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel import runtime

    samples = _samples(70, seed=9)
    plan = runtime.plan_from_config(
        {
            "NeuralNetwork": {
                "Training": {
                    "Parallelism": {
                        "scheme": "dp",
                        "pipeline": {"workers": 3, "depth": 2, "chunk": 2},
                    }
                }
            }
        }
    )
    assert plan.pipeline_workers == 3
    plan0 = dataclasses.replace(plan, pipeline_workers=0)

    def batches(p):
        ld = runtime.wrap_loader(
            p, GraphLoader(samples, 4, shuffle=True, seed=2), train=True
        )
        ld.set_epoch(1)
        return [
            jax.tree_util.tree_map(
                lambda a: np.array(a, copy=True), b
            )
            for b in ld
        ]

    for a, b in zip(batches(plan), batches(plan0)):
        _assert_batches_equal(a, b)


def test_pipeline_workers_exceeding_depth_never_deadlock():
    """Regression: with workers > depth, flow-control tokens must be
    acquired BEFORE claiming a chunk task — claim-then-acquire let
    out-of-order claimants starve the chunk the consumer needed next
    (observed as a live hang in the bench). Many tiny chunks maximize
    the race."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    pipe = ParallelPipelineLoader(
        GraphLoader(_samples(60, seed=13), 2, shuffle=True, seed=0),
        workers=4,
        depth=1,
        packed=True,
        chunk=1,
    )
    done = []

    def run():
        for epoch in range(3):
            pipe.set_epoch(epoch)
            done.append(sum(1 for _ in pipe))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=60.0)
    assert not t.is_alive(), "pipeline deadlocked with workers > depth"
    assert done == [30, 30, 30]


def test_pipeline_stats_surface():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import (
        ParallelPipelineLoader,
        pipeline_stats,
    )
    from hydragnn_tpu.data.prefetch import PrefetchLoader

    pipe = ParallelPipelineLoader(
        GraphLoader(_samples(20), 4), workers=2, depth=2
    )
    wrapped = PrefetchLoader(pipe, to_device=False)
    list(wrapped)
    st = pipeline_stats(wrapped)  # found through the wrapper chain
    assert st is not None
    d = st.as_dict()
    assert d["delivered_batches"] >= 1
    assert d["epochs"] == 1
    assert "collate_ms_avg" in d
    assert pipeline_stats(GraphLoader(_samples(4), 2)) is None


def test_pipeline_sample_lands_in_tracer(tmp_path):
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader
    from hydragnn_tpu.utils import tracer as tr

    tr._TRACERS.clear()
    tr.initialize(["RegionTimer"])
    try:
        pipe = ParallelPipelineLoader(
            GraphLoader(_samples(12), 4), workers=2, depth=2
        )
        list(pipe)
        timer = tr._TRACERS["RegionTimer"]
        assert timer.counts.get("pipeline/collate_s", 0) >= 1
        assert "pipeline/starved_steps" in timer.totals
    finally:
        tr._TRACERS.clear()


def test_prefetch_worker_exits_after_early_generator_close():
    """Shutdown-leak fix: with the consumer gone after one item, the
    worker must not stay blocked on q.put forever."""
    from hydragnn_tpu.data.prefetch import PrefetchLoader

    pre = {
        t.ident for t in threading.enumerate()
        if t.name == "hgtpu-prefetch"
    }

    class Slowly:
        def __iter__(self):
            for i in range(100):
                yield np.full((8,), float(i), np.float32)

        def __len__(self):
            return 100

    loader = PrefetchLoader(Slowly(), depth=1, to_device=False)
    it = iter(loader)
    next(it)
    it.close()  # early close: pre-fix, the refilling worker hangs
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [
            t
            for t in threading.enumerate()
            if t.name == "hgtpu-prefetch" and t.ident not in pre
        ]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, "prefetch worker leaked after early close"
