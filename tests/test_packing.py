"""Bin-packed batch forming: budget fitting, first-fit-decreasing
epoch packing, loader integration, packed-vs-ladder parity, and the
packing-off bit-identity invariant (ISSUE 3 tentpole).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import dataclasses

import jax


def _mols(n, lo, hi, seed=0, with_node_targets=False):
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.ops.neighbors import radius_graph

    r = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(r.integers(lo, hi))
        # constant density (box scales with k^(1/3)): edge counts stay
        # roughly node-linear, like molecular datasets
        pos = r.uniform(0, 1.6 * k ** (1 / 3), (k, 3)).astype(np.float32)
        kw = {}
        if with_node_targets:
            kw["y_node"] = r.normal(size=(k, 1)).astype(np.float32)
        out.append(
            GraphSample(
                x=np.full((k, 1), float(i), np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2),
                y_graph=np.array([float(i)], np.float32),
                **kw,
            )
        )
    return out


def _batches_equal(la, lb):
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        for f in dataclasses.fields(x):
            u, v = getattr(x, f.name), getattr(y, f.name)
            if (u is None) != (v is None):
                return False
            if u is None:
                continue
            if not np.array_equal(np.asarray(u), np.asarray(v)):
                return False
    return True


# ----------------------------------------------------------------------
# Fitting + FFD arithmetic
# ----------------------------------------------------------------------


def test_ffd_covers_epoch_within_capacity():
    from hydragnn_tpu.data.padschedule import (
        epoch_batch_indices,
        fit_pack_budgets,
        pack_epoch_ffd,
    )

    r = np.random.default_rng(0)
    ns = r.integers(10, 40, 300)
    es = (ns * 8 + r.integers(-15, 15, 300)).clip(1)
    budgets = fit_pack_budgets(ns, es, 32)
    assert budgets and budgets[0].capacity_nodes >= int(ns.max())
    order = np.concatenate(
        list(epoch_batch_indices(300, 32, shuffle=True, seed=3, epoch=0))
    )
    bins = pack_epoch_ffd(order, ns, es, budgets)
    # every sample exactly once
    got = np.concatenate([idx for idx, _ in bins])
    assert sorted(got.tolist()) == sorted(order.tolist())
    for idx, spec in bins:
        assert spec.fits(
            int(ns[idx].sum()), int(es[idx].sum()), len(idx)
        )
    # deterministic for identical inputs
    bins2 = pack_epoch_ffd(order, ns, es, budgets)
    assert all(
        np.array_equal(a[0], b[0]) and a[1] == b[1]
        for a, b in zip(bins, bins2)
    )


def test_packing_cuts_pad_waste_on_varied_sizes():
    """The acceptance shape: zinc-like sizes pack to a low residual."""
    from hydragnn_tpu.data.padschedule import (
        dataset_size_arrays,
        epoch_batch_indices,
        fit_pack_budgets,
        pack_epoch_ffd,
    )

    samples = _mols(256, 18, 39, seed=2)
    ns, es = dataset_size_arrays(samples)
    budgets = fit_pack_budgets(ns, es, 64)
    exe = real = 0.0
    for ep in range(2):
        order = np.concatenate(
            list(
                epoch_batch_indices(
                    256, 64, shuffle=True, seed=0, epoch=ep
                )
            )
        )
        for idx, spec in pack_epoch_ffd(order, ns, es, budgets):
            exe += spec.num_nodes + spec.num_edges
            real += float(ns[idx].sum() + es[idx].sum())
    assert exe / real <= 1.10  # ISSUE acceptance bound


def test_oversized_graph_rejected():
    from hydragnn_tpu.data.graph import PackSpec
    from hydragnn_tpu.data.padschedule import pack_epoch_ffd

    ns = np.array([5, 200], np.int64)
    es = np.array([10, 400], np.int64)
    tiny = PackSpec(num_nodes=64, num_edges=128, num_graphs=9)
    with pytest.raises(ValueError, match="exceeds the largest"):
        pack_epoch_ffd(np.array([0, 1]), ns, es, [tiny])


def test_max_nodes_per_graph_ignores_padding_slots():
    """Packed tail bins carry long padding-node runs whose slot ids
    count up to the padded remainder; the dense-layout bound must
    reflect REAL graphs only."""
    from hydragnn_tpu.data.graph import PadSpec, collate

    samples = _mols(3, 6, 10, seed=11)
    real_max = max(s.num_nodes for s in samples)
    n = sum(s.num_nodes for s in samples)
    spec = PadSpec(
        num_nodes=n + 100, num_edges=512, num_graphs=len(samples) + 20
    )
    batch = collate(samples, spec, as_numpy=True)
    assert batch.max_nodes_per_graph == real_max


def test_non_nested_budget_set_rejected():
    """Bins open under the largest budget only; a non-nested sibling
    (edge-heavy but node-narrow) would silently never be used — loud
    error instead."""
    from hydragnn_tpu.data.graph import PackSpec
    from hydragnn_tpu.data.padschedule import pack_epoch_ffd

    ns = np.array([10, 10], np.int64)
    es = np.array([20, 20], np.int64)
    wide = PackSpec(num_nodes=257, num_edges=512, num_graphs=17)
    edge_heavy = PackSpec(num_nodes=129, num_edges=4096, num_graphs=17)
    with pytest.raises(ValueError, match="nested"):
        pack_epoch_ffd(np.array([0, 1]), ns, es, [wide, edge_heavy])


def test_auto_baseline_uses_worst_case_clamp(monkeypatch):
    """When the ladder would blow the bucket budget and the run would
    clamp to ONE worst-case shape, the auto decision must compare
    against THAT (the motivating 1.4x regime), not an idealized
    per-batch ladder."""
    from hydragnn_tpu.data.padschedule import packing_beats_ladder

    r = np.random.default_rng(0)
    ns = r.integers(8, 120, 512)  # high variance: many bucket keys
    es = ns * 9
    monkeypatch.setenv("HYDRAGNN_TPU_MAX_PAD_BUCKETS", "2")
    won = packing_beats_ladder(ns, es, 32)
    assert won is not None  # vs the worst-case clamp packing wins big
    budgets, slack = won
    assert budgets and slack is not None
    # forced baselines mirror the resolved fixed-pad mode
    assert packing_beats_ladder(ns, es, 32, baseline="worst") is not None


# ----------------------------------------------------------------------
# Loader integration
# ----------------------------------------------------------------------


def test_packed_loader_delivers_every_graph_once():
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _mols(120, 8, 24, seed=1)
    ld = GraphLoader(samples, 16, shuffle=True, seed=5, packing=True)
    assert len(ld) == len(list(ld.epoch_plan(0)))
    seen = []
    for b in ld:
        gm = np.asarray(b.graph_mask)
        seen += [int(v) for v in np.asarray(b.y_graph)[gm, 0]]
    assert sorted(seen) == list(range(120))
    st = ld.packing_stats()
    assert st is not None and 0.5 < st["node_fill"] <= 1.0
    assert st["pad_ratio"] >= 1.0
    # shapes come only from the fitted budgets
    keys = ld.planned_spec_keys()
    assert 1 <= len(keys) <= 2


def test_epoch_plan_bit_identical_with_packing_off():
    """The invariant the ISSUE pins: with packing disabled, epoch_plan
    reproduces the pre-packing sequences exactly — the shuffled batch
    index arrays from epoch_batch_indices, with the documented spec
    arithmetic (bucket ladder / fixed worst case)."""
    from hydragnn_tpu.data.graph import PadSpec, bucket_size
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.padschedule import (
        dataset_size_arrays,
        epoch_batch_indices,
    )

    samples = _mols(90, 8, 24, seed=4)
    ns, es = dataset_size_arrays(samples)
    for fixed in (True, False):
        ld = GraphLoader(
            samples, 16, shuffle=True, seed=7, fixed_pad=fixed
        )
        for ep in (0, 1):
            plan = list(ld.epoch_plan(ep))
            exp_idx = list(
                epoch_batch_indices(
                    90, 16, shuffle=True, seed=7, epoch=ep
                )
            )
            assert len(plan) == len(exp_idx)
            for (idx, spec), eidx in zip(plan, exp_idx):
                assert np.array_equal(idx, eidx)
                if fixed:
                    assert spec.num_nodes == ld.pad_spec.num_nodes
                    assert spec.num_edges == ld.pad_spec.num_edges
                else:
                    assert spec == PadSpec(
                        num_nodes=bucket_size(int(ns[eidx].sum()) + 1),
                        num_edges=bucket_size(
                            max(int(es[eidx].sum()), 1)
                        ),
                        num_graphs=len(eidx) + 1,
                        num_triplets=None,
                    )


def test_packing_rejects_incompatible_modes():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.padschedule import (
        dataset_size_arrays,
        dp_spec_schedule,
    )

    samples = _mols(40, 8, 16, seed=0)
    ns, es = dataset_size_arrays(samples)
    sched = dp_spec_schedule(
        ns, es, batch_size=8, n_procs=1, steps_group=1, seed=0,
        shuffle=True,
    )
    with pytest.raises(ValueError, match="spec_schedule"):
        GraphLoader(
            samples, 8, shuffle=True, packing=True, spec_schedule=sched
        )
    with pytest.raises(ValueError, match="triplet"):
        GraphLoader(samples, 8, packing=True, with_triplets=True)


def test_pipeline_bit_identical_under_packing():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader

    samples = _mols(96, 8, 24, seed=6)
    la = list(GraphLoader(samples, 16, shuffle=True, seed=2, packing=True))
    for workers, chunk in ((1, 1), (3, 2)):
        lb = list(
            ParallelPipelineLoader(
                GraphLoader(
                    samples, 16, shuffle=True, seed=2, packing=True
                ),
                workers=workers,
                depth=2,
                packed=True,
                chunk=chunk,
            )
        )
        assert _batches_equal(la, lb)


# ----------------------------------------------------------------------
# Model-level parity: packing changes only padding, never numerics.
# ----------------------------------------------------------------------


def _parity_model(batch):
    from hydragnn_tpu.models.create import create_model, init_params
    from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig

    cfg = ModelConfig(
        mpnn_type="SchNet",
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("g", "graph", 1), HeadSpec("n", "node", 1)),
        graph_branches=(BranchSpec(),),
        node_branches=(
            BranchSpec(
                node_head_type="mlp",
                dim_headlayers=(8, 8),
                num_headlayers=2,
            ),
        ),
        task_weights=(1.0, 1.0),
        radius=2.2,
        num_gaussians=8,
        num_filters=8,
    )
    model = create_model(cfg)
    params, bs = init_params(model, batch)
    return model, cfg, params, bs


def test_packed_vs_ladder_loss_and_grad_parity():
    """The SAME graphs collated at the ladder spec vs at a (larger)
    packed budget spec: masking + per-graph heads make the extra
    padding inert. Total/per-task losses and per-graph node outputs
    come out bit-exact at the node level; losses, gradients and pooled
    graph outputs match to reduction-order ulps (sums over
    differently-padded rows regroup XLA's reduction tree — tolerance
    1e-6 relative)."""
    from hydragnn_tpu.data.graph import PadSpec, collate
    from hydragnn_tpu.train.loop import make_loss_fn

    samples = _mols(10, 6, 14, seed=3, with_node_targets=True)
    ladder = collate(samples, PadSpec.for_samples(samples))
    n = sum(s.num_nodes for s in samples)
    e = sum(s.num_edges for s in samples)
    packed_spec = PadSpec(
        num_nodes=n + 41, num_edges=e + 96, num_graphs=len(samples) + 9
    )
    packed = collate(samples, packed_spec)
    model, cfg, params, bs = _parity_model(ladder)

    loss_fn = make_loss_fn(model, cfg)
    (la, (ta, _)), ga = jax.value_and_grad(loss_fn, has_aux=True)(
        params, bs, ladder
    )
    (lb, (tb, _)), gb = jax.value_and_grad(loss_fn, has_aux=True)(
        params, bs, packed
    )
    np.testing.assert_allclose(
        np.asarray(la), np.asarray(lb), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(ta), np.asarray(tb), rtol=1e-6
    )
    for x, y in zip(
        jax.tree_util.tree_leaves(ga), jax.tree_util.tree_leaves(gb)
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7
        )

    outs_a = model.apply(
        {"params": params, "batch_stats": bs}, ladder, train=False
    )
    outs_b = model.apply(
        {"params": params, "batch_stats": bs}, packed, train=False
    )
    n_real = int(np.asarray(ladder.node_mask).sum())
    g_real = int(np.asarray(ladder.graph_mask).sum())
    # node head: row-aligned compute, bit-exact across paddings
    np.testing.assert_array_equal(
        np.asarray(outs_a[1])[:n_real], np.asarray(outs_b[1])[:n_real]
    )
    # graph head: pooled through a segment reduce, ulp-level only
    np.testing.assert_allclose(
        np.asarray(outs_a[0])[:g_real],
        np.asarray(outs_b[0])[:g_real],
        rtol=1e-6,
        atol=1e-7,
    )


def test_packed_loader_trains_end_to_end():
    """A jitted train step consumes the packed loader's mixed budget
    shapes (one compile per budget) and the loss goes down."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    samples = _mols(48, 6, 14, seed=8, with_node_targets=True)
    ld = GraphLoader(samples, 12, shuffle=True, seed=0, packing=True)
    first = next(iter(ld))
    model, cfg, params, bs = _parity_model(first)
    tx = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    )
    state = create_train_state(params, tx, bs)
    step = make_train_step(model, tx, cfg)
    losses = []
    for ep in range(12):
        ld.set_epoch(ep)
        ep_loss = 0.0
        for batch in ld:
            state, tot, _ = step(state, batch)
            ep_loss += float(tot)
        losses.append(ep_loss)
    assert losses[-1] < losses[0] * 0.7


def test_runner_resolve_packing_envelope():
    """Packing applies on the single scheme (per-batch bins) and on
    single-process dp meshes (device-coordinated bins — docs/PACKING.md
    sharded fast path); multibranch and triplet models fall back."""
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.parallel.runtime import ParallelPlan
    from hydragnn_tpu.runner import _resolve_packing

    samples = _mols(64, 8, 20, seed=9)
    single = ParallelPlan(scheme="single", packing=True)
    on, budgets, slack = _resolve_packing(single, False, 16, samples)
    assert on and budgets and slack is not None
    # dp on a single-process mesh now rides the coordinated packer
    dp_plan = ParallelPlan(
        scheme="dp", mesh=make_mesh({"data": 8}), packing=True
    )
    on, budgets, slack = _resolve_packing(dp_plan, False, 16, samples)
    assert on and budgets and slack is not None
    # ...but a training split too small to feed every device does not
    on, _, _ = _resolve_packing(dp_plan, False, 16, samples[:6])
    assert not on
    on, _, _ = _resolve_packing(
        ParallelPlan(scheme="multibranch", packing=True),
        False, 16, samples,
    )
    assert not on
    on, _, _ = _resolve_packing(single, True, 16, samples)  # triplets
    assert not on
    off = ParallelPlan(scheme="single", packing=False)
    on, _, _ = _resolve_packing(off, False, 16, samples)
    assert not on
    # auto: uniform sizes gain nothing -> ladder kept; varied sizes win
    auto = ParallelPlan(scheme="single", packing="auto")
    uniform = _mols(64, 12, 13, seed=9)
    on_u, _, _ = _resolve_packing(auto, False, 16, uniform)
    varied = _mols(256, 18, 39, seed=2)
    on_v, b_v, s_v = _resolve_packing(auto, False, 64, varied)
    assert on_v and b_v and s_v is not None
    assert isinstance(on_u, bool)


def test_plan_from_config_packing_block():
    from hydragnn_tpu.parallel.runtime import plan_from_config

    cfg = {
        "NeuralNetwork": {
            "Training": {
                "Parallelism": {
                    "scheme": "single",
                    "packing": {
                        "enabled": True,
                        "max_budgets": 3,
                        "slack": 1.05,
                        "max_graphs": 96,
                    },
                }
            }
        }
    }
    plan = plan_from_config(cfg, devices=[object()])
    assert plan.packing is True
    assert plan.packing_max_budgets == 3
    assert plan.packing_slack == 1.05
    assert plan.packing_max_graphs == 96
    # default: auto
    plan = plan_from_config(
        {"NeuralNetwork": {"Training": {}}}, devices=[object()]
    )
    assert plan.packing == "auto"
    # string spellings of false must DISABLE, never truthy-enable
    for off in ("false", "0", "no", "off", False):
        cfg_off = {
            "NeuralNetwork": {
                "Training": {
                    "Parallelism": {"packing": {"enabled": off}}
                }
            }
        }
        assert plan_from_config(cfg_off, devices=[object()]).packing is False
    cfg_on = {
        "NeuralNetwork": {
            "Training": {"Parallelism": {"packing": {"enabled": "true"}}}
        }
    }
    assert plan_from_config(cfg_on, devices=[object()]).packing is True
    # unknown spellings are a loud error, not a silent enable
    cfg_bad = {
        "NeuralNetwork": {
            "Training": {
                "Parallelism": {"packing": {"enabled": "sometimes"}}
            }
        }
    }
    with pytest.raises(ValueError, match="not recognized"):
        plan_from_config(cfg_bad, devices=[object()])
