"""Property-based physics tests: rotational invariance.

The TPU analog of reference tests/test_rotational_invariance.py — scalar
predictions of geometric models must be unchanged under rigid rotation
of the atomic positions (edge sets are distance-based, so rotations
preserve them).
"""

import numpy as np
import pytest

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.neighbors import radius_graph

GEOMETRIC_MODELS = ["SchNet", "EGNN", "PAINN", "PNAEq", "PNAPlus"]


def _rotation_matrix(seed=3):
    rng = np.random.default_rng(seed)
    a, b, c = rng.uniform(0, 2 * np.pi, 3)
    rx = np.array(
        [[1, 0, 0], [0, np.cos(a), -np.sin(a)], [0, np.sin(a), np.cos(a)]]
    )
    ry = np.array(
        [[np.cos(b), 0, np.sin(b)], [0, 1, 0], [-np.sin(b), 0, np.cos(b)]]
    )
    rz = np.array(
        [[np.cos(c), -np.sin(c), 0], [np.sin(c), np.cos(c), 0], [0, 0, 1]]
    )
    return (rz @ ry @ rx).astype(np.float32)


def _samples(rotation=None, seed=0, n_graphs=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_graphs):
        n = int(rng.integers(5, 10))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        if rotation is not None:
            pos = pos @ rotation.T
        # Same edge set regardless of rotation: build from unrotated
        # geometry is unnecessary — radius graphs are rotation invariant.
        ei = radius_graph(pos, 2.5, max_neighbours=16)
        out.append(
            GraphSample(
                x=rng.normal(size=(n, 2)).astype(np.float32),
                pos=pos,
                edge_index=ei,
                y_graph=np.zeros(1, np.float32),
            )
        )
    return out


def _config(mpnn_type):
    return ModelConfig(
        mpnn_type=mpnn_type,
        input_dim=2,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("e", "graph", 1), HeadSpec("n", "node", 1)),
        graph_branches=(BranchSpec(),),
        node_branches=(BranchSpec(),),
        task_weights=(0.5, 0.5),
        radius=2.5,
        num_radial=6,
        num_gaussians=8,
        num_filters=8,
        equivariance=True,
        pna_deg=(0, 1, 4, 6, 4, 1),
    )


@pytest.mark.parametrize("mpnn_type", GEOMETRIC_MODELS)
def test_rotational_invariance(mpnn_type):
    import jax

    cfg = _config(mpnn_type)
    model = create_model(cfg)

    rot = _rotation_matrix()
    base = collate(_samples())
    rotated = collate(_samples(rotation=rot))

    params, bs = init_params(model, base)
    fwd = jax.jit(
        lambda p, b: model.apply({"params": p, "batch_stats": bs}, b, train=False)
    )
    out0 = fwd(params, base)
    out1 = fwd(params, rotated)
    for h0, h1 in zip(out0, out1):
        np.testing.assert_allclose(
            np.asarray(h0), np.asarray(h1), rtol=2e-4, atol=2e-5
        )


def test_translation_invariance():
    import jax

    cfg = _config("EGNN")
    model = create_model(cfg)
    base = collate(_samples())
    shifted_samples = _samples()
    for s in shifted_samples:
        s.pos = s.pos + np.array([5.0, -3.0, 2.0], np.float32)
    shifted = collate(shifted_samples)
    params, bs = init_params(model, base)
    fwd = jax.jit(
        lambda p, b: model.apply({"params": p, "batch_stats": bs}, b, train=False)
    )
    out0 = fwd(params, base)
    out1 = fwd(params, shifted)
    for h0, h1 in zip(out0, out1):
        np.testing.assert_allclose(
            np.asarray(h0), np.asarray(h1), rtol=2e-4, atol=2e-5
        )
