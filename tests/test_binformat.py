"""Binary dataset container (ADIOS2-equivalent layer, SURVEY.md §2.6):
write/read roundtrip, partial reads, preload/subset modes, metadata
attrs, sharded multi-file concat, and e2e run_training ingestion.
"""

import os

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.binformat import (
    BinDataset,
    MultiBinDataset,
    write_bin_dataset,
)
from hydragnn_tpu.data.graph import GraphSample


def _samples(n, seed=0, with_energy=True):
    r = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(r.integers(3, 8))
        e = int(r.integers(2, 10))
        out.append(
            GraphSample(
                x=r.normal(size=(k, 2)).astype(np.float32),
                pos=r.normal(size=(k, 3)).astype(np.float32),
                edge_index=r.integers(0, k, (2, e)).astype(np.int64),
                edge_attr=r.normal(size=(e, 1)).astype(np.float32),
                y_graph=np.array([float(i)], np.float32),
                y_node=r.normal(size=(k, 1)).astype(np.float32),
                cell=np.eye(3, dtype=np.float32) * (i + 1),
                energy=float(-i) if with_energy else None,
                dataset_id=i % 3,
            )
        )
    return out


def _assert_same(a: GraphSample, b: GraphSample):
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.pos, b.pos)
    np.testing.assert_array_equal(a.edge_index, b.edge_index)
    np.testing.assert_array_equal(a.edge_attr, b.edge_attr)
    np.testing.assert_array_equal(a.y_graph, b.y_graph)
    np.testing.assert_array_equal(a.y_node, b.y_node)
    np.testing.assert_array_equal(a.cell, b.cell)
    assert a.dataset_id == b.dataset_id
    assert (a.energy is None) == (b.energy is None)
    if a.energy is not None:
        assert a.energy == b.energy


def test_roundtrip_direct_and_preload(tmp_path):
    samples = _samples(12)
    path = str(tmp_path / "ds.hgb")
    write_bin_dataset(
        path, samples, attrs={"minmax": [0.0, 1.0], "avg_num_neighbors": 5.5}
    )
    # direct (mmap partial reads)
    ds = BinDataset(path)
    assert len(ds) == 12
    for i in (0, 5, 11):
        _assert_same(samples[i], ds[i])
    assert ds.attrs["minmax"] == [0.0, 1.0]
    assert ds.avg_num_neighbors == 5.5
    # preload + subset
    sub = BinDataset(path, preload=True, subset=[2, 7, 9])
    assert len(sub) == 3
    _assert_same(samples[7], sub[1])


def test_missing_energy_and_optional_fields(tmp_path):
    samples = _samples(4, with_energy=False)
    for s in samples:
        s.edge_attr = None
        s.cell = None
    path = str(tmp_path / "ds2.hgb")
    write_bin_dataset(path, samples)
    ds = BinDataset(path)
    assert ds[0].energy is None
    assert ds[0].edge_attr is None
    assert ds[0].cell is None


def test_partially_present_field_rejected(tmp_path):
    samples = _samples(3)
    samples[1].edge_attr = None
    with pytest.raises(ValueError, match="only some"):
        write_bin_dataset(str(tmp_path / "bad.hgb"), samples)


def test_bad_magic(tmp_path):
    p = tmp_path / "junk.hgb"
    p.write_bytes(b"not a container")
    with pytest.raises(ValueError, match="not a HGTPUBIN1"):
        BinDataset(str(p))


def test_sharded_concat(tmp_path):
    all_samples = _samples(10, seed=3)
    stem = str(tmp_path / "shards")
    write_bin_dataset(f"{stem}.p0.hgb", all_samples[:6], attrs={"a": 1})
    write_bin_dataset(f"{stem}.p1.hgb", all_samples[6:], attrs={"b": 2})
    ds = BinDataset.open_sharded(stem)
    assert isinstance(ds, MultiBinDataset)
    assert len(ds) == 10
    _assert_same(all_samples[7], ds[7])
    assert ds.attrs == {"a": 1, "b": 2}
    assert [s.dataset_id for s in ds] == [s.dataset_id for s in all_samples]


def test_field_widths_metadata_matches_scan(tmp_path):
    """Header-derived ensure_fields map == the full-scan map, and the
    loader's worst-case PadSpec needs NO payload reads on a BinDataset
    (ADVICE r3: no per-loader disk scan of lazy datasets)."""
    from hydragnn_tpu.data.graph import optional_field_widths
    from hydragnn_tpu.data.loader import GraphLoader

    samples = _samples(10, seed=5)
    path = str(tmp_path / "fw.hgb")
    write_bin_dataset(path, samples)
    ds = BinDataset(path)

    scan = optional_field_widths(list(samples))
    assert ds.field_widths() == scan
    assert optional_field_widths(ds) == scan

    nodes, edges = ds.sample_sizes()
    assert list(nodes) == [s.x.shape[0] for s in samples]
    assert list(edges) == [s.edge_index.shape[1] for s in samples]

    # Loader construction over the lazy container must not decode any
    # sample payload (metadata covers widths + pad spec).
    loads = []
    orig = BinDataset._load
    BinDataset._load = lambda self, i: loads.append(i) or orig(self, i)
    try:
        loader = GraphLoader(ds, 4)
        assert loads == []
        batches = list(loader)
    finally:
        BinDataset._load = orig
    assert len(batches) == 3
    # Lazy pass-through: the loader holds the container itself.
    assert loader.dataset is ds

    # Sharded: merged metadata map, no fallback scan.
    stem = str(tmp_path / "fwsh")
    write_bin_dataset(f"{stem}.p0.hgb", samples[:4])
    write_bin_dataset(f"{stem}.p1.hgb", samples[4:])
    multi = BinDataset.open_sharded(stem)
    assert multi.field_widths() == scan
    mn, me = multi.sample_sizes()
    assert list(mn) == list(nodes)


def test_field_widths_multi_merges_lazily(tmp_path):
    """The train/val/test union map merges per-dataset metadata maps
    without decoding payloads, and rejects cross-split label
    divergence."""
    from hydragnn_tpu.data.graph import (
        optional_field_widths,
        optional_field_widths_multi,
    )

    train, val = _samples(8, seed=1), _samples(4, seed=2)
    p1, p2 = str(tmp_path / "t.hgb"), str(tmp_path / "v.hgb")
    write_bin_dataset(p1, train)
    write_bin_dataset(p2, val)
    d1, d2 = BinDataset(p1), BinDataset(p2)

    loads = []
    orig = BinDataset._load
    BinDataset._load = lambda self, i: loads.append(i) or orig(self, i)
    try:
        merged = optional_field_widths_multi([d1, d2, []])
    finally:
        BinDataset._load = orig
    assert merged == optional_field_widths(list(train))
    assert loads == []  # metadata fast path end to end

    # Label divergence across splits (val without y_node) must raise.
    bad = _samples(4, seed=3)
    for s in bad:
        s.y_node = None
    p3 = str(tmp_path / "bad.hgb")
    write_bin_dataset(p3, bad)
    with pytest.raises(ValueError, match="differ across datasets"):
        optional_field_widths_multi([d1, BinDataset(p3)])


def test_pickle_meta_field_widths(tmp_path):
    """Full-set pickle writers record the ensure_fields map in meta;
    shard writers leave it unset and readers fall back to a cached
    scan."""
    from hydragnn_tpu.data.graph import optional_field_widths
    from hydragnn_tpu.data.pickledataset import (
        SimplePickleDataset,
        SimplePickleWriter,
    )

    samples = _samples(6, seed=7)
    scan = optional_field_widths(list(samples))

    full_dir = str(tmp_path / "full")
    SimplePickleWriter(samples, full_dir)
    ds = SimplePickleDataset(full_dir)
    assert ds.field_widths() == scan
    assert optional_field_widths(ds) == scan

    shard_dir = str(tmp_path / "shard")
    SimplePickleWriter(samples[:3], shard_dir, total=6, write_meta=True)
    SimplePickleWriter(samples[3:], shard_dir, offset=3, write_meta=False)
    ds2 = SimplePickleDataset(shard_dir)
    assert ds2.field_widths() is None
    assert optional_field_widths(ds2) == scan  # scan fallback
    assert ds2._cached_field_widths == scan  # ... cached on the object


def test_e2e_run_training_binary_format(tmp_path):
    """run_training ingests Dataset.format='binary' splits end to end."""
    import hydragnn_tpu
    from hydragnn_tpu.ops.neighbors import radius_graph

    r = np.random.default_rng(0)

    def mk(n, seed):
        rr = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            k = int(rr.integers(4, 8))
            pos = rr.uniform(0, 3.0, (k, 3)).astype(np.float32)
            x = rr.normal(size=(k, 1)).astype(np.float32)
            out.append(
                GraphSample(
                    x=x,
                    pos=pos,
                    edge_index=radius_graph(pos, 2.5, max_neighbours=10),
                    y_graph=np.array([x.mean()], np.float32),
                )
            )
        return out

    paths = {}
    for split, n, seed in (
        ("train", 32, 1),
        ("validate", 8, 2),
        ("test", 8, 3),
    ):
        p = str(tmp_path / f"{split}.hgb")
        write_bin_dataset(p, mk(n, seed))
        paths[split] = p

    config = {
        "Dataset": {"format": "binary", "path": paths},
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "num_epoch": 4,
                "batch_size": 8,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
            },
        },
    }
    state, model, cfg, hist, full = hydragnn_tpu.run_training(config)
    assert np.isfinite(hist.train_loss).all()
    assert hist.train_loss[-1] < hist.train_loss[0]


def test_multibin_rejects_mixed_featurizer_stamps(tmp_path):
    """Shards stamped with different SMILES featurizer paths (rdkit vs
    native) are value-divergent — MultiBinDataset must fail loudly
    (round-4 advisor; utils/descriptors.smiles_featurizer_path)."""
    import pytest

    from hydragnn_tpu.data.binformat import (
        BinDataset,
        MultiBinDataset,
        write_bin_dataset,
    )

    samples = _samples(4)
    a = str(tmp_path / "a.hgb")
    b = str(tmp_path / "b.hgb")
    write_bin_dataset(a, samples, attrs={"smiles_featurizer": "rdkit"})
    write_bin_dataset(b, samples, attrs={"smiles_featurizer": "native"})
    with pytest.raises(ValueError, match="smiles_featurizer"):
        MultiBinDataset([BinDataset(a), BinDataset(b)])
    # Agreeing stamps (or absent ones) are fine.
    c = str(tmp_path / "c.hgb")
    write_bin_dataset(c, samples, attrs={"smiles_featurizer": "rdkit"})
    ds = MultiBinDataset([BinDataset(a), BinDataset(c)])
    assert len(ds) == 8
