"""Sharded fast path under data parallelism (ISSUE 5): the
device-coordinated packer, packed ``[D, ...]`` delivery through serial
and pipeline feeds, and the dp superstep executor's bitwise-identity
contract on the fake 8-device CPU mesh (tests/conftest.py pins
``--xla_force_host_platform_device_count=8``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.data.graph import GraphSample, MacroBatch
from hydragnn_tpu.ops.neighbors import radius_graph


def _mols(n, lo=5, hi=11, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(r.integers(lo, hi))
        pos = r.uniform(0, 1.8 * k ** (1 / 3), (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.integers(0, 3, (k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.2, max_neighbours=16),
                y_graph=np.array([r.normal()], np.float32),
            )
        )
    return out


def _config(
    *,
    steps=1,
    workers=0,
    packing=True,
    num_epoch=2,
    batch_size=4,
    data=8,
):
    return {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.2,
                "max_neighbours": 16,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": batch_size,
                "num_epoch": num_epoch,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
                "Parallelism": {
                    "scheme": "dp",
                    "data": data,
                    "pipeline": {"workers": workers},
                    "packing": {"enabled": packing},
                    "superstep": {"steps": steps},
                },
            },
        }
    }


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(u), np.asarray(v))
        for u, v in zip(la, lb)
    )


# ----------------------------------------------------------------------
# Device-coordinated packer (pure plan arithmetic)
# ----------------------------------------------------------------------


@pytest.mark.parametrize(
    "n,d,lohi,bs",
    [
        (300, 8, (5, 40), 8),  # varied sizes, several budgets
        (64, 8, (5, 11), 4),  # small epoch: one step per spec at most
        (200, 4, (20, 21), 8),  # uniform sizes
        (53, 8, (5, 30), 4),  # awkward counts force balancing splits
    ],
)
def test_pack_epoch_ffd_dp_device_agreement(n, d, lohi, bs):
    """The coordination invariant: every device sees the same number of
    steps, the same budget (compiled shape) at every step, and the
    union of all bins is exactly the epoch's sample multiset — nothing
    dropped, nothing duplicated."""
    from hydragnn_tpu.data.padschedule import (
        epoch_batch_indices,
        fit_pack_budgets,
        pack_epoch_ffd_dp,
    )

    r = np.random.default_rng(1)
    ns = r.integers(*lohi, size=n).astype(np.int64)
    es = (ns * 3).astype(np.int64)
    budgets = fit_pack_budgets(ns, es, bs)
    for ep in range(3):
        order = np.concatenate(
            list(
                epoch_batch_indices(
                    n, bs, shuffle=True, seed=0, epoch=ep
                )
            )
        )
        plan = pack_epoch_ffd_dp(order, ns, es, budgets, d)
        # plan length a multiple of D: equal per-device step counts
        assert len(plan) % d == 0 and len(plan) >= d
        n_steps = len(plan) // d
        # per-step budget identity across the data axis, and therefore
        # an identical per-epoch spec SEQUENCE on every device
        per_dev = [
            [
                (s.num_nodes, s.num_edges, s.num_graphs)
                for (_, s) in plan[dev :: d]
            ]
            for dev in range(d)
        ]
        assert all(seq == per_dev[0] for seq in per_dev[1:])
        assert all(len(seq) == n_steps for seq in per_dev)
        # no sample dropped or duplicated
        got = np.sort(np.concatenate([idx for idx, _ in plan]))
        assert np.array_equal(got, np.sort(order))
        # every bin respects its budget's capacity
        for idx, s in plan:
            assert int(ns[idx].sum()) + 1 <= s.num_nodes
            assert int(es[idx].sum()) <= s.num_edges
            assert len(idx) + 1 <= s.num_graphs


def test_pack_epoch_ffd_dp_feasibility_is_epoch_invariant():
    """The canonical (-n, -e, pos) packing order makes the bin
    size-structure — and therefore the balance pass's feasibility AND
    the per-epoch spec sequence — a function of the size multiset
    alone: the runner's epoch-0 probe proves every later shuffle.
    Heavy node-count ties with divergent edge counts (the hazardous
    case: epoch-order tie-breaking would reshape bins per shuffle)."""
    from hydragnn_tpu.data.padschedule import (
        epoch_batch_indices,
        fit_pack_budgets,
        pack_epoch_ffd_dp,
    )

    r = np.random.default_rng(0)
    ns = np.repeat([10, 20, 30], 40).astype(np.int64)
    es = (ns * 2 + r.integers(0, 25, size=120)).astype(np.int64)
    budgets = fit_pack_budgets(ns, es, 6)
    profiles = set()
    for ep in range(12):
        order = np.concatenate(
            list(
                epoch_batch_indices(
                    120, 6, shuffle=True, seed=0, epoch=ep
                )
            )
        )
        plan = pack_epoch_ffd_dp(order, ns, es, budgets, 8)
        profiles.add(
            tuple(
                (s.num_nodes, s.num_edges, s.num_graphs)
                for _, s in plan
            )
        )
    assert len(profiles) == 1


def test_pack_dp_shards_rejects_resampling():
    """num_samples resamples the size multiset per epoch, so a later
    epoch could become infeasible to coordinate — rejected up front
    instead of raising mid-train."""
    from hydragnn_tpu.data.loader import GraphLoader

    with pytest.raises(ValueError, match="num_samples"):
        GraphLoader(
            _mols(64), 4, shuffle=True, num_samples=128,
            packing=True, pack_dp_shards=8,
        )


def test_pack_epoch_ffd_dp_too_few_graphs_raises():
    from hydragnn_tpu.data.padschedule import (
        fit_pack_budgets,
        pack_epoch_ffd_dp,
    )

    ns = np.full(4, 10, np.int64)
    es = np.full(4, 20, np.int64)
    budgets = fit_pack_budgets(ns, es, 2)
    with pytest.raises(ValueError, match="coordinate packed bins"):
        pack_epoch_ffd_dp(np.arange(4), ns, es, budgets, 8)


def test_dp_step_plan_folds_and_flags_mixed_steps():
    from hydragnn_tpu.data.graph import PadSpec
    from hydragnn_tpu.data.padschedule import dp_step_plan

    a = PadSpec(num_nodes=64, num_edges=128, num_graphs=5)
    b = PadSpec(num_nodes=32, num_edges=64, num_graphs=5)
    plan = [(0, a), (1, a), (2, a), (3, b), (4, a), (5, b), (6, a)]
    steps, tail = dp_step_plan(plan, 3)
    # step 0 shares spec a; step 1 mixes a/b -> ungroupable (None)
    assert [s for _, s in steps] == [a, None]
    assert [e[0] for e in tail] == [6]


# ----------------------------------------------------------------------
# resolve_superstep_k under dp
# ----------------------------------------------------------------------


def test_resolve_superstep_k_dp():
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.parallel.runtime import (
        ParallelPlan,
        resolve_superstep_k,
    )

    samples = _mols(256, seed=3)
    mesh = make_mesh({"data": 8})
    loader = GraphLoader(samples, 4, fixed_pad=True)
    # explicit pin wins (mesh present)
    plan = ParallelPlan(scheme="dp", mesh=mesh, superstep_steps=4)
    assert resolve_superstep_k(plan, loader) == 4
    # dp without a mesh is degenerate: K=1
    plan = ParallelPlan(scheme="dp", superstep_steps=4)
    assert resolve_superstep_k(plan, loader) == 1
    # auto on a short STEP-level plan (64 batches / 8 devices = 8
    # steps, under the 64-step floor): K=1
    short = GraphLoader(_mols(64, seed=3), 4, fixed_pad=True)
    plan = ParallelPlan(scheme="dp", mesh=mesh, superstep_steps="auto")
    assert resolve_superstep_k(plan, short) == 1
    # multibranch stays pinned at 1
    plan = ParallelPlan(scheme="multibranch", superstep_steps=4)
    assert resolve_superstep_k(plan, loader) == 1


# ----------------------------------------------------------------------
# Delivery: packed [D, ...] stacking, serial vs pipeline, K=1 wrappers
# ----------------------------------------------------------------------


def _delivered(loader):
    out = []
    for item in loader:
        if isinstance(item, MacroBatch):
            out.append(
                (item.k, jax.tree_util.tree_map(np.asarray, item.batch))
            )
        else:
            out.append(
                (1, jax.tree_util.tree_map(np.asarray, item))
            )
    return out


def test_dp_packed_delivery_serial_vs_pipeline_bit_identical():
    """Packed [D, ...] (and [K, D, ...]) delivery under dp must be
    bit-identical between the serial feed and the worker pipeline —
    the PR-1 contract extended to the sharded fast path."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.pipeline import ParallelPipelineLoader
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(160, seed=11)
    mesh = make_mesh({"data": 8})

    def _base():
        return GraphLoader(
            samples, 4, shuffle=True, seed=0, packing=True,
            pack_dp_shards=8,
        )

    for k in (1, 2):
        serial = DPLoader(_base(), mesh, superstep_k=k)
        piped = DPLoader(
            ParallelPipelineLoader(
                _base(),
                workers=2,
                to_device=False,
                hold=DPLoader.required_hold(mesh, superstep_k=k),
            ),
            mesh,
            superstep_k=k,
        )
        a = _delivered(serial)
        b = _delivered(piped)
        assert len(a) == len(b) and len(a) > 0
        for (ka, ba), (kb, bb) in zip(a, b):
            assert ka == kb
            assert _leaves_equal(ba, bb)


def test_dp_superstep_delivery_matches_k1_content():
    """Grouping changes dispatch boundaries, never content: flattening
    the K-axis of macro deliveries reproduces the K=1 step sequence
    bit for bit."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(160, seed=11)
    mesh = make_mesh({"data": 8})

    def _base():
        return GraphLoader(
            samples, 4, shuffle=True, seed=0, packing=True,
            pack_dp_shards=8,
        )

    flat = _delivered(DPLoader(_base(), mesh, superstep_k=1))
    grouped = _delivered(DPLoader(_base(), mesh, superstep_k=2))
    regrouped = []
    for k, b in grouped:
        if k == 1:
            regrouped.append(b)
        else:
            for t in range(k):
                regrouped.append(
                    jax.tree_util.tree_map(lambda x: x[t], b)
                )
    assert len(regrouped) == len(flat)
    for (_, a), b in zip(flat, regrouped):
        assert _leaves_equal(a, b)


def test_wrap_loader_dp_k1_and_superstep_false_keep_todays_chain():
    """With K resolved (or forced) to 1 the dp chain is exactly today's
    wrappers: a DPLoader that yields plain [D, ...] GraphBatches —
    superstep=False consumers (run_test's per-sample collection) are
    untouched even when the plan asks for K>1."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.loader import iter_loader_chain
    from hydragnn_tpu.parallel import runtime
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(96, seed=5)
    mesh = make_mesh({"data": 8})
    plan = runtime.ParallelPlan(
        scheme="dp", mesh=mesh, superstep_steps=4, pipeline_workers=0
    )
    loader = GraphLoader(samples, 4, fixed_pad=True)
    wrapped = runtime.wrap_loader(plan, loader, superstep=False)
    dpl = next(
        ld
        for ld in iter_loader_chain(wrapped)
        if isinstance(ld, DPLoader)
    )
    assert dpl.superstep_k == 1
    assert all(not isinstance(b, MacroBatch) for b in wrapped)
    # with superstep allowed, the plan's pin reaches the DPLoader
    wrapped2 = runtime.wrap_loader(plan, GraphLoader(samples, 4, fixed_pad=True))
    dpl2 = next(
        ld
        for ld in iter_loader_chain(wrapped2)
        if isinstance(ld, DPLoader)
    )
    assert dpl2.superstep_k == 4


def test_dp_delivery_with_fastpath_off_is_pre_pr_identical():
    """Acceptance: with packing disabled and K=1 the delivered [D, ...]
    sequence is bit-identical to the pre-PR chain (a bare DPLoader over
    the same spec-schedule-free loader)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel import runtime
    from hydragnn_tpu.parallel.dp import DPLoader
    from hydragnn_tpu.parallel.mesh import make_mesh

    samples = _mols(96, seed=5)
    mesh = make_mesh({"data": 8})
    plan = runtime.ParallelPlan(
        scheme="dp", mesh=mesh, superstep_steps=1,
        pipeline_workers=0, prefetch=0, packing=False,
    )
    new = _delivered(
        runtime.wrap_loader(
            plan, GraphLoader(samples, 4, fixed_pad=True)
        )
    )
    old = _delivered(
        DPLoader(GraphLoader(samples, 4, fixed_pad=True), mesh)
    )
    assert len(new) == len(old) > 0
    for (ka, a), (kb, b) in zip(new, old):
        assert ka == kb == 1
        assert _leaves_equal(a, b)


# ----------------------------------------------------------------------
# The dp superstep executor: bitwise identity (the ISSUE acceptance)
# ----------------------------------------------------------------------


def test_run_training_dp_packing_falls_back_per_split():
    """A split too small to feed every device a coordinated packed plan
    falls back to the spec-schedule former PER SPLIT at startup (the
    len() probe) — the train loader keeps the packed fast path, the
    run completes, and nothing can raise mid-train (feasibility is
    epoch-invariant under the canonical packing order)."""
    from hydragnn_tpu.runner import run_training

    samples = _mols(80, seed=9)
    # val/test splits of 5 graphs each: < 8 devices, uncoordinatable;
    # the 70-graph train split coordinates fine
    tr, va, te = samples[:70], samples[70:75], samples[75:]
    cfg = _config(steps=1, workers=0, packing=True, num_epoch=1)
    state, _, _, hist, _ = run_training(cfg, datasets=(tr, va, te), seed=0)
    assert len(hist.train_loss) == 1
    assert np.isfinite(hist.train_loss).all()
    # the packed-train run must differ from an all-unpacked run only in
    # eval handling: compare against packing fully disabled — training
    # trajectories DIFFER (packed former) while both runs complete
    cfg2 = _config(steps=1, workers=0, packing=False, num_epoch=1)
    _, _, _, hist2, _ = run_training(cfg2, datasets=(tr, va, te), seed=0)
    assert hist.train_loss != hist2.train_loss, (
        "train split lost its packed former to an eval-split fallback"
    )


def test_dp_scan_bitwise_vs_sequential_dp_steps():
    """K scanned dp steps == K sequential jitted dp step dispatches,
    bit for bit (loss/task sums AND final params), on the fake
    8-device mesh — the dp form of the PR-4 contract."""
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.parallel.dp import (
        DPLoader,
        make_dp_superstep_fn,
        make_dp_train_step,
        replicate_state,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    samples = _mols(128, seed=3)
    cfgd = update_config(_config(), samples)
    model, cfg = create_model_config(cfgd)
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    mesh = make_mesh({"data": 8})

    base = GraphLoader(samples, 4, fixed_pad=True)
    params, bs = init_params(model, next(iter(base)))
    host_params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    host_bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )

    def fresh_state():
        return replicate_state(
            create_train_state(
                jax.tree_util.tree_map(jnp.array, host_params),
                tx,
                jax.tree_util.tree_map(jnp.array, host_bs),
            ),
            mesh,
        )

    k = 4
    flat = list(iter(DPLoader(base, mesh)))[:k]
    assert len(flat) == k

    step = make_dp_train_step(model, tx, cfg, mesh)
    st = fresh_state()
    loss_sum = tasks_sum = ng = None
    for sb in flat:
        g = jnp.sum(sb.graph_mask).astype(jnp.float32)
        st, tot, tasks = step(st, sb)
        if loss_sum is None:
            loss_sum, tasks_sum, ng = tot * g, tasks * g, g
        else:
            loss_sum = loss_sum + tot * g
            tasks_sum = tasks_sum + tasks * g
            ng = ng + g
    seq_params = jax.device_get(st.params)
    seq_acc = jax.device_get((loss_sum, tasks_sum, ng))

    sstep = make_dp_superstep_fn(model, tx, cfg, mesh, train=True)
    base2 = GraphLoader(samples, 4, fixed_pad=True)
    macro = next(
        iter(DPLoader(base2, mesh, superstep_k=k))
    )
    assert isinstance(macro, MacroBatch) and macro.k == k
    st2 = fresh_state()
    acc0 = (
        jnp.zeros((), jnp.float32),
        jnp.zeros((1,), jnp.float32),
        jnp.zeros((), jnp.float32),
    )
    st2, acc = sstep(st2, acc0, macro.batch)
    scan_params = jax.device_get(st2.params)
    scan_acc = jax.device_get(acc)

    assert _leaves_equal(seq_params, scan_params)
    for a, b in zip(seq_acc, scan_acc):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_dp_superstep_composes_with_fsdp_bitwise():
    """The scan carries the param shardings unchanged: on a
    {data:4, fsdp:2} mesh the K-scan over the fsdp-sharded dp step is
    still bit-equal to K sequential dispatches."""
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.parallel.dp import (
        DPLoader,
        make_dp_superstep_fn,
        make_dp_train_step,
        replicate_state,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    samples = _mols(96, seed=6)
    cfgd = update_config(_config(), samples)
    model, cfg = create_model_config(cfgd)
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    mesh = make_mesh({"data": 4, "fsdp": 2})
    base = GraphLoader(samples, 4, fixed_pad=True)
    params, bs = init_params(model, next(iter(base)))
    host_params = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(params)
    )
    host_bs = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), jax.device_get(bs)
    )

    def fresh_state():
        return replicate_state(
            create_train_state(
                jax.tree_util.tree_map(jnp.array, host_params),
                tx,
                jax.tree_util.tree_map(jnp.array, host_bs),
            ),
            mesh,
            fsdp=True,
        )

    k = 2
    flat = list(iter(DPLoader(base, mesh)))[:k]
    step = make_dp_train_step(model, tx, cfg, mesh)
    st = fresh_state()
    for sb in flat:
        st, _, _ = step(st, sb)
    seq_params = jax.device_get(st.params)

    macro = next(
        iter(
            DPLoader(
                GraphLoader(samples, 4, fixed_pad=True),
                mesh,
                superstep_k=k,
            )
        )
    )
    assert isinstance(macro, MacroBatch)
    sstep = make_dp_superstep_fn(model, tx, cfg, mesh, train=True)
    st2 = fresh_state()
    st2, _ = sstep(
        st2,
        (
            jnp.zeros((), jnp.float32),
            jnp.zeros((1,), jnp.float32),
            jnp.zeros((), jnp.float32),
        ),
        macro.batch,
    )
    assert _leaves_equal(seq_params, jax.device_get(st2.params))


def test_dp_eval_superstep_bitwise(tmp_path):
    from hydragnn_tpu.config import update_config
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config, init_params
    from hydragnn_tpu.parallel.dp import (
        DPLoader,
        make_dp_eval_step,
        make_dp_superstep_fn,
        replicate_state,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    samples = _mols(128, seed=4)
    cfgd = update_config(_config(), samples)
    model, cfg = create_model_config(cfgd)
    tx = select_optimizer(cfgd["NeuralNetwork"]["Training"])
    mesh = make_mesh({"data": 8})
    base = GraphLoader(samples, 4, fixed_pad=True)
    params, bs = init_params(model, next(iter(base)))
    state = replicate_state(
        create_train_state(params, tx, bs), mesh
    )

    k = 4
    flat = list(iter(DPLoader(base, mesh)))[:k]
    estep = make_dp_eval_step(model, cfg, mesh)
    loss_sum = tasks_sum = ng = None
    for sb in flat:
        g = jnp.sum(sb.graph_mask).astype(jnp.float32)
        tot, tasks = estep(state, sb)
        if loss_sum is None:
            loss_sum, tasks_sum, ng = tot * g, tasks * g, g
        else:
            loss_sum = loss_sum + tot * g
            tasks_sum = tasks_sum + tasks * g
            ng = ng + g
    seq = jax.device_get((loss_sum, tasks_sum, ng))

    sstep = make_dp_superstep_fn(model, tx, cfg, mesh, train=False)
    macro = next(
        iter(
            DPLoader(
                GraphLoader(samples, 4, fixed_pad=True),
                mesh,
                superstep_k=k,
            )
        )
    )
    acc = sstep(
        state,
        (
            jnp.zeros((), jnp.float32),
            jnp.zeros((1,), jnp.float32),
            jnp.zeros((), jnp.float32),
        ),
        macro.batch,
    )
    scan = jax.device_get(acc)
    for a, b in zip(seq, scan):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_run_training_dp_superstep_bitwise_identity():
    """THE acceptance gate: packed + K-scan dp training through
    run_training (>= 8 optimizer steps per epoch) produces bit-equal
    losses AND params vs K=1 sequential dp steps, through both the
    serial and the pipeline feed."""
    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_training

    samples = _mols(400, seed=13)
    tr, va, te = split_dataset(samples, 0.8)
    runs = {}
    for name, steps, workers in (
        ("k1_serial", 1, 0),
        ("k4_serial", 4, 0),
        ("k4_pipeline", 4, 2),
    ):
        cfg = _config(steps=steps, workers=workers, packing=True)
        state, _, _, hist, _ = run_training(
            cfg, datasets=(tr, va, te), seed=0
        )
        runs[name] = (
            jax.device_get(state.params),
            list(hist.train_loss),
            list(hist.val_loss),
            list(hist.test_loss),
        )
    ref = runs["k1_serial"]
    # >= 8 steps per epoch: 320 train graphs / batch 4 / 8 devices = 10
    assert len(ref[1]) == 2
    for name in ("k4_serial", "k4_pipeline"):
        got = runs[name]
        assert _leaves_equal(ref[0], got[0]), f"{name}: params differ"
        assert ref[1] == got[1], f"{name}: train losses differ"
        assert ref[2] == got[2], f"{name}: val losses differ"
        assert ref[3] == got[3], f"{name}: test losses differ"
