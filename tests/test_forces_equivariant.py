"""Force equivariance: F(R·x) = R·F(x) (reference
tests/test_forces_equivariant.py:12-25) across MPNN types, head types,
structure geometries, and rotations. Forces are -dE/dpos, so any scalar
rotation-invariant energy model yields equivariant forces; this test
guards the whole chain (embedding, message passing, heads, segment ops)
against accidental use of absolute coordinates.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.train.mlip import energy_and_forces


def _rotation(seed):
    rng = np.random.default_rng(seed)
    q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
    if np.linalg.det(q) < 0:
        q[:, 0] = -q[:, 0]
    return q.astype(np.float32)


def _structure(kind, n, rng):
    if kind == "linear":
        pos = np.stack(
            [np.linspace(0, 2.5, n), np.zeros(n), np.zeros(n)], axis=1
        )
        pos = pos + rng.normal(scale=0.05, size=(n, 3))
    elif kind == "planar":
        pos = np.concatenate(
            [rng.uniform(0, 3.0, (n, 2)), np.zeros((n, 1))], axis=1
        )
    else:
        pos = rng.uniform(0, 3.0, (n, 3))
    return pos.astype(np.float32)


def _sample(kind, seed, rotation=None):
    rng = np.random.default_rng(seed)
    n = 8
    pos = _structure(kind, n, rng)
    if rotation is not None:
        pos = (pos @ rotation.T).astype(np.float32)
    ei = radius_graph(pos, 2.0, max_neighbours=12)
    return GraphSample(
        x=rng.integers(1, 5, (n, 1)).astype(np.float32),
        pos=pos,
        edge_index=ei,
        energy=0.0,
        forces=np.zeros((n, 3), np.float32),
    )


def _cfg(mpnn_type, head_type):
    head = (
        HeadSpec("energy", "node", 1)
        if head_type == "node"
        else HeadSpec("energy", "graph", 1)
    )
    return ModelConfig(
        mpnn_type=mpnn_type,
        input_dim=1,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(head,),
        graph_branches=(BranchSpec(),),
        node_branches=(BranchSpec(),),
        task_weights=(1.0,),
        radius=2.0,
        num_gaussians=8,
        num_filters=8,
        num_radial=6,
        graph_pooling="add" if head_type == "graph" else "mean",
        enable_interatomic_potential=True,
        force_weight=1.0,
    )


@pytest.mark.parametrize("mpnn_type", ["SchNet", "EGNN", "PAINN"])
@pytest.mark.parametrize("head_type", ["node", "graph"])
@pytest.mark.parametrize("kind", ["random", "linear", "planar"])
def test_force_equivariance(mpnn_type, head_type, kind):
    cfg = _cfg(mpnn_type, head_type)
    model = create_model(cfg)
    rot = _rotation(seed=11)

    base = collate([_sample(kind, seed=5)])
    rotated = collate([_sample(kind, seed=5, rotation=rot)])
    params, bs = init_params(model, base)
    variables = {"params": params, "batch_stats": bs}

    e0, f0, _ = energy_and_forces(model, variables, base, cfg)
    e1, f1, _ = energy_and_forces(model, variables, rotated, cfg)

    # Energy invariant, forces equivariant.
    np.testing.assert_allclose(
        np.asarray(e0), np.asarray(e1), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(f0) @ rot.T, np.asarray(f1), rtol=1e-3, atol=1e-4
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_force_equivariance_many_rotations(seed):
    cfg = _cfg("SchNet", "node")
    model = create_model(cfg)
    rot = _rotation(seed=seed + 100)
    base = collate([_sample("random", seed=seed)])
    rotated = collate([_sample("random", seed=seed, rotation=rot)])
    params, bs = init_params(model, base)
    variables = {"params": params, "batch_stats": bs}
    _, f0, _ = energy_and_forces(model, variables, base, cfg)
    _, f1, _ = energy_and_forces(model, variables, rotated, cfg)
    np.testing.assert_allclose(
        np.asarray(f0) @ rot.T, np.asarray(f1), rtol=1e-3, atol=1e-4
    )
