"""Graph-dimension parallelism: a single giant graph sharded over the
8-device CPU mesh must produce the same energy, forces, and parameter
gradients as the single-device computation (the collectives are
all_gather / psum_scatter pairs, transposed correctly under autodiff).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.parallel.graphshard import (
    GraphShards,
    init_params,
    reference_mpnn_forward,
    sharded_mpnn_forward,
)
from hydragnn_tpu.parallel.mesh import make_mesh

CUTOFF = 2.5
NG = 12
LAYERS = 2


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    n = 200  # one "giant" graph
    pos = rng.uniform(0, 8.0, (n, 3)).astype(np.float32)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    ei = radius_graph(pos, CUTOFF, max_neighbours=24)
    mesh = make_mesh({"graph": 8})
    shards = GraphShards.build(x, pos, ei, 8).device_put(mesh)
    params = init_params(jax.random.PRNGKey(0), 4, 16, LAYERS, NG)
    return mesh, shards, params


def _ref(params, shards):
    return reference_mpnn_forward(
        params,
        shards.x,
        shards.pos,
        shards.node_mask,
        shards.senders,
        shards.receivers,
        shards.edge_mask,
        cutoff=CUTOFF,
        num_gaussians=NG,
        num_layers=LAYERS,
    )


def test_forward_matches_single_device(setup):
    mesh, shards, params = setup
    e_sharded = sharded_mpnn_forward(
        params, shards, mesh, cutoff=CUTOFF, num_gaussians=NG,
        num_layers=LAYERS,
    )
    e_ref = _ref(params, shards)
    np.testing.assert_allclose(
        float(e_sharded), float(e_ref), rtol=1e-5
    )


def test_forces_match_single_device(setup):
    mesh, shards, params = setup

    def e_sharded(pos):
        import dataclasses

        s = dataclasses.replace(shards, pos=pos)
        return sharded_mpnn_forward(
            params, s, mesh, cutoff=CUTOFF, num_gaussians=NG,
            num_layers=LAYERS,
        )

    def e_ref(pos):
        import dataclasses

        s = dataclasses.replace(shards, pos=pos)
        return _ref(params, s)

    f_sh = -jax.grad(e_sharded)(shards.pos)
    f_rf = -jax.grad(e_ref)(shards.pos)
    np.testing.assert_allclose(
        np.asarray(f_sh), np.asarray(f_rf), rtol=1e-4, atol=1e-5
    )


def test_param_grads_match_single_device(setup):
    mesh, shards, params = setup
    g_sh = jax.grad(
        lambda p: sharded_mpnn_forward(
            p, shards, mesh, cutoff=CUTOFF, num_gaussians=NG,
            num_layers=LAYERS,
        )
    )(params)
    g_rf = jax.grad(lambda p: _ref(p, shards))(params)
    flat_sh = jax.tree_util.tree_leaves(g_sh)
    flat_rf = jax.tree_util.tree_leaves(g_rf)
    for a, b in zip(flat_sh, flat_rf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_jit_compiles_with_collectives(setup):
    mesh, shards, params = setup
    f = jax.jit(
        lambda p, pos: sharded_mpnn_forward(
            p,
            __import__("dataclasses").replace(shards, pos=pos),
            mesh,
            cutoff=CUTOFF,
            num_gaussians=NG,
            num_layers=LAYERS,
        )
    )
    e1 = f(params, shards.pos)
    e2 = f(params, shards.pos + 0.0)
    np.testing.assert_allclose(float(e1), float(e2), rtol=1e-6)


def test_ring_attention_matches_dense(setup):
    """Ring attention over the sharded giant graph must reproduce the
    single-device dense masked softmax attention exactly (online
    softmax blockwise == full softmax), including through autodiff."""
    mesh, shards, _ = setup
    heads = 2
    params = init_params(
        jax.random.PRNGKey(3), 4, 16, LAYERS, NG, attn_heads=heads
    )

    e_sharded = sharded_mpnn_forward(
        params, shards, mesh,
        cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
        attn_heads=heads,
    )
    e_ref = reference_mpnn_forward(
        params,
        shards.x, shards.pos, shards.node_mask,
        shards.senders, shards.receivers, shards.edge_mask,
        cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
        attn_heads=heads,
    )
    np.testing.assert_allclose(
        float(e_sharded), float(e_ref), rtol=2e-5
    )

    # Forces (grad wrt positions) agree through ppermute + online
    # softmax backward.
    import dataclasses

    g_sharded = jax.grad(
        lambda p: sharded_mpnn_forward(
            params, dataclasses.replace(shards, pos=p), mesh,
            cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
            attn_heads=heads,
        )
    )(shards.pos)
    g_ref = jax.grad(
        lambda p: reference_mpnn_forward(
            params, shards.x, p, shards.node_mask,
            shards.senders, shards.receivers, shards.edge_mask,
            cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
            attn_heads=heads,
        )
    )(shards.pos)
    np.testing.assert_allclose(
        np.asarray(g_sharded), np.asarray(g_ref), rtol=1e-3, atol=2e-5
    )
