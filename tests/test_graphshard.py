"""Graph-dimension parallelism: a single giant graph sharded over the
8-device CPU mesh must produce the same energy, forces, and parameter
gradients as the single-device computation (the collectives are
all_gather / psum_scatter pairs, transposed correctly under autodiff).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.parallel.graphshard import (
    GraphShards,
    init_params,
    reference_mpnn_forward,
    sharded_mpnn_forward,
)
from hydragnn_tpu.parallel.mesh import make_mesh

CUTOFF = 2.5
NG = 12
LAYERS = 2


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    n = 200  # one "giant" graph
    pos = rng.uniform(0, 8.0, (n, 3)).astype(np.float32)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    ei = radius_graph(pos, CUTOFF, max_neighbours=24)
    mesh = make_mesh({"graph": 8})
    shards = GraphShards.build(x, pos, ei, 8).device_put(mesh)
    params = init_params(jax.random.PRNGKey(0), 4, 16, LAYERS, NG)
    return mesh, shards, params


def _ref(params, shards):
    return reference_mpnn_forward(
        params,
        shards.x,
        shards.pos,
        shards.node_mask,
        shards.senders,
        shards.receivers,
        shards.edge_mask,
        cutoff=CUTOFF,
        num_gaussians=NG,
        num_layers=LAYERS,
    )


def test_forward_matches_single_device(setup):
    mesh, shards, params = setup
    e_sharded = sharded_mpnn_forward(
        params, shards, mesh, cutoff=CUTOFF, num_gaussians=NG,
        num_layers=LAYERS,
    )
    e_ref = _ref(params, shards)
    np.testing.assert_allclose(
        float(e_sharded), float(e_ref), rtol=1e-5
    )


def test_forces_match_single_device(setup):
    mesh, shards, params = setup

    def e_sharded(pos):
        import dataclasses

        s = dataclasses.replace(shards, pos=pos)
        return sharded_mpnn_forward(
            params, s, mesh, cutoff=CUTOFF, num_gaussians=NG,
            num_layers=LAYERS,
        )

    def e_ref(pos):
        import dataclasses

        s = dataclasses.replace(shards, pos=pos)
        return _ref(params, s)

    f_sh = -jax.grad(e_sharded)(shards.pos)
    f_rf = -jax.grad(e_ref)(shards.pos)
    np.testing.assert_allclose(
        np.asarray(f_sh), np.asarray(f_rf), rtol=1e-4, atol=1e-5
    )


def test_param_grads_match_single_device(setup):
    mesh, shards, params = setup
    g_sh = jax.grad(
        lambda p: sharded_mpnn_forward(
            p, shards, mesh, cutoff=CUTOFF, num_gaussians=NG,
            num_layers=LAYERS,
        )
    )(params)
    g_rf = jax.grad(lambda p: _ref(p, shards))(params)
    flat_sh = jax.tree_util.tree_leaves(g_sh)
    flat_rf = jax.tree_util.tree_leaves(g_rf)
    for a, b in zip(flat_sh, flat_rf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_jit_compiles_with_collectives(setup):
    mesh, shards, params = setup
    f = jax.jit(
        lambda p, pos: sharded_mpnn_forward(
            p,
            __import__("dataclasses").replace(shards, pos=pos),
            mesh,
            cutoff=CUTOFF,
            num_gaussians=NG,
            num_layers=LAYERS,
        )
    )
    e1 = f(params, shards.pos)
    e2 = f(params, shards.pos + 0.0)
    np.testing.assert_allclose(float(e1), float(e2), rtol=1e-6)


def test_ring_attention_matches_dense(setup):
    """Ring attention over the sharded giant graph must reproduce the
    single-device dense masked softmax attention exactly (online
    softmax blockwise == full softmax), including through autodiff."""
    mesh, shards, _ = setup
    heads = 2
    params = init_params(
        jax.random.PRNGKey(3), 4, 16, LAYERS, NG, attn_heads=heads
    )

    e_sharded = sharded_mpnn_forward(
        params, shards, mesh,
        cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
        attn_heads=heads,
    )
    e_ref = reference_mpnn_forward(
        params,
        shards.x, shards.pos, shards.node_mask,
        shards.senders, shards.receivers, shards.edge_mask,
        cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
        attn_heads=heads,
    )
    np.testing.assert_allclose(
        float(e_sharded), float(e_ref), rtol=2e-5
    )

    # Forces (grad wrt positions) agree through ppermute + online
    # softmax backward.
    import dataclasses

    g_sharded = jax.grad(
        lambda p: sharded_mpnn_forward(
            params, dataclasses.replace(shards, pos=p), mesh,
            cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
            attn_heads=heads,
        )
    )(shards.pos)
    g_ref = jax.grad(
        lambda p: reference_mpnn_forward(
            params, shards.x, p, shards.node_mask,
            shards.senders, shards.receivers, shards.edge_mask,
            cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS,
            attn_heads=heads,
        )
    )(shards.pos)
    np.testing.assert_allclose(
        np.asarray(g_sharded), np.asarray(g_ref), rtol=1e-3, atol=2e-5
    )


@pytest.fixture(scope="module")
def halo_setup():
    """A locality-ordered giant graph (nodes sorted along z) — the
    regime halo exchange exists for: boundary shells are thin, so the
    halo is much smaller than the full node set."""
    from hydragnn_tpu.parallel.graphshard import HaloShards

    rng = np.random.default_rng(3)
    n = 240
    # Elongated box: each of the 8 z-slabs is deeper than the cutoff,
    # so only adjacent slabs exchange and the halo is a thin shell.
    pos = (
        rng.uniform(0, 1.0, (n, 3)) * np.array([6.0, 6.0, 24.0])
    ).astype(np.float32)
    pos = pos[np.argsort(pos[:, 2])]  # spatial ordering
    x = rng.normal(size=(n, 4)).astype(np.float32)
    ei = radius_graph(pos, CUTOFF, max_neighbours=24)
    mesh = make_mesh({"graph": 8})
    full = GraphShards.build(x, pos, ei, 8).device_put(mesh)
    halo = HaloShards.build(x, pos, ei, 8).device_put(mesh)
    params = init_params(jax.random.PRNGKey(1), 4, 16, LAYERS, NG)
    return mesh, full, halo, params


def test_halo_matches_allgather_and_reference(halo_setup):
    """Differential proof: the halo-exchange forward equals both the
    all-gather sharded forward and the single-device reference on the
    same graph."""
    from hydragnn_tpu.parallel.graphshard import halo_mpnn_forward

    mesh, full, halo, params = halo_setup
    kw = dict(cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS)
    e_halo = float(halo_mpnn_forward(params, halo, mesh, **kw))
    e_gather = float(sharded_mpnn_forward(params, full, mesh, **kw))
    e_ref = float(_ref(params, full))
    np.testing.assert_allclose(e_halo, e_gather, rtol=1e-5)
    np.testing.assert_allclose(e_halo, e_ref, rtol=1e-5)


def test_halo_forces_match(halo_setup):
    """Forces = -grad wrt positions must flow through the ppermute
    halo exchange (transpose = reverse ppermute)."""
    import dataclasses

    from hydragnn_tpu.parallel.graphshard import halo_mpnn_forward

    mesh, full, halo, params = halo_setup
    kw = dict(cutoff=CUTOFF, num_gaussians=NG, num_layers=LAYERS)

    g_halo = jax.grad(
        lambda p: halo_mpnn_forward(
            params, dataclasses.replace(halo, pos=p), mesh, **kw
        )
    )(halo.pos)
    g_ref = jax.grad(
        lambda p: reference_mpnn_forward(
            params, full.x, p, full.node_mask, full.senders,
            full.receivers, full.edge_mask, **kw
        )
    )(full.pos)
    np.testing.assert_allclose(
        np.asarray(g_halo), np.asarray(g_ref), rtol=1e-4, atol=1e-5
    )


def test_halo_memory_model(halo_setup):
    """The whole point: per-device rows materialized by a layer must be
    well below the full node count on a locality-ordered graph (the
    all-gather path pays N_pad rows per device)."""
    _, _, halo, _ = halo_setup
    assert halo.halo_rows < halo.num_nodes_padded / 2
    # Cutoff 2.5 on a z-sorted 10A box: only adjacent shards exchange.
    assert len(halo.hops) <= 2
