"""Multi-host (multi-process) training: 2 coordinated processes x 4
virtual CPU devices each run run_training over one global {data: 8}
mesh — rendezvous, process-sharded data, global-collective metric
reduction, and process-0 checkpointing (reference counterpart: the
2-rank MPI CI pytest, .github/workflows/CI.yml:62-67, and
distributed.py:113-275 setup_ddp).

Runs as subprocesses because each process needs its own JAX backend
(the in-process test session already pinned an 8-device single-process
platform).

KNOWN ENVIRONMENT LIMIT (recorded in PR 13, ROADMAP "Every scheme
rides the fast path" caveat): jax 0.4.37's CPU backend cannot run
cross-process XLA computations at all — the dp/fsdp/multibranch
workers here hang or crash inside their first global collective — and
the ``jax_num_cpu_devices`` option the workers need for their 4-device
split does not exist in this jax. On such hosts the cases below are
marked xfail WITHOUT RUNNING (``run=False``): tier-1 output then
distinguishes this environment limit from a real regression, and the
suite stops paying two coordinated 1200s-timeout subprocesses per case
for a foregone conclusion. Multi-process coverage on these hosts lives
in the coordination-service drills (``multiproc_preemption_drill``,
``fleet_observability_drill``), which keep computations process-local
by design. Do NOT "fix" the tests — revisit on a jax upgrade whose CPU
backend supports both.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import jax

_ENV_CANNOT_MULTIPROC_XLA = not hasattr(jax.config, "jax_num_cpu_devices")
_XFAIL_REASON = (
    "jax 0.4.37 CPU backend: no cross-process XLA computations and no "
    "jax_num_cpu_devices option — known environment limit (PR 13), "
    "not a regression; skipped-without-running to keep tier-1 cheap"
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.e2e
@pytest.mark.xfail(
    _ENV_CANNOT_MULTIPROC_XLA,
    reason=_XFAIL_REASON,
    run=False,
    strict=False,
)
@pytest.mark.parametrize(
    "parallelism",
    [
        '{"scheme": "dp", "data": 8}',
        # fsdp axis spanning both processes: params sharded across
        # hosts, checkpoint all-gather crosses process boundaries.
        '{"scheme": "dp", "data": 4, "fsdp": 2}',
        # task parallelism across hosts: each process iterates only
        # its local device slots' branch loaders.
        '{"scheme": "multibranch"}',
    ],
    ids=["dp", "dp_fsdp", "multibranch"],
)
def test_two_process_training(tmp_path, parallelism):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "HYDRAGNN_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "HYDRAGNN_TPU_NUM_PROCESSES": "2",
                "HYDRAGNN_TPU_PROCESS_ID": str(pid),
                "HYDRAGNN_TPU_LOCAL_DEVICES": "4",
                "HYDRAGNN_TEST_PARALLELISM": parallelism,
                "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
            }
        )
        # The pytest session's XLA_FLAGS pin 8 host devices; the workers
        # use jax_num_cpu_devices=4 instead.
        env["XLA_FLAGS"] = " ".join(
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(repo, "tests", "multihost_worker.py"),
                    str(tmp_path),
                ],
                env=env,
                cwd=repo,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            # generous: ~50s uncontended, but the 2 coordinated workers
            # stall hard when the host is oversubscribed
            out, _ = p.communicate(timeout=1200)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"

    hists = []
    for pid in range(2):
        with open(tmp_path / f"hist_{pid}.json") as f:
            hists.append(json.load(f))
    # Metrics are global XLA collectives: every process must see the
    # exact same loss history.
    assert hists[0]["train"] == hists[1]["train"]
    assert hists[0]["val"] == hists[1]["val"]
    assert len(hists[0]["train"]) == 3
    assert all(x > 0 and x == x for x in hists[0]["train"])
    # Process 0 wrote the checkpoint; both saw it on the shared fs.
    assert hists[0]["ckpt_exists"] and hists[1]["ckpt_exists"]
    # Multi-host per-sample collection: run_prediction gathers the FULL
    # true/pred set on every process (reference gather_tensor_ranks,
    # train_validate_test.py:1082-1088). 128 samples, test split
    # (1-0.75)/2 -> 16, plus one deliberately-odd extra sample that the
    # equal-shard truncation cannot place: 17 total via leftover merge.
    if "pred_n_samples" in hists[0]:
        for h in hists:
            assert h["pred_n_samples"] == 17, h
            assert h["pred_n_pred"] == 17, h
            assert h["pred_error"] == hists[0]["pred_error"]
            # Lazy mmap-backed containers through the same path: the
            # leftover merge must index (not slice) the dataset and
            # produce the identical full collection.
            assert h["pred_lazy_n"] == 17, h
            # Lazy and eager round-trip the SAME samples through the
            # same state, so their errors must be equal — a merge path
            # consistently wrong on both processes can't hide.
            assert h["pred_lazy_error"] == h["pred_error"], h
