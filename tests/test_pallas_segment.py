"""Pallas sorted-segment-sum kernel: differential tests against
jax.ops.segment_sum (forward + gradient), plan construction edge cases.
Runs in interpret mode on the CPU mesh; the same code path compiles via
Mosaic on TPU (measured ~20% faster than XLA's scatter lowering at
E=32k/N=3k/F=128 — see module docstring).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.pallas_segment import (
    DEFAULT_BE,
    DEFAULT_BN,
    SortedSegmentPlan,
    plan_sorted_blocks,
    segment_sum_sorted,
)


def test_plan_covers_all_edges():
    rng = np.random.default_rng(0)
    seg = np.sort(rng.integers(0, 1000, 5000)).astype(np.int32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, 1000)
    assert len(perm) == len(seg_p) == len(valid)
    assert len(perm) % DEFAULT_BE == 0
    assert len(window) == len(perm) // DEFAULT_BE
    # every original edge appears exactly once among valid slots
    assert sorted(perm[valid]) == list(range(5000))
    # every valid slot's segment sits inside its block's window
    for b in range(len(window)):
        s = seg_p[b * DEFAULT_BE : (b + 1) * DEFAULT_BE]
        v = valid[b * DEFAULT_BE : (b + 1) * DEFAULT_BE]
        if v.any():
            assert np.all(s[v] // DEFAULT_BN == window[b])
    # windows non-decreasing (consecutive-revisit accumulation contract)
    assert np.all(np.diff(window) >= 0)


def test_plan_empty():
    perm, seg_p, valid, window = plan_sorted_blocks(
        np.zeros(0, np.int32), 16
    )
    assert not valid.any()
    assert len(window) == 1


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("shape", [(700, 128), (5000, 256)])
def test_forward_matches_xla(seed, shape):
    e, f = shape
    n = max(e // 10, 4)
    rng = np.random.default_rng(seed)
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    ref = jax.ops.segment_sum(data, jnp.asarray(seg), num_segments=n)
    out = segment_sum_sorted(data, jnp.asarray(seg), n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_gradient_matches_xla():
    rng = np.random.default_rng(3)
    e, n, f = 600, 64, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)

    def loss_pallas(d):
        return jnp.sum(segment_sum_sorted(d, jnp.asarray(seg), n) ** 2)

    def loss_xla(d):
        return jnp.sum(
            jax.ops.segment_sum(d, jnp.asarray(seg), num_segments=n) ** 2
        )

    g1 = jax.grad(loss_pallas)(data)
    g2 = jax.grad(loss_xla)(data)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-3
    )


def test_plan_reuse_inside_jit():
    """A prebuilt plan is jittable (arrays become constants)."""
    rng = np.random.default_rng(5)
    e, n, f = 900, 100, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    plan = SortedSegmentPlan(seg, n)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    out = jax.jit(plan.__call__)(data)
    ref = jax.ops.segment_sum(data, jnp.asarray(seg), num_segments=n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_empty_segments_are_zero():
    """Windows with no edges stay zero in the output."""
    e, n, f = 600, 1024, 128  # ids only in [0, 50): most windows empty
    rng = np.random.default_rng(7)
    seg = np.sort(rng.integers(0, 50, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    out = np.asarray(segment_sum_sorted(data, jnp.asarray(seg), n))
    assert np.all(out[50:] == 0.0)


def test_fused_product_matches_xla():
    """segment_sum_product_planned(a, b) == segment_sum(a * b): the
    fused kernel multiplies in VMEM instead of materializing the
    message intermediate."""
    from hydragnn_tpu.ops.pallas_segment import (
        plan_sorted_blocks,
        segment_sum_product_planned,
    )

    rng = np.random.default_rng(11)
    e, n, f = 900, 96, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, n)
    out = segment_sum_product_planned(
        a, b, jnp.asarray(perm), jnp.asarray(seg_p),
        jnp.asarray(valid), jnp.asarray(window), n,
    )
    ref = jax.ops.segment_sum(a * b, jnp.asarray(seg), num_segments=n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_fused_product_gradients_match_xla():
    """Both operands' gradients flow correctly through the fused VJP
    (d/da = b * g[seg], d/db = a * g[seg])."""
    from hydragnn_tpu.ops.pallas_segment import (
        plan_sorted_blocks,
        segment_sum_product_planned,
    )

    rng = np.random.default_rng(13)
    e, n, f = 500, 48, 64
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, n)
    args = (
        jnp.asarray(perm), jnp.asarray(seg_p),
        jnp.asarray(valid), jnp.asarray(window),
    )

    def loss_pallas(x, y):
        return jnp.sum(
            segment_sum_product_planned(x, y, *args, n) ** 2
        )

    def loss_xla(x, y):
        return jnp.sum(
            jax.ops.segment_sum(x * y, jnp.asarray(seg), num_segments=n)
            ** 2
        )

    ga1, gb1 = jax.grad(loss_pallas, argnums=(0, 1))(a, b)
    ga2, gb2 = jax.grad(loss_xla, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(
        np.asarray(ga1), np.asarray(ga2), rtol=1e-4, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(gb1), np.asarray(gb2), rtol=1e-4, atol=1e-3
    )


def test_aggregate_receivers_product_dispatch():
    """The fused helper matches the XLA path on a planned batch (CPU
    forces use_plan explicitly; the batch carries plan fields from
    collate with_segment_plan). The in-kernel-multiply variant is
    opt-in via HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused."""
    import os

    prior = os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL")
    os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = "pallas_fused"
    try:
        _run_dispatch_check()
    finally:
        if prior is None:
            os.environ.pop("HYDRAGNN_TPU_SEGMENT_IMPL", None)
        else:
            os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = prior


def _run_dispatch_check():
    from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
    from hydragnn_tpu.ops.segment import aggregate_receivers_product

    rng = np.random.default_rng(17)
    samples = []
    for _ in range(4):
        nn_ = int(rng.integers(5, 9))
        ei = np.stack(
            [rng.integers(0, nn_, 24), rng.integers(0, nn_, 24)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(nn_, 3)).astype(np.float32),
                edge_index=ei,
            )
        )
    spec = PadSpec.for_samples(samples)
    batch = collate(samples, spec, with_segment_plan=True)
    assert batch.seg_window is not None
    e = batch.senders.shape[0]
    a = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    fused = aggregate_receivers_product(a, b, batch, use_plan=True)
    plain = aggregate_receivers_product(a, b, batch, use_plan=False)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(plain), rtol=1e-5, atol=1e-4
    )


# ----------------------------------------------------------------------
# Shape-keyed crossover dispatch (ISSUE 3: never pick the planned
# kernel for oc20-class shapes where ROOFLINE_TPU.txt measures it
# 0.48-0.77x vs XLA).
# ----------------------------------------------------------------------


def test_planned_profitable_crossover_both_ways():
    """Pure table lookup (env/backend overrides live only in
    ops.segment.planned_path_wanted)."""
    from hydragnn_tpu.ops.pallas_segment import planned_profitable

    # the two measured anchor shapes
    assert planned_profitable(33792, 4224) is True  # qm9_b128
    assert planned_profitable(327680, 8192) is False  # oc20_b32
    # neighbors in log space land on the nearest verdict
    assert planned_profitable(20000, 3000) is True
    assert planned_profitable(8000, 1000) is True
    assert planned_profitable(500000, 16384) is False
    assert planned_profitable(250000, 8000) is False


def test_planned_path_wanted_env_force(monkeypatch):
    """The ONE env/backend override grammar, both directions."""
    from hydragnn_tpu.ops import segment

    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert segment.planned_path_wanted(327680, 8192) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment.planned_path_wanted(33792, 4224) is False
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    assert segment.planned_path_wanted(33792, 4224) is True
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "cpu")
    assert segment.planned_path_wanted(33792, 4224) is False


def test_aggregate_receivers_dispatch_decision(monkeypatch):
    """Unit-test of the dispatch decision itself (ops/segment.py
    _plan_dispatch) on a TPU-shaped backend, both ways: a qm9-class
    planned batch takes the kernel, an oc20-class one must fall back to
    the XLA scatter even though it carries a plan."""
    from hydragnn_tpu.ops import segment

    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)

    class FakeBatch:
        def __init__(self, e, n, planned=True):
            self.seg_window = object() if planned else None
            self.num_edges = e
            self.num_nodes = n

    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is True
    assert segment._plan_dispatch(FakeBatch(327680, 8192)) is False
    # no plan attached -> never the kernel, whatever the shape
    assert segment._plan_dispatch(FakeBatch(33792, 4224, False)) is False
    # forcing wins over the table
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert segment._plan_dispatch(FakeBatch(327680, 8192)) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is False
    # off-TPU: scatter unless forced to interpret mode
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "cpu")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is False


def test_loader_auto_segment_plan(monkeypatch):
    """with_segment_plan="auto": the host-side edge sort + block plan
    is only attached where the kernel would win AND be dispatched."""
    from hydragnn_tpu.data.graph import GraphSample, PadSpec
    from hydragnn_tpu.data.loader import GraphLoader

    rng = np.random.default_rng(0)
    samples = [
        GraphSample(
            x=rng.normal(size=(6, 1)).astype(np.float32),
            edge_index=np.stack(
                [rng.integers(0, 6, 12), rng.integers(0, 6, 12)]
            ),
        )
        for _ in range(8)
    ]
    ld = GraphLoader(samples, 4, with_segment_plan="auto")
    qm9ish = PadSpec(num_nodes=4224, num_edges=33792, num_graphs=129)
    oc20ish = PadSpec(num_nodes=8192, num_edges=327680, num_graphs=33)
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    # CPU backend: no plan (it would never be dispatched)
    assert ld.segment_plan_enabled(qm9ish) is False
    # forced interpret mode: follows the table per shape
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert ld.segment_plan_enabled(qm9ish) is True
    assert ld.segment_plan_enabled(oc20ish) is True  # force wins
    # explicit bool still wins over auto resolution
    ld_on = GraphLoader(samples, 4, with_segment_plan=True)
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    assert ld_on.segment_plan_enabled(oc20ish) is True
    batch = next(iter(ld_on))
    assert batch.seg_window is not None


# ----------------------------------------------------------------------
# Fused edge pipeline (ISSUE 9): gather -> filter multiply -> dense
# matmul -> segment reduce in ONE Pallas pass over aligned plan tiles.
# Ulp-tolerance CONTRACT (docs/ROOFLINE.md "Fused edge pipeline"):
# bitwise identity with the XLA scatter is explicitly NOT required —
# the block decomposition regroups the f32 accumulation. Gates:
#   f32:  rtol 1e-5 / atol 1e-4  (reduction regrouping only)
#   bf16: rtol 4e-2 / atol 2.5e-1 vs the SAME-dtype XLA reference
#         (a few bf16 ulps of the accumulated magnitude; the kernel
#         keeps f32 output tiles, the reference accumulates in bf16,
#         so the kernel is the more precise side)
# plus converged-loss parity in test_optimizer_precision_losses.py.
# ----------------------------------------------------------------------

F32_TOL = dict(rtol=1e-5, atol=1e-4)
BF16_TOL = dict(rtol=4e-2, atol=2.5e-1)


def _pipeline_case(seed=23, e=1300, n=160, f_in=64, f_out=32):
    rng = np.random.default_rng(seed)
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = rng.normal(size=(e, f_in)).astype(np.float32)
    b = rng.normal(size=(e, f_in)).astype(np.float32)
    w = rng.normal(size=(f_in, f_out)).astype(np.float32)
    plan = plan_sorted_blocks(seg, n)
    return seg, a, b, w, tuple(jnp.asarray(p) for p in plan)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("stages", ["a", "ab", "aw", "abw"])
def test_edge_pipeline_forward_matches_xla(dtype, stages):
    """Forward parity of every stage combination (reduce-only, +filter,
    +weight, full pipeline) against the XLA scatter reference in the
    SAME dtype, within the documented ulp tolerances."""
    from hydragnn_tpu.ops.pallas_segment import edge_pipeline_planned

    seg, a_np, b_np, w_np, plan = _pipeline_case()
    n = 160
    dt = jnp.dtype(dtype)
    a = jnp.asarray(a_np, dt)
    b = jnp.asarray(b_np, dt) if "b" in stages else None
    w = jnp.asarray(w_np) if "w" in stages else None  # f32 master weight
    out = edge_pipeline_planned(a, b, w, *plan, n)
    ref = a if b is None else a * b
    if w is not None:
        ref = ref @ w
    ref = jax.ops.segment_sum(ref, jnp.asarray(seg), num_segments=n)
    tol = F32_TOL if dtype == "float32" else BF16_TOL
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), **tol
    )


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_edge_pipeline_vjp_matches_xla(dtype):
    """custom_vjp backward parity for ALL THREE operands (a, b, w):
    pull back the SAME cotangent through both implementations (fixing
    the cotangent isolates the backward rule from the forward's own
    ulp difference, which a loss-composed grad would amplify)."""
    from hydragnn_tpu.ops.pallas_segment import edge_pipeline_planned

    seg, a_np, b_np, w_np, plan = _pipeline_case(e=900, n=96)
    n = 96
    dt = jnp.dtype(dtype)
    a, b = jnp.asarray(a_np, dt), jnp.asarray(b_np, dt)
    w = jnp.asarray(w_np)
    out1, vjp1 = jax.vjp(
        lambda x, y, ww: edge_pipeline_planned(x, y, ww, *plan, n),
        a, b, w,
    )
    out2, vjp2 = jax.vjp(
        lambda x, y, ww: jax.ops.segment_sum(
            (x * y) @ ww, jnp.asarray(seg), num_segments=n
        ),
        a, b, w,
    )
    rng = np.random.default_rng(43)
    g = jnp.asarray(rng.normal(size=out1.shape), out1.dtype)
    tol = (
        dict(rtol=1e-4, atol=1e-3)
        if dtype == "float32"
        else BF16_TOL
    )
    for got, ref, name in zip(vjp1(g), vjp2(g), "abw"):
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(ref, np.float32),
            err_msg=f"d{name}",
            **tol,
        )


def test_edge_pipeline_masked_edges():
    """edge_valid folds the batch edge mask INTO the plan: masked
    (padding) edges contribute nothing to forward or backward, with no
    pre-masked operand copy."""
    from hydragnn_tpu.ops.pallas_segment import (
        edge_pipeline_planned,
        plan_blocks_static,
        static_block_bound,
    )

    rng = np.random.default_rng(29)
    e, n, f = 1100, 128, 32
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    ev = rng.random(e) < 0.7
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    plan = plan_blocks_static(
        seg, n, static_block_bound(e, n), edge_valid=ev
    )
    plan = tuple(jnp.asarray(p) for p in plan)
    out = edge_pipeline_planned(a, b, None, *plan, n)
    ref = jax.ops.segment_sum(
        jnp.where(jnp.asarray(ev)[:, None], a * b, 0),
        jnp.asarray(seg),
        num_segments=n,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), **F32_TOL
    )
    # masked edges get ZERO gradient (the where-grad of the old
    # pre-mask, now via the plan's valid slots)
    g = jax.grad(
        lambda x: jnp.sum(edge_pipeline_planned(x, b, None, *plan, n) ** 2)
    )(a)
    assert np.all(np.asarray(g)[~ev] == 0.0)


def test_edge_pipeline_empty_windows_and_static_padding():
    """Empty node windows stay zero and plan_blocks_static padding
    blocks accumulate nothing — the all-invalid blocks read tile 0 and
    must not perturb the window they nominally target."""
    from hydragnn_tpu.ops.pallas_segment import (
        edge_pipeline_planned,
        plan_blocks_static,
        static_block_bound,
    )

    rng = np.random.default_rng(31)
    e, n, f = 700, 2048, 48  # ids only in [0, 40): most windows empty
    seg = np.sort(rng.integers(0, 40, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(f, 16)), jnp.float32)
    bound = static_block_bound(e, n)
    plan = plan_blocks_static(seg, n, bound)
    assert len(plan[3]) == bound  # padding blocks present
    plan = tuple(jnp.asarray(p) for p in plan)
    out = np.asarray(edge_pipeline_planned(a, b, w, *plan, n))
    ref = np.asarray(
        jax.ops.segment_sum(
            (a * b) @ w, jnp.asarray(seg), num_segments=n
        )
    )
    np.testing.assert_allclose(out, ref, **F32_TOL)
    assert np.all(out[40:] == 0.0)


def test_plan_aligned_tiles_invariant():
    """The fused kernel's gather contract: every block's slots are ONE
    be-aligned tile of the sorted edge array (perm[b*be] % be == 0 and
    slot i holds row perm[b*be] + i, clamped at the array end) — this
    is what lets a BlockSpec index_map stage the gather."""
    rng = np.random.default_rng(37)
    for e, n in ((5000, 1000), (700, 64), (90, 2000)):
        seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
        perm, _, valid, _ = plan_sorted_blocks(seg, n)
        tiles = perm.reshape(-1, DEFAULT_BE)
        assert np.all(tiles[:, 0] % DEFAULT_BE == 0)
        expect = np.minimum(
            tiles[:, :1] + np.arange(DEFAULT_BE)[None, :], e - 1
        )
        assert np.all(tiles == expect)
        # every real edge still appears exactly once among valid slots
        assert sorted(perm[valid].tolist()) == list(range(e))


def test_crossover_table_what_if_rows_never_dispatch(tmp_path, monkeypatch):
    """The no-fabrication rule: rows whose verdict was not measured on
    a real device (*_measured=false) are invisible to dispatch; a
    measured fused win IS dispatched on."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps

    table = {
        "version": 1,
        "rows": [
            {
                "num_edges": 30000, "num_segments": 4000,
                "planned_wins": True, "planned_measured": True,
                "fused_wins": True, "fused_measured": False,  # WHAT-IF
            },
            {
                "num_edges": 300000, "num_segments": 8000,
                "planned_wins": False, "planned_measured": True,
                "fused_wins": True, "fused_measured": True,
            },
        ],
    }
    p = tmp_path / "table.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    assert ps.planned_profitable(30000, 4000) is True
    assert ps.planned_profitable(300000, 8000) is False
    # the qm9-class WHAT-IF fused row must NOT dispatch; the measured
    # oc20-class one must
    assert ps.fused_profitable(30000, 4000) is True  # nearest MEASURED
    # row is the oc20 one — only measured rows exist in fused space
    assert ps.fused_profitable(300000, 8000) is True
    # empty/corrupt table -> no basis -> False everywhere
    p2 = tmp_path / "corrupt.json"
    p2.write_text("{not json")
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p2))
    assert ps.planned_profitable(30000, 4000) is False
    assert ps.fused_profitable(30000, 4000) is False


def test_seed_table_fused_is_what_if():
    """The CHECKED-IN seed carries fused verdicts only as WHAT-IF
    (modeled traffic): until tools/roofline_segment.py --write-table
    runs on a real TPU, fused dispatch must stay off everywhere."""
    from hydragnn_tpu.ops.pallas_segment import (
        fused_profitable,
        load_crossover_table,
    )

    rows = load_crossover_table()
    assert rows, "seed table missing"
    assert all("fused_wins" in r for r in rows)  # verdict per row
    assert not any(r.get("fused_measured") for r in rows)
    assert fused_profitable(33792, 4224) is False
    assert fused_profitable(327680, 8192) is False


def test_fused_path_wanted_grammar(monkeypatch):
    """The ONE env grammar for the kernel-flavor policy: pallas_fused
    forces, xla forbids, pallas keeps the fused choice table-driven."""
    from hydragnn_tpu.ops import segment

    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    assert segment.fused_path_wanted(33792, 4224) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment.fused_path_wanted(33792, 4224) is False
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    # planned forced, fused still table-driven (seed: WHAT-IF only)
    assert segment.fused_path_wanted(33792, 4224) is False
    assert segment.planned_path_wanted(33792, 4224) is True


def test_aggregate_receivers_pipeline_matches_reference():
    """The dispatched full-pipeline helper: fused (forced) and unfused
    paths agree with the plain scatter+matmul reference on a planned
    batch, including the mean variant (degree division commutes with
    the matmul within tolerance)."""
    import os

    from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
    from hydragnn_tpu.ops.segment import aggregate_receivers_pipeline

    rng = np.random.default_rng(41)
    samples = []
    for _ in range(4):
        nn_ = int(rng.integers(5, 9))
        ei = np.stack(
            [rng.integers(0, nn_, 24), rng.integers(0, nn_, 24)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(nn_, 3)).astype(np.float32),
                edge_index=ei,
            )
        )
    spec = PadSpec.for_samples(samples)
    batch = collate(samples, spec, with_segment_plan=True)
    e = batch.senders.shape[0]
    a = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    ref = (
        jax.ops.segment_sum(
            jnp.where(batch.edge_mask[:, None], a * b, 0),
            batch.receivers,
            num_segments=batch.num_nodes,
        )
        @ w
    )
    prior = os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL")
    os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = "pallas_fused"
    try:
        fused = aggregate_receivers_pipeline(
            a, b, batch, weight=w, use_plan=True
        )
        fused_mean = aggregate_receivers_pipeline(
            a, None, batch, weight=w, mean=True, use_plan=True
        )
    finally:
        if prior is None:
            os.environ.pop("HYDRAGNN_TPU_SEGMENT_IMPL", None)
        else:
            os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = prior
    unfused = aggregate_receivers_pipeline(
        a, b, batch, weight=w, use_plan=False
    )
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), **F32_TOL)
    np.testing.assert_allclose(np.asarray(unfused), np.asarray(ref), **F32_TOL)
    from hydragnn_tpu.ops.segment import degree

    cnt = jnp.maximum(
        degree(batch.receivers, batch.num_nodes, mask=batch.edge_mask), 1
    )
    ref_mean = (
        jax.ops.segment_sum(
            jnp.where(batch.edge_mask[:, None], a, 0),
            batch.receivers,
            num_segments=batch.num_nodes,
        )
        / cnt[:, None]
    ) @ w
    np.testing.assert_allclose(
        np.asarray(fused_mean), np.asarray(ref_mean), rtol=1e-4, atol=1e-4
    )


def test_reduce_only_sites_never_ride_a_fused_only_win(tmp_path, monkeypatch):
    """Dispatch layering (the acceptance rule's sharp edge): on a shape
    where the reduce-only planned kernel MEASURED a loss but the fused
    kernel a win, fused-capable call sites dispatch, plain-sum call
    sites must keep the XLA scatter (no fused variant exists for them),
    and the loader still attaches the plan (the fused path needs it)."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps
    from hydragnn_tpu.ops import segment

    table = {
        "rows": [
            {
                "num_edges": 327680, "num_segments": 8192,
                "planned_wins": False, "planned_measured": True,
                "fused_wins": True, "fused_measured": True,
            }
        ]
    }
    p = tmp_path / "fused_only.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")

    class FakeBatch:
        seg_window = object()
        num_edges = 327680
        num_nodes = 8192

    assert segment._plan_dispatch(FakeBatch()) is False
    assert segment._plan_dispatch(FakeBatch(), fused_capable=True) is True
    assert segment.fused_path_wanted(327680, 8192) is True
    assert segment.planned_path_wanted(327680, 8192) is True  # attach


def test_crossover_lookup_keys_on_feature_dim(tmp_path, monkeypatch):
    """A regenerated table carries one row per feature width at the
    same (E, N): the lookup must key on F when the call site provides
    it, and vote CONSERVATIVELY (all tied rows must win) when it
    cannot."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps

    table = {
        "rows": [
            {
                "num_edges": 33792, "num_segments": 4224,
                "feature_dim": 64,
                "fused_wins": False, "fused_measured": True,
            },
            {
                "num_edges": 33792, "num_segments": 4224,
                "feature_dim": 256,
                "fused_wins": True, "fused_measured": True,
            },
        ]
    }
    p = tmp_path / "fgrid.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    assert ps.fused_profitable(33792, 4224, feature_dim=256) is True
    assert ps.fused_profitable(33792, 4224, feature_dim=64) is False
    # no F from the call site: equidistant rows disagree -> never take
    # the kernel on a possibly-losing shape
    assert ps.fused_profitable(33792, 4224) is False


def test_segment_impl_override_last_set_wins(monkeypatch):
    """Training.segment_impl plumbs through a last-set-wins override
    (cleared by an absent key), NOT an env setdefault — consecutive
    runs in one process must not inherit each other's flavor; the env
    var still outranks it."""
    from hydragnn_tpu.ops import segment

    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    try:
        segment.set_segment_impl_override("pallas_fused")
        assert segment._segment_impl() == "pallas_fused"
        segment.set_segment_impl_override("xla")
        assert segment._segment_impl() == "xla"
        segment.set_segment_impl_override(None)  # absent config key
        assert segment._segment_impl() == ""
        segment.set_segment_impl_override("pallas_fused")
        monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
        assert segment._segment_impl() == "xla"  # env outranks config
    finally:
        segment.set_segment_impl_override(None)


def test_attach_policy_optimistic_on_feature_ties(tmp_path, monkeypatch):
    """An F-specific measured fused win (the 'flip the oc20 row'
    outcome) must stay REACHABLE: the loader's attach decision has no
    feature width, so it votes optimistically across the F grid —
    while dispatch without F stays conservative and dispatch WITH F
    picks the matching row."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps
    from hydragnn_tpu.ops import segment

    table = {
        "rows": [
            {
                "num_edges": 327680, "num_segments": 8192,
                "feature_dim": 128,
                "planned_wins": False, "planned_measured": True,
                "fused_wins": False, "fused_measured": True,
            },
            {
                "num_edges": 327680, "num_segments": 8192,
                "feature_dim": 256,
                "planned_wins": False, "planned_measured": True,
                "fused_wins": True, "fused_measured": True,
            },
        ]
    }
    p = tmp_path / "fgrid_oc20.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    # loader attach: optimistic — the F=256 fused win keeps plans on
    assert segment.planned_path_wanted(327680, 8192) is True
    # dispatch without F: conservative (tied rows disagree)
    assert ps.fused_profitable(327680, 8192) is False
    # dispatch with F: the matching row decides
    assert ps.fused_profitable(327680, 8192, feature_dim=256) is True
    assert ps.fused_profitable(327680, 8192, feature_dim=128) is False

    class FakeBatch:
        seg_window = object()
        num_edges = 327680
        num_nodes = 8192

    assert (
        segment._plan_dispatch(FakeBatch(), feature_dim=256, fused_capable=True)
        is True
    )
    assert (
        segment._plan_dispatch(FakeBatch(), feature_dim=128, fused_capable=True)
        is False
    )


# ----------------------------------------------------------------------
# Symmetric Pallas backward (ISSUE 18): grad-parity of the one-pass
# pullback vs the XLA reference, its dispatch gating, and the table
# cache reload.
# ----------------------------------------------------------------------

# Documented ulp tolerances for the fused VJP (looser than the forward
# F32_TOL: d_w accumulates E products per element and the block
# decomposition regroups the f32 adds).
VJP_F32_TOL = dict(rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("stages", ["a", "ab", "aw", "abw"])
def test_fused_bwd_matches_xla_pullback(dtype, stages, monkeypatch):
    """Grad parity of the symmetric Pallas backward for EVERY operand
    variant (b/w present and absent) in both precisions: the same
    cotangent pulled back through the fused kernel (forced via
    HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused) and through the XLA
    pullback must agree within the documented ulp tolerances."""
    from hydragnn_tpu.ops.pallas_segment import (
        _edge_pipeline_bwd_xla,
        edge_pipeline_planned,
    )

    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    seg, a_np, b_np, w_np, plan = _pipeline_case(e=900, n=96)
    n = 96
    dt = jnp.dtype(dtype)
    a = jnp.asarray(a_np, dt)
    b = jnp.asarray(b_np, dt) if "b" in stages else None
    w = jnp.asarray(w_np) if "w" in stages else None  # f32 master weight
    def run(*ops):
        it = iter(ops)
        return edge_pipeline_planned(
            next(it),
            next(it) if "b" in stages else None,
            next(it) if "w" in stages else None,
            *plan,
            n,
        )

    out, vjp = jax.vjp(run, *[t for t in (a, b, w) if t is not None])
    rng = np.random.default_rng(47)
    g = jnp.asarray(rng.normal(size=out.shape), out.dtype)
    got = vjp(g)
    ref = _edge_pipeline_bwd_xla(a, b, w, *plan[:3], g)
    tol = VJP_F32_TOL if dtype == "float32" else BF16_TOL
    names = "a" + ("b" if "b" in stages else "") + ("w" if "w" in stages else "")
    for got_t, ref_t, name in zip(got, [r for r in ref if r is not None], names):
        np.testing.assert_allclose(
            np.asarray(got_t, np.float32),
            np.asarray(ref_t, np.float32),
            err_msg=f"d{name} ({stages}, {dtype})",
            **tol,
        )


def test_fused_bwd_masked_edges_and_static_padding(monkeypatch):
    """The fused pullback under a STATIC-padded plan with masked edges:
    padding blocks (which read input tile 0) must not corrupt the
    gradients of tile 0's real edges — the cummax out-tile routing —
    and masked edges must get exactly zero gradient."""
    from hydragnn_tpu.ops.pallas_segment import (
        _edge_pipeline_bwd_xla,
        edge_pipeline_planned,
        plan_blocks_static,
        static_block_bound,
    )

    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    rng = np.random.default_rng(53)
    e, n, fi, fo = 1100, 2048, 32, 16  # ids in [0, 60): empty windows +
    seg = np.sort(rng.integers(0, 60, e)).astype(np.int32)  # padding
    ev = rng.random(e) < 0.7
    a = jnp.asarray(rng.normal(size=(e, fi)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, fi)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(fi, fo)), jnp.float32)
    bound = static_block_bound(e, n)
    plan = plan_blocks_static(seg, n, bound, edge_valid=ev)
    assert len(plan[3]) == bound  # padding blocks present
    plan = tuple(jnp.asarray(p) for p in plan)
    out, vjp = jax.vjp(
        lambda x, y, ww: edge_pipeline_planned(x, y, ww, *plan, n), a, b, w
    )
    g = jnp.asarray(rng.normal(size=out.shape), out.dtype)
    got = vjp(g)
    ref = _edge_pipeline_bwd_xla(a, b, w, *plan[:3], g)
    for got_t, ref_t, name in zip(got, ref, "abw"):
        np.testing.assert_allclose(
            np.asarray(got_t),
            np.asarray(ref_t),
            err_msg=f"d{name}",
            **VJP_F32_TOL,
        )
    assert np.all(np.asarray(got[0])[~ev] == 0.0)
    assert np.all(np.asarray(got[1])[~ev] == 0.0)


def test_fused_bwd_single_block_and_empty_edges(monkeypatch):
    """Shape edges of the fused pullback: a sub-tile edge array (one
    block, E < be) round-trips, and E == 0 short-circuits to zero
    gradients without calling the kernel."""
    from hydragnn_tpu.ops.pallas_segment import (
        _edge_pipeline_bwd_xla,
        edge_pipeline_planned,
        plan_sorted_blocks,
    )

    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    rng = np.random.default_rng(59)
    e, n, f = 37, 12, 16
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    plan = tuple(jnp.asarray(p) for p in plan_sorted_blocks(seg, n))
    assert plan[3].shape[0] == 1  # single block
    out, vjp = jax.vjp(
        lambda x, y: edge_pipeline_planned(x, y, None, *plan, n), a, b
    )
    g = jnp.asarray(rng.normal(size=out.shape), out.dtype)
    got = vjp(g)
    ref = _edge_pipeline_bwd_xla(a, b, None, *plan[:3], g)
    for got_t, ref_t in zip(got, ref[:2]):
        np.testing.assert_allclose(
            np.asarray(got_t), np.asarray(ref_t), **VJP_F32_TOL
        )
    # E == 0: zeros out, zero grads, no kernel call
    a0 = jnp.zeros((0, f), jnp.float32)
    plan0 = tuple(
        jnp.asarray(p) for p in plan_sorted_blocks(np.zeros(0, np.int32), n)
    )
    out0, vjp0 = jax.vjp(
        lambda x: edge_pipeline_planned(x, None, None, *plan0, n), a0
    )
    assert out0.shape == (n, f) and not np.asarray(out0).any()
    (g0,) = vjp0(jnp.ones((n, f), jnp.float32))
    assert g0.shape == (0, f)


def test_fused_bwd_wanted_grammar(tmp_path, monkeypatch):
    """The env/backend grammar of the BACKWARD flavor policy:
    pallas_fused forces the symmetric kernel, xla forbids it, and a
    non-TPU backend without the force stays on the XLA pullback even
    when the table claims a measured bwd win — CPU/CI never takes the
    kernel silently."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps
    from hydragnn_tpu.ops import segment

    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas_fused")
    assert segment.fused_bwd_wanted(33792, 4224) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment.fused_bwd_wanted(33792, 4224) is False
    # no force, CPU backend, measured win in the table -> still XLA
    table = {
        "rows": [
            {
                "num_edges": 33792, "num_segments": 4224,
                "bwd_wins": True, "bwd_measured": True,
            }
        ]
    }
    p = tmp_path / "bwd.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    assert segment.fused_bwd_wanted(33792, 4224) is False  # CPU
    # on TPU the measured row decides
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    assert segment.fused_bwd_wanted(33792, 4224) is True
    assert ps.bwd_profitable(33792, 4224) is True


def test_seed_table_bwd_is_what_if():
    """The CHECKED-IN seed carries bwd verdicts only as WHAT-IF
    (modeled traffic, 1.4-1.8x): until --write-table runs on a real
    TPU, the symmetric backward must stay off everywhere — gradients
    get no fabrication exemption."""
    from hydragnn_tpu.ops.pallas_segment import (
        bwd_profitable,
        load_crossover_table,
    )

    rows = load_crossover_table()
    assert rows, "seed table missing"
    assert all("bwd_wins" in r for r in rows)  # verdict per row
    assert not any(r.get("bwd_measured") for r in rows)
    assert bwd_profitable(33792, 4224) is False
    assert bwd_profitable(327680, 8192) is False


def test_reload_crossover_table(tmp_path, monkeypatch):
    """The staleness fix: a table rewritten on disk is invisible to the
    per-path cache until reload_crossover_table() drops it — after the
    reload, dispatch sees the new verdicts (and path=None clears every
    cached path, for env-var swaps)."""
    import json

    from hydragnn_tpu.ops import pallas_segment as ps

    p = tmp_path / "t.json"
    row = {
        "num_edges": 1000, "num_segments": 100,
        "bwd_wins": False, "bwd_measured": True,
    }
    p.write_text(json.dumps({"rows": [row]}))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    assert ps.bwd_profitable(1000, 100) is False
    row["bwd_wins"] = True
    p.write_text(json.dumps({"rows": [row]}))
    # stale cache: still the old verdict
    assert ps.bwd_profitable(1000, 100) is False
    ps.reload_crossover_table(str(p))
    assert ps.bwd_profitable(1000, 100) is True
    # path=None clears everything (env-var swap case)
    row["bwd_wins"] = False
    p.write_text(json.dumps({"rows": [row]}))
    ps.reload_crossover_table()
    assert ps.bwd_profitable(1000, 100) is False


def test_write_table_reloads_cache(tmp_path, monkeypatch):
    """roofline_segment.write_table must invalidate the in-process
    cache after writing, so measure -> write -> dispatch in one
    process sees the fresh verdicts."""
    import json
    import os
    import sys

    from hydragnn_tpu.ops import pallas_segment as ps

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    try:
        import roofline_segment as rs
    finally:
        sys.path.pop(0)

    p = tmp_path / "w.json"
    p.write_text(json.dumps({"rows": []}))
    monkeypatch.setenv(ps.CROSSOVER_TABLE_ENV, str(p))
    assert ps.load_crossover_table(str(p)) == ()  # cache the empty table
    results = {
        ("tiny", "bfloat16"): {
            "xla_pipeline": (2.0, 0.0),
            "pallas_pipeline": (1.0, 0.0),
            "xla_pipeline_w": (2.0, 0.0),
            "pallas_pipeline_w": (2.0, 0.0),
            "pallas_fused_pipeline": (1.0, 0.0),
            "xla_bwd": (2.0, 0.0),
            "pallas_fused_bwd": (1.0, 0.0),
        }
    }
    monkeypatch.setitem(rs.SHAPES, "tiny", (100, 1000, 32))
    rs.write_table(results, str(p))
    rows = ps.load_crossover_table(str(p))  # must NOT be the stale ()
    assert len(rows) == 1
    r = rows[0]
    assert r["bwd_wins"] is True and "bwd_measured" in r
    assert r["fused_wins"] is True and r["planned_wins"] is True
