"""Pallas sorted-segment-sum kernel: differential tests against
jax.ops.segment_sum (forward + gradient), plan construction edge cases.
Runs in interpret mode on the CPU mesh; the same code path compiles via
Mosaic on TPU (measured ~20% faster than XLA's scatter lowering at
E=32k/N=3k/F=128 — see module docstring).
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.pallas_segment import (
    DEFAULT_BE,
    DEFAULT_BN,
    SortedSegmentPlan,
    plan_sorted_blocks,
    segment_sum_sorted,
)


def test_plan_covers_all_edges():
    rng = np.random.default_rng(0)
    seg = np.sort(rng.integers(0, 1000, 5000)).astype(np.int32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, 1000)
    assert len(perm) == len(seg_p) == len(valid)
    assert len(perm) % DEFAULT_BE == 0
    assert len(window) == len(perm) // DEFAULT_BE
    # every original edge appears exactly once among valid slots
    assert sorted(perm[valid]) == list(range(5000))
    # every valid slot's segment sits inside its block's window
    for b in range(len(window)):
        s = seg_p[b * DEFAULT_BE : (b + 1) * DEFAULT_BE]
        v = valid[b * DEFAULT_BE : (b + 1) * DEFAULT_BE]
        if v.any():
            assert np.all(s[v] // DEFAULT_BN == window[b])
    # windows non-decreasing (consecutive-revisit accumulation contract)
    assert np.all(np.diff(window) >= 0)


def test_plan_empty():
    perm, seg_p, valid, window = plan_sorted_blocks(
        np.zeros(0, np.int32), 16
    )
    assert not valid.any()
    assert len(window) == 1


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("shape", [(700, 128), (5000, 256)])
def test_forward_matches_xla(seed, shape):
    e, f = shape
    n = max(e // 10, 4)
    rng = np.random.default_rng(seed)
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    ref = jax.ops.segment_sum(data, jnp.asarray(seg), num_segments=n)
    out = segment_sum_sorted(data, jnp.asarray(seg), n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_gradient_matches_xla():
    rng = np.random.default_rng(3)
    e, n, f = 600, 64, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)

    def loss_pallas(d):
        return jnp.sum(segment_sum_sorted(d, jnp.asarray(seg), n) ** 2)

    def loss_xla(d):
        return jnp.sum(
            jax.ops.segment_sum(d, jnp.asarray(seg), num_segments=n) ** 2
        )

    g1 = jax.grad(loss_pallas)(data)
    g2 = jax.grad(loss_xla)(data)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-3
    )


def test_plan_reuse_inside_jit():
    """A prebuilt plan is jittable (arrays become constants)."""
    rng = np.random.default_rng(5)
    e, n, f = 900, 100, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    plan = SortedSegmentPlan(seg, n)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    out = jax.jit(plan.__call__)(data)
    ref = jax.ops.segment_sum(data, jnp.asarray(seg), num_segments=n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_empty_segments_are_zero():
    """Windows with no edges stay zero in the output."""
    e, n, f = 600, 1024, 128  # ids only in [0, 50): most windows empty
    rng = np.random.default_rng(7)
    seg = np.sort(rng.integers(0, 50, e)).astype(np.int32)
    data = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    out = np.asarray(segment_sum_sorted(data, jnp.asarray(seg), n))
    assert np.all(out[50:] == 0.0)


def test_fused_product_matches_xla():
    """segment_sum_product_planned(a, b) == segment_sum(a * b): the
    fused kernel multiplies in VMEM instead of materializing the
    message intermediate."""
    from hydragnn_tpu.ops.pallas_segment import (
        plan_sorted_blocks,
        segment_sum_product_planned,
    )

    rng = np.random.default_rng(11)
    e, n, f = 900, 96, 128
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, n)
    out = segment_sum_product_planned(
        a, b, jnp.asarray(perm), jnp.asarray(seg_p),
        jnp.asarray(valid), jnp.asarray(window), n,
    )
    ref = jax.ops.segment_sum(a * b, jnp.asarray(seg), num_segments=n)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4
    )


def test_fused_product_gradients_match_xla():
    """Both operands' gradients flow correctly through the fused VJP
    (d/da = b * g[seg], d/db = a * g[seg])."""
    from hydragnn_tpu.ops.pallas_segment import (
        plan_sorted_blocks,
        segment_sum_product_planned,
    )

    rng = np.random.default_rng(13)
    e, n, f = 500, 48, 64
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    a = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, f)), jnp.float32)
    perm, seg_p, valid, window = plan_sorted_blocks(seg, n)
    args = (
        jnp.asarray(perm), jnp.asarray(seg_p),
        jnp.asarray(valid), jnp.asarray(window),
    )

    def loss_pallas(x, y):
        return jnp.sum(
            segment_sum_product_planned(x, y, *args, n) ** 2
        )

    def loss_xla(x, y):
        return jnp.sum(
            jax.ops.segment_sum(x * y, jnp.asarray(seg), num_segments=n)
            ** 2
        )

    ga1, gb1 = jax.grad(loss_pallas, argnums=(0, 1))(a, b)
    ga2, gb2 = jax.grad(loss_xla, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(
        np.asarray(ga1), np.asarray(ga2), rtol=1e-4, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(gb1), np.asarray(gb2), rtol=1e-4, atol=1e-3
    )


def test_aggregate_receivers_product_dispatch():
    """The fused helper matches the XLA path on a planned batch (CPU
    forces use_plan explicitly; the batch carries plan fields from
    collate with_segment_plan). The in-kernel-multiply variant is
    opt-in via HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused."""
    import os

    prior = os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL")
    os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = "pallas_fused"
    try:
        _run_dispatch_check()
    finally:
        if prior is None:
            os.environ.pop("HYDRAGNN_TPU_SEGMENT_IMPL", None)
        else:
            os.environ["HYDRAGNN_TPU_SEGMENT_IMPL"] = prior


def _run_dispatch_check():
    from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
    from hydragnn_tpu.ops.segment import aggregate_receivers_product

    rng = np.random.default_rng(17)
    samples = []
    for _ in range(4):
        nn_ = int(rng.integers(5, 9))
        ei = np.stack(
            [rng.integers(0, nn_, 24), rng.integers(0, nn_, 24)]
        )
        samples.append(
            GraphSample(
                x=rng.normal(size=(nn_, 3)).astype(np.float32),
                edge_index=ei,
            )
        )
    spec = PadSpec.for_samples(samples)
    batch = collate(samples, spec, with_segment_plan=True)
    assert batch.seg_window is not None
    e = batch.senders.shape[0]
    a = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(e, 16)), jnp.float32)
    fused = aggregate_receivers_product(a, b, batch, use_plan=True)
    plain = aggregate_receivers_product(a, b, batch, use_plan=False)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(plain), rtol=1e-5, atol=1e-4
    )


# ----------------------------------------------------------------------
# Shape-keyed crossover dispatch (ISSUE 3: never pick the planned
# kernel for oc20-class shapes where ROOFLINE_TPU.txt measures it
# 0.48-0.77x vs XLA).
# ----------------------------------------------------------------------


def test_planned_profitable_crossover_both_ways():
    """Pure table lookup (env/backend overrides live only in
    ops.segment.planned_path_wanted)."""
    from hydragnn_tpu.ops.pallas_segment import planned_profitable

    # the two measured anchor shapes
    assert planned_profitable(33792, 4224) is True  # qm9_b128
    assert planned_profitable(327680, 8192) is False  # oc20_b32
    # neighbors in log space land on the nearest verdict
    assert planned_profitable(20000, 3000) is True
    assert planned_profitable(8000, 1000) is True
    assert planned_profitable(500000, 16384) is False
    assert planned_profitable(250000, 8000) is False


def test_planned_path_wanted_env_force(monkeypatch):
    """The ONE env/backend override grammar, both directions."""
    from hydragnn_tpu.ops import segment

    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert segment.planned_path_wanted(327680, 8192) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment.planned_path_wanted(33792, 4224) is False
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    assert segment.planned_path_wanted(33792, 4224) is True
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "cpu")
    assert segment.planned_path_wanted(33792, 4224) is False


def test_aggregate_receivers_dispatch_decision(monkeypatch):
    """Unit-test of the dispatch decision itself (ops/segment.py
    _plan_dispatch) on a TPU-shaped backend, both ways: a qm9-class
    planned batch takes the kernel, an oc20-class one must fall back to
    the XLA scatter even though it carries a plan."""
    from hydragnn_tpu.ops import segment

    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)

    class FakeBatch:
        def __init__(self, e, n, planned=True):
            self.seg_window = object() if planned else None
            self.num_edges = e
            self.num_nodes = n

    monkeypatch.setattr(segment.jax, "default_backend", lambda: "tpu")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is True
    assert segment._plan_dispatch(FakeBatch(327680, 8192)) is False
    # no plan attached -> never the kernel, whatever the shape
    assert segment._plan_dispatch(FakeBatch(33792, 4224, False)) is False
    # forcing wins over the table
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert segment._plan_dispatch(FakeBatch(327680, 8192)) is True
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "xla")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is False
    # off-TPU: scatter unless forced to interpret mode
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    monkeypatch.setattr(segment.jax, "default_backend", lambda: "cpu")
    assert segment._plan_dispatch(FakeBatch(33792, 4224)) is False


def test_loader_auto_segment_plan(monkeypatch):
    """with_segment_plan="auto": the host-side edge sort + block plan
    is only attached where the kernel would win AND be dispatched."""
    from hydragnn_tpu.data.graph import GraphSample, PadSpec
    from hydragnn_tpu.data.loader import GraphLoader

    rng = np.random.default_rng(0)
    samples = [
        GraphSample(
            x=rng.normal(size=(6, 1)).astype(np.float32),
            edge_index=np.stack(
                [rng.integers(0, 6, 12), rng.integers(0, 6, 12)]
            ),
        )
        for _ in range(8)
    ]
    ld = GraphLoader(samples, 4, with_segment_plan="auto")
    qm9ish = PadSpec(num_nodes=4224, num_edges=33792, num_graphs=129)
    oc20ish = PadSpec(num_nodes=8192, num_edges=327680, num_graphs=33)
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    # CPU backend: no plan (it would never be dispatched)
    assert ld.segment_plan_enabled(qm9ish) is False
    # forced interpret mode: follows the table per shape
    monkeypatch.setenv("HYDRAGNN_TPU_SEGMENT_IMPL", "pallas")
    assert ld.segment_plan_enabled(qm9ish) is True
    assert ld.segment_plan_enabled(oc20ish) is True  # force wins
    # explicit bool still wins over auto resolution
    ld_on = GraphLoader(samples, 4, with_segment_plan=True)
    monkeypatch.delenv("HYDRAGNN_TPU_SEGMENT_IMPL", raising=False)
    assert ld_on.segment_plan_enabled(oc20ish) is True
    batch = next(iter(ld_on))
    assert batch.seg_window is not None
