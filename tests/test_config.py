"""Config system (reference tests/test_config.py + config_utils.py):
defaulting pass, dimension derivation from data, merge semantics, and
save/load roundtrip.
"""

import json
import os

import numpy as np
import pytest

import tests._cpu  # noqa: F401

from hydragnn_tpu.config import load_config, merge_config, update_config
from hydragnn_tpu.data.graph import GraphSample
from hydragnn_tpu.ops.neighbors import radius_graph


def _samples(n=6, seed=0, dim=2, with_node_targets=True):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(4, 8))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        out.append(
            GraphSample(
                x=r.normal(size=(k, dim)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.5),
                y_graph=np.zeros(1, np.float32),
                y_node=(
                    np.zeros((k, 1), np.float32)
                    if with_node_targets
                    else None
                ),
            )
        )
    return out


def _minimal_config():
    return {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "num_gaussians": 8,
                "num_filters": 8,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0, 1],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {"num_epoch": 1, "batch_size": 4},
        }
    }


def test_update_config_derives_dims():
    config = update_config(_minimal_config(), _samples())
    arch = config["NeuralNetwork"]["Architecture"]
    assert arch["input_dim"] == 2  # from input_node_features
    assert arch["num_nodes"] >= 4
    assert "activation_function" in arch
    assert arch["enable_interatomic_potential"] is False


def test_update_config_accepts_fleet_telemetry_keys():
    """ISSUE 14: the fleet heartbeat key validates eagerly like the
    rest of the Telemetry block — accepted when spelled right,
    rejected loudly when not."""
    import pytest

    cfg = _minimal_config()
    cfg["NeuralNetwork"]["Training"]["Telemetry"] = {
        "enabled": False,
        "heartbeat_interval_s": 0.5,
    }
    update_config(cfg, _samples())  # must not raise
    cfg["NeuralNetwork"]["Training"]["Telemetry"] = {
        "enabled": False,
        "heartbeat_interval": 0.5,  # misspelled: must fail EAGERLY
    }
    with pytest.raises(ValueError, match="heartbeat_interval_s"):
        update_config(cfg, _samples())


def test_update_config_pna_degree():
    cfg = _minimal_config()
    cfg["NeuralNetwork"]["Architecture"]["mpnn_type"] = "PNA"
    config = update_config(cfg, _samples())
    deg = config["NeuralNetwork"]["Architecture"]["pna_deg"]
    assert deg is not None and sum(deg) > 0


def test_update_config_mace_avg_neighbors():
    cfg = _minimal_config()
    arch = cfg["NeuralNetwork"]["Architecture"]
    arch.update(
        {"mpnn_type": "MACE", "num_radial": 4, "max_ell": 1, "node_max_ell": 1}
    )
    config = update_config(cfg, _samples())
    ann = config["NeuralNetwork"]["Architecture"]["avg_num_neighbors"]
    assert ann is not None and ann > 0


def test_merge_config_deep():
    base = {"a": {"b": 1, "c": 2}, "d": 3}
    over = {"a": {"b": 10}, "e": 4}
    merged = merge_config(base, over)
    assert merged["a"]["b"] == 10
    assert merged["a"]["c"] == 2
    assert merged["d"] == 3 and merged["e"] == 4


def test_load_config_path_and_dict(tmp_path):
    cfg = _minimal_config()
    p = tmp_path / "c.json"
    p.write_text(json.dumps(cfg))
    from_path = load_config(str(p))
    from_dict = load_config(cfg)
    assert (
        from_path["NeuralNetwork"]["Architecture"]["mpnn_type"]
        == from_dict["NeuralNetwork"]["Architecture"]["mpnn_type"]
    )
    # load_config must deep-copy dict inputs (caller's dict unharmed)
    from_dict["NeuralNetwork"]["Architecture"]["mpnn_type"] = "GIN"
    assert cfg["NeuralNetwork"]["Architecture"]["mpnn_type"] == "SchNet"


def test_unknown_mpnn_type_raises():
    from hydragnn_tpu.models.create import create_model_config

    cfg = update_config(_minimal_config(), _samples())
    cfg["NeuralNetwork"]["Architecture"]["mpnn_type"] = "NotAModel"
    with pytest.raises(ValueError, match="Unknown mpnn_type"):
        create_model_config(cfg)
