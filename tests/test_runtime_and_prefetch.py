"""Runtime helpers (walltime stop, memory stats), prefetch loader,
stratified subsampling, and conv-type node heads e2e.
"""

import os
import time

import numpy as np
import pytest

import tests._cpu  # noqa: F401


def test_walltime_deadline_env(monkeypatch):
    from hydragnn_tpu.utils.runtime import check_remaining, job_end_time

    monkeypatch.delenv("HYDRAGNN_WALLCLOCK_DEADLINE", raising=False)
    monkeypatch.delenv("SLURM_JOB_END_TIME", raising=False)
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    assert job_end_time() is None
    assert check_remaining() is True  # no scheduler info -> keep going

    monkeypatch.setenv(
        "HYDRAGNN_WALLCLOCK_DEADLINE", str(time.time() + 10_000)
    )
    assert check_remaining(300) is True
    monkeypatch.setenv(
        "HYDRAGNN_WALLCLOCK_DEADLINE", str(time.time() + 100)
    )
    assert check_remaining(300) is False


def test_walltime_stops_training(monkeypatch, tmp_path):
    """The epoch loop must stop early and still run the checkpoint
    callback when the deadline is near."""
    import hydragnn_tpu
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.config import load_config

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv(
        "HYDRAGNN_WALLCLOCK_DEADLINE", str(time.time() + 60)
    )
    data = str(tmp_path / "ds")
    deterministic_graph_data(data, number_configurations=30, seed=3)
    here = os.path.dirname(os.path.abspath(__file__))
    config = load_config(os.path.join(here, "inputs", "ci.json"))
    config["Dataset"]["path"] = {"total": data}
    config["NeuralNetwork"]["Training"]["num_epoch"] = 50
    config["NeuralNetwork"]["Training"]["walltime_min_seconds_left"] = 300
    state, model, cfg, hist, full = hydragnn_tpu.run_training(config)
    assert len(hist.train_loss) < 50  # stopped on walltime, not epochs


def test_memory_stats_shape():
    from hydragnn_tpu.utils.runtime import memory_stats, print_peak_memory

    s = memory_stats()  # CPU backend: usually {}
    assert isinstance(s, dict)
    print_peak_memory(lambda *_: None)


def test_memory_stats_hardened_against_raising_and_partial(monkeypatch):
    """ISSUE 8 regression: older libtpu / PJRT plugins can RAISE from
    ``Device.memory_stats()`` or report only a subset of the allocator
    keys — the helper must degrade to partial/empty dicts, never
    propagate (telemetry ``memory`` rows call it inside the run)."""
    import jax

    from hydragnn_tpu.utils import runtime

    class _Raises:
        def __repr__(self):
            return "dev:raises"

        def memory_stats(self):
            raise RuntimeError("allocator stats unavailable")

    class _Partial:
        def __repr__(self):
            return "dev:partial"

        def memory_stats(self):
            return {"bytes_in_use": 123}  # no peak, no limit

    class _NoneStats:
        def __repr__(self):
            return "dev:none"

        def memory_stats(self):
            return None

    monkeypatch.setattr(
        jax, "devices", lambda: [_Raises(), _Partial(), _NoneStats()]
    )
    s = runtime.memory_stats()
    assert s == {"dev:partial": {"bytes_in_use": 123}}
    # and a devices() that itself raises -> {}
    def _boom():
        raise RuntimeError("backend gone")

    monkeypatch.setattr(jax, "devices", _boom)
    assert runtime.memory_stats() == {}


def test_host_memory_reports_rss():
    from hydragnn_tpu.utils.runtime import host_memory

    hm = host_memory()
    # linux container: both sources exist and are sane (> 1 MiB)
    assert hm.get("host_rss_bytes", 0) > 1 << 20
    assert hm.get("host_peak_rss_bytes", 0) >= hm["host_rss_bytes"] // 2


def test_prefetch_loader_equivalent():
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.prefetch import PrefetchLoader
    from hydragnn_tpu.ops.neighbors import radius_graph

    r = np.random.default_rng(0)
    samples = []
    for i in range(17):
        k = int(r.integers(4, 8))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        samples.append(
            GraphSample(
                x=np.full((k, 1), float(i), np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.5),
                y_graph=np.array([float(i)], np.float32),
            )
        )
    plain = GraphLoader(samples, 4, shuffle=True, seed=1)
    pref = PrefetchLoader(GraphLoader(samples, 4, shuffle=True, seed=1))
    plain.set_epoch(2)
    pref.set_epoch(2)
    a = [np.asarray(b.y_graph) for b in plain]
    b = [np.asarray(b.y_graph) for b in pref]
    assert len(a) == len(b) == len(pref)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_prefetch_loader_propagates_errors():
    from hydragnn_tpu.data.prefetch import PrefetchLoader

    def bad_gen():
        yield 1
        raise RuntimeError("boom")

    class Bad:
        def __iter__(self):
            return bad_gen()

        def __len__(self):
            return 2

    with pytest.raises(RuntimeError, match="boom"):
        list(PrefetchLoader(Bad()))


def test_stratified_sample():
    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.sampling import stratified_sample

    samples = []
    for comp, n in ((1.0, 100), (2.0, 40), (3.0, 4)):
        for _ in range(n):
            samples.append(
                GraphSample(x=np.full((5, 1), comp, np.float32))
            )
    sub = stratified_sample(samples, 0.25, seed=0)
    comps = np.array([s.x[0, 0] for s in sub])
    assert abs((comps == 1.0).sum() - 25) <= 1
    assert abs((comps == 2.0).sum() - 10) <= 1
    assert (comps == 3.0).sum() >= 1  # rare category survives
    with pytest.raises(ValueError):
        stratified_sample(samples, 0.0)


def test_conv_node_head_e2e():
    import jax

    from hydragnn_tpu.data.graph import GraphSample, collate
    from hydragnn_tpu.models.create import create_model, init_params
    from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    r = np.random.default_rng(0)
    samples = []
    for _ in range(6):
        k = int(r.integers(5, 9))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        x = r.normal(size=(k, 2)).astype(np.float32)
        samples.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5),
                y_node=x[:, :1].copy(),
            )
        )
    batch = collate(samples)
    cfg = ModelConfig(
        mpnn_type="SchNet",
        input_dim=2,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("n", "node", 1),),
        graph_branches=(BranchSpec(),),
        node_branches=(
            BranchSpec(
                node_head_type="conv",
                dim_headlayers=(8, 8),
                num_headlayers=2,
            ),
        ),
        task_weights=(1.0,),
        radius=2.5,
        num_gaussians=8,
        num_filters=8,
    )
    model = create_model(cfg)
    params, bs = init_params(model, batch)
    tx = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    )
    state = create_train_state(params, tx, bs)
    step = make_train_step(model, tx, cfg)
    losses = []
    for _ in range(25):
        state, tot, _ = step(state, batch)
        losses.append(float(tot))
    assert losses[-1] < losses[0] * 0.5


def test_conv_checkpointing_matches_plain():
    """remat must change memory, not math: losses identical."""
    import jax

    from hydragnn_tpu.data.graph import GraphSample, collate
    from hydragnn_tpu.models.create import create_model, init_params
    from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.train.loop import make_train_step
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    r = np.random.default_rng(1)
    k = 8
    pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
    x = r.normal(size=(k, 1)).astype(np.float32)
    batch = collate(
        [
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5),
                y_graph=np.array([0.3], np.float32),
            )
        ]
    )
    results = []
    for ckpt in (False, True):
        cfg = ModelConfig(
            mpnn_type="SchNet",
            input_dim=1,
            hidden_dim=8,
            num_conv_layers=2,
            heads=(HeadSpec("g", "graph", 1),),
            graph_branches=(BranchSpec(),),
            node_branches=(),
            task_weights=(1.0,),
            radius=2.5,
            num_gaussians=8,
            num_filters=8,
            conv_checkpointing=ckpt,
        )
        model = create_model(cfg)
        params, bs = init_params(model, batch)
        tx = select_optimizer(
            {"Optimizer": {"type": "Adam", "learning_rate": 1e-2}}
        )
        state = create_train_state(params, tx, bs)
        step = make_train_step(model, tx, cfg)
        ls = []
        for _ in range(5):
            state, tot, _ = step(state, batch)
            ls.append(float(tot))
        results.append(ls)
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)


def test_dump_testdata_env(tmp_path, monkeypatch):
    """HYDRAGNN_TPU_DUMP_TESTDATA writes per-sample test outputs
    (reference HYDRAGNN_DUMP_TESTDATA)."""
    import numpy as np

    from hydragnn_tpu.data.graph import GraphSample
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model, init_params
    from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
    from hydragnn_tpu.ops.neighbors import radius_graph
    from hydragnn_tpu.train.loop import test as run_test
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.state import create_train_state

    monkeypatch.setenv("HYDRAGNN_TPU_DUMP_TESTDATA", str(tmp_path / "dump"))
    r = np.random.default_rng(0)
    samples = []
    for _ in range(6):
        k = int(r.integers(4, 8))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        samples.append(
            GraphSample(
                x=r.normal(size=(k, 1)).astype(np.float32),
                pos=pos,
                edge_index=radius_graph(pos, 2.5),
                y_graph=np.array([0.1], np.float32),
            )
        )
    cfg = ModelConfig(
        mpnn_type="SchNet", input_dim=1, hidden_dim=8, num_conv_layers=2,
        heads=(HeadSpec("g", "graph", 1),), graph_branches=(BranchSpec(),),
        node_branches=(), task_weights=(1.0,), radius=2.5,
        num_gaussians=8, num_filters=8,
    )
    model = create_model(cfg)
    loader = GraphLoader(samples, 3)
    params, bs = init_params(model, next(iter(loader)))
    tx = select_optimizer({"Optimizer": {"type": "AdamW"}})
    state = create_train_state(params, tx, bs)
    run_test(model, cfg, state, loader)
    data = np.load(tmp_path / "dump" / "testdata.npz")
    assert data["true_0"].shape == data["pred_0"].shape
    assert data["true_0"].shape[0] == 6


def test_compilation_cache_env(monkeypatch, tmp_path):
    """HYDRAGNN_TPU_COMPILE_CACHE=<dir> turns on jax's persistent
    compilation cache and populates it through run_training — INCLUDING
    in a process that already compiled something beforehand (jax
    latches the cache module as "initialized, disabled" on the first
    compile; maybe_enable_compilation_cache must reset the latch, else
    this test passes standalone and fails after any earlier test)."""
    import jax

    from hydragnn_tpu.utils import runtime as rt

    monkeypatch.delenv("HYDRAGNN_TPU_COMPILE_CACHE", raising=False)
    assert rt.maybe_enable_compilation_cache() is None

    # Latch the cache module the way a real process does: one compile
    # before the cache dir is configured (order-independence guard).
    jax.jit(lambda x: x - 1.0)(jax.numpy.zeros(())).block_until_ready()

    cache_dir = str(tmp_path / "xla_cache")
    monkeypatch.setenv("HYDRAGNN_TPU_COMPILE_CACHE", cache_dir)
    try:
        assert rt.maybe_enable_compilation_cache() == cache_dir
        assert jax.config.jax_compilation_cache_dir == cache_dir

        @jax.jit
        def f(x):
            return x * 2.0 + 1.0

        f(jax.numpy.ones((8, 8))).block_until_ready()
        assert os.listdir(cache_dir), "cache dir must gain entries"
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0
        )
        # Back to pristine: drop the handle on the tmp dir so later
        # tests (and their compiles) see an uninitialized cache module.
        rt.reset_compilation_cache()
