"""Unit tests for segment ops, RBFs, dense batching, and neighbor lists."""

import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.data.graph import GraphSample, PadSpec, collate
from hydragnn_tpu.ops import (
    bessel_basis,
    cosine_cutoff,
    edge_vectors_and_lengths,
    from_dense_batch,
    gaussian_smearing,
    polynomial_cutoff,
    radius_graph,
    radius_graph_jax,
    radius_graph_pbc,
    segment_max,
    segment_mean,
    segment_softmax,
    segment_sum,
    to_dense_batch,
)


def test_segment_sum_mean_max():
    data = jnp.array([[1.0], [2.0], [3.0], [10.0]])
    ids = jnp.array([0, 0, 1, 2])
    mask = jnp.array([True, True, True, False])
    np.testing.assert_allclose(
        segment_sum(data, ids, 3, mask), [[3.0], [3.0], [0.0]]
    )
    np.testing.assert_allclose(
        segment_mean(data, ids, 3, mask), [[1.5], [3.0], [0.0]]
    )
    np.testing.assert_allclose(
        segment_max(data, ids, 3, mask), [[2.0], [3.0], [0.0]]
    )


def test_segment_multi_aggregate_matches_separate_ops():
    """PNA's two-pass (mean, min, max, std) stack (ISSUE 18) is
    numerically identical to the four separate segment ops, including
    masked (padding) edges and empty segments."""
    from hydragnn_tpu.ops.segment import (
        degree,
        segment_min,
        segment_multi_aggregate,
    )

    batch = collate(_two_triangle_samples())
    rng = np.random.default_rng(7)
    h = jnp.asarray(
        rng.normal(size=(batch.num_edges, 5)), jnp.float32
    )
    mean, mn, mx, std = segment_multi_aggregate(h, batch)
    rcv, n, mask = batch.receivers, batch.num_nodes, batch.edge_mask
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(segment_mean(h, rcv, n, mask)),
        rtol=1e-6, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(mn), np.asarray(segment_min(h, rcv, n, mask))
    )
    np.testing.assert_allclose(
        np.asarray(mx), np.asarray(segment_max(h, rcv, n, mask))
    )
    cnt = np.maximum(np.asarray(degree(rcv, n, mask=mask)), 1)[:, None]
    m = np.asarray(segment_mean(h, rcv, n, mask))
    sq = np.asarray(segment_sum(h * h, rcv, n, mask)) / cnt
    ref_std = np.sqrt(np.maximum(sq - m * m, 0.0) + 1e-5)
    np.testing.assert_allclose(
        np.asarray(std), ref_std, rtol=1e-6, atol=1e-6
    )
    # empty (padding) segments: all four aggregates are exactly zero
    # except std, which is sqrt(eps) of the zero moments
    empty = np.ones(n, bool)
    empty[np.asarray(rcv)[np.asarray(mask)]] = False
    assert empty.any()
    assert np.all(np.asarray(mean)[empty] == 0.0)
    assert np.all(np.asarray(mn)[empty] == 0.0)
    assert np.all(np.asarray(mx)[empty] == 0.0)


def test_segment_softmax_normalizes():
    logits = jnp.array([1.0, 2.0, 3.0, 5.0])
    ids = jnp.array([0, 0, 1, 1])
    out = segment_softmax(logits, ids, 2)
    np.testing.assert_allclose(out[0] + out[1], 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[2] + out[3], 1.0, rtol=1e-6)


def test_rbf_shapes_and_cutoffs():
    d = jnp.linspace(0.1, 4.0, 7)
    assert gaussian_smearing(d, 0.0, 5.0, 16).shape == (7, 16)
    assert bessel_basis(d, 5.0, 8).shape == (7, 8)
    c = cosine_cutoff(jnp.array([0.0, 2.5, 5.0, 6.0]), 5.0)
    assert c[0] == pytest.approx(1.0)
    assert float(c[2]) == pytest.approx(0.0, abs=1e-6)
    assert float(c[3]) == 0.0
    p = polynomial_cutoff(jnp.array([0.0, 5.0, 6.0]), 5.0)
    assert p[0] == pytest.approx(1.0)
    assert float(p[1]) == pytest.approx(0.0, abs=1e-6)


def _two_triangle_samples():
    tri = np.array(
        [[0.0, 0.0, 0.0], [1.0, 0.0, 0.0], [0.0, 1.0, 0.0]], dtype=np.float32
    )
    edges = np.array([[0, 1, 1, 2, 2, 0], [1, 0, 2, 1, 0, 2]])
    return [
        GraphSample(
            x=np.full((3, 1), float(i)),
            pos=tri + i,
            edge_index=edges,
            y_graph=np.array([float(i)]),
        )
        for i in range(2)
    ]


def test_collate_padding_and_masks():
    batch = collate(_two_triangle_samples())
    assert batch.num_graphs == 3  # 2 real + 1 padding slot
    assert int(batch.node_mask.sum()) == 6
    assert int(batch.edge_mask.sum()) == 12
    assert int(batch.graph_mask.sum()) == 2
    # Padded edges self-loop on a padding node.
    pad_edges = np.asarray(batch.senders)[~np.asarray(batch.edge_mask)]
    assert (pad_edges >= 6).all()
    # Second graph's node indices are offset.
    real_senders = np.asarray(batch.senders)[np.asarray(batch.edge_mask)]
    assert real_senders[6:].min() >= 3
    np.testing.assert_allclose(np.asarray(batch.y_graph)[:2, 0], [0.0, 1.0])


def test_dense_batch_roundtrip():
    batch = collate(_two_triangle_samples())
    x = jnp.asarray(np.random.default_rng(0).normal(size=(batch.num_nodes, 4)))
    dense, mask = to_dense_batch(x, batch, max_nodes=3)
    assert dense.shape == (3, 3, 4)
    assert int(mask.sum()) == 6
    back = from_dense_batch(dense, batch, max_nodes=3)
    np.testing.assert_allclose(
        np.asarray(back)[np.asarray(batch.node_mask)],
        np.asarray(x)[np.asarray(batch.node_mask)],
        rtol=1e-6,
    )


def test_radius_graph_matches_bruteforce():
    rng = np.random.default_rng(3)
    pos = rng.uniform(0, 4, size=(40, 3))
    r = 1.2
    ei = radius_graph(pos, r)
    got = set(zip(ei[0].tolist(), ei[1].tolist()))
    want = set()
    for i in range(40):
        for j in range(40):
            if i != j and np.linalg.norm(pos[i] - pos[j]) <= r:
                want.add((j, i))
    assert got == want


def test_radius_graph_max_neighbours():
    pos = np.array([[0, 0, 0], [0.1, 0, 0], [0.2, 0, 0], [0.3, 0, 0]], dtype=float)
    ei = radius_graph(pos, 1.0, max_neighbours=2)
    counts = np.bincount(ei[1], minlength=4)
    assert (counts <= 2).all()


def test_radius_graph_pbc_images():
    # Two atoms near opposite faces of a unit cell: connected via PBC.
    cell = np.eye(3) * 4.0
    pos = np.array([[0.1, 2.0, 2.0], [3.9, 2.0, 2.0]])
    ei, shifts = radius_graph_pbc(pos, cell, 0.5)
    assert ei.shape[1] == 2  # one edge each direction
    vec, length = edge_vectors_and_lengths(
        jnp.asarray(pos), jnp.asarray(ei[0]), jnp.asarray(ei[1]), jnp.asarray(shifts)
    )
    np.testing.assert_allclose(np.asarray(length), [0.2, 0.2], atol=1e-6)


def test_radius_graph_jax_matches_host():
    samples = _two_triangle_samples()
    batch = collate(samples)
    snd, rcv, emask, overflow = radius_graph_jax(
        batch.pos, 1.5, batch.node_graph_idx, batch.node_mask, max_edges=32
    )
    assert int(overflow) == 0
    got = {
        (int(s), int(r))
        for s, r, m in zip(snd, rcv, emask)
        if bool(m)
    }
    want = {
        (int(s), int(r))
        for s, r, m in zip(batch.senders, batch.receivers, batch.edge_mask)
        if bool(m)
    }
    assert got == want


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_radius_graph_jax_property_parity(seed):
    """Property parity (ISSUE 15): on random multi-graph configs the
    jit builder's masked/compacted edge set equals the host cell-list
    path's — including the overflow COUNT when ``max_edges`` is
    undersized (count = real edges minus capacity, and the kept slots
    are all real edges)."""
    rng = np.random.default_rng(100 + seed)
    samples = []
    for _ in range(int(rng.integers(1, 4))):
        n = int(rng.integers(3, 12))
        pos = rng.uniform(0, 3.0, (n, 3)).astype(np.float32)
        samples.append(
            GraphSample(
                x=np.ones((n, 1), np.float32),
                pos=pos,
                edge_index=radius_graph(pos.astype(np.float64), 1.5),
            )
        )
    batch = collate(samples)
    want = {
        (int(s), int(r))
        for s, r, m in zip(batch.senders, batch.receivers, batch.edge_mask)
        if bool(m)
    }

    # Roomy capacity: exact edge-set parity, zero overflow.
    snd, rcv, em, ovf = radius_graph_jax(
        batch.pos, 1.5, batch.node_graph_idx, batch.node_mask,
        max_edges=batch.num_edges,
    )
    got = {
        (int(s), int(r))
        for s, r, m in zip(snd, rcv, em)
        if bool(m)
    }
    assert int(ovf) == 0
    assert got == want

    # Undersized capacity: every kept slot is a real edge and the
    # overflow count is exactly the shortfall.
    if len(want) > 1:
        cap = max(1, len(want) // 2)
        snd, rcv, em, ovf = radius_graph_jax(
            batch.pos, 1.5, batch.node_graph_idx, batch.node_mask,
            max_edges=cap,
        )
        kept = {
            (int(s), int(r))
            for s, r, m in zip(snd, rcv, em)
            if bool(m)
        }
        assert int(ovf) == len(want) - cap
        assert len(kept) == cap
        assert kept <= want


def test_build_triplets_path_graph():
    # Path 0->1->2 (directed both ways): triplets at each middle vertex.
    from hydragnn_tpu.data.graph import build_triplets

    senders = np.array([0, 1, 1, 2])
    receivers = np.array([1, 0, 2, 1])
    kj, ji = build_triplets(senders, receivers, 3)
    trips = {(int(senders[a]), int(senders[b]), int(receivers[b])) for a, b in zip(kj, ji)}
    # k -> j -> i with k != i: only 0->1->2 and 2->1->0
    assert trips == {(0, 1, 2), (2, 1, 0)}


def test_collate_triplets_match_unpadded():
    from hydragnn_tpu.data.graph import PadSpec, build_triplets, collate

    samples = _two_triangle_samples()
    spec = PadSpec.for_samples(samples, with_triplets=True)
    batch = collate(samples, spec)
    n_real = sum(s.num_nodes for s in samples)
    e_real = sum(s.num_edges for s in samples)
    kj, ji = build_triplets(
        np.asarray(batch.senders[:e_real]),
        np.asarray(batch.receivers[:e_real]),
        n_real,
    )
    m = np.asarray(batch.triplet_mask)
    assert int(m.sum()) == len(kj)
    np.testing.assert_array_equal(np.asarray(batch.t_kj)[m], kj)
    np.testing.assert_array_equal(np.asarray(batch.t_ji)[m], ji)
    # Triplets never cross graphs.
    ngi = np.asarray(batch.node_graph_idx)
    snd = np.asarray(batch.senders)
    assert (ngi[snd[np.asarray(batch.t_kj)[m]]] == ngi[snd[np.asarray(batch.t_ji)[m]]]).all()


def test_spherical_basis_finite_and_masked():
    from hydragnn_tpu.ops.sbf import spherical_basis

    dist = jnp.asarray(np.linspace(0.0, 2.0, 10), jnp.float32)
    angle = jnp.asarray(np.linspace(0, np.pi, 6), jnp.float32)
    idx_kj = jnp.asarray(np.arange(6) % 10, jnp.int32)
    out = spherical_basis(
        dist, angle, idx_kj, cutoff=2.0, num_spherical=7, num_radial=6
    )
    assert out.shape == (6, 42)
    assert np.isfinite(np.asarray(out)).all()


def test_legendre_matches_numpy():
    from numpy.polynomial.legendre import legval

    from hydragnn_tpu.ops.sbf import legendre_pl

    c = np.linspace(-1, 1, 41)
    got = np.asarray(legendre_pl(jnp.asarray(c, jnp.float32), 6))
    for l in range(7):
        coef = np.zeros(l + 1)
        coef[l] = 1
        np.testing.assert_allclose(got[:, l], legval(c, coef), atol=1e-5)
