"""Multibranch task-parallel training (reference MultiTaskModelMP,
hydragnn/models/MultiTaskModelMP.py:269-532): branch split, per-branch
gradient semantics, dual optimizer, gradient accumulation, and an e2e
sanity run over an 8-device CPU mesh.
"""

import numpy as np
import pytest

import tests._cpu  # noqa: F401

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphSample, collate
from hydragnn_tpu.models.create import create_model, init_params
from hydragnn_tpu.models.spec import BranchSpec, HeadSpec, ModelConfig
from hydragnn_tpu.ops.neighbors import radius_graph
from hydragnn_tpu.parallel.mesh import make_mesh
from hydragnn_tpu.parallel.multibranch import (
    MultiBranchLoader,
    accumulate,
    branch_of_device,
    dual_optimizer,
    make_multibranch_train_step,
    proportional_branch_split,
    rescale_decoder_grads,
)
from hydragnn_tpu.train.losses import multihead_loss
from hydragnn_tpu.train.state import create_train_state


def test_proportional_branch_split():
    assert proportional_branch_split([100, 100], 8) == [4, 4]
    assert sum(proportional_branch_split([500, 100, 100], 8)) == 8
    split = proportional_branch_split([1000, 10], 8)
    assert split[0] > split[1] >= 1
    with pytest.raises(ValueError):
        proportional_branch_split([1, 1, 1], 2)
    assert list(branch_of_device([2, 1])) == [0, 0, 1]


def _samples(n, dataset_id, seed):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(r.integers(4, 8))
        pos = r.uniform(0, 3.0, (k, 3)).astype(np.float32)
        x = r.normal(size=(k, 2)).astype(np.float32)
        # Learnable target with a branch-specific scale so branch heads
        # must specialize.
        y = (1.0 + dataset_id) * float(x.mean())
        out.append(
            GraphSample(
                x=x,
                pos=pos,
                edge_index=radius_graph(pos, 2.5, max_neighbours=12),
                y_graph=np.array([y], np.float32),
                dataset_id=dataset_id,
            )
        )
    return out


def _cfg(n_branches=2):
    return ModelConfig(
        mpnn_type="SchNet",
        input_dim=2,
        hidden_dim=8,
        num_conv_layers=2,
        heads=(HeadSpec("e", "graph", 1),),
        graph_branches=tuple(
            BranchSpec(name=f"branch-{i}") for i in range(n_branches)
        ),
        node_branches=(),
        task_weights=(1.0,),
        radius=2.5,
        num_gaussians=8,
        num_filters=8,
    )


def test_multibranch_gradient_semantics():
    """The rescaled full-mesh gradient mean must equal the reference's
    two-process-group reduction: encoder grads averaged over WORLD,
    branch-b decoder grads averaged over branch b's devices only
    (MultiTaskModelMP.gradient_all_reduce, :458-460)."""
    cfg = _cfg()
    model = create_model(cfg)
    dpb = [3, 1]  # 4 "devices", branch 0 gets 3
    D = sum(dpb)
    bod = branch_of_device(dpb)
    from hydragnn_tpu.data.graph import PadSpec

    spec = PadSpec(num_nodes=24, num_edges=192, num_graphs=3)
    batches = [
        collate(_samples(2, int(bod[d]), seed=d), spec) for d in range(D)
    ]
    from hydragnn_tpu.parallel.mesh import stack_batches

    stacked = stack_batches(batches)
    params, bs = init_params(model, batches[0])

    def device_loss(p, batch):
        out = model.apply({"params": p, "batch_stats": bs}, batch, train=False)
        tot, _ = multihead_loss(out, batch, cfg)
        return tot

    # Full-mesh mean + rescale (what the multibranch step does).
    def mesh_loss(p):
        return jnp.mean(jax.vmap(lambda b: device_loss(p, b))(stacked))

    mesh_grads = jax.grad(mesh_loss)(params)
    rescaled = rescale_decoder_grads(mesh_grads, cfg, D, tuple(dpb))

    # Reference semantics computed directly.
    per_dev = [jax.grad(device_loss)(params, b) for b in batches]

    def mean_over(devs):
        return jax.tree_util.tree_map(
            lambda *xs: sum(xs) / len(xs), *[per_dev[d] for d in devs]
        )

    world_mean = mean_over(range(D))
    branch_means = [
        mean_over([d for d in range(D) if bod[d] == bi])
        for bi in range(len(dpb))
    ]

    flat_r = jax.tree_util.tree_flatten_with_path(rescaled)[0]
    flat_w = jax.tree_util.tree_flatten_with_path(world_mean)[0]
    flat_b = [
        jax.tree_util.tree_flatten_with_path(bm)[0] for bm in branch_means
    ]
    for i, (path, g) in enumerate(flat_r):
        keys = [getattr(p, "key", "") for p in path]
        is_decoder = any(k.startswith("decoder") for k in keys)
        if is_decoder:
            bi = 0 if any(k.endswith("branch-0") for k in keys) else 1
            expected = flat_b[bi][i][1]
        else:
            expected = flat_w[i][1]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(expected), rtol=1e-4, atol=1e-6
        )


def test_multibranch_train_step_runs():
    cfg = _cfg()
    model = create_model(cfg)
    mesh = make_mesh({"data": 8})
    dpb = proportional_branch_split([60, 20], 8)
    branch_sets = [_samples(60, 0, seed=1), _samples(20, 1, seed=2)]
    loader = MultiBranchLoader(
        branch_sets, dpb, batch_size=4, mesh=mesh, seed=0
    )
    batch0 = next(iter(loader.loaders[0]))
    params, bs = init_params(model, batch0)
    tx = dual_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}},
        decoder_lr=3e-3,
    )
    state = create_train_state(params, tx, bs)
    from hydragnn_tpu.parallel.dp import replicate_state

    state = replicate_state(state, mesh)
    step = make_multibranch_train_step(model, tx, cfg, mesh, dpb)
    losses = []
    for epoch in range(8):
        loader.set_epoch(epoch)
        for stacked in loader:
            state, tot, tasks = step(state, stacked)
            losses.append(float(tot))
    assert np.isfinite(losses).all()
    k = max(len(losses) // 4, 1)
    assert np.mean(losses[-k:]) < np.mean(losses[:k]), (
        losses[:3],
        losses[-3:],
    )


def test_accumulate_wrapper():
    import optax

    tx = accumulate(optax.sgd(1e-2), every=4)
    params = {"w": jnp.ones(3)}
    st = tx.init(params)
    g = {"w": jnp.ones(3)}
    p = params
    for i in range(4):
        updates, st = tx.update(g, st, p)
        p = optax.apply_updates(p, updates)
    # After 4 accumulation steps exactly one SGD step has been applied.
    np.testing.assert_allclose(np.asarray(p["w"]), 1.0 - 1e-2, rtol=1e-5)


def test_multibranch_heterogeneous_branch_fields():
    """One periodic branch (cell/edge_shifts) + one gas-phase branch:
    every device slot's batches must share ONE pytree structure (the
    optional-field union is computed over the concatenated branch
    datasets), so cross-slot stacking works — regression for the
    mixed-dataset structure divergence fixed in collate/ensure_fields."""
    import dataclasses

    mesh = make_mesh({"data": 8})
    molecules = _samples(40, 0, seed=1)
    crystals = [
        dataclasses.replace(
            s,
            edge_shifts=np.zeros((s.num_edges, 3), np.float32),
            cell=np.eye(3, dtype=np.float32),
        )
        for s in _samples(40, 1, seed=2)
    ]
    dpb = proportional_branch_split([40, 40], 8)
    loader = MultiBranchLoader(
        [molecules, crystals], dpb, batch_size=4, mesh=mesh, seed=0
    )
    structures = set()
    for stacked in loader:
        structures.add(str(jax.tree_util.tree_structure(stacked)))
        assert stacked.edge_shifts is not None
        assert stacked.cell is not None
    assert len(structures) == 1


def test_multibranch_run_prediction_public_api(tmp_path, monkeypatch):
    """run_prediction under the multibranch scheme (the reference runs
    prediction through the wrapper it trained with,
    run_prediction.py:62-71): per-branch per-sample collection through
    the trained state, and the disk-restored state must reproduce the
    in-memory predictions exactly."""
    import os

    from hydragnn_tpu.data.loader import split_dataset
    from hydragnn_tpu.runner import run_prediction, run_training

    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SchNet",
                "radius": 2.5,
                "max_neighbours": 12,
                "num_gaussians": 8,
                "num_filters": 16,
                "hidden_dim": 16,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 16,
                        "num_headlayers": 1,
                        "dim_headlayers": [16],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0, 1],
                "output_names": ["y"],
                "output_index": [0],
                "type": ["graph"],
                "output_dim": [1],
            },
            "Training": {
                "batch_size": 4,
                "num_epoch": 2,
                "Optimizer": {"type": "AdamW", "learning_rate": 5e-3},
                "Parallelism": {"scheme": "multibranch"},
            },
        }
    }
    sets = [
        split_dataset(_samples(40, 0, seed=21), 0.7),
        split_dataset(_samples(56, 1, seed=22), 0.7),
    ]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        state, model, cfg, hist, full = run_training(
            config, datasets=sets, seed=0
        )
        err0, tasks0, trues0, preds0 = run_prediction(
            full, datasets=sets, state=state, model=model, cfg=cfg
        )
        # Keyed by branch: one (trues, preds) list per branch, sized to
        # that branch's test split.
        assert len(trues0) == len(preds0) == 2
        for bi, (_, _, te) in enumerate(sets):
            assert len(preds0[bi][0]) == len(te)
        assert np.isfinite(err0)
        # Disk restore through the public API reproduces exactly.
        err1, _, _, preds1 = run_prediction(full, datasets=sets)
        np.testing.assert_allclose(err0, err1, rtol=1e-6)
        for b0, b1 in zip(preds0, preds1):
            for p0, p1 in zip(b0, b1):
                np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)
    finally:
        os.chdir(cwd)
