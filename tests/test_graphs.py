"""End-to-end train-to-threshold tests.

The TPU analog of the reference's central E2E tests
(tests/test_graphs.py:25-201): generate the deterministic synthetic BCC
dataset, run full run_training + run_prediction for each model type, and
assert head RMSE / sample MAE below per-model thresholds (threshold table
reference tests/test_graphs.py:144-158).
"""

import json
import os

import numpy as np
import pytest

import hydragnn_tpu
from hydragnn_tpu.config import load_config
from hydragnn_tpu.data.synthetic import deterministic_graph_data

# Reference threshold table (head RMSE, sample MAE) — see
# /root/reference/tests/test_graphs.py:144-158 and BASELINE.md.
THRESHOLDS = {
    "SchNet": (0.20, 0.20),
    "GIN": (0.25, 0.20),
    "SAGE": (0.20, 0.20),
    "MFC": (0.20, 0.30),
    "GAT": (0.60, 0.70),
    "CGCNN": (0.50, 0.40),
    "PNA": (0.20, 0.20),
    "PNAPlus": (0.20, 0.20),
    "DimeNet": (0.50, 0.50),
    "EGNN": (0.20, 0.20),
    "PAINN": (0.60, 0.60),
    "PNAEq": (0.60, 0.60),
    "MACE": (0.60, 0.70),
}


def _make_dataset(tmp_path, n_configs=300):
    path = os.path.join(tmp_path, "dataset", "unit_test")
    deterministic_graph_data(path, number_configurations=n_configs, seed=7)
    return path


def _base_config(data_path):
    here = os.path.dirname(__file__)
    config = load_config(os.path.join(here, "inputs", "ci.json"))
    config["Dataset"]["path"] = {"total": data_path}
    # Model-quality thresholds are calibrated for single-device
    # stepping; on the 8-device test mesh the auto plan would otherwise
    # train data-parallel with an 8x effective batch (fewer optimizer
    # steps). The parallel path has its own E2E suite
    # (tests/test_parallel_runtime.py).
    config["NeuralNetwork"]["Training"]["Parallelism"] = {"scheme": "single"}
    return config


def run_e2e(config, mpnn_type, overrides=None):
    arch = config["NeuralNetwork"]["Architecture"]
    arch["mpnn_type"] = mpnn_type
    if overrides:
        arch.update(overrides)
    state, model, cfg, hist, full_config = hydragnn_tpu.run_training(config)
    error, tasks, trues, preds = hydragnn_tpu.run_prediction(
        full_config,
        datasets=None,
        state=state,
        model=model,
        cfg=cfg,
    )
    return error, tasks, trues, preds


def check_thresholds(mpnn_type, tasks, trues, preds):
    thr_rmse, thr_mae = THRESHOLDS[mpnn_type]
    for hi, (t, p) in enumerate(zip(trues, preds)):
        rmse = float(np.sqrt(np.mean((t - p) ** 2)))
        mae = float(np.mean(np.abs(t - p)))
        assert rmse < thr_rmse, f"head {hi} RMSE {rmse} >= {thr_rmse}"
        assert mae < thr_mae, f"head {hi} MAE {mae} >= {thr_mae}"


@pytest.fixture(scope="module")
def dataset_path(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("e2e")
    return _make_dataset(str(tmp))


@pytest.mark.parametrize(
    "mpnn_type",
    [
        "SchNet",
        "GIN",
        "SAGE",
        # MFC trains with BN recalibration enabled (see the test body):
        # with ~7 train batches/epoch the BN EMA (momentum 0.9) lags
        # ~1.5 epochs behind MFC's drifting per-degree feature tables,
        # so the stats the model carries out of training are stale.
        # The end-of-training recalibration pass
        # (train/loop.recalibrate_batch_stats: frozen-param forward
        # passes pooling exact masked moments into the running stats,
        # fed by the runner's eval-shaped unpacked loader) is the
        # ROADMAP's measured fix (RMSE 0.39 -> 0.16); PyG's
        # max_degree=10 cap and batch_axis init both measured WORSE
        # (0.54) — do not retry. Per-epoch recalibration also measured
        # worse (0.30): it feeds the plateau scheduler a meaningful
        # val curve, keeps the LR hot, and the 210-sample run overfits
        # — the annealed raw trajectory + refreshed final stats is the
        # fix.
        "MFC",
        "CGCNN",
        "GAT",
        "PNA",
        "PNAPlus",
        "DimeNet",
        "EGNN",
        "PAINN",
        "PNAEq",
    ],
)
def test_train_singlehead_graph(dataset_path, mpnn_type):
    config = _base_config(dataset_path)
    if mpnn_type == "MFC":
        # End-of-training BatchNorm recalibration — required on
        # 7-batch CI epochs where the BN EMA lags the drifting
        # per-degree feature scales (see the parametrize comment).
        # One pass is exact: the stats are pooled moments, not
        # another EMA (RMSE 0.164 here vs 0.386 raw).
        config["NeuralNetwork"]["Training"]["bn_recalibration"] = {
            "enabled": True
        }
    # Re-ingest via the raw path (reference flow: text files -> raw loader
    # -> serialized samples -> loaders).
    error, tasks, trues, preds = run_e2e(config, mpnn_type)
    check_thresholds(mpnn_type, tasks, trues, preds)


def _multihead_config(data_path):
    """Graph head + two node heads (reference multihead CI config shape,
    tests/inputs/ci_multihead.json)."""
    config = _base_config(data_path)
    nn_cfg = config["NeuralNetwork"]
    nn_cfg["Variables_of_interest"] = {
        "input_node_features": [0],
        "output_names": ["sum_x_x2_x3", "x2", "x3"],
        "output_index": [0, 1, 2],
        "type": ["graph", "node", "node"],
        "denormalize_output": False,
    }
    nn_cfg["Architecture"]["task_weights"] = [1.0, 1.0, 1.0]
    nn_cfg["Architecture"]["output_heads"]["node"] = {
        "num_headlayers": 2,
        "dim_headlayers": [16, 16],
        "type": "mlp",
    }
    return config


@pytest.mark.parametrize("mpnn_type", ["SchNet", "PNA", "GAT"])
def test_train_multihead(dataset_path, mpnn_type):
    config = _multihead_config(dataset_path)
    error, tasks, trues, preds = run_e2e(config, mpnn_type)
    assert len(trues) == 3
    check_thresholds(mpnn_type, tasks, trues, preds)


def test_train_per_node_head(dataset_path):
    """mlp_per_node heads need fixed-size graphs; restrict to 1x1x1 BCC
    cells (2 nodes each) like the reference's fixed-graph tests."""
    path = os.path.join(os.path.dirname(dataset_path), "fixed_size")
    deterministic_graph_data(
        path,
        number_configurations=100,
        unit_cell_x_range=(1, 2),
        unit_cell_y_range=(1, 2),
        unit_cell_z_range=(1, 2),
        seed=11,
    )
    config = _multihead_config(path)
    arch = config["NeuralNetwork"]["Architecture"]
    arch["output_heads"]["node"]["type"] = "mlp_per_node"
    arch["num_nodes"] = 2
    config["NeuralNetwork"]["Training"]["num_epoch"] = 40
    error, tasks, trues, preds = run_e2e(config, "SchNet")
    assert np.isfinite(error)


def test_train_mace(dataset_path):
    """MACE trains to the reference threshold (reference
    tests/test_graphs.py:144-158: MACE 0.60/0.70). Atomic "numbers" are
    the synthetic 0..2 types, clamped into 1..118 exactly as the
    reference's process_node_attributes does (MACEStack.py:510-541)."""
    config = _base_config(dataset_path)
    error, tasks, trues, preds = run_e2e(
        config,
        "MACE",
        overrides={
            "max_ell": 2,
            "node_max_ell": 2,
            "correlation": 2,
            "hidden_dim": 8,
        },
    )
    check_thresholds("MACE", tasks, trues, preds)


@pytest.mark.parametrize("global_attn_type", ["multihead", "performer"])
def test_train_global_attention(dataset_path, global_attn_type):
    """GPS-wrapped SchNet trains to threshold (reference
    tests/test_graphs.py global-attention variants)."""
    config = _base_config(dataset_path)
    arch = config["NeuralNetwork"]["Architecture"]
    arch["global_attn_engine"] = "GPS"
    arch["global_attn_type"] = global_attn_type
    arch["global_attn_heads"] = 2
    arch["pe_dim"] = 6
    arch["hidden_dim"] = 16
    error, tasks, trues, preds = run_e2e(config, "SchNet")
    check_thresholds("SchNet", tasks, trues, preds)
