#!/usr/bin/env python
"""graftlint CLI — JAX-aware static analysis for hydragnn_tpu.

Usage:
    python tools/graftlint.py [paths...] [options]

Options:
    --check            gate mode: exit 1 when any NEW finding exists
                       (not suppressed, not in the baseline); exit 0
                       otherwise. Stale baseline entries are reported
                       but do not fail the gate.
    --baseline PATH    baseline file (default tools/graftlint_baseline.json;
                       pass --baseline '' to disable baselining)
    --write-baseline   rewrite the baseline to exactly the current
                       finding set (prunes stale entries), then exit 0
    --json             machine-readable output (findings + summary,
                       including the per-rule stats table)
    --stats            print the per-rule finding/suppression/baseline
                       table (ratchet drift is visible in PR diffs);
                       composes with --check
    --rules r1,r2      run only the named rules
    --diff REV         lint only files changed vs git REV (plus
                       untracked files) that fall inside the default
                       scope — the pre-commit fast path. A restricted
                       view: config-schema falls back to the on-disk
                       default vocabulary, stale-baseline reporting is
                       suppressed (unchanged files can't vouch for
                       their entries), and --write-baseline refuses it
                       like any restricted run.
    --explain RULE     print the rule's catalog entry: description,
                       seed registry (when call-graph scoped), and the
                       rule module's full docstring
    --list-rules       print the rule catalog and exit

Exit codes: 0 clean (or informational mode), 1 new findings under
--check, 2 usage / internal error.

Paths default to the package + examples + tests/inputs +
__graft_entry__.py (see hydragnn_tpu.analysis.DEFAULT_PATHS). The repo
root is located from this script's own path, so the CLI works from any
cwd.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools", "graftlint_baseline.json")


def _changed_files(rev: str):
    """Lintable files changed between REV and the working tree (plus
    untracked), restricted to the default scope. Raises ValueError on
    a bad rev — a typo must be a usage error, never a green no-op."""
    import subprocess

    from hydragnn_tpu.analysis.rules import DEFAULT_PATHS

    def git(*args):
        r = subprocess.run(
            ["git", "-C", _REPO_ROOT] + list(args),
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            raise ValueError(
                f"git {' '.join(args[:2])} failed: "
                f"{r.stderr.strip() or r.stdout.strip()}"
            )
        return r.stdout.splitlines()

    names = set(git("diff", "--name-only", rev, "--"))
    names.update(
        git("ls-files", "--others", "--exclude-standard")
    )
    scope_dirs = tuple(
        p + "/" for p in DEFAULT_PATHS if not p.endswith(".py")
    )
    scope_files = tuple(p for p in DEFAULT_PATHS if p.endswith(".py"))
    out = []
    for n in sorted(names):
        if not n.endswith((".py", ".json")):
            continue
        if not (n.startswith(scope_dirs) or n in scope_files):
            continue
        if os.path.exists(os.path.join(_REPO_ROOT, n)):
            out.append(n)  # deleted files have nothing to lint
    return out


def _stats_table(result) -> str:
    """Fixed-width per-rule counts, rules sorted by name — the table
    diffs cleanly in PRs, so a family's ratchet drifting (new
    baselined entries, suppression creep) is one visible hunk."""
    header = f"{'rule':22s} {'new':>5s} {'baselined':>10s} {'suppressed':>11s}"
    lines = [header, "-" * len(header)]
    tot = {"new": 0, "baselined": 0, "suppressed": 0}
    for rule in sorted(result.per_rule):
        c = result.per_rule[rule]
        lines.append(
            f"{rule:22s} {c['new']:>5d} {c['baselined']:>10d} "
            f"{c['suppressed']:>11d}"
        )
        for k in tot:
            tot[k] += c[k]
    lines.append(
        f"{'total':22s} {tot['new']:>5d} {tot['baselined']:>10d} "
        f"{tot['suppressed']:>11d}"
    )
    return "\n".join(lines)


def _explain(rule) -> str:
    import inspect
    import sys as _sys

    lines = [f"{rule.name} — {rule.description}", ""]
    if getattr(rule, "seeds", ()):
        lines.append("seed registry (path suffix, qualname):")
        for path_sfx, qual in rule.seeds:
            lines.append(f"  {path_sfx:28s} {qual}")
        from hydragnn_tpu.analysis.rules.hot_coverage import (
            HOT_EXEMPT,
            HotCoverageRule,
        )

        if isinstance(rule, HotCoverageRule) and HOT_EXEMPT:
            lines.append("exemptions:")
            for (p, q), why in sorted(HOT_EXEMPT.items()):
                lines.append(f"  {p}:{q} — {why}")
        lines.append("")
    doc = inspect.getdoc(_sys.modules[type(rule).__module__]) or ""
    lines.append(doc)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--stats", action="store_true")
    ap.add_argument("--rules", default=None)
    ap.add_argument("--diff", default=None, metavar="REV")
    ap.add_argument("--explain", default=None, metavar="RULE")
    ap.add_argument("--list-rules", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    baseline = args.baseline or None
    if args.diff is not None and args.paths:
        print("graftlint: --diff and explicit paths are exclusive",
              file=sys.stderr)
        return 2
    if args.write_baseline:
        # validate BEFORE the (multi-second) lint run
        if not baseline:
            print("graftlint: --write-baseline needs a --baseline path",
                  file=sys.stderr)
            return 2
        if args.paths or args.rules or args.diff:
            # a restricted run sees only a subset of findings; writing
            # it would silently drop every grandfathered entry outside
            # the restriction
            print(
                "graftlint: --write-baseline requires a full default-"
                "scope run (no explicit paths, no --rules, no --diff)",
                file=sys.stderr,
            )
            return 2

    try:
        from hydragnn_tpu.analysis import (
            rules_by_name, run_lint, write_baseline,
        )
        from hydragnn_tpu.analysis.rules import all_rules

        if args.list_rules:
            for r in all_rules():
                print(f"{r.name:18s} {r.description}")
            return 0
        if args.explain:
            (rule,) = rules_by_name([args.explain])
            print(_explain(rule))
            return 0

        paths = args.paths or None
        if args.diff is not None:
            paths = _changed_files(args.diff)
            if not paths:
                print(
                    f"graftlint: no lintable files changed vs "
                    f"{args.diff}"
                )
                return 0

        rules = (
            rules_by_name(args.rules.split(",")) if args.rules else None
        )
        result = run_lint(
            _REPO_ROOT,
            paths=paths,
            rules=rules,
            baseline_path=None if args.write_baseline else baseline,
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal error must not masquerade as clean
        import traceback

        traceback.print_exc()
        print(f"graftlint: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline, result.findings)
        print(
            f"graftlint: wrote {len(result.findings)} finding(s) to "
            f"{os.path.relpath(baseline, _REPO_ROOT)}"
        )
        return 0

    from hydragnn_tpu.analysis.rules.jax_api import installed_jax_version

    if args.as_json:
        # identity, not equality: duplicate findings share (rule, path,
        # message) but only `count` of them are baselined
        baselined_ids = {id(f) for f in result.baselined}
        print(json.dumps({
            "jax_version": installed_jax_version(),
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "line": f.line,
                    "message": f.message,
                    "fingerprint": f.fingerprint,
                    "baselined": id(f) in baselined_ids,
                }
                for f in result.findings
            ],
            "new": len(result.new),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed,
            "per_rule": result.per_rule,
            # --diff is a restricted view: entries for unchanged files
            # vanish from the finding set, which is not staleness
            "stale_baseline": (
                sorted(result.stale_baseline)
                if args.diff is None
                else []
            ),
            "ok": result.ok,
        }, indent=2))
    else:
        for f in result.new:
            print(f.render())
        if result.baselined and not args.check:
            for f in result.baselined:
                print(f"{f.render()}  [baselined]")
        if result.stale_baseline and args.diff is None:
            # a --diff view can't judge staleness: entries for
            # unchanged files vanish from the restricted finding set
            print(
                f"graftlint: {len(result.stale_baseline)} stale baseline "
                "entr(ies) no longer match — prune with --write-baseline"
            )
        if args.stats:
            print(_stats_table(result))
        print(
            f"graftlint: {len(result.new)} new, "
            f"{len(result.baselined)} baselined, "
            f"{result.suppressed} suppressed "
            f"(jax {installed_jax_version()})"
        )

    if args.check:
        return 0 if result.ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
