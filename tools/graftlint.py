#!/usr/bin/env python
"""graftlint CLI — JAX-aware static analysis for hydragnn_tpu.

Usage:
    python tools/graftlint.py [paths...] [options]

Options:
    --check            gate mode: exit 1 when any NEW finding exists
                       (not suppressed, not in the baseline); exit 0
                       otherwise. Stale baseline entries are reported
                       but do not fail the gate.
    --baseline PATH    baseline file (default tools/graftlint_baseline.json;
                       pass --baseline '' to disable baselining)
    --write-baseline   rewrite the baseline to exactly the current
                       finding set (prunes stale entries), then exit 0
    --json             machine-readable output (findings + summary)
    --rules r1,r2      run only the named rules
    --list-rules       print the rule catalog and exit

Exit codes: 0 clean (or informational mode), 1 new findings under
--check, 2 usage / internal error.

Paths default to the package + examples + tests/inputs +
__graft_entry__.py (see hydragnn_tpu.analysis.DEFAULT_PATHS). The repo
root is located from this script's own path, so the CLI works from any
cwd.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools", "graftlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--rules", default=None)
    ap.add_argument("--list-rules", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    baseline = args.baseline or None
    if args.write_baseline:
        # validate BEFORE the (multi-second) lint run
        if not baseline:
            print("graftlint: --write-baseline needs a --baseline path",
                  file=sys.stderr)
            return 2
        if args.paths or args.rules:
            # a restricted run sees only a subset of findings; writing
            # it would silently drop every grandfathered entry outside
            # the restriction
            print(
                "graftlint: --write-baseline requires a full default-"
                "scope run (no explicit paths, no --rules)",
                file=sys.stderr,
            )
            return 2

    try:
        from hydragnn_tpu.analysis import (
            rules_by_name, run_lint, write_baseline,
        )
        from hydragnn_tpu.analysis.rules import all_rules

        if args.list_rules:
            for r in all_rules():
                print(f"{r.name:14s} {r.description}")
            return 0

        rules = (
            rules_by_name(args.rules.split(",")) if args.rules else None
        )
        result = run_lint(
            _REPO_ROOT,
            paths=args.paths or None,
            rules=rules,
            baseline_path=None if args.write_baseline else baseline,
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal error must not masquerade as clean
        import traceback

        traceback.print_exc()
        print(f"graftlint: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline, result.findings)
        print(
            f"graftlint: wrote {len(result.findings)} finding(s) to "
            f"{os.path.relpath(baseline, _REPO_ROOT)}"
        )
        return 0

    from hydragnn_tpu.analysis.rules.jax_api import installed_jax_version

    if args.as_json:
        # identity, not equality: duplicate findings share (rule, path,
        # message) but only `count` of them are baselined
        baselined_ids = {id(f) for f in result.baselined}
        print(json.dumps({
            "jax_version": installed_jax_version(),
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "line": f.line,
                    "message": f.message,
                    "fingerprint": f.fingerprint,
                    "baselined": id(f) in baselined_ids,
                }
                for f in result.findings
            ],
            "new": len(result.new),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed,
            "stale_baseline": sorted(result.stale_baseline),
            "ok": result.ok,
        }, indent=2))
    else:
        for f in result.new:
            print(f.render())
        if result.baselined and not args.check:
            for f in result.baselined:
                print(f"{f.render()}  [baselined]")
        if result.stale_baseline:
            print(
                f"graftlint: {len(result.stale_baseline)} stale baseline "
                "entr(ies) no longer match — prune with --write-baseline"
            )
        print(
            f"graftlint: {len(result.new)} new, "
            f"{len(result.baselined)} baselined, "
            f"{result.suppressed} suppressed "
            f"(jax {installed_jax_version()})"
        )

    if args.check:
        return 0 if result.ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
