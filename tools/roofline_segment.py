#!/usr/bin/env python
"""Roofline measurement for the message-passing aggregation hot op.

Compares, at QM9- and OC20-scale batch shapes, bf16 and f32:

  xla_reduce      out[n] = sum_{e: rcv[e]=n} msg[e]        (XLA scatter)
  pallas_reduce   same, via the sorted-block one-hot MXU kernel
  xla_pipeline    out = segment_sum(x[snd] * filt, rcv)    (full edge op)
  pallas_pipeline gather+mul by XLA, reduce by the Pallas kernel

and reports achieved HBM bandwidth against the chip's peak — the op is
memory-bound, so %peak IS the utilization measure (MXU FLOPs are
irrelevant here; see docs/ROOFLINE.md for the written finding).

Run on the real chip:  python tools/roofline_segment.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")

# Peak HBM bandwidth by device_kind (public specs, bytes/sec).
PEAK_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}

SHAPES = {
    # name: (num_nodes, num_edges, feature_dim)
    "qm9_b128": (4224, 33792, 128),
    "oc20_b32": (8192, 327680, 256),
}

# HYDRAGNN_ROOFLINE_SHAPES=small: tiny shapes for validating the tool
# itself (e.g. CPU interpret mode) — numbers are meaningless there.
_shapes_env = os.environ.get("HYDRAGNN_ROOFLINE_SHAPES")
if _shapes_env == "small":
    SHAPES = {"tiny": (512, 4096, 64)}
elif _shapes_env:
    raise SystemExit(
        f"HYDRAGNN_ROOFLINE_SHAPES={_shapes_env!r} not recognized "
        "(only 'small'); unset it for the full-scale shapes"
    )


def _graph(n, e, seed=0):
    rng = np.random.default_rng(seed)
    rcv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    snd = rng.integers(0, n, e).astype(np.int32)
    return snd, rcv


def _time(fn, *args, iters=30):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.ops.pallas_segment import SortedSegmentPlan

    kind = jax.devices()[0].device_kind
    peak = PEAK_BW.get(kind)
    print(f"device: {kind}  peak HBM: {peak/1e9 if peak else '?'} GB/s")
    results = {}
    for name, (n, e, f) in SHAPES.items():
        snd, rcv = _graph(n, e)
        for dtype in (jnp.bfloat16, jnp.float32):
            sz = dtype.dtype.itemsize if hasattr(dtype, "dtype") else np.dtype(dtype).itemsize
            rng = np.random.default_rng(1)
            msg = jnp.asarray(rng.normal(size=(e, f)), dtype)
            x = jnp.asarray(rng.normal(size=(n, f)), dtype)
            filt = jnp.asarray(rng.normal(size=(e, f)), dtype)
            rcv_d = jnp.asarray(rcv)
            snd_d = jnp.asarray(snd)
            plan = SortedSegmentPlan(rcv, n)

            xla_reduce = jax.jit(
                lambda m: jax.ops.segment_sum(m, rcv_d, num_segments=n)
            )
            pallas_reduce = jax.jit(lambda m: plan(m))
            xla_pipe = jax.jit(
                lambda xx, ff: jax.ops.segment_sum(
                    xx[snd_d] * ff, rcv_d, num_segments=n
                )
            )
            pallas_pipe = jax.jit(lambda xx, ff: plan(xx[snd_d] * ff))
            # multiply inside the reduce kernel; both permuted operands
            # still materialize outside it, so this row DECIDES whether
            # in-kernel multiply wins over XLA fusing the multiply into
            # the plan gather (docs/ROOFLINE.md)
            pallas_fused = jax.jit(
                lambda xx, ff: plan.reduce_product(xx[snd_d], ff)
            )

            # Correctness cross-check (f32 exact-ish).
            ref = np.asarray(xla_pipe(x, filt), np.float32)
            got = np.asarray(pallas_pipe(x, filt), np.float32)
            err = np.abs(ref - got).max() / max(np.abs(ref).max(), 1e-6)
            assert err < (2e-2 if dtype == jnp.bfloat16 else 1e-5), err
            got_f = np.asarray(pallas_fused(x, filt), np.float32)
            err_f = np.abs(ref - got_f).max() / max(np.abs(ref).max(), 1e-6)
            assert err_f < (2e-2 if dtype == jnp.bfloat16 else 1e-5), err_f

            rows = {}
            reduce_bytes = (e * f + n * f) * sz
            pipe_bytes = (2 * e * f + n * f + e * f) * sz  # gather read,
            # filt read, msg materialize/stream, out write (upper bound
            # assumes the gather+mul fuses into one stream)
            for label, fn, args, bts in (
                ("xla_reduce", xla_reduce, (msg,), reduce_bytes),
                ("pallas_reduce", pallas_reduce, (msg,), reduce_bytes),
                ("xla_pipeline", xla_pipe, (x, filt), pipe_bytes),
                ("pallas_pipeline", pallas_pipe, (x, filt), pipe_bytes),
                ("pallas_fused", pallas_fused, (x, filt), pipe_bytes),
            ):
                dt = _time(fn, *args)
                bw = bts / dt
                rows[label] = (dt, bw)
                pct = f"{100*bw/peak:.0f}%" if peak else "n/a"
                print(
                    f"{name:10s} {np.dtype(dtype).name:8s} {label:16s} "
                    f"{dt*1e6:8.1f} us  {bw/1e9:7.1f} GB/s  ({pct} peak)"
                )
            results[(name, np.dtype(dtype).name)] = rows
            r = rows
            print(
                f"{name:10s} {np.dtype(dtype).name:8s} "
                f"pallas/xla reduce: {r['xla_reduce'][0]/r['pallas_reduce'][0]:.2f}x   "
                f"pipeline: {r['xla_pipeline'][0]/r['pallas_pipeline'][0]:.2f}x   "
                f"fused: {r['xla_pipeline'][0]/r['pallas_fused'][0]:.2f}x"
            )
    return results


if __name__ == "__main__":
    main()
