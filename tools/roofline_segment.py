#!/usr/bin/env python
"""Roofline measurement + crossover-table generator for the
message-passing edge pipeline.

Measures, over a SHAPE GRID covering the packed-budget classes
(zinc / qm9 / oc20 scales x feature width), bf16 and f32:

  xla_reduce            out[n] = sum_{rcv[e]=n} msg[e]     (XLA scatter)
  pallas_reduce         same, via the planned one-hot MXU kernel
                        (plan gather in-kernel)
  xla_pipeline          segment_sum(x[snd] * filt)         (XLA fusion)
  pallas_pipeline       XLA gather+multiply, planned Pallas reduce
  pallas_fused          gather AND multiply inside the kernel
  xla_pipeline_w        segment_sum(x[snd] * filt) @ W     (full edge op)
  pallas_pipeline_w     unfused planned reduce, then @ W   (full edge op)
  pallas_fused_pipeline gather+multiply+matmul+reduce in ONE pass
                        (ops/pallas_segment.edge_pipeline_planned)
  xla_bwd               the XLA pullback of the full edge op (gathers
                        g[seg], RE-MATERIALIZES the [E, F] message for
                        d_w, scatters d_h back)
  pallas_fused_bwd      the symmetric one-pass Pallas pullback
                        (edge_pipeline_bwd_planned): cotangent gather
                        as a window-tile read, message recomputed in
                        VMEM, d_a/d_b at aligned tiles

Each shape also prints MODELED bwd bytes (modeled_pipeline_bwd_traffic)
with the message-rematerialization and slot-cotangent terms broken out
— the fused column shows both terms at exactly 0 (they never touch
HBM); that is the traffic the symmetric backward exists to delete.

and reports achieved HBM bandwidth against the chip's peak — the
reduce-only rows are memory-bound so %peak IS their utilization
measure; the `_w` rows add real MXU flops per HBM byte, which is the
arithmetic-intensity raise `graftboard roofline` attributes
(docs/ROOFLINE.md).

Run on the real chip:   python tools/roofline_segment.py
Refresh the dispatch table (tools/segment_crossover.json):
                        python tools/roofline_segment.py --write-table

Table refresh MERGES by (num_edges, num_segments, feature_dim): rows
measured on a TPU get ``planned_measured``/``fused_measured``/
``bwd_measured`` = true and become dispatch verdicts; rows produced
off-TPU are labeled WHAT-IF (``*_measured`` = false) and are NEVER
dispatched on (graftboard's no-fabrication rule) — the checked-in
seed therefore stays the CPU/CI fallback with only the
ROOFLINE_TPU.txt-measured planned anchors active. After a write the
in-process table cache is invalidated (reload_crossover_table), so a
refreshed table takes effect without a process restart.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Peak HBM bandwidth by device_kind (public specs, bytes/sec).
PEAK_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}

# Shape grid: the packed-budget classes x feature width. num_filters
# for zinc/qm9-class models is 64-128; oc20-class runs wider. The
# anchors (qm9_b128_f128, oc20_b32_f256) coincide with the
# ROOFLINE_TPU.txt round-3 measured shapes so the historical planned
# verdicts stay attached to real rows.
SHAPES = {
    # name: (num_nodes, num_edges, feature_dim)
    "zinc_b64_f64": (1408, 3456, 64),
    "zinc_b64_f128": (1408, 3456, 128),
    "qm9_b128_f64": (4224, 33792, 64),
    "qm9_b128_f128": (4224, 33792, 128),
    "qm9_b128_f256": (4224, 33792, 256),
    "oc20_b32_f128": (8192, 327680, 128),
    "oc20_b32_f256": (8192, 327680, 256),
}

# HYDRAGNN_ROOFLINE_SHAPES=small: tiny shapes for validating the tool
# itself (e.g. CPU interpret mode) — numbers are meaningless there.
_shapes_env = os.environ.get("HYDRAGNN_ROOFLINE_SHAPES")
if _shapes_env == "small":
    SHAPES = {"tiny_f64": (512, 4096, 64)}
elif _shapes_env:
    raise SystemExit(
        f"HYDRAGNN_ROOFLINE_SHAPES={_shapes_env!r} not recognized "
        "(only 'small'); unset it for the full-scale shapes"
    )


def _graph(n, e, seed=0):
    rng = np.random.default_rng(seed)
    rcv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    snd = rng.integers(0, n, e).astype(np.int32)
    return snd, rcv


def _time(fn, *args, iters=30):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def measure():
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.ops.pallas_segment import (
        SortedSegmentPlan,
        _edge_pipeline_bwd_xla,
        edge_pipeline_bwd_planned,
        modeled_pipeline_bwd_traffic,
    )

    kind = jax.devices()[0].device_kind
    peak = PEAK_BW.get(kind)
    print(f"device: {kind}  peak HBM: {peak/1e9 if peak else '?'} GB/s")
    results = {}
    for name, (n, e, f) in SHAPES.items():
        snd, rcv = _graph(n, e)
        for dtype in (jnp.bfloat16, jnp.float32):
            sz = np.dtype(dtype).itemsize
            rng = np.random.default_rng(1)
            msg = jnp.asarray(rng.normal(size=(e, f)), dtype)
            x = jnp.asarray(rng.normal(size=(n, f)), dtype)
            filt = jnp.asarray(rng.normal(size=(e, f)), dtype)
            # The dense weight stays f32 (master-weight discipline);
            # under bf16 the MXU rounds it per pass exactly like the
            # model's Dense layers.
            wmat = jnp.asarray(rng.normal(size=(f, f)), jnp.float32)
            rcv_d = jnp.asarray(rcv)
            snd_d = jnp.asarray(snd)
            plan = SortedSegmentPlan(rcv, n)

            xla_reduce = jax.jit(
                lambda m: jax.ops.segment_sum(m, rcv_d, num_segments=n)
            )
            pallas_reduce = jax.jit(lambda m: plan(m))
            xla_pipe = jax.jit(
                lambda xx, ff: jax.ops.segment_sum(
                    xx[snd_d] * ff, rcv_d, num_segments=n
                )
            )
            pallas_pipe = jax.jit(lambda xx, ff: plan(xx[snd_d] * ff))
            # gather + multiply inside the reduce kernel (one HBM pass
            # over aligned plan tiles)
            pallas_fused = jax.jit(
                lambda xx, ff: plan.reduce_product(xx[snd_d], ff)
            )
            # the FULL edge op: + the dense matmul. BOTH unfused
            # comparators must include @W — comparing the fused
            # full-op time against a matmul-less row would bias the
            # verdict against the kernel this tool exists to judge.
            xla_pipe_w = jax.jit(
                lambda xx, ff: jax.ops.segment_sum(
                    xx[snd_d] * ff, rcv_d, num_segments=n
                )
                @ wmat
            )
            pallas_pipe_w = jax.jit(
                lambda xx, ff: plan(xx[snd_d] * ff) @ wmat
            )
            pallas_fused_pipe = jax.jit(
                lambda xx, ff: plan.pipeline(xx[snd_d], ff, wmat)
            )

            # Correctness cross-check (documented ulp tolerances:
            # tests/test_pallas_segment.py is the gate; this is a
            # tool-level sanity net).
            ref = np.asarray(xla_pipe(x, filt), np.float32)
            for fn_, nm in ((pallas_pipe, "pipe"), (pallas_fused, "fused")):
                got = np.asarray(fn_(x, filt), np.float32)
                err = np.abs(ref - got).max() / max(np.abs(ref).max(), 1e-6)
                assert err < (2e-2 if dtype == jnp.bfloat16 else 1e-5), (nm, err)
            ref_w = np.asarray(xla_pipe_w(x, filt), np.float32)
            got_w = np.asarray(pallas_fused_pipe(x, filt), np.float32)
            err_w = np.abs(ref_w - got_w).max() / max(np.abs(ref_w).max(), 1e-6)
            assert err_w < (3e-2 if dtype == jnp.bfloat16 else 1e-4), err_w

            # BACKWARD of the full edge op: both pullbacks run over the
            # SAME residuals the vjp holds (the gathered edge operand,
            # the filter, the f32 weight) and the same cotangent.
            a_edge = jax.jit(lambda xx: xx[snd_d])(x)
            gvec = jnp.asarray(
                rng.normal(size=(n, f)),
                jnp.promote_types(dtype, jnp.float32),
            )
            pargs = (plan.perm, plan.seg_padded, plan.valid)
            xla_bwd = jax.jit(
                lambda gg: _edge_pipeline_bwd_xla(
                    a_edge, filt, wmat, *pargs, gg
                )
            )
            pallas_bwd = jax.jit(
                lambda gg: edge_pipeline_bwd_planned(
                    gg, a_edge, filt, wmat, *pargs, plan.window_id, n
                )
            )
            ref_g = [np.asarray(t, np.float32) for t in xla_bwd(gvec)]
            got_g = [np.asarray(t, np.float32) for t in pallas_bwd(gvec)]
            for rg, gg in zip(ref_g, got_g):
                err_b = np.abs(rg - gg).max() / max(np.abs(rg).max(), 1e-6)
                assert err_b < (3e-2 if dtype == jnp.bfloat16 else 1e-4), err_b

            mb_u = modeled_pipeline_bwd_traffic(
                e, n, f, f, fused=False, dtype_bytes=sz
            )
            mb_f = modeled_pipeline_bwd_traffic(
                e, n, f, f, fused=True, dtype_bytes=sz
            )
            print(
                f"{name:14s} {np.dtype(dtype).name:8s} bwd modeled bytes: "
                f"unfused {mb_u['hbm_bytes']/1e6:7.1f} MB "
                f"(msg_remat {mb_u['msg_remat_bytes']/1e6:.1f} MB, "
                f"slot_ct {mb_u['slot_ct_bytes']/1e6:.1f} MB) -> "
                f"fused {mb_f['hbm_bytes']/1e6:7.1f} MB "
                f"(msg_remat {mb_f['msg_remat_bytes']/1e6:.1f} MB, "
                f"slot_ct {mb_f['slot_ct_bytes']/1e6:.1f} MB)"
            )

            rows = {}
            reduce_bytes = (e * f + n * f) * sz
            pipe_bytes = (2 * e * f + n * f + e * f) * sz  # gather read,
            # filt read, msg materialize/stream, out write (upper bound
            # assumes the gather+mul fuses into one stream)
            pipe_w_bytes = pipe_bytes + (f * f + n * f) * sz
            for label, fn, args, bts in (
                ("xla_reduce", xla_reduce, (msg,), reduce_bytes),
                ("pallas_reduce", pallas_reduce, (msg,), reduce_bytes),
                ("xla_pipeline", xla_pipe, (x, filt), pipe_bytes),
                ("pallas_pipeline", pallas_pipe, (x, filt), pipe_bytes),
                ("pallas_fused", pallas_fused, (x, filt), pipe_bytes),
                ("xla_pipeline_w", xla_pipe_w, (x, filt), pipe_w_bytes),
                ("pallas_pipeline_w", pallas_pipe_w, (x, filt), pipe_w_bytes),
                (
                    "pallas_fused_pipeline",
                    pallas_fused_pipe,
                    (x, filt),
                    pipe_w_bytes,
                ),
                ("xla_bwd", xla_bwd, (gvec,), mb_u["hbm_bytes"]),
                ("pallas_fused_bwd", pallas_bwd, (gvec,), mb_f["hbm_bytes"]),
            ):
                dt = _time(fn, *args)
                bw = bts / dt
                rows[label] = (dt, bw)
                pct = f"{100*bw/peak:.0f}%" if peak else "n/a"
                print(
                    f"{name:14s} {np.dtype(dtype).name:8s} {label:22s} "
                    f"{dt*1e6:8.1f} us  {bw/1e9:7.1f} GB/s  ({pct} peak)"
                )
            results[(name, np.dtype(dtype).name)] = rows
            r = rows
            print(
                f"{name:14s} {np.dtype(dtype).name:8s} "
                f"pallas/xla reduce: {r['xla_reduce'][0]/r['pallas_reduce'][0]:.2f}x   "
                f"pipeline: {r['xla_pipeline'][0]/r['pallas_pipeline'][0]:.2f}x   "
                f"fused: {r['xla_pipeline'][0]/r['pallas_fused'][0]:.2f}x   "
                f"fused_w: {r['xla_pipeline_w'][0]/r['pallas_fused_pipeline'][0]:.2f}x   "
                f"bwd: {r['xla_bwd'][0]/r['pallas_fused_bwd'][0]:.2f}x"
            )
    return results


def default_table_path():
    from hydragnn_tpu.ops.pallas_segment import crossover_table_path

    return crossover_table_path()


def build_rows(results, device_kind: str, measured: bool):
    """Verdict rows from the bf16 measurements (the production
    precision): planned verdict from the unfused pipeline pair, fused
    verdict = the one-pass kernel beats the BEST unfused full-op path,
    bwd verdict = the symmetric pullback beats the XLA pullback."""
    rows = []
    for (name, dtname), r in results.items():
        if dtname != "bfloat16":
            continue
        n, e, f = SHAPES[name]
        planned_ratio = r["xla_pipeline"][0] / r["pallas_pipeline"][0]
        # fused verdict: the one-pass kernel vs the best UNFUSED
        # full-op path (both comparators include the dense matmul)
        best_unfused_w = min(
            r["xla_pipeline_w"][0], r["pallas_pipeline_w"][0]
        )
        fused_ratio = best_unfused_w / r["pallas_fused_pipeline"][0]
        bwd_ratio = r["xla_bwd"][0] / r["pallas_fused_bwd"][0]
        rows.append(
            {
                "name": name,
                "num_edges": int(e),
                "num_segments": int(n),
                "feature_dim": int(f),
                "planned_wins": bool(planned_ratio > 1.0),
                "planned_measured": bool(measured),
                "planned_ratio": round(float(planned_ratio), 3),
                "fused_wins": bool(fused_ratio > 1.0),
                "fused_measured": bool(measured),
                "fused_ratio": round(float(fused_ratio), 3),
                "bwd_wins": bool(bwd_ratio > 1.0),
                "bwd_measured": bool(measured),
                "bwd_ratio": round(float(bwd_ratio), 3),
                "dtype": "bfloat16",
                "basis": (
                    f"timed on {device_kind}"
                    if measured
                    else f"WHAT-IF: timed off-TPU ({device_kind}) — "
                    "not a dispatch basis"
                ),
            }
        )
    return rows


def write_table(results, path=None):
    import jax

    path = path or default_table_path()
    kind = jax.devices()[0].device_kind
    measured = jax.devices()[0].platform == "tpu"
    new_rows = build_rows(results, kind, measured)
    doc = {"version": 1, "rows": []}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except ValueError:
            pass
    key = lambda r: (r["num_edges"], r["num_segments"], r.get("feature_dim"))  # noqa: E731
    merged = {key(r): r for r in doc.get("rows", [])}
    for r in new_rows:
        old = merged.get(key(r))
        if old and not measured and (
            old.get("planned_measured")
            or old.get("fused_measured")
            or old.get("bwd_measured")
        ):
            # never downgrade a measured row with a WHAT-IF re-run
            continue
        merged[key(r)] = r
    doc.update(
        version=1,
        generated_by="tools/roofline_segment.py --write-table",
        device_kind=kind,
        what_if_note=(
            "rows with *_measured=false are WHAT-IF (modeled or timed "
            "off-TPU) and are never used for dispatch "
            "(ops/pallas_segment._measured_verdicts)"
        ),
        rows=sorted(
            merged.values(),
            key=lambda r: (r["num_edges"], r["num_segments"]),
        ),
    )
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    # The dispatch table is cached per path in-process; a regenerated
    # table must take effect immediately (e.g. measure -> write -> run
    # in one process), not at the next interpreter start.
    from hydragnn_tpu.ops.pallas_segment import reload_crossover_table

    reload_crossover_table(path)
    print(f"wrote {len(doc['rows'])} rows -> {path} (measured={measured})")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--write-table",
        action="store_true",
        help="merge verdict rows into tools/segment_crossover.json",
    )
    ap.add_argument("--table", default=None, help="table path override")
    args = ap.parse_args(argv)
    results = measure()
    if args.write_table:
        write_table(results, args.table)
    return results


if __name__ == "__main__":
    main()
