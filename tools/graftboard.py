#!/usr/bin/env python
"""graftboard — render a run report from a telemetry JSONL stream.

Stdlib-only companion CLI to the run-telemetry subsystem
(hydragnn_tpu/utils/telemetry.py, docs/OBSERVABILITY.md): reads the
structured step stream a training run emitted (plus, when present, the
tracer timing CSVs next to it) and renders what the ROADMAP's perf work
needs to see — step-time composition (input-wait / host-dispatch /
sampled device-complete), per-spec live MFU against the roofline peak,
the recompile log with retrace-leak flags, pipeline starvation, and the
checkpoint writer's cost rows.

Usage:
    graftboard.py report <run>   [--json] [--csv PATH]
    graftboard.py roofline <run> [--json]
    graftboard.py diff <runA> <runB> [--json]
    graftboard.py fleet <run>    [--json]

``<run>`` is a ``telemetry.jsonl`` path or a run directory containing
one (e.g. ``logs/<log_name>``). ``diff`` renders an A/B comparison of
two runs (throughput, MFU, phase shares, recompiles) — the harness for
"did the optimization work" questions.

``fleet`` (ISSUE 14, docs/OBSERVABILITY.md "Fleet observability")
merges one run's per-process shards (``telemetry.jsonl`` +
``telemetry.proc<i>.jsonl``) and renders what single-stream reports
cannot see: per-process step-time skew per epoch, per-site
barrier-wait decomposition naming the LAST ARRIVER (the process its
peers waited on — identified by minimum ``barrier_ms``, which needs no
cross-host clock), a straggler verdict per epoch, and dead/stalled
process detection from heartbeat gaps. Partial fleets degrade LOUDLY:
a missing shard, a shard with no close row (killed process) or a
truncated tail each produce a warning in the report, never a crash.

``roofline`` renders the per-spec attribution table (ISSUE 8): analytic
vs counted flops, HBM bytes, arithmetic intensity, the roofline
ceiling ``min(peak_flops, intensity * peak_bw)``, the fraction of that
ceiling achieved, and a memory-bound / compute-bound verdict — the
measurement frame the bf16 + fused-Pallas work is judged in
(ROADMAP "Attack single-digit MFU"). Everything comes from the
stream's own emitted fields (``executable`` + ``spec_rollup`` rows and
the header's peak basis); a spec with no executable row renders with
no verdict — the tool never fabricates a bound-ness claim. When the
peak basis is ``roofline_anchor`` (CPU-captured streams) the table is
labeled a what-if on the anchor chip.

Robust parsing: a SIGKILL mid-write leaves at most one truncated tail
line (the stream writer appends whole lines); unparseable lines are
SKIPPED and counted (``skipped_lines``), never fatal — a killed run's
stream must still render.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

STREAM_NAME = "telemetry.jsonl"


# ----------------------------------------------------------------------
# Parsing
# ----------------------------------------------------------------------


def resolve_stream(path: str) -> str:
    if os.path.isdir(path):
        cand = os.path.join(path, STREAM_NAME)
        if os.path.exists(cand):
            return cand
        raise FileNotFoundError(
            f"{path} has no {STREAM_NAME} — was the run started with "
            "Training.Telemetry.enabled?"
        )
    return path


def read_stream(path: str) -> Tuple[List[dict], int]:
    """(rows, skipped_lines). Unparseable lines — the truncated tail a
    kill leaves, stray text — are skipped and counted, never fatal."""
    rows: List[dict] = []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(row, dict):
                rows.append(row)
            else:
                skipped += 1
    return rows, skipped


def _health_summary(health: List[dict], checkpoints: List[dict]) -> dict:
    """Aggregate the divergence guard's ``health`` rows
    (docs/OBSERVABILITY.md schema) into the numbers the report/diff
    sections render: skip/rollback/halt counts, the grad-norm envelope,
    bad-step provenance, and the writer's rejected (non-finite) saves.
    Empty rows → an all-zero summary so ``diff`` can compare runs with
    and without the guard.

    Rows are CUMULATIVE within an epoch (the monitor resets its
    grad-norm/bad-step accounting at epoch start, and an escalation
    row duplicates the epoch row's running stats), so the grad-norm
    envelope takes ONE row per epoch — the one with the most resolved
    samples — and combines across epochs; summing every row would
    double-count each escalated epoch. Bad steps are epoch-LOCAL
    indices in the rows, so they are summarized as ``[epoch, step]``
    pairs — e0:s3 and e1:s3 are different skipped batches, and
    ``diff`` must see them differ."""
    bad_steps = set()
    actions = {"epoch": 0, "rollback": 0, "halt": 0}
    fault_plans = set()
    skipped_total = rollbacks = 0
    per_epoch_gn: Dict[int, dict] = {}
    for r in health:
        ep = int(r.get("epoch", 0))
        actions[r.get("action", "epoch")] = (
            actions.get(r.get("action", "epoch"), 0) + 1
        )
        for b in r.get("bad_steps") or []:
            bad_steps.add((ep, int(b)))
        skipped_total = max(skipped_total, int(r.get("skipped_total", 0)))
        rollbacks = max(rollbacks, int(r.get("rollbacks", 0)))
        if r.get("gnorm_steps"):
            prev = per_epoch_gn.get(ep)
            if prev is None or int(r["gnorm_steps"]) >= int(
                prev["gnorm_steps"]
            ):
                per_epoch_gn[ep] = r
        if r.get("fault_plan"):
            fault_plans.add(r["fault_plan"])
    gn_min = gn_max = None
    gn_sum = 0.0
    gn_steps = 0
    for r in per_epoch_gn.values():
        n = int(r["gnorm_steps"])
        gn_steps += n
        gn_sum += float(r.get("gnorm_mean", 0.0)) * n
        lo, hi = r.get("gnorm_min"), r.get("gnorm_max")
        if lo is not None:
            gn_min = lo if gn_min is None else min(gn_min, lo)
        if hi is not None:
            gn_max = hi if gn_max is None else max(gn_max, hi)
    rejected = sum(
        1 for r in checkpoints if r.get("event") == "rejected"
    )
    return {
        "rows": len(health),
        "skipped_total": skipped_total,
        "bad_steps": [list(p) for p in sorted(bad_steps)],
        "rollbacks": rollbacks,
        "halts": actions.get("halt", 0),
        "rejected_saves": rejected,
        "gnorm_min": gn_min,
        "gnorm_max": gn_max,
        "gnorm_mean": (gn_sum / gn_steps) if gn_steps else None,
        "gnorm_steps": gn_steps,
        "fault_plans": sorted(fault_plans),
    }


def _serve_summary(serve: List[dict], rollups: List[dict]) -> dict:
    """Aggregate the serving rows (docs/SERVING.md "Telemetry"): the
    LAST ``serve_rollup`` carries the run's p50/p99/slot-waste
    headline; the per-bin ``serve`` rows contribute the per-spec
    dispatch breakdown and the queue-depth envelope. Empty rows → an
    all-empty summary so ``report`` on a pure-training stream renders
    no serving section."""
    per_spec: Dict[str, dict] = {}
    depth_max = 0
    for r in serve:
        spec = r.get("spec", "?")
        agg = per_spec.setdefault(
            spec,
            {
                "dispatches": 0,
                "graphs": 0,
                "nodes": 0,
                "edges": 0,
                "reasons": {},
            },
        )
        agg["dispatches"] += 1
        agg["graphs"] += int(r.get("graphs", 0))
        agg["nodes"] += int(r.get("nodes", 0))
        agg["edges"] += int(r.get("edges", 0))
        reason = r.get("reason", "?")
        agg["reasons"][reason] = agg["reasons"].get(reason, 0) + 1
        depth_max = max(depth_max, int(r.get("queue_depth", 0) or 0))
    return {
        "bins": len(serve),
        "queue_depth_max": depth_max,
        "per_spec": per_spec,
        "rollup": rollups[-1] if rollups else None,
    }


def _rollout_summary(rollout: List[dict], events: List[dict]) -> dict:
    """Aggregate the MD ``rollout`` rows (docs/SIMULATION.md,
    docs/OBSERVABILITY.md schema): committed steps, macro dispatches,
    rebuild totals, containment events (overflow / non-finite / policy
    actions), the energy-drift envelope and the throughput headline.
    Empty rows → an all-zero summary so ``report`` on a pure-training
    stream renders no simulation section."""
    actions = {}
    for e in events:
        a = e.get("action", "?")
        actions[a] = actions.get(a, 0) + 1
    last = rollout[-1] if rollout else {}
    drift_max = 0.0
    overflow_events = nonfinite_events = 0
    per_spec: Dict[str, int] = {}
    for r in rollout:
        drift_max = max(drift_max, abs(float(r.get("drift", 0.0) or 0.0)))
        if int(r.get("overflow", 0) or 0) > 0:
            overflow_events += 1
        if r.get("nonfinite"):
            nonfinite_events += 1
        spec = r.get("spec", "?")
        per_spec[spec] = per_spec.get(spec, 0) + 1
    return {
        "macros": len(rollout),
        "steps": int(last.get("step", 0) or 0),
        "k": last.get("k"),
        "dt": last.get("dt"),
        "rebuilds": int(last.get("rebuilds", 0) or 0),
        "overflow_events": overflow_events,
        "nonfinite_events": nonfinite_events,
        "actions": actions,
        "halts": actions.get("halt", 0),
        "drift_last": last.get("drift"),
        "drift_max": drift_max,
        "steps_per_sec": last.get("steps_per_sec"),
        "ns_per_day": last.get("ns_per_day"),
        "per_spec": per_spec,
    }


def build_report(path: str) -> dict:
    """Aggregate a stream into the report dict ``render_report`` prints
    (and tests/the telemetry_smoke entry leg assert on)."""
    path = resolve_stream(path)
    rows, skipped = read_stream(path)
    return _report_from_rows(path, rows, skipped)


def _report_from_rows(path: str, rows: List[dict], skipped: int) -> dict:
    """The aggregation core of ``build_report``, factored so ``fleet``
    can reuse it on shards it already read (one pass per shard)."""
    header = next((r for r in rows if r.get("t") == "header"), {})
    close = next((r for r in rows if r.get("t") == "close"), None)

    epochs = [r for r in rows if r.get("t") == "epoch"]
    epochs.sort(key=lambda r: r.get("epoch", 0))

    # Step-time breakdown per (region, feed, scheme, spec).
    breakdown: Dict[tuple, dict] = {}
    for r in rows:
        if r.get("t") != "step":
            continue
        key = (
            r.get("region", "?"),
            r.get("feed", "?"),
            r.get("scheme", "?"),
            r.get("spec", "?"),
        )
        agg = breakdown.setdefault(
            key,
            {
                "dispatches": 0,
                "steps": 0,
                "input_wait_ms": 0.0,
                "dispatch_ms": 0.0,
                "wall_ms": 0.0,
                "device_complete_ms": 0.0,
                "device_samples": 0,
                "device_sampled_steps": 0,
                "graphs": 0.0,
            },
        )
        agg["dispatches"] += 1
        agg["steps"] += int(r.get("k", 1))
        agg["input_wait_ms"] += float(r.get("input_wait_ms", 0.0))
        agg["dispatch_ms"] += float(r.get("dispatch_ms", 0.0))
        agg["wall_ms"] += float(r.get("wall_ms", 0.0))
        if "device_complete_ms" in r:
            agg["device_complete_ms"] += float(r["device_complete_ms"])
            agg["device_samples"] += 1
            # a superstep macro's fence covers k optimizer steps —
            # per-step division must use the steps the samples cover
            agg["device_sampled_steps"] += int(r.get("k", 1))
        agg["graphs"] += float(
            r.get("graphs", r.get("graphs_plan", 0.0)) or 0.0
        )

    # Per-step loss curve (ordered) — the bit-exact reconstruction
    # hook: epoch rollup losses are the loop's History floats verbatim.
    step_losses = [
        (r.get("epoch", 0), r.get("step", 0), r["loss"])
        for r in rows
        if r.get("t") == "step"
        and r.get("region") == "train"
        and "loss" in r
    ]
    step_losses.sort(key=lambda x: (x[0], x[1]))

    mfu_rows = [r for r in rows if r.get("t") == "spec_rollup"]
    executables = [r for r in rows if r.get("t") == "executable"]
    memory = [r for r in rows if r.get("t") == "memory"]
    profile = [r for r in rows if r.get("t") == "profile"]
    compiles = [r for r in rows if r.get("t") == "compile"]
    compile_summary = next(
        (r for r in rows if r.get("t") == "compile_summary"), None
    )
    post_warmup = [r for r in compiles if r.get("retrace_leak")]
    pipeline = [r for r in rows if r.get("t") == "pipeline"]
    checkpoints = [r for r in rows if r.get("t") == "checkpoint"]
    health = [r for r in rows if r.get("t") == "health"]
    serve = [r for r in rows if r.get("t") == "serve"]
    serve_rollups = [r for r in rows if r.get("t") == "serve_rollup"]
    rollout = [r for r in rows if r.get("t") == "rollout"]
    rollout_events = [r for r in rows if r.get("t") == "rollout_event"]
    barriers = [r for r in rows if r.get("t") == "barrier"]
    heartbeats = [r for r in rows if r.get("t") == "heartbeat"]

    return {
        "path": path,
        "header": header,
        "schema": header.get("schema"),
        "skipped_lines": skipped,
        "rows": len(rows),
        "epochs": epochs,
        "train_loss_by_epoch": [r.get("train_loss") for r in epochs],
        "val_loss_by_epoch": [r.get("val_loss") for r in epochs],
        "step_losses": step_losses,
        "breakdown": {
            "|".join(k): v for k, v in sorted(breakdown.items())
        },
        "mfu": mfu_rows,
        "executables": executables,
        "memory": memory,
        "profile": profile,
        "compiles": compiles,
        "compile_summary": compile_summary,
        "post_warmup_compiles": len(post_warmup),
        "retrace_leaks": post_warmup,
        "pipeline": pipeline,
        "checkpoints": checkpoints,
        "health": health,
        "health_summary": _health_summary(health, checkpoints),
        "serve": serve,
        "serve_rollups": serve_rollups,
        "serve_summary": _serve_summary(serve, serve_rollups),
        "rollout": rollout,
        "rollout_events": rollout_events,
        "rollout_summary": _rollout_summary(rollout, rollout_events),
        "barriers": barriers,
        "heartbeats": heartbeats,
        "barrier_summary": _barrier_site_summary(barriers),
        "process_index": header.get("process_index", 0),
        "drops": (close or {}).get("dropped"),
        "write_errors": (close or {}).get("write_errors"),
        "close": close,
    }


def _barrier_site_summary(barriers: List[dict]) -> dict:
    """Per-site aggregates of this stream's ``barrier`` rows — the
    single-shard view (the cross-process decomposition lives in
    ``fleet``): crossings, total/max ``wait_ms``, max ``barrier_ms``
    (rendezvous park only)."""
    sites: Dict[str, dict] = {}
    for r in barriers:
        s = sites.setdefault(
            r.get("site", "?"),
            {
                "crossings": 0,
                "wait_ms_total": 0.0,
                "wait_ms_max": 0.0,
                "barrier_ms_max": 0.0,
            },
        )
        s["crossings"] += 1
        w = float(r.get("wait_ms", 0.0) or 0.0)
        s["wait_ms_total"] = round(s["wait_ms_total"] + w, 3)
        s["wait_ms_max"] = max(s["wait_ms_max"], w)
        s["barrier_ms_max"] = max(
            s["barrier_ms_max"], float(r.get("barrier_ms", 0.0) or 0.0)
        )
    return sites


# ----------------------------------------------------------------------
# Roofline attribution
# ----------------------------------------------------------------------


def _steady_rollups(rep: dict) -> Dict[tuple, dict]:
    """Last-epoch ``spec_rollup`` row per (region, spec) — the steady
    state the roofline verdict should describe (epoch-0 rows carry the
    compile stalls)."""
    out: Dict[tuple, dict] = {}
    for r in rep["mfu"]:
        key = (r.get("region", "?"), r.get("spec", "?"))
        prev = out.get(key)
        if prev is None or r.get("epoch", 0) >= prev.get("epoch", 0):
            out[key] = r
    return out


def build_roofline(rep: dict) -> dict:
    """Per-spec roofline attribution from the stream's OWN emitted
    fields: analytic vs counted flops, bytes, intensity, the ceiling
    ``min(peak_flops, intensity * peak_bw)``, achieved fraction of it,
    and a memory-bound/compute-bound verdict. A spec whose dispatches
    have no executable attribution (capture failed, cost_analysis
    unavailable, ``Telemetry.cost_analysis: false``) gets ``verdict:
    None`` — bound-ness is never fabricated from analytic numbers."""
    header = rep["header"]
    execs_by_key: Dict[tuple, int] = {}
    for r in rep["executables"]:
        key = (r.get("region", "?"), r.get("spec", "?"))
        execs_by_key[key] = execs_by_key.get(key, 0) + 1
    specs: List[dict] = []
    for (region, spec), row in sorted(_steady_rollups(rep).items()):
        peak = row.get("peak_flops") or header.get("peak_flops")
        basis = row.get("peak_basis") or header.get("peak_basis")
        bw = row.get("peak_hbm_bytes_per_sec") or header.get(
            "peak_hbm_bytes_per_sec"
        )
        bw_basis = row.get("peak_hbm_basis") or header.get(
            "peak_hbm_basis"
        )
        wall_s = float(row.get("wall_ms") or 0.0) / 1e3
        e = {
            "region": region,
            "spec": spec,
            "epoch": row.get("epoch"),
            "steps": row.get("steps"),
            "graphs_per_sec": row.get("graphs_per_sec"),
            "model_flops_per_graph": row.get("model_flops_per_graph"),
            "mfu": row.get("mfu"),
            "hw_mfu": row.get("hw_mfu"),
            "hw_flops": row.get("hw_flops"),
            "hw_bytes_accessed": row.get("hw_bytes_accessed"),
            "hw_over_model_flops": row.get("hw_over_model_flops"),
            "intensity": row.get("intensity"),
            "hw_missing_dispatches": row.get("hw_missing_dispatches"),
            "executables": execs_by_key.get((region, spec), 0),
            "peak_flops": peak,
            "peak_basis": basis,
            "peak_hbm_bytes_per_sec": bw,
            "peak_hbm_basis": bw_basis,
            "verdict": None,
        }
        intensity = e["intensity"]
        if intensity and peak and bw:
            ridge = peak / bw  # flops/byte where the roofs intersect
            ceiling = min(peak, intensity * bw)
            e["ridge_intensity"] = ridge
            e["roofline_ceiling_flops_per_sec"] = ceiling
            if e["hw_flops"] and wall_s > 0:
                e["ceiling_frac"] = (e["hw_flops"] / wall_s) / ceiling
            e["verdict"] = (
                "memory-bound" if intensity < ridge else "compute-bound"
            )
        specs.append(e)
    hdr_keys = (
        "log_name",
        "scheme",
        "hostname",
        "jax_version",
        "device_kind",
        "platform",
        "device_count",
        "process_count",
        "peak_flops",
        "peak_basis",
        "peak_hbm_bytes_per_sec",
        "peak_hbm_basis",
    )
    return {
        "path": rep["path"],
        "header": {
            k: header.get(k) for k in hdr_keys if header.get(k) is not None
        },
        "what_if": header.get("peak_basis") == "roofline_anchor",
        "specs": specs,
        "profile": rep["profile"],
    }


def _pct(v) -> str:
    return f"{100.0 * v:.4g}%" if v is not None else "-"


def _eng(v) -> str:
    return f"{v:.3e}" if v is not None else "-"


def render_roofline(rl: dict) -> str:
    out = [f"== graftboard roofline: {rl['path']}"]
    h = rl["header"]
    out.append(
        f"device={h.get('device_kind', '-')}  "
        f"peak_flops={_eng(h.get('peak_flops'))} "
        f"({h.get('peak_basis', '-')})  "
        f"peak_hbm={_eng(h.get('peak_hbm_bytes_per_sec'))} B/s "
        f"({h.get('peak_hbm_basis', '-')})  "
        f"devices={h.get('device_count', '-')}x{h.get('platform', '-')}"
    )
    if rl["what_if"]:
        out.append(
            "NOTE: peak basis is the ROOFLINE_TPU.txt anchor chip — "
            "utilization/ceiling columns are a WHAT-IF on that chip, "
            "not a measurement of this host."
        )
    rows = []
    for e in rl["specs"]:
        rows.append(
            [
                f"{e['region']}/{e['spec']}",
                _fmt(e.get("steps"), 0),
                _eng(e.get("model_flops_per_graph")),
                _pct(e.get("mfu")),
                _pct(e.get("hw_mfu")),
                _fmt(e.get("hw_over_model_flops"), 3),
                _fmt(e.get("intensity"), 3),
                _eng(e.get("roofline_ceiling_flops_per_sec")),
                _pct(e.get("ceiling_frac")),
                e.get("verdict") or "-",
            ]
        )
    out.append(
        _table(
            [
                "region/spec",
                "steps",
                "model F/graph",
                "mfu",
                "hw_mfu",
                "hw/model",
                "F/byte",
                "ceiling F/s",
                "%ceiling",
                "verdict",
            ],
            rows,
        )
    )
    missing = [
        e for e in rl["specs"] if e["verdict"] is None
    ]
    if missing:
        out.append(
            f"({len(missing)} spec(s) without executable attribution — "
            "no verdict; enable Telemetry.cost_analysis or see "
            "exec_capture_failures in the close row)"
        )
    if rl["profile"]:
        for r in rl["profile"]:
            out.append(
                f"-- profile {r.get('event')}: epoch={r.get('epoch', '-')} "
                f"steps={r.get('steps', '-')} "
                f"trace_dir={r.get('trace_dir', '-')} "
                f"reason={r.get('reason', '-')}"
            )
    return "\n".join(out)


# ----------------------------------------------------------------------
# Rendering
# ----------------------------------------------------------------------


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    line = "  ".join(h.ljust(widths[i]) for i, h in enumerate(headers))
    out = [line, "  ".join("-" * w for w in widths)]
    for row in rows:
        out.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        )
    return "\n".join(out)


def render_report(rep: dict, csv_path: Optional[str] = None) -> str:
    out: List[str] = []
    hdr = rep["header"]
    out.append(f"== graftboard report: {rep['path']}")
    out.append(
        f"schema v{rep.get('schema')}  log={hdr.get('log_name', '-')}  "
        f"scheme={hdr.get('scheme', '-')}  rows={rep['rows']}  "
        f"skipped_lines={rep['skipped_lines']}"
    )
    if rep["drops"] is not None:
        out.append(
            f"stream accounting: dropped={rep['drops']} "
            f"write_errors={rep['write_errors']}"
        )
    if rep["epochs"]:
        out.append("")
        out.append("-- epochs")
        out.append(
            _table(
                ["epoch", "train", "val", "test", "lr", "seconds"],
                [
                    [
                        str(r.get("epoch")),
                        _fmt(r.get("train_loss"), 6),
                        _fmt(r.get("val_loss"), 6),
                        _fmt(r.get("test_loss"), 6),
                        _fmt(r.get("lr"), 6),
                        _fmt(r.get("seconds"), 2),
                    ]
                    for r in rep["epochs"]
                ],
            )
        )
    if rep["breakdown"]:
        out.append("")
        out.append(
            "-- step-time breakdown (per region|feed|scheme|spec; "
            "device-complete only on sampled fence steps)"
        )
        rows = []
        for key, agg in rep["breakdown"].items():
            wall = agg["wall_ms"] or 1.0
            dev = (
                agg["device_complete_ms"]
                / (agg.get("device_sampled_steps") or agg["device_samples"])
                if agg["device_samples"]
                else None
            )
            rows.append(
                [
                    key,
                    str(agg["steps"]),
                    str(agg["dispatches"]),
                    _fmt(agg["input_wait_ms"], 1),
                    _fmt(100.0 * agg["input_wait_ms"] / wall, 1) + "%",
                    _fmt(agg["dispatch_ms"], 1),
                    _fmt(dev, 2),
                    _fmt(agg["wall_ms"], 1),
                ]
            )
        out.append(
            _table(
                [
                    "region|feed|scheme|spec",
                    "steps",
                    "disp",
                    "wait_ms",
                    "wait%",
                    "dispatch_ms",
                    "dev_ms/step",
                    "wall_ms",
                ],
                rows,
            )
        )
    if rep["mfu"]:
        out.append("")
        out.append("-- live MFU per spec (model FLOPs x graphs/s / peak)")
        rows = []
        for r in rep["mfu"]:
            rows.append(
                [
                    f"{r.get('region')}/{r.get('epoch')}",
                    str(r.get("spec")),
                    str(r.get("steps")),
                    _fmt(r.get("graphs_per_sec"), 1),
                    _fmt(r.get("model_flops_per_graph")),
                    (
                        f"{100.0 * r['mfu']:.4g}%"
                        if r.get("mfu") is not None
                        else "-"
                    ),
                    str(r.get("peak_basis", "-")),
                ]
            )
        out.append(
            _table(
                [
                    "region/epoch",
                    "spec",
                    "steps",
                    "graphs/s",
                    "flops/graph",
                    "mfu",
                    "peak_basis",
                ],
                rows,
            )
        )
    if rep["executables"]:
        out.append("")
        out.append(
            "-- executables (XLA cost/memory accounting at first "
            "dispatch; flops/bytes are per dispatch — k steps)"
        )
        rows = []
        for r in rep["executables"]:
            rows.append(
                [
                    f"{r.get('region')}/{r.get('spec')}",
                    str(r.get("k", 1)),
                    _eng(r.get("flops")),
                    _eng(r.get("bytes_accessed")),
                    _eng(r.get("temp_bytes")),
                    _eng(r.get("argument_bytes")),
                    (
                        "ERR"
                        if r.get("capture_error")
                        else ("n/a" if r.get("cost_unavailable") else "ok")
                    ),
                ]
            )
        out.append(
            _table(
                [
                    "region/spec",
                    "k",
                    "flops",
                    "bytes",
                    "temp_B",
                    "arg_B",
                    "cost",
                ],
                rows,
            )
        )
    if rep["memory"]:
        last = rep["memory"][-1]
        peak_dev = max(
            (r.get("peak_bytes_in_use", 0) for r in rep["memory"]),
            default=0,
        )
        peak_host = max(
            (r.get("host_peak_rss_bytes", 0) for r in rep["memory"]),
            default=0,
        )
        out.append("")
        out.append(
            f"-- memory: rows={len(rep['memory'])} "
            f"peak_device_bytes={peak_dev or '-'} "
            f"peak_host_rss={peak_host or '-'} "
            f"last_tag={last.get('tag')}"
        )
    for r in rep["profile"]:
        out.append(
            f"-- profile {r.get('event')}: epoch={r.get('epoch', '-')} "
            f"steps={r.get('steps', '-')} "
            f"trace_dir={r.get('trace_dir', '-')}"
        )
    cs = rep["compile_summary"] or {}
    out.append("")
    out.append(
        f"-- compiles: total={cs.get('compile_count', len(rep['compiles']))} "
        f"({_fmt(cs.get('compile_ms'), 1)}ms)  "
        f"cache_hits={cs.get('cache_hits', '-')} "
        f"cache_misses={cs.get('cache_misses', '-')}  "
        f"POST-WARMUP={rep['post_warmup_compiles']}"
    )
    if rep["retrace_leaks"]:
        out.append("   RETRACE LEAKS (compilation after epoch 0):")
        for r in rep["retrace_leaks"]:
            out.append(
                f"     #{r.get('seq')} epoch={r.get('epoch')} "
                f"{_fmt(r.get('ms'), 1)}ms"
            )
    if rep["pipeline"]:
        last = rep["pipeline"][-1]
        out.append("")
        out.append(
            f"-- input pipeline: delivered={last.get('delivered_batches')} "
            f"starved_steps={last.get('starved_steps')} "
            f"collate_ms_avg={_fmt(last.get('collate_ms_avg'))} "
            f"h2d_ms_avg={_fmt(last.get('h2d_ms_avg'))} "
            f"queue_depth_avg={_fmt(last.get('queue_depth_avg'))}"
        )
    hs = rep.get("health_summary") or {}
    if hs.get("rows"):
        out.append("")
        out.append(
            "-- health (divergence guard): "
            f"skipped_steps={hs['skipped_total']} "
            f"rollbacks={hs['rollbacks']} halts={hs['halts']} "
            f"rejected_saves={hs['rejected_saves']}"
        )
        if hs["bad_steps"]:
            shown = [f"e{e}:s{s}" for e, s in hs["bad_steps"][:24]]
            more = len(hs["bad_steps"]) - len(shown)
            out.append(
                f"   bad optimizer steps: {shown}"
                + (f" (+{more} more)" if more > 0 else "")
            )
        if hs.get("gnorm_steps"):
            out.append(
                f"   grad-norm: min={_eng(hs['gnorm_min'])} "
                f"mean={_eng(hs['gnorm_mean'])} "
                f"max={_eng(hs['gnorm_max'])} "
                f"over {hs['gnorm_steps']} step(s)"
            )
        if hs["fault_plans"]:
            out.append(
                f"   injected fault plan(s): {hs['fault_plans']}"
            )
    ss = rep.get("serve_summary") or {}
    if ss.get("bins") or ss.get("rollup"):
        ru = ss.get("rollup") or {}
        out.append("")
        out.append(
            "-- serving (deadline-batched inference; docs/SERVING.md): "
            f"requests={ru.get('requests', '-')} "
            f"dispatches={ss.get('bins')} "
            f"shapes={ru.get('shapes', '-')} "
            f"p50={_fmt(ru.get('p50_ms'), 2)}ms "
            f"p99={_fmt(ru.get('p99_ms'), 2)}ms "
            f"graphs/s={_fmt(ru.get('graphs_per_sec'), 1)} "
            f"slot_waste={_pct(ru.get('slot_waste'))} "
            f"queue_depth_max={ss.get('queue_depth_max')}"
        )
        if ss.get("per_spec"):
            rows = []
            for spec, agg in sorted(ss["per_spec"].items()):
                g = agg["graphs"] or 1
                rows.append(
                    [
                        spec,
                        str(agg["dispatches"]),
                        str(agg["graphs"]),
                        _fmt(agg["nodes"] / g, 1),
                        _fmt(agg["edges"] / g, 1),
                        ",".join(
                            f"{k}:{v}"
                            for k, v in sorted(agg["reasons"].items())
                        ),
                    ]
                )
            out.append(
                _table(
                    [
                        "spec",
                        "disp",
                        "graphs",
                        "nodes/graph",
                        "edges/graph",
                        "dispatch reasons",
                    ],
                    rows,
                )
            )
    rls = rep.get("rollout_summary") or {}
    if rls.get("macros"):
        out.append("")
        out.append(
            "-- simulation (MD rollout; docs/SIMULATION.md): "
            f"steps={rls.get('steps')} "
            f"macros={rls.get('macros')} "
            f"k={rls.get('k', '-')} "
            f"dt={_fmt(rls.get('dt'), 6)} "
            f"rebuilds={rls.get('rebuilds')} "
            f"drift_last={_fmt(rls.get('drift_last'), 6)} "
            f"drift_max={_fmt(rls.get('drift_max'), 6)} "
            f"steps/s={_fmt(rls.get('steps_per_sec'), 1)} "
            f"ns/day={_fmt(rls.get('ns_per_day'), 4)}"
        )
        if (
            rls.get("overflow_events")
            or rls.get("nonfinite_events")
            or rls.get("actions")
        ):
            out.append(
                "   containment: "
                f"overflow_macros={rls.get('overflow_events', 0)} "
                f"nonfinite_macros={rls.get('nonfinite_events', 0)} "
                f"actions={rls.get('actions') or {}}"
            )
        if rls.get("per_spec") and len(rls["per_spec"]) > 1:
            # More than one spec means the capacity ladder re-jitted
            # mid-run — worth surfacing per spec.
            out.append(
                "   specs: "
                + ", ".join(
                    f"{k}:{v} macro(s)"
                    for k, v in sorted(rls["per_spec"].items())
                )
            )
    if rep["barrier_summary"]:
        out.append("")
        out.append(
            "-- barriers (coordination waits; wait_ms = whole "
            "crossing, barrier_ms = rendezvous park — see "
            "`fleet` for the cross-process decomposition)"
        )
        rows = [
            [
                site,
                str(s["crossings"]),
                _fmt(s["wait_ms_total"], 1),
                _fmt(s["wait_ms_max"], 1),
                _fmt(s["barrier_ms_max"], 1),
            ]
            for site, s in sorted(rep["barrier_summary"].items())
        ]
        out.append(
            _table(
                ["site", "n", "wait_ms", "max_wait", "max_barrier"],
                rows,
            )
        )
    if rep["heartbeats"]:
        hb = rep["heartbeats"]
        first, last = hb[0], hb[-1]
        out.append(
            f"-- heartbeats: {len(hb)} beat(s) over "
            f"{_fmt(float(last.get('ts', 0)) - float(first.get('ts', 0)), 1)}s"
            f"  last_phase={last.get('phase', '-')}"
            + (
                f"  waiting_on={last['waiting_on']}"
                if last.get("waiting_on")
                else ""
            )
        )
    if rep["checkpoints"]:
        saves = [
            r for r in rep["checkpoints"] if r.get("event") == "save"
        ]
        writes = [
            r for r in rep["checkpoints"] if r.get("event") == "write"
        ]
        snap = sum(float(r.get("snapshot_block_ms", 0)) for r in saves)
        wr = sum(float(r.get("serialize_write_ms", 0)) for r in writes)
        out.append(
            f"-- checkpoints: saves={len(saves)} "
            f"snapshot_block_ms_total={_fmt(snap, 2)} "
            f"serialize_write_ms_total={_fmt(wr, 2)} "
            f"failed_writes={sum(1 for r in writes if r.get('failed'))}"
        )
    if csv_path and os.path.exists(csv_path):
        out.append("")
        out.append(f"-- tracer CSV: {csv_path}")
        with open(csv_path) as f:
            for line in f.read().splitlines()[:40]:
                out.append("   " + line)
    return "\n".join(out)


# ----------------------------------------------------------------------
# Diff
# ----------------------------------------------------------------------


def build_diff(rep_a: dict, rep_b: dict) -> dict:
    def _total(rep, field):
        # TRAIN region only: eval cadence can differ between runs
        # (HYDRAGNN_TPU_VALTEST, different val sizes) — folding eval
        # wall into a "train faster" ratio is exactly the false A/B
        # signal this harness exists to prevent.
        return (
            sum(
                v[field]
                for k, v in rep["breakdown"].items()
                if k.split("|")[0] == "train"
            )
            or None
        )

    def _ratio(a, b):
        if a is None or b is None or b == 0:
            return None
        return a / b

    def _mfu_by_spec(rep):
        out = {}
        for r in rep["mfu"]:
            if r.get("region") != "train" or r.get("mfu") is None:
                continue
            # last epoch wins (steady state)
            out[r["spec"]] = r["mfu"]
        return out

    def _roofline_train(rep):
        return {
            e["spec"]: e
            for e in build_roofline(rep)["specs"]
            if e["region"] == "train"
        }

    roof_a, roof_b = _roofline_train(rep_a), _roofline_train(rep_b)

    def _delta(spec, field):
        a = roof_a.get(spec, {}).get(field)
        b = roof_b.get(spec, {}).get(field)
        return {
            "a": a,
            "b": b,
            "delta": (b - a) if a is not None and b is not None else None,
        }

    mfu_a, mfu_b = _mfu_by_spec(rep_a), _mfu_by_spec(rep_b)
    return {
        "a": rep_a["path"],
        "b": rep_b["path"],
        "train_loss_a": rep_a["train_loss_by_epoch"],
        "train_loss_b": rep_b["train_loss_by_epoch"],
        "loss_identical": (
            rep_a["train_loss_by_epoch"] == rep_b["train_loss_by_epoch"]
        ),
        "wall_ms_ratio_b_over_a": _ratio(
            _total(rep_b, "wall_ms"), _total(rep_a, "wall_ms")
        ),
        "input_wait_ratio_b_over_a": _ratio(
            _total(rep_b, "input_wait_ms"), _total(rep_a, "input_wait_ms")
        ),
        "mfu_delta_by_spec": {
            spec: {
                "a": mfu_a.get(spec),
                "b": mfu_b.get(spec),
                "delta": (
                    mfu_b[spec] - mfu_a[spec]
                    if spec in mfu_a and spec in mfu_b
                    else None
                ),
            }
            for spec in sorted(set(mfu_a) | set(mfu_b))
        },
        # Roofline movement (ISSUE 8): did the optimization change the
        # KIND of work, not just its speed? Rising intensity = fewer
        # bytes per flop (fusion working); rising ceiling fraction =
        # closer to what this intensity allows at the peak basis.
        "roofline_delta_by_spec": {
            spec: {
                "intensity": _delta(spec, "intensity"),
                "ceiling_frac": _delta(spec, "ceiling_frac"),
                "hw_mfu": _delta(spec, "hw_mfu"),
                "verdict_a": roof_a.get(spec, {}).get("verdict"),
                "verdict_b": roof_b.get(spec, {}).get("verdict"),
            }
            for spec in sorted(set(roof_a) | set(roof_b))
        },
        "post_warmup_compiles": {
            "a": rep_a["post_warmup_compiles"],
            "b": rep_b["post_warmup_compiles"],
        },
        # Coordination-wait movement (ISSUE 14): total barrier wait
        # per run — an "optimization" that moved time from steps into
        # barrier parks did not get faster, it got less observable.
        "barrier_wait_ms": {
            "a": round(
                sum(
                    s["wait_ms_total"]
                    for s in rep_a.get("barrier_summary", {}).values()
                ),
                3,
            ),
            "b": round(
                sum(
                    s["wait_ms_total"]
                    for s in rep_b.get("barrier_summary", {}).values()
                ),
                3,
            ),
        },
        "drops": {"a": rep_a["drops"], "b": rep_b["drops"]},
        # Numerical-health comparison (docs/DURABILITY.md "Divergence
        # recovery"): two runs of "the same" config whose guard
        # histories differ did NOT execute the same trajectory — a
        # skipped step, a rollback or a rejected save in exactly one
        # of them is a divergence-signature difference the wall/MFU
        # ratios above would silently absorb.
        "health": _health_diff(rep_a, rep_b),
    }


_HEALTH_DIFF_KEYS = (
    "skipped_total",
    "bad_steps",
    "rollbacks",
    "halts",
    "rejected_saves",
    "fault_plans",
)


def _health_diff(rep_a: dict, rep_b: dict) -> dict:
    a = rep_a.get("health_summary") or {}
    b = rep_b.get("health_summary") or {}
    differs = any(
        a.get(k) != b.get(k) for k in _HEALTH_DIFF_KEYS
    )
    return {
        "differs": differs,
        "a": {k: a.get(k) for k in _HEALTH_DIFF_KEYS},
        "b": {k: b.get(k) for k in _HEALTH_DIFF_KEYS},
    }


def render_diff(d: dict) -> str:
    out = [f"== graftboard diff\n   A: {d['a']}\n   B: {d['b']}"]
    out.append(
        f"loss curves identical: {d['loss_identical']}"
        + (
            ""
            if d["loss_identical"]
            else f"\n   A {d['train_loss_a']}\n   B {d['train_loss_b']}"
        )
    )
    r = d["wall_ms_ratio_b_over_a"]
    out.append(
        f"train wall (B/A): {_fmt(r, 3)}"
        + (f"  ({100 * (1 - r):+.1f}% faster B)" if r else "")
    )
    out.append(
        f"input-wait (B/A): {_fmt(d['input_wait_ratio_b_over_a'], 3)}"
    )
    if d["mfu_delta_by_spec"]:
        rows = [
            [
                spec,
                _fmt(v["a"], 5),
                _fmt(v["b"], 5),
                _fmt(v["delta"], 5),
            ]
            for spec, v in d["mfu_delta_by_spec"].items()
        ]
        out.append(_table(["spec", "mfu A", "mfu B", "delta"], rows))
    roof = {
        spec: v
        for spec, v in d.get("roofline_delta_by_spec", {}).items()
        if v["intensity"]["a"] is not None
        or v["intensity"]["b"] is not None
    }
    if roof:
        rows = [
            [
                spec,
                _fmt(v["intensity"]["a"], 3),
                _fmt(v["intensity"]["b"], 3),
                _fmt(v["intensity"]["delta"], 3),
                _pct(v["ceiling_frac"]["a"]),
                _pct(v["ceiling_frac"]["b"]),
                _fmt(v["ceiling_frac"]["delta"], 5),
                f"{v['verdict_a'] or '-'}→{v['verdict_b'] or '-'}",
            ]
            for spec, v in roof.items()
        ]
        out.append(
            _table(
                [
                    "spec",
                    "F/B A",
                    "F/B B",
                    "ΔF/B",
                    "%ceil A",
                    "%ceil B",
                    "Δceil",
                    "verdict",
                ],
                rows,
            )
        )
    pw = d["post_warmup_compiles"]
    out.append(
        f"post-warmup compiles: A={pw['a']} B={pw['b']}   "
        f"drops: A={d['drops']['a']} B={d['drops']['b']}"
    )
    bw = d.get("barrier_wait_ms") or {}
    if bw.get("a") or bw.get("b"):
        out.append(
            f"barrier wait totals: A={_fmt(bw['a'], 1)}ms "
            f"B={_fmt(bw['b'], 1)}ms"
        )
    h = d.get("health") or {}
    if h.get("differs"):
        out.append(
            "HEALTH DIVERGENCE: the runs' guard histories differ — "
            "they did not execute the same trajectory"
        )
        out.append(f"   A {h['a']}")
        out.append(f"   B {h['b']}")
    elif h:
        out.append(
            f"health: identical (skipped={h['a'].get('skipped_total')} "
            f"rollbacks={h['a'].get('rollbacks')} "
            f"rejected_saves={h['a'].get('rejected_saves')})"
        )
    return "\n".join(out)


# ----------------------------------------------------------------------
# Fleet: merged per-process shards (ISSUE 14)
# ----------------------------------------------------------------------

# Straggler thresholds (documented in docs/OBSERVABILITY.md "Straggler
# verdict"): below these floors skew is measurement noise, not a
# verdict.
_STRAGGLER_MIN_MS = 50.0
_STRAGGLER_BARRIER_FRAC = 0.05  # of the mean per-process epoch wall
_STRAGGLER_WAIT_FRAC = 0.10


def discover_shards(path: str) -> Dict[int, str]:
    """Map ``process_index -> shard path`` for one run: the base
    stream (process 0's legacy path) plus every
    ``<root>.proc<i><ext>`` sibling. Accepts a run directory, the base
    ``telemetry.jsonl`` path, or any single shard path."""
    import re

    if os.path.isdir(path):
        base = os.path.join(path, STREAM_NAME)
    else:
        base = path
    d = os.path.dirname(base) or "."
    root, ext = os.path.splitext(os.path.basename(base))
    m = re.match(r"^(.*)\.proc(\d+)$", root)
    if m:  # caller pointed at a non-0 shard: rebase on its root
        root = m.group(1)
        base = os.path.join(d, root + ext)
    shards: Dict[int, str] = {}
    if os.path.exists(base):
        shards[0] = base
    pat = re.compile(
        re.escape(root) + r"\.proc(\d+)" + re.escape(ext) + r"$"
    )
    if os.path.isdir(d):
        for f in sorted(os.listdir(d)):
            mm = pat.match(f)
            if mm:
                shards[int(mm.group(1))] = os.path.join(d, f)
    if not shards:
        raise FileNotFoundError(
            f"{path}: no telemetry shard found (expected {base} "
            f"and/or {root}.proc<i>{ext} next to it — was the run "
            "started with Training.Telemetry.enabled?)"
        )
    return dict(sorted(shards.items()))


def _zero_epoch_agg() -> dict:
    return {
        "steps": 0,
        "dispatches": 0,
        "input_wait_ms": 0.0,
        "dispatch_ms": 0.0,
        "wall_ms": 0.0,
    }


def _fleet_serving(
    rows_by_proc: Dict[int, List[dict]], heartbeats: dict
) -> Optional[dict]:
    """Merge the serving tier's per-replica shards into the fleet
    serving section (docs/SERVING.md "Fleet tier", OBSERVABILITY.md
    "Serving rows"): per-replica request/latency rollups and p99 skew,
    a queue-depth straggler verdict, shed/reroute/rollover accounting,
    and dead-replica detection cross-referenced against re-route
    coverage. None when the run has no serving rows at all (a training
    fleet renders without a serving section)."""
    per: Dict[str, dict] = {}
    sheds: Dict[str, int] = {}
    sheds_by_class: Dict[str, int] = {}
    reroutes: List[dict] = []
    rollovers = {"done": 0, "refused": 0}
    any_rows = False
    for pidx, rows in rows_by_proc.items():
        for r in rows:
            t = r.get("t")
            if t not in (
                "serve",
                "serve_rollup",
                "shed",
                "reroute",
                "rollover",
            ):
                continue
            any_rows = True
            if t == "shed":
                reason = str(r.get("reason", "?"))
                sheds[reason] = sheds.get(reason, 0) + 1
                c = str(r.get("class", "?"))
                sheds_by_class[c] = sheds_by_class.get(c, 0) + 1
                continue
            if t == "reroute":
                reroutes.append(
                    {
                        "from_replica": r.get("from_replica"),
                        "recovered": r.get("recovered"),
                        "moved": r.get("moved"),
                        "shed_expired": r.get("shed_expired"),
                    }
                )
                continue
            if t == "rollover":
                phase = str(r.get("phase", "?"))
                if phase in rollovers:
                    rollovers[phase] += 1
                continue
            # serve / serve_rollup: replica tag wins, shard index is
            # the fallback (single-stream runs have no tag).
            rep = str(r.get("replica", pidx))
            e = per.setdefault(
                rep,
                {
                    "serve_rows": 0,
                    "requests": 0,
                    "dispatches": 0,
                    "queue_depth_max": 0,
                    "p50_ms": None,
                    "p99_ms": None,
                },
            )
            if t == "serve":
                e["serve_rows"] += 1
                e["queue_depth_max"] = max(
                    e["queue_depth_max"],
                    int(r.get("queue_depth", 0) or 0),
                )
            else:
                # Last rollup wins: it aggregates the whole run.
                e["requests"] = int(r.get("requests", 0) or 0)
                e["dispatches"] = int(r.get("dispatches", 0) or 0)
                e["p50_ms"] = r.get("p50_ms")
                e["p99_ms"] = r.get("p99_ms")
    if not any_rows:
        return None
    p99s = {
        k: v["p99_ms"] for k, v in per.items() if v["p99_ms"]
    }
    p99_skew = (
        round(max(p99s.values()) / max(min(p99s.values()), 1e-9), 3)
        if len(p99s) >= 2
        else None
    )
    # Queue-depth straggler: a replica whose max queue depth is at
    # least double the fleet median is falling behind its peers —
    # routing skew or a slow replica, either way the p99 donor.
    depths = sorted(v["queue_depth_max"] for v in per.values())
    verdict = "balanced"
    if len(depths) >= 2:
        med = depths[len(depths) // 2]
        worst = max(
            per.items(), key=lambda kv: kv[1]["queue_depth_max"]
        )
        if worst[1]["queue_depth_max"] >= max(2 * med, med + 4):
            verdict = (
                f"replica {worst[0]} queue-depth straggler "
                f"(max depth {worst[1]['queue_depth_max']} vs "
                f"median {med})"
            )
    # Dead replicas (no close row + heartbeat gap) vs re-route
    # coverage: a dead replica with no reroute row means its pending
    # requests were LOST — the exact silent drop the tier exists to
    # prevent.
    dead = list(heartbeats.get("dead") or [])
    covered = {
        int(rr["from_replica"])
        for rr in reroutes
        if rr.get("from_replica") is not None
    }
    uncovered = sorted(set(int(d) for d in dead) - covered)
    return {
        "per_replica": per,
        "p99_skew": p99_skew,
        "queue_verdict": verdict,
        "sheds_by_reason": sheds,
        "sheds_by_class": sheds_by_class,
        "shed_total": sum(sheds.values()),
        "reroutes": reroutes,
        "rollovers": rollovers,
        "dead_replicas": dead,
        "dead_without_reroute": uncovered,
    }


def build_fleet(path: str) -> dict:
    """Merge one run's shards into the fleet report dict
    ``render_fleet`` prints (stable keys — ``--json`` is the CI
    surface). Degrades LOUDLY on partial fleets: every anomaly lands
    in ``warnings`` (and the dead-process list), never an exception —
    a killed run's fleet must still render, that is the point."""
    shards = discover_shards(path)
    warnings: List[str] = []
    procs: Dict[str, dict] = {}
    rows_by_proc: Dict[int, List[dict]] = {}
    expected = 0
    for pidx, spath in shards.items():
        rows, skipped = read_stream(spath)
        rep = _report_from_rows(spath, rows, skipped)
        rows_by_proc[pidx] = rows
        hdr = rep["header"]
        hdr_idx = hdr.get("process_index")
        if hdr_idx is not None and int(hdr_idx) != pidx:
            warnings.append(
                f"shard {os.path.basename(spath)} claims "
                f"process_index {hdr_idx} but is named proc{pidx} — "
                "trusting the filename"
            )
        expected = max(expected, int(hdr.get("process_count", 0) or 0))
        if skipped:
            warnings.append(
                f"proc{pidx}: {skipped} unparseable line(s) skipped "
                "(truncated tail — the shard was cut mid-write)"
            )
        clean = rep["close"] is not None
        if not clean:
            warnings.append(
                f"proc{pidx}: shard has no close row — the process "
                "died or was killed mid-run (see the heartbeat section)"
            )
        procs[str(pidx)] = {
            "path": spath,
            "rows": rep["rows"],
            "skipped_lines": skipped,
            "drops": rep["drops"],
            "write_errors": rep["write_errors"],
            "clean_exit": clean,
            "hostname": hdr.get("hostname"),
            "epochs": len(rep["epochs"]),
            "post_warmup_compiles": rep["post_warmup_compiles"],
            "barrier_summary": rep["barrier_summary"],
        }
    present = sorted(rows_by_proc)
    expected = max(expected, len(present), (present[-1] + 1) if present else 0)
    missing = sorted(set(range(expected)) - set(present))
    if missing:
        warnings.append(
            f"missing shard(s) for process(es) {missing} of "
            f"{expected} — merged views cover only the present "
            "shards; skew/straggler numbers are LOWER BOUNDS"
        )

    barrier_events = _merge_barriers(rows_by_proc)
    barrier_sites = _rollup_barrier_sites(barrier_events)
    epoch_align = _align_epochs(rows_by_proc)
    stragglers = _straggler_verdicts(epoch_align, barrier_events)
    heartbeats = _heartbeat_health(rows_by_proc, procs, warnings)
    serving = _fleet_serving(rows_by_proc, heartbeats)
    if serving and serving["dead_without_reroute"]:
        warnings.append(
            "dead serving replica(s) "
            f"{serving['dead_without_reroute']} have NO reroute row — "
            "their pending requests were lost, not recovered"
        )

    return {
        "path": path,
        "shards": {str(i): p for i, p in shards.items()},
        "process_count": expected,
        "present": present,
        "missing": missing,
        "warnings": warnings,
        "processes": procs,
        "barrier_events": barrier_events,
        "barrier_sites": barrier_sites,
        "epoch_align": epoch_align,
        "stragglers": stragglers,
        "heartbeats": heartbeats,
        "serving": serving,
    }


def _merge_barriers(rows_by_proc: Dict[int, List[dict]]) -> List[dict]:
    """Align ``barrier`` rows across shards by (site, seq) — the seq
    is minted identically on every process (utils/checkpoint
    ``_barrier_seq`` / the writer's per-job sequence), so the pair IS
    the event identity. The LAST ARRIVER of an event is the process
    with minimum ``barrier_ms`` (it barely parks — everyone else was
    already waiting): a clock-skew-free signal, unlike comparing
    ``ts`` across hosts. ``peer_wait_ms`` is the longest wait the last
    arriver inflicted on a peer — the number the straggler verdict
    charges to it."""
    events: Dict[Tuple[str, int], dict] = {}
    for pidx, rows in rows_by_proc.items():
        for r in rows:
            if r.get("t") != "barrier":
                continue
            key = (str(r.get("site", "?")), int(r.get("seq", 0)))
            ev = events.setdefault(
                key,
                {
                    "site": key[0],
                    "seq": key[1],
                    "epoch": r.get("epoch"),
                    "broadcast": False,
                    "wait_ms": {},
                    "barrier_ms": {},
                },
            )
            if r.get("epoch") is not None and ev.get("epoch") is None:
                ev["epoch"] = r.get("epoch")
            if r.get("broadcast"):
                ev["broadcast"] = True
            ev["wait_ms"][str(pidx)] = float(r.get("wait_ms", 0.0) or 0.0)
            if "barrier_ms" in r:
                ev["barrier_ms"][str(pidx)] = float(r["barrier_ms"])
    out = []
    for (site, seq), ev in sorted(events.items()):
        waits = ev["wait_ms"]
        ev["max_wait_ms"] = max(waits.values()) if waits else 0.0
        ev["max_wait_proc"] = (
            int(max(waits, key=waits.get)) if waits else None
        )
        # Rendezvous events only: a broadcast (KV set/get) is
        # asymmetric — only processes arriving before the setter
        # park, late arrivers read instantly — so min-barrier_ms
        # "last arriver" would blame an innocent late reader. Its
        # waits are still reported per process, unattributed. And
        # NEVER fall back to min-wait_ms: wait_ms includes the
        # straggler's own pre-barrier stall, so it would invert the
        # attribution — rows without barrier_ms stay unattributed.
        src = None if ev["broadcast"] else (
            ev["barrier_ms"] if len(ev["barrier_ms"]) >= 2 else None
        )
        if src is not None:
            last = min(src, key=src.get)
            ev["last_arriver"] = int(last)
            ev["peer_wait_ms"] = max(
                (v for p, v in src.items() if p != last), default=0.0
            )
        else:
            ev["last_arriver"] = None
            ev["peer_wait_ms"] = 0.0
        out.append(ev)
    return out


def _rollup_barrier_sites(events: List[dict]) -> Dict[str, dict]:
    sites: Dict[str, dict] = {}
    for ev in events:
        s = sites.setdefault(
            ev["site"],
            {
                "events": 0,
                "wait_ms_total_by_proc": {},
                "max_wait_ms": 0.0,
                "peer_wait_ms_total": 0.0,
                "last_arrivals": {},
                "worst": None,
            },
        )
        s["events"] += 1
        for p, v in ev["wait_ms"].items():
            s["wait_ms_total_by_proc"][p] = round(
                s["wait_ms_total_by_proc"].get(p, 0.0) + v, 3
            )
        la = ev["last_arriver"]
        if la is not None:
            s["last_arrivals"][str(la)] = (
                s["last_arrivals"].get(str(la), 0) + 1
            )
            s["peer_wait_ms_total"] = round(
                s["peer_wait_ms_total"] + ev["peer_wait_ms"], 3
            )
        if ev["max_wait_ms"] >= s["max_wait_ms"]:
            s["max_wait_ms"] = ev["max_wait_ms"]
            s["worst"] = {
                "seq": ev["seq"],
                "epoch": ev.get("epoch"),
                "max_wait_ms": ev["max_wait_ms"],
                "max_wait_proc": ev["max_wait_proc"],
                "last_arriver": la,
                "peer_wait_ms": ev["peer_wait_ms"],
            }
    return sites


def _align_epochs(rows_by_proc: Dict[int, List[dict]]) -> List[dict]:
    """Per-(region, epoch) alignment of step rows across processes:
    each process's input-wait / dispatch / wall totals side by side,
    plus the skews (max − min) — the per-host load-imbalance view the
    process-coordinated packing work will be judged with."""
    agg: Dict[Tuple[str, int], Dict[str, dict]] = {}
    for pidx, rows in rows_by_proc.items():
        for r in rows:
            if r.get("t") != "step":
                continue
            key = (str(r.get("region", "?")), int(r.get("epoch", 0)))
            a = agg.setdefault(key, {}).setdefault(
                str(pidx), _zero_epoch_agg()
            )
            a["steps"] += int(r.get("k", 1))
            a["dispatches"] += 1
            a["input_wait_ms"] = round(
                a["input_wait_ms"] + float(r.get("input_wait_ms", 0.0)), 3
            )
            a["dispatch_ms"] = round(
                a["dispatch_ms"] + float(r.get("dispatch_ms", 0.0)), 3
            )
            a["wall_ms"] = round(
                a["wall_ms"] + float(r.get("wall_ms", 0.0)), 3
            )
    out = []
    for (region, epoch), per in sorted(agg.items()):
        walls = {p: v["wall_ms"] for p, v in per.items()}
        inwait = {p: v["input_wait_ms"] for p, v in per.items()}
        entry = {
            "region": region,
            "epoch": epoch,
            "per_process": per,
            "wall_skew_ms": (
                round(max(walls.values()) - min(walls.values()), 3)
                if len(walls) >= 2
                else 0.0
            ),
            "input_wait_skew_ms": (
                round(max(inwait.values()) - min(inwait.values()), 3)
                if len(inwait) >= 2
                else 0.0
            ),
            "slowest": int(max(walls, key=walls.get)) if walls else None,
            "most_input_wait": (
                int(max(inwait, key=inwait.get)) if inwait else None
            ),
        }
        out.append(entry)
    return out


def _straggler_verdicts(
    epoch_align: List[dict], barrier_events: List[dict]
) -> List[dict]:
    """One verdict per TRAIN epoch (docs/OBSERVABILITY.md "Straggler
    verdict"): barrier attribution wins (the peer wait charged to an
    epoch's last arrivers — a stalled process slows the fleet without
    slowing itself, so its own step rows look innocent); otherwise
    input-wait skew (the slow-host case); otherwise ``balanced``.
    Thresholds: ``max(50ms, 5% of mean per-process wall)`` for
    barrier peer wait, ``max(50ms, 10%)`` for input-wait skew."""
    peer_by_epoch: Dict[int, Dict[int, float]] = {}
    site_by_epoch: Dict[Tuple[int, int], Dict[str, float]] = {}
    for ev in barrier_events:
        la, ep = ev["last_arriver"], ev.get("epoch")
        if la is None or ep is None or not ev["peer_wait_ms"]:
            continue
        ep = int(ep)
        peer_by_epoch.setdefault(ep, {})
        peer_by_epoch[ep][la] = (
            peer_by_epoch[ep].get(la, 0.0) + ev["peer_wait_ms"]
        )
        sb = site_by_epoch.setdefault((ep, la), {})
        sb[ev["site"]] = sb.get(ev["site"], 0.0) + ev["peer_wait_ms"]
    verdicts = []
    for entry in epoch_align:
        if entry["region"] != "train":
            continue
        epoch = entry["epoch"]
        per = entry["per_process"]
        walls = [v["wall_ms"] for v in per.values()]
        mean_wall = (sum(walls) / len(walls)) if walls else 0.0
        v = {
            "epoch": epoch,
            "straggler": None,
            "cause": None,
            "peer_wait_ms": 0.0,
            "wall_skew_ms": entry["wall_skew_ms"],
            "input_wait_skew_ms": entry["input_wait_skew_ms"],
        }
        peers = peer_by_epoch.get(epoch) or {}
        if peers:
            worst = max(peers, key=peers.get)
            if peers[worst] >= max(
                _STRAGGLER_MIN_MS, _STRAGGLER_BARRIER_FRAC * mean_wall
            ):
                sb = site_by_epoch.get((epoch, worst)) or {}
                site = max(sb, key=sb.get) if sb else "?"
                v.update(
                    straggler=int(worst),
                    cause=f"barrier:{site}",
                    peer_wait_ms=round(peers[worst], 3),
                )
        if v["straggler"] is None and len(per) >= 2:
            if entry["input_wait_skew_ms"] >= max(
                _STRAGGLER_MIN_MS, _STRAGGLER_WAIT_FRAC * mean_wall
            ):
                v.update(
                    straggler=entry["most_input_wait"],
                    cause="input_wait",
                )
        if v["straggler"] is None:
            v["cause"] = "balanced"
        verdicts.append(v)
    return verdicts


def _heartbeat_health(
    rows_by_proc: Dict[int, List[dict]],
    procs: Dict[str, dict],
    warnings: List[str],
) -> dict:
    """Dead/stalled-process detection from heartbeat gaps: the fleet's
    last beat is the reference clock; a process with no close row
    whose last beat trails it by more than ``max(3 × interval, 1s)``
    was SIGKILLed or wedged — exactly what a ``stall:``-class hang
    looks like from outside. A clean close row downgrades an old last
    beat to "exited" (finished earlier, not dead)."""
    per: Dict[str, dict] = {}
    fleet_last = None
    for pidx, rows in rows_by_proc.items():
        hb = [r for r in rows if r.get("t") == "heartbeat"]
        if not hb:
            continue
        ts = [float(r.get("ts", 0.0) or 0.0) for r in hb]
        gaps = [b - a for a, b in zip(ts, ts[1:])]
        last = hb[-1]
        per[str(pidx)] = {
            "beats": len(hb),
            "first_ts": ts[0],
            "last_ts": ts[-1],
            "interval_s": float(last.get("interval_s", 0.0) or 0.0),
            "max_gap_s": round(max(gaps), 3) if gaps else 0.0,
            "last_phase": last.get("phase"),
            "last_waiting_on": last.get("waiting_on"),
            "last_counters": last.get("counters"),
        }
        fleet_last = (
            ts[-1] if fleet_last is None else max(fleet_last, ts[-1])
        )
    silent = [
        p for p in rows_by_proc if str(p) not in per
    ]
    if per and silent:
        warnings.append(
            f"process(es) {sorted(silent)} emitted no heartbeat rows "
            "while peers did — dead before the first beat, or "
            "heartbeats disabled on that process"
        )
    dead = []
    for p, e in sorted(per.items()):
        gap = round((fleet_last or 0.0) - e["last_ts"], 3)
        e["gap_s"] = gap
        thresh = max(3.0 * (e["interval_s"] or 0.0), 1.0)
        clean = procs.get(p, {}).get("clean_exit", False)
        e["exited"] = bool(clean)
        e["dead"] = bool(not clean and gap > thresh)
        if e["dead"]:
            dead.append(int(p))
            warnings.append(
                f"proc{p}: DEAD/STALLED — last heartbeat {gap:.1f}s "
                f"behind the fleet (threshold {thresh:.1f}s), no close "
                f"row; last phase={e['last_phase']!r}"
                + (
                    f", waiting_on={e['last_waiting_on']!r}"
                    if e["last_waiting_on"]
                    else ""
                )
            )
    return {
        "per_process": per,
        "fleet_last_ts": fleet_last,
        "dead": dead,
    }


def render_fleet(fl: dict) -> str:
    out = [f"== graftboard fleet: {fl['path']}"]
    out.append(
        f"processes: {fl['process_count']} expected, "
        f"{len(fl['present'])} shard(s) present "
        f"{fl['present']}"
        + (f", MISSING {fl['missing']}" if fl["missing"] else "")
    )
    for w in fl["warnings"]:
        out.append(f"WARNING: {w}")
    if fl["processes"]:
        rows = []
        for p, e in sorted(fl["processes"].items(), key=lambda kv: int(kv[0])):
            rows.append(
                [
                    f"proc{p}",
                    str(e["rows"]),
                    str(e["epochs"]),
                    _fmt(e["drops"], 0),
                    str(e["skipped_lines"]),
                    "yes" if e["clean_exit"] else "NO",
                    str(e["post_warmup_compiles"]),
                ]
            )
        out.append("")
        out.append(
            _table(
                ["proc", "rows", "epochs", "drops", "skipped",
                 "clean_exit", "retraces"],
                rows,
            )
        )
    if fl["epoch_align"]:
        out.append("")
        out.append(
            "-- per-epoch step-time skew (per process: "
            "input_wait/wall ms)"
        )
        rows = []
        for e in fl["epoch_align"]:
            per = ", ".join(
                f"p{p}:{_fmt(v['input_wait_ms'], 0)}/{_fmt(v['wall_ms'], 0)}"
                for p, v in sorted(
                    e["per_process"].items(), key=lambda kv: int(kv[0])
                )
            )
            rows.append(
                [
                    f"{e['region']}/{e['epoch']}",
                    per,
                    _fmt(e["input_wait_skew_ms"], 1),
                    _fmt(e["wall_skew_ms"], 1),
                    (
                        f"p{e['slowest']}"
                        if e["slowest"] is not None
                        else "-"
                    ),
                ]
            )
        out.append(
            _table(
                ["region/epoch", "per-proc wait/wall", "wait_skew",
                 "wall_skew", "slowest"],
                rows,
            )
        )
    if fl["barrier_sites"]:
        out.append("")
        out.append(
            "-- barrier decomposition (last arriver = min barrier_ms "
            "— the process its peers waited on)"
        )
        rows = []
        for site, s in sorted(fl["barrier_sites"].items()):
            worst = s["worst"] or {}
            arrivals = ",".join(
                f"p{p}:{n}"
                for p, n in sorted(s["last_arrivals"].items())
            )
            rows.append(
                [
                    site,
                    str(s["events"]),
                    _fmt(s["max_wait_ms"], 1),
                    _fmt(s["peer_wait_ms_total"], 1),
                    arrivals or "-",
                    (
                        f"seq{worst.get('seq')}→p"
                        f"{worst.get('last_arriver')}"
                        if worst.get("last_arriver") is not None
                        else "-"
                    ),
                ]
            )
        out.append(
            _table(
                ["site", "n", "max_wait_ms", "peer_wait_ms",
                 "last_arrivals", "worst"],
                rows,
            )
        )
    if fl["stragglers"]:
        out.append("")
        out.append("-- straggler verdict per epoch")
        for v in fl["stragglers"]:
            if v["straggler"] is None:
                out.append(f"   epoch {v['epoch']}: balanced")
            else:
                out.append(
                    f"   epoch {v['epoch']}: STRAGGLER proc"
                    f"{v['straggler']} ({v['cause']}"
                    + (
                        f", peers waited {_fmt(v['peer_wait_ms'], 0)}ms"
                        if v["peer_wait_ms"]
                        else ""
                    )
                    + ")"
                )
    hb = fl["heartbeats"]
    if hb["per_process"]:
        out.append("")
        out.append("-- heartbeats (liveness)")
        rows = []
        for p, e in sorted(
            hb["per_process"].items(), key=lambda kv: int(kv[0])
        ):
            status = (
                "DEAD"
                if e["dead"]
                else ("exited" if e["exited"] else "alive-at-end")
            )
            rows.append(
                [
                    f"proc{p}",
                    str(e["beats"]),
                    _fmt(e["gap_s"], 1),
                    _fmt(e["max_gap_s"], 1),
                    str(e["last_phase"] or "-"),
                    str(e["last_waiting_on"] or "-"),
                    status,
                ]
            )
        out.append(
            _table(
                ["proc", "beats", "tail_gap_s", "max_gap_s",
                 "last_phase", "waiting_on", "status"],
                rows,
            )
        )
        if hb["dead"]:
            out.append(
                f"   DEAD PROCESS(ES): {hb['dead']} — heartbeat gap "
                "with no close row (SIGKILL or hard stall)"
            )
    sv = fl.get("serving")
    if sv:
        out.append("")
        out.append("-- serving tier (per-replica)")
        rows = []
        for rep, e in sorted(
            sv["per_replica"].items(), key=lambda kv: str(kv[0])
        ):
            rows.append(
                [
                    f"r{rep}",
                    str(e["requests"]),
                    str(e["dispatches"]),
                    _fmt(e["p50_ms"], 2),
                    _fmt(e["p99_ms"], 2),
                    str(e["queue_depth_max"]),
                ]
            )
        out.append(
            _table(
                ["replica", "requests", "dispatches", "p50_ms",
                 "p99_ms", "queue_max"],
                rows,
            )
        )
        if sv["p99_skew"] is not None:
            out.append(
                f"   p99 skew (max/min across replicas): "
                f"{sv['p99_skew']}x"
            )
        out.append(f"   queue verdict: {sv['queue_verdict']}")
        if sv["shed_total"]:
            out.append(
                f"   sheds: {sv['shed_total']} "
                f"(by reason {sv['sheds_by_reason']}, "
                f"by class {sv['sheds_by_class']})"
            )
        else:
            out.append("   sheds: 0")
        for rr in sv["reroutes"]:
            out.append(
                f"   reroute from replica {rr['from_replica']}: "
                f"{rr['recovered']} recovered, {rr['moved']} moved, "
                f"{rr['shed_expired']} shed expired"
            )
        ro = sv["rollovers"]
        if ro["done"] or ro["refused"]:
            out.append(
                f"   rollovers: {ro['done']} completed, "
                f"{ro['refused']} refused at admission"
            )
        if sv["dead_replicas"]:
            cov = (
                "re-route covered"
                if not sv["dead_without_reroute"]
                else "REQUESTS LOST: no reroute row for "
                f"{sv['dead_without_reroute']}"
            )
            out.append(
                f"   dead replica(s) {sv['dead_replicas']} — {cov}"
            )
    return "\n".join(out)


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="graftboard", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("report", help="render one run's report")
    pr.add_argument("run", help="telemetry.jsonl or run directory")
    pr.add_argument("--json", action="store_true", dest="as_json")
    pr.add_argument("--csv", default=None, help="tracer timing CSV to append")
    pf = sub.add_parser(
        "roofline", help="per-spec cost/memory roofline attribution"
    )
    pf.add_argument("run", help="telemetry.jsonl or run directory")
    pf.add_argument("--json", action="store_true", dest="as_json")
    pd = sub.add_parser("diff", help="A/B two runs")
    pd.add_argument("run_a")
    pd.add_argument("run_b")
    pd.add_argument("--json", action="store_true", dest="as_json")
    pfl = sub.add_parser(
        "fleet",
        help="merge one run's per-process shards: skew, barrier "
        "attribution, stragglers, dead processes",
    )
    pfl.add_argument("run", help="run directory or any shard path")
    pfl.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    try:
        if args.cmd == "report":
            rep = build_report(args.run)
            if args.as_json:
                print(json.dumps(rep))
            else:
                print(render_report(rep, csv_path=args.csv))
        elif args.cmd == "roofline":
            rl = build_roofline(build_report(args.run))
            if args.as_json:
                print(json.dumps(rl))
            else:
                print(render_roofline(rl))
        elif args.cmd == "fleet":
            fl = build_fleet(args.run)
            if args.as_json:
                print(json.dumps(fl))
            else:
                print(render_fleet(fl))
        else:
            d = build_diff(
                build_report(args.run_a), build_report(args.run_b)
            )
            if args.as_json:
                print(json.dumps(d))
            else:
                print(render_diff(d))
    except FileNotFoundError as e:
        print(f"graftboard: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
