"""Radial basis functions, cutoffs, and distance transforms.

Functional JAX equivalents of the reference's radial machinery:
Gaussian smearing (hydragnn/models/SCFStack.py GaussianSmearing via PyG),
Bessel basis (hydragnn/models/PNAPlusStack.py:40-143, DIMEStack),
sinc basis + cosine cutoff (hydragnn/models/PAINNStack.py:331-352),
Bessel/Chebyshev/Gaussian bases + PolynomialCutoff + Agnesi/Soft transforms
(hydragnn/utils/model/mace_utils/modules/radial.py:23-248).

All are pure elementwise functions of edge length [E] -> [E, num_basis];
XLA fuses them into the surrounding edge MLPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def gaussian_smearing(
    dist: jax.Array, start: float, stop: float, num_gaussians: int
) -> jax.Array:
    """exp(-gamma (d - mu_k)^2) on an even grid of centers."""
    offset = jnp.linspace(start, stop, num_gaussians, dtype=dist.dtype)
    coeff = -0.5 / (offset[1] - offset[0]) ** 2
    diff = dist[..., None] - offset
    return jnp.exp(coeff * diff**2)


def bessel_basis(dist: jax.Array, cutoff: float, num_radial: int) -> jax.Array:
    """sqrt(2/c) * sin(n pi d / c) / d — spherical Bessel j0 basis."""
    freq = jnp.arange(1, num_radial + 1, dtype=dist.dtype) * jnp.pi
    d = dist[..., None] / cutoff
    d_safe = jnp.where(d < 1e-8, 1e-8, d)
    prefactor = jnp.asarray(np.sqrt(2.0 / cutoff), dist.dtype)
    return prefactor * jnp.sin(freq * d_safe) / (d_safe * cutoff)


def sinc_basis(dist: jax.Array, cutoff: float, num_basis: int) -> jax.Array:
    """sinc-like expansion sin(n pi d/c)/d used by PaiNN
    (reference: hydragnn/models/PAINNStack.py:331-341)."""
    n = jnp.arange(1, num_basis + 1, dtype=dist.dtype)
    d_safe = jnp.where(dist < 1e-8, 1e-8, dist)[..., None]
    return jnp.sin(n * jnp.pi * d_safe / cutoff) / d_safe


def chebyshev_basis(dist: jax.Array, cutoff: float, num_basis: int) -> jax.Array:
    """Chebyshev polynomials of scaled distance on [-1, 1]
    (reference: mace_utils/modules/radial.py ChebychevBasis)."""
    x = jnp.clip(2.0 * dist / cutoff - 1.0, -1.0, 1.0)[..., None]
    n = jnp.arange(1, num_basis + 1, dtype=dist.dtype)
    return jnp.cos(n * jnp.arccos(x))


def cosine_cutoff(dist: jax.Array, cutoff: float) -> jax.Array:
    """0.5 (cos(pi d/c) + 1) for d < c else 0 (SchNet/PaiNN cutoff)."""
    out = 0.5 * (jnp.cos(jnp.pi * dist / cutoff) + 1.0)
    return jnp.where(dist < cutoff, out, 0.0)


def polynomial_cutoff(dist: jax.Array, cutoff: float, p: int = 6) -> jax.Array:
    """MACE polynomial envelope, C^p smooth at the cutoff
    (reference: mace_utils/modules/radial.py PolynomialCutoff)."""
    d = dist / cutoff
    pf = float(p)
    out = (
        1.0
        - (pf + 1.0) * (pf + 2.0) / 2.0 * d**p
        + pf * (pf + 2.0) * d ** (p + 1)
        - pf * (pf + 1.0) / 2.0 * d ** (p + 2)
    )
    return jnp.where(d < 1.0, out, 0.0)


def envelope(dist_scaled: jax.Array, exponent: int = 5) -> jax.Array:
    """DimeNet smooth envelope u(d) with u(1)=u'(1)=u''(1)=0
    (reference: hydragnn/models/PNAPlusStack.py Envelope / DimeNet)."""
    p = exponent + 1
    a = -(p + 1) * (p + 2) / 2.0
    b = p * (p + 2.0)
    c = -p * (p + 1) / 2.0
    x = dist_scaled
    x_safe = jnp.where(x < 1e-8, 1e-8, x)
    out = 1.0 / x_safe + a * x ** (p - 1) + b * x**p + c * x ** (p + 1)
    return jnp.where(x < 1.0, out, 0.0)


def agnesi_transform(
    dist: jax.Array,
    r_0: jax.Array,
    a: float = 1.0805,
    q: float = 0.9183,
    p: float = 4.5791,
) -> jax.Array:
    """Agnesi distance transform (mace_utils/modules/radial.py:151-196):
    (1 + a (d/r_0)^q / (1 + (d/r_0)^(q-p)))^-1, decreasing 1 -> 0. The
    transformed value REPLACES the distance fed to the radial basis;
    ``r_0`` is the per-edge mean covalent radius of the endpoints."""
    x = jnp.maximum(dist / r_0, 1e-12)
    return 1.0 / (1.0 + a * x**q / (1.0 + x ** (q - p)))


def soft_transform(
    dist: jax.Array, r_0: jax.Array, a: float = 0.2, b: float = 3.0
) -> jax.Array:
    """Soft distance transform (mace_utils/modules/radial.py:204-248):
    d + 0.5 tanh(-(d/r_0) - a (d/r_0)^b) + 0.5, with ``r_0`` the per-edge
    quarter-sum of the endpoint covalent radii."""
    x = dist / r_0
    return dist + 0.5 * jnp.tanh(-x - a * x**b) + 0.5


def edge_vectors_and_lengths(
    pos: jax.Array,
    senders: jax.Array,
    receivers: jax.Array,
    shifts: jax.Array | None = None,
    *,
    normalize: bool = False,
    eps: float = 1e-9,
) -> tuple[jax.Array, jax.Array]:
    """PBC-aware displacement primitive: vec = pos[s] - pos[r] + shift.

    The single geometric primitive all geometric stacks share (reference:
    hydragnn/utils/model/operations.py:21 get_edge_vectors_and_lengths).
    Returns (vectors [E,3], lengths [E]).
    """
    vec = pos[senders] - pos[receivers]
    if shifts is not None:
        vec = vec + shifts
    length = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + eps)
    if normalize:
        vec = vec / length[..., None]
    return vec, length
