from hydragnn_tpu.ops.segment import (
    segment_sum,
    segment_mean,
    segment_max,
    segment_min,
    segment_std,
    segment_softmax,
    segment_multi_aggregate,
    degree,
)
from hydragnn_tpu.ops.rbf import (
    gaussian_smearing,
    bessel_basis,
    sinc_basis,
    chebyshev_basis,
    cosine_cutoff,
    polynomial_cutoff,
    envelope,
    agnesi_transform,
    soft_transform,
    edge_vectors_and_lengths,
)
from hydragnn_tpu.ops.dense import to_dense_batch, from_dense_batch
from hydragnn_tpu.ops.neighbors import (
    radius_graph,
    radius_graph_pbc,
    radius_graph_jax,
    ensure_connected,
)
from hydragnn_tpu.ops.pe import laplacian_pe, relative_pe
