"""E(3) math core: real spherical harmonics + real Clebsch-Gordan tensors.

From-scratch JAX replacement for the e3nn machinery the reference imports
for MACE (hydragnn/utils/model/mace_utils/tools/cg.py:22-136,
o3.SphericalHarmonics / o3.TensorProduct usage in
hydragnn/utils/model/mace_utils/modules/blocks.py).

Design: every convention (basis ordering, phases, normalization) is
fixed ONCE, numerically, at import time on the host:

1. Real spherical harmonics are defined analytically (associated
   Legendre × cos/sin) and then *fitted* to homogeneous Cartesian
   polynomial coefficient tensors. Runtime evaluation is a single
   monomials @ coeffs matmul — no trig, traceable, MXU-friendly.
2. Complex Wigner 3j symbols come from the Racah closed form (exact in
   float64 for the small l used here); the real-basis 3j tensor is
   obtained by numerically fitting the real↔complex change of basis to
   the SAME real harmonics as (1), so self-consistency holds by
   construction. Each generated tensor is verified to be rotation
   invariant under Wigner D matrices derived from the harmonics
   themselves; generation fails loudly otherwise.

Component normalization (e3nn "component"): E[|Y_lm|^2] = 1 over the
sphere, i.e. ||Y_l||^2 = 2l+1 for a unit vector.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "sh_dim",
    "sh_basis",
    "real_wigner_3j",
    "wigner_d_from_sh",
    "monomial_powers",
    "sh_coeff_matrix",
]


def sh_dim(lmax: int) -> int:
    """Total dimension of l = 0..lmax concatenated: (lmax+1)^2."""
    return (lmax + 1) ** 2


# ----------------------------------------------------------------------
# Host-side analytic real spherical harmonics (definition of record)
# ----------------------------------------------------------------------


def _assoc_legendre(l: int, m: int, x: np.ndarray) -> np.ndarray:
    """P_l^m(x) WITHOUT the Condon-Shortley phase (plain convention)."""
    pmm = np.ones_like(x)
    if m > 0:
        somx2 = np.sqrt(np.maximum(1.0 - x * x, 0.0))
        fact = 1.0
        for _ in range(m):
            pmm = pmm * fact * somx2
            fact += 2.0
    if l == m:
        return pmm
    pmmp1 = x * (2 * m + 1) * pmm
    if l == m + 1:
        return pmmp1
    pll = np.zeros_like(x)
    for ll in range(m + 2, l + 1):
        pll = ((2 * ll - 1) * x * pmmp1 - (ll + m - 1) * pmm) / (ll - m)
        pmm = pmmp1
        pmmp1 = pll
    return pll


def _real_sh_reference(l: int, vecs: np.ndarray) -> np.ndarray:
    """[K, 2l+1] real SH at unit vectors, component normalization.

    Component order m = -l..l: negative m are sin(|m| phi) terms,
    m = 0 the zonal term, positive m the cos(m phi) terms.
    """
    x, y, z = vecs[:, 0], vecs[:, 1], vecs[:, 2]
    r = np.sqrt(x * x + y * y + z * z)
    ct = np.clip(z / r, -1.0, 1.0)
    phi = np.arctan2(y, x)
    out = np.zeros((vecs.shape[0], 2 * l + 1))
    for m in range(0, l + 1):
        nrm = math.sqrt(
            (2 * l + 1) * math.factorial(l - m) / math.factorial(l + m)
        )
        plm = _assoc_legendre(l, m, ct)
        if m == 0:
            out[:, l] = nrm * plm
        else:
            out[:, l + m] = math.sqrt(2.0) * nrm * plm * np.cos(m * phi)
            out[:, l - m] = math.sqrt(2.0) * nrm * plm * np.sin(m * phi)
    return out


def monomial_powers(l: int) -> np.ndarray:
    """[(l+1)(l+2)/2, 3] exponent triples (a,b,c) with a+b+c = l."""
    return np.array(
        [(a, b, l - a - b) for a in range(l + 1) for b in range(l - a + 1)],
        dtype=np.int32,
    ).reshape(-1, 3)


def _monomials_np(vecs: np.ndarray, l: int) -> np.ndarray:
    """[K, n_monomials] degree-l monomials of each row, in the
    ``monomial_powers`` ordering — the convention the fitted
    ``sh_coeff_matrix`` coefficients are contracted against."""
    powers = monomial_powers(l)
    return np.prod(vecs[:, None, :] ** powers[None, :, :], axis=-1)


@lru_cache(maxsize=None)
def sh_coeff_matrix(l: int) -> np.ndarray:
    """[n_monomials, 2l+1] coefficients: Y_l(v) = monomials(v) @ C.

    Fitted from the analytic definition at random unit vectors; exact
    because restricted-to-sphere real SH are homogeneous degree-l
    polynomials.
    """
    if l == 0:
        return np.ones((1, 1))
    rng = np.random.default_rng(20240731 + l)
    k = max(4 * len(monomial_powers(l)), 64)
    v = rng.normal(size=(k, 3))
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    mono = _monomials_np(v, l)  # [K, P]
    target = _real_sh_reference(l, v)  # [K, 2l+1]
    coef, residuals, _, _ = np.linalg.lstsq(mono, target, rcond=None)
    fit = mono @ coef
    err = np.abs(fit - target).max()
    if err > 1e-9:
        raise RuntimeError(f"SH l={l} polynomial fit failed: max err {err}")
    return coef


def sh_basis(vec: jax.Array, lmax: int, *, normalize: bool = True) -> jax.Array:
    """Real spherical harmonics of l = 0..lmax, concatenated.

    vec [..., 3] -> [..., (lmax+1)^2]; component normalization. With
    ``normalize`` the input is first projected to the unit sphere
    (matching o3.SphericalHarmonics(normalize=True), reference
    MACEStack.py:158-162).
    """
    if normalize:
        n = jnp.sqrt(jnp.sum(vec * vec, axis=-1, keepdims=True) + 1e-18)
        vec = vec / n
    x, y, z = vec[..., 0], vec[..., 1], vec[..., 2]
    outs = [jnp.ones_like(x)[..., None]]
    for l in range(1, lmax + 1):
        powers = monomial_powers(l)
        coef = jnp.asarray(sh_coeff_matrix(l), vec.dtype)
        mono = jnp.stack(
            [
                (x ** int(a)) * (y ** int(b)) * (z ** int(c))
                for a, b, c in powers
            ],
            axis=-1,
        )
        outs.append(mono @ coef)
    return jnp.concatenate(outs, axis=-1)


# ----------------------------------------------------------------------
# Wigner 3j: complex (Racah) -> real basis (numerically fitted)
# ----------------------------------------------------------------------


def _f(n: int) -> float:
    return float(math.factorial(n))


def _complex_cg(j1: int, j2: int, j3: int, m1: int, m2: int, m3: int) -> float:
    """Clebsch-Gordan <j1 m1 j2 m2 | j3 m3> (standard convention)."""
    if m1 + m2 != m3:
        return 0.0
    if not (abs(j1 - j2) <= j3 <= j1 + j2):
        return 0.0
    pre = math.sqrt(
        (2 * j3 + 1)
        * _f(j3 + j1 - j2)
        * _f(j3 - j1 + j2)
        * _f(j1 + j2 - j3)
        / _f(j1 + j2 + j3 + 1)
    )
    pre *= math.sqrt(
        _f(j3 + m3)
        * _f(j3 - m3)
        * _f(j1 - m1)
        * _f(j1 + m1)
        * _f(j2 - m2)
        * _f(j2 + m2)
    )
    s = 0.0
    for k in range(0, j1 + j2 + j3 + 1):
        denoms = [
            j1 + j2 - j3 - k,
            j1 - m1 - k,
            j2 + m2 - k,
            j3 - j2 + m1 + k,
            j3 - j1 - m2 + k,
        ]
        if any(d < 0 for d in denoms) or k < 0:
            continue
        s += (-1.0) ** k / (
            _f(k) * np.prod([_f(d) for d in denoms])
        )
    return pre * s


@lru_cache(maxsize=None)
def _real_from_complex(l: int) -> np.ndarray:
    """A_l [2l+1, 2l+1] complex: Y_real = A_l @ Y_complex_CS.

    Built against the standard complex SH *with* Condon-Shortley phase
    (so it composes with the standard CG above): for m>0
    real_{+m} = ((-1)^m Y_m + Y_{-m})/sqrt(2),
    real_{-m} = ((-1)^m Y_m - Y_{-m})/(i sqrt(2)), real_0 = Y_0.
    """
    A = np.zeros((2 * l + 1, 2 * l + 1), dtype=complex)
    A[l, l] = 1.0
    for m in range(1, l + 1):
        s = (-1.0) ** m
        A[l + m, l + m] = s / math.sqrt(2)
        A[l + m, l - m] = 1.0 / math.sqrt(2)
        A[l - m, l + m] = s / (1j * math.sqrt(2))
        A[l - m, l - m] = -1.0 / (1j * math.sqrt(2))
    return A


@lru_cache(maxsize=None)
def real_wigner_3j(l1: int, l2: int, l3: int) -> np.ndarray:
    """Real-basis coupling tensor C [2l1+1, 2l2+1, 2l3+1].

    Normalized so that sum C^2 = 2l3+1 (component normalization of the
    coupled output). Rotation invariance under the representations
    carried by ``sh_basis`` is asserted at generation time.
    """
    if not (abs(l1 - l2) <= l3 <= l1 + l2):
        return np.zeros((2 * l1 + 1, 2 * l2 + 1, 2 * l3 + 1))
    # Complex CG in the m-index cube.
    cg = np.zeros((2 * l1 + 1, 2 * l2 + 1, 2 * l3 + 1))
    for m1 in range(-l1, l1 + 1):
        for m2 in range(-l2, l2 + 1):
            m3 = m1 + m2
            if -l3 <= m3 <= l3:
                cg[l1 + m1, l2 + m2, l3 + m3] = _complex_cg(
                    l1, l2, l3, m1, m2, m3
                )
    A1 = _real_from_complex(l1)
    A2 = _real_from_complex(l2)
    A3 = _real_from_complex(l3)
    # C_real[a,b,c] couples real components: Y_real = A Y, so the
    # invariant coupling in the real basis is A1 A2 conj(A3) cg.
    t = np.einsum("au,bv,cw,uvw->abc", A1, A2, np.conj(A3), cg)
    re, im = np.real(t), np.imag(t)
    t = re if np.abs(re).sum() >= np.abs(im).sum() else im
    nrm = np.sqrt((t**2).sum())
    if nrm < 1e-12:
        raise RuntimeError(f"real 3j ({l1},{l2},{l3}) vanished")
    t = t * math.sqrt(2 * l3 + 1) / nrm
    _assert_invariant(t, l1, l2, l3)
    return t


@lru_cache(maxsize=None)
def _rotation_samples() -> Tuple[np.ndarray, ...]:
    rng = np.random.default_rng(7)
    rots = []
    for _ in range(2):
        q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
        if np.linalg.det(q) < 0:
            q[:, 0] = -q[:, 0]
        rots.append(q)
    return tuple(rots)


@lru_cache(maxsize=None)
def _wigner_d_np(l: int, rot_key: int) -> np.ndarray:
    rot = _rotation_samples()[rot_key]
    return wigner_d_from_sh(l, rot)


def _sh_basis_np(vecs: np.ndarray, l: int) -> np.ndarray:
    """[K, 2l+1] single-l harmonics in float64 numpy, from the SAME
    ``sh_coeff_matrix`` constants the runtime ``sh_basis`` matmuls —
    identical math, no device roundtrip. Generation-time code must not
    evaluate through JAX: on TPU the MXU's reduced-precision matmul
    perturbs the harmonics past the 1e-6 fit tolerance below (observed
    live: 'Wigner D fit failed for l=1: err 6.0e-3' on TPU v5 lite).
    Inputs are coerced to float64 numpy for the same reason: a float32
    (or jax, under default x64-off) vector set would drag the whole
    evaluation to fp32, where the tolerance is unreachable."""
    vecs = np.asarray(vecs, dtype=np.float64)
    if l == 0:
        return np.ones((vecs.shape[0], 1))
    return _monomials_np(vecs, l) @ sh_coeff_matrix(l)


def wigner_d_from_sh(l: int, rot: np.ndarray) -> np.ndarray:
    """Wigner D matrix in our real basis: Y_l(R v) = D_l(R) Y_l(v).

    Derived by least squares from the harmonics themselves, so it is
    exactly the representation the rest of the stack uses.

    The fit runs ENTIRELY in float64 numpy regardless of the caller's
    dtype or the jax x64 setting: a float32 (or jax-array, x64-off)
    ``rot`` would otherwise poison ``v @ rot.T`` — numpy's matmul
    defers to ``jax.Array.__rmatmul__``, the whole pipeline silently
    drops to fp32, and the 1e-6 verification tolerance (calibrated for
    fp64 lstsq residuals) becomes unreachable (BENCH_TPU.json:
    ``Wigner D fit failed for l=1: err 0.00599`` — a float32-precision
    error magnitude).
    """
    rot = np.asarray(rot, dtype=np.float64)
    if l == 0:
        return np.ones((1, 1))
    rng = np.random.default_rng(99 + l)
    v = rng.normal(size=(8 * (2 * l + 1), 3))
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    y = _sh_basis_np(v, l)
    yr = _sh_basis_np(v @ rot.T, l)
    d, res, _, _ = np.linalg.lstsq(y, yr, rcond=None)
    err = np.abs(y @ d - yr).max()
    if err > 1e-6:
        raise RuntimeError(f"Wigner D fit failed for l={l}: err {err}")
    return d.T  # y_rot^T = D y^T  with rows = components


def _assert_invariant(t: np.ndarray, l1: int, l2: int, l3: int) -> None:
    for k in range(2):
        d1 = _wigner_d_np(l1, k)
        d2 = _wigner_d_np(l2, k)
        d3 = _wigner_d_np(l3, k)
        t2 = np.einsum("au,bv,cw,uvw->abc", d1, d2, d3, t)
        if np.abs(t2 - t).max() > 1e-5:
            raise RuntimeError(
                f"real 3j ({l1},{l2},{l3}) not invariant: "
                f"{np.abs(t2 - t).max():.2e}"
            )
