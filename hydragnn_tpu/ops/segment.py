"""Segment reductions — the scatter/gather core of message passing.

TPU-native replacement for torch_scatter/torch_sparse segment ops
(reference dep: requirements-pyg.txt; used by every PyG conv in
hydragnn/models/*). Built on ``jax.ops.segment_*`` with static
``num_segments`` so XLA lowers them to one-hot matmuls / sorted scatters
that tile onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, 0)
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    total = segment_sum(data, segment_ids, num_segments, mask)
    ones = jnp.ones(data.shape[0], dtype=data.dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    count = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    count = jnp.maximum(count, 1)
    return total / _bcast_trailing(count, total)


def segment_max(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    neg = jnp.finfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, neg)
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    # Segments with no (unmasked) contributions come back as -inf/min;
    # normalize them to empty_value so padding graphs stay finite.
    return jnp.where(out <= neg, jnp.asarray(empty_value, out.dtype), out)


def segment_min(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    pos = jnp.finfo(data.dtype).max if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).max
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, pos)
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jnp.where(out >= pos, jnp.asarray(empty_value, out.dtype), out)


def segment_std(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    eps: float = 1e-5,
) -> jax.Array:
    """Per-segment standard deviation (PNA 'std' aggregator)."""
    mean = segment_mean(data, segment_ids, num_segments, mask)
    sq_mean = segment_mean(data * data, segment_ids, num_segments, mask)
    var = jnp.maximum(sq_mean - mean * mean, 0.0)
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Numerically-stable softmax within segments (GAT attention)."""
    seg_max = segment_max(logits, segment_ids, num_segments, mask)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(_bcast(mask, exp), exp, 0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    denom = jnp.maximum(denom, 1e-16)
    return exp / denom[segment_ids]


def aggregate_receivers(
    msg: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver-side message aggregation [E, F] -> [N, F].

    Dispatches to the Pallas sorted-segment kernel when the batch
    carries a block plan (collate with_segment_plan=True) and we're on
    TPU — or anywhere when HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused]
    forces it (interpret mode off-TPU); falls back to the XLA scatter
    path otherwise. Both apply the edge mask.
    """
    if use_plan is None:
        use_plan = batch.seg_window is not None and (
            jax.default_backend() == "tpu"
            or _segment_impl().startswith("pallas")
        )
    if use_plan and batch.seg_window is not None:
        from hydragnn_tpu.ops.pallas_segment import segment_sum_planned

        data = jnp.where(_bcast(batch.edge_mask, msg), msg, 0)
        return segment_sum_planned(
            data,
            batch.seg_perm,
            batch.seg_ids,
            batch.seg_valid,
            batch.seg_window,
            batch.num_nodes,
        )
    return segment_sum(
        msg, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def aggregate_receivers_product(
    a: jax.Array, b: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver aggregation of an elementwise product: segment_sum(a*b)
    where a is typically gathered sender features and b the per-edge
    filter (the SchNet message pipeline). With a batch block plan the
    reduce runs through the planned Pallas kernel; the in-kernel
    multiply variant is opt-in (HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused)
    until the roofline measurement shows it beating the unfused plan —
    XLA fuses the multiply into the plan gather on the default path."""
    if use_plan is None:
        use_plan = batch.seg_window is not None and (
            jax.default_backend() == "tpu"
            or _segment_impl().startswith("pallas")
        )
    if use_plan and batch.seg_window is not None:
        if _segment_impl() == "pallas_fused":
            from hydragnn_tpu.ops.pallas_segment import (
                segment_sum_product_planned,
            )

            # masking ONE operand zeroes the product; the kernel also
            # ANDs valid into the one-hot
            return segment_sum_product_planned(
                jnp.where(_bcast(batch.edge_mask, a), a, 0),
                b,
                batch.seg_perm,
                batch.seg_ids,
                batch.seg_valid,
                batch.seg_window,
                batch.num_nodes,
            )
        return aggregate_receivers(a * b, batch, use_plan=True)
    return segment_sum(
        a * b, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def _segment_impl() -> str:
    import os

    return os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL", "")


def degree(
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    ones = jnp.ones(segment_ids.shape[0], dtype=dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def _bcast(mask: jax.Array, data: jax.Array) -> jax.Array:
    """Reshape a [K] mask to broadcast against [K, ...] data."""
    return mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))


def _bcast_trailing(vec: jax.Array, data: jax.Array) -> jax.Array:
    return vec.reshape(vec.shape + (1,) * (data.ndim - vec.ndim))
