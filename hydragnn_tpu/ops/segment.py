"""Segment reductions — the scatter/gather core of message passing.

TPU-native replacement for torch_scatter/torch_sparse segment ops
(reference dep: requirements-pyg.txt; used by every PyG conv in
hydragnn/models/*). Built on ``jax.ops.segment_*`` with static
``num_segments`` so XLA lowers them to one-hot matmuls / sorted scatters
that tile onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, 0)
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    total = segment_sum(data, segment_ids, num_segments, mask)
    ones = jnp.ones(data.shape[0], dtype=data.dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    count = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    count = jnp.maximum(count, 1)
    return total / _bcast_trailing(count, total)


def segment_max(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    neg = jnp.finfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, neg)
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    # Segments with no (unmasked) contributions come back as -inf/min;
    # normalize them to empty_value so padding graphs stay finite.
    return jnp.where(out <= neg, jnp.asarray(empty_value, out.dtype), out)


def segment_min(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    pos = jnp.finfo(data.dtype).max if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).max
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, pos)
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jnp.where(out >= pos, jnp.asarray(empty_value, out.dtype), out)


def segment_std(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    eps: float = 1e-5,
) -> jax.Array:
    """Per-segment standard deviation (PNA 'std' aggregator)."""
    mean = segment_mean(data, segment_ids, num_segments, mask)
    sq_mean = segment_mean(data * data, segment_ids, num_segments, mask)
    var = jnp.maximum(sq_mean - mean * mean, 0.0)
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Numerically-stable softmax within segments (GAT attention)."""
    seg_max = segment_max(logits, segment_ids, num_segments, mask)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(_bcast(mask, exp), exp, 0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    denom = jnp.maximum(denom, 1e-16)
    return exp / denom[segment_ids]


def planned_path_wanted(num_edges: int, num_segments: int) -> bool:
    """THE dispatch policy for the planned sorted-segment kernels on a
    padded (E, N) shape: the shape must sit on the winning side of the
    regenerable crossover table (tools/segment_crossover.json via
    ops/pallas_segment.planned_profitable / fused_profitable — only
    TPU-measured rows count; oc20-class shapes measured 0.48-0.77x vs
    the XLA scatter and must never take the kernel silently) and the
    backend must be TPU. HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused]
    forces the planned path anywhere (interpret mode off-TPU); =xla
    forces the scatter — the override ladder lives ONCE in
    ``_impl_gate``, composed by this attach-level policy (the loader's
    decision to pay the host-side edge sort,
    GraphLoader.segment_plan_enabled), by the per-call-site dispatch
    (``_plan_dispatch``, which adds the feature width and the call
    site's kernel-flavor capability), and by the flavor choice
    (``fused_path_wanted``) — one grammar, so plans are attached
    exactly where they can be consumed."""
    gate = _impl_gate()
    if gate is not None:
        return gate
    from hydragnn_tpu.ops.pallas_segment import (
        fused_profitable,
        planned_profitable,
    )

    # ATTACH-level vote: optimistic across the table's feature-width
    # rows (a plan is cheap and harmless if the per-call dispatch —
    # which knows F — declines; a pessimistic veto here would make an
    # F-specific measured fused win permanently unreachable).
    return planned_profitable(
        num_edges, num_segments, optimistic_ties=True
    ) or fused_profitable(num_edges, num_segments, optimistic_ties=True)


def _impl_gate() -> Optional[bool]:
    """THE env/backend override ladder, in one place: True = planned
    path forced on (HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused]; interpret
    mode off-TPU), False = forced off (=xla, or a non-TPU backend),
    None = no override — consult the crossover table."""
    impl = _segment_impl()
    if impl.startswith("pallas"):
        return True
    if impl == "xla" or jax.default_backend() != "tpu":
        return False
    return None


def fused_path_wanted(
    num_edges: int,
    num_segments: int,
    feature_dim: Optional[int] = None,
) -> bool:
    """Kernel FLAVOR policy, subordinate to ``planned_path_wanted``:
    given that the planned path runs, should the fused edge-pipeline
    kernel (in-kernel gather/multiply/matmul) be taken over the
    reduce-only planned kernel? True only where the crossover table
    carries a TPU-MEASURED fused win (WHAT-IF rows never dispatch —
    graftboard's no-fabrication rule), or when
    HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused forces it for measurement
    (interpret mode off-TPU)."""
    impl = _segment_impl()
    if impl == "pallas_fused":
        return True
    if impl == "xla":
        return False
    from hydragnn_tpu.ops.pallas_segment import fused_profitable

    return fused_profitable(
        num_edges, num_segments, feature_dim=feature_dim
    )


def fused_bwd_wanted(
    num_edges: int,
    num_segments: int,
    feature_dim: Optional[int] = None,
) -> bool:
    """BACKWARD flavor policy (ISSUE 18), the pullback analogue of
    ``fused_path_wanted``: given that the forward ran
    ``edge_pipeline_planned`` (any flavor — its vjp is where this is
    consulted), should the symmetric one-pass Pallas backward kernel
    replace the XLA gather/scatter pullback? True only where the
    crossover table carries a TPU-MEASURED ``bwd_wins`` row (WHAT-IF
    rows never dispatch — gradients get no fabrication exemption), or
    when HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused forces it for
    measurement (interpret mode off-TPU). A non-TPU backend without
    the force stays on XLA: CPU/CI never takes the kernel silently."""
    impl = _segment_impl()
    if impl == "pallas_fused":
        return True
    if impl == "xla" or jax.default_backend() != "tpu":
        return False
    from hydragnn_tpu.ops.pallas_segment import bwd_profitable

    return bwd_profitable(
        num_edges, num_segments, feature_dim=feature_dim
    )


def _plan_dispatch(
    batch,
    feature_dim: Optional[int] = None,
    fused_capable: bool = False,
) -> bool:
    """Planned-kernel dispatch for a batch: a block plan must be
    present (collate with_segment_plan) AND the shared shape/backend
    policy must want THIS call site's kernel flavor. Reduce-only call
    sites (``aggregate_receivers`` — no fused variant exists for a
    plain sum) dispatch on the PLANNED verdict alone; fused-capable
    sites (product/pipeline) also dispatch where only the fused
    verdict wins. This is what keeps the acceptance rule honest: a
    shape where the reduce-only kernel measured a LOSS but the fused
    kernel a win must not drag plain sums onto the losing kernel.
    Shapes are trace-time constants, so the decision compiles away."""
    if batch.seg_window is None:
        return False
    gate = _impl_gate()
    if gate is not None:
        return gate
    from hydragnn_tpu.ops.pallas_segment import (
        fused_profitable,
        planned_profitable,
    )

    if planned_profitable(
        batch.num_edges, batch.num_nodes, feature_dim=feature_dim
    ):
        return True
    return fused_capable and fused_profitable(
        batch.num_edges, batch.num_nodes, feature_dim=feature_dim
    )


def aggregate_receivers(
    msg: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver-side message aggregation [E, F] -> [N, F].

    Dispatches to the Pallas sorted-segment kernel when the batch
    carries a block plan (collate with_segment_plan=True), we're on
    TPU, AND the padded shape is on the kernel's winning side of the
    measured crossover table (``_plan_dispatch``) — or anywhere when
    HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused] forces it (interpret mode
    off-TPU); falls back to the XLA scatter path otherwise. Both apply
    the edge mask — on the planned path it is FOLDED INTO the plan's
    ``valid`` slots at collate time (apply_segment_plan), so no masked
    copy of ``msg`` is materialized ahead of the in-kernel gather.
    """
    if use_plan is None:
        use_plan = _plan_dispatch(batch, feature_dim=msg.shape[-1])
    if use_plan and batch.seg_window is not None:
        from hydragnn_tpu.ops.pallas_segment import segment_sum_planned

        return segment_sum_planned(
            msg,
            batch.seg_perm,
            batch.seg_ids,
            batch.seg_valid,
            batch.seg_window,
            batch.num_nodes,
        )
    return segment_sum(
        msg, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def aggregate_receivers_product(
    a: jax.Array, b: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver aggregation of an elementwise product: segment_sum(a*b)
    where a is typically gathered sender features and b the per-edge
    filter (the SchNet message pipeline). With a batch block plan the
    reduce runs through the planned Pallas kernel; the fused variant
    (gather AND multiply inside the kernel — one HBM pass) dispatches
    through ``fused_path_wanted`` (TPU-measured table rows, or forced
    by HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused)."""
    if use_plan is None:
        use_plan = _plan_dispatch(
            batch, feature_dim=a.shape[-1], fused_capable=True
        )
    if use_plan and batch.seg_window is not None:
        if fused_path_wanted(
            batch.num_edges, batch.num_nodes, feature_dim=a.shape[-1]
        ):
            from hydragnn_tpu.ops.pallas_segment import (
                segment_sum_product_planned,
            )

            # padding edges are invalid plan slots (edge_mask folded
            # into seg_valid at collate) — NO pre-masked copy of the
            # operands, that is the traffic the fusion removes
            return segment_sum_product_planned(
                a,
                b,
                batch.seg_perm,
                batch.seg_ids,
                batch.seg_valid,
                batch.seg_window,
                batch.num_nodes,
            )
        return aggregate_receivers(a * b, batch, use_plan=True)
    return segment_sum(
        a * b, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def aggregate_receivers_pipeline(
    a: jax.Array,
    b: Optional[jax.Array],
    batch,
    *,
    weight: Optional[jax.Array] = None,
    mean: bool = False,
    use_plan: Optional[bool] = None,
) -> jax.Array:
    """The FULL edge pipeline as one dispatched op:

        out = segment_sum((a * b) @ weight)        [N, F_out]

    (``b`` may be None to drop the filter multiply, ``weight`` None to
    drop the matmul; ``mean=True`` divides by the masked in-degree).
    On the fused planned path (``fused_path_wanted``) the whole chain
    runs in one Pallas pass over the batch's block plan — gather,
    multiply, matmul, reduce with no HBM intermediate, and the mean's
    per-node degree scale divides AFTER the fused sum (it commutes
    with the matmul mathematically; the reorder is inside the fused
    path's documented ulp tolerance). The fallback decomposes into the
    dispatched product/sum aggregation, the mean division, then the
    XLA matmul — the EXACT op order of the Dense-after-aggregate call
    sites it replaces."""
    if use_plan is None:
        use_plan = _plan_dispatch(
            batch, feature_dim=a.shape[-1], fused_capable=True
        )
    count = None
    if mean:
        count = jnp.maximum(
            degree(
                batch.receivers, batch.num_nodes, mask=batch.edge_mask,
                dtype=a.dtype,
            ),
            1,
        )
    if (
        use_plan
        and batch.seg_window is not None
        and fused_path_wanted(
            batch.num_edges, batch.num_nodes, feature_dim=a.shape[-1]
        )
    ):
        from hydragnn_tpu.ops.pallas_segment import edge_pipeline_planned

        out = edge_pipeline_planned(
            a,
            b,
            weight,
            batch.seg_perm,
            batch.seg_ids,
            batch.seg_valid,
            batch.seg_window,
            batch.num_nodes,
        )
        if count is not None:
            out = out / _bcast_trailing(count.astype(out.dtype), out)
        return out
    if b is not None:
        out = aggregate_receivers_product(a, b, batch, use_plan=use_plan)
    else:
        out = aggregate_receivers(a, batch, use_plan=use_plan)
    if count is not None:
        out = out / _bcast_trailing(count.astype(out.dtype), out)
    if weight is not None:
        out = out @ weight
    return out


def aggregate_receivers_mean(
    msg: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver-side MEAN aggregation [E, F] -> [N, F] through the same
    planned-kernel dispatch as ``aggregate_receivers`` (sum via the
    winning path, then divide by the masked in-degree). Bit-identical
    to ``segment_mean(msg, batch.receivers, ...)`` on the scatter path
    — same masked sum, same count clamp."""
    total = aggregate_receivers(msg, batch, use_plan=use_plan)
    count = degree(
        batch.receivers, batch.num_nodes, mask=batch.edge_mask,
        dtype=msg.dtype,
    )
    count = jnp.maximum(count, 1)
    return total / _bcast_trailing(count, total)


def segment_multi_aggregate(
    h: jax.Array,
    batch,
    *,
    eps: float = 1e-5,
    use_plan: Optional[bool] = None,
):
    """PNA's (mean, min, max, std) aggregator stack in TWO passes over
    the receiver-sorted edge array instead of four independent segment
    ops (ISSUE 18). The moment pass reduces ``concat([h, h*h])``
    through ``aggregate_receivers`` — ONE planned-dispatchable
    segment sum at feature width 2F that yields mean and std (the
    same ``sqrt(max(E[x^2]-E[x]^2, 0) + eps)`` arithmetic as
    ``segment_std``). The extreme pass reduces ``concat([h, -h])``
    through ONE ``segment_min`` (max = -min(-h); min and max have no
    sum decomposition, so they cannot ride the planned kernel — but
    they can share a scatter). Empty segments: the min-of-(-h)
    normalization yields -0.0 for the max half, which equals the 0.0
    ``empty_value`` of the separate ops. Numerically identical to the
    old four-op decomposition — same formulas, same clamp, same eps —
    just batched."""
    f = h.shape[-1]
    moments = aggregate_receivers(
        jnp.concatenate([h, h * h], axis=-1), batch, use_plan=use_plan
    )
    count = jnp.maximum(
        degree(
            batch.receivers, batch.num_nodes, mask=batch.edge_mask,
            dtype=h.dtype,
        ),
        1,
    )
    moments = moments / _bcast_trailing(count.astype(moments.dtype), moments)
    mean, sq_mean = moments[:, :f], moments[:, f:]
    var = jnp.maximum(sq_mean - mean * mean, 0.0)
    std = jnp.sqrt(var + eps)
    ext = segment_min(
        jnp.concatenate([h, -h], axis=-1),
        batch.receivers,
        batch.num_nodes,
        mask=batch.edge_mask,
    )
    mn, mx = ext[:, :f], -ext[:, f:]
    return mean, mn, mx, std


_IMPL_OVERRIDE = ""


def set_segment_impl_override(value: Optional[str]) -> None:
    """Config-surface kernel-flavor override (Training.segment_impl),
    last-set-wins. ``run_training`` calls this on EVERY run — an
    absent config key CLEARS it — so back-to-back runs in one process
    cannot leak each other's flavor (an env setdefault would latch the
    first run's value forever). The env var still takes precedence:
    one grammar, shell wins over config."""
    global _IMPL_OVERRIDE
    _IMPL_OVERRIDE = value or ""


def _segment_impl() -> str:
    import os

    return os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL") or _IMPL_OVERRIDE


def degree(
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    ones = jnp.ones(segment_ids.shape[0], dtype=dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def _bcast(mask: jax.Array, data: jax.Array) -> jax.Array:
    """Reshape a [K] mask to broadcast against [K, ...] data."""
    return mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))


def _bcast_trailing(vec: jax.Array, data: jax.Array) -> jax.Array:
    return vec.reshape(vec.shape + (1,) * (data.ndim - vec.ndim))
