"""Segment reductions — the scatter/gather core of message passing.

TPU-native replacement for torch_scatter/torch_sparse segment ops
(reference dep: requirements-pyg.txt; used by every PyG conv in
hydragnn/models/*). Built on ``jax.ops.segment_*`` with static
``num_segments`` so XLA lowers them to one-hot matmuls / sorted scatters
that tile onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, 0)
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    total = segment_sum(data, segment_ids, num_segments, mask)
    ones = jnp.ones(data.shape[0], dtype=data.dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    count = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    count = jnp.maximum(count, 1)
    return total / _bcast_trailing(count, total)


def segment_max(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    neg = jnp.finfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, neg)
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    # Segments with no (unmasked) contributions come back as -inf/min;
    # normalize them to empty_value so padding graphs stay finite.
    return jnp.where(out <= neg, jnp.asarray(empty_value, out.dtype), out)


def segment_min(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    empty_value: float = 0.0,
) -> jax.Array:
    pos = jnp.finfo(data.dtype).max if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).max
    if mask is not None:
        data = jnp.where(_bcast(mask, data), data, pos)
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jnp.where(out >= pos, jnp.asarray(empty_value, out.dtype), out)


def segment_std(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    *,
    eps: float = 1e-5,
) -> jax.Array:
    """Per-segment standard deviation (PNA 'std' aggregator)."""
    mean = segment_mean(data, segment_ids, num_segments, mask)
    sq_mean = segment_mean(data * data, segment_ids, num_segments, mask)
    var = jnp.maximum(sq_mean - mean * mean, 0.0)
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Numerically-stable softmax within segments (GAT attention)."""
    seg_max = segment_max(logits, segment_ids, num_segments, mask)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(_bcast(mask, exp), exp, 0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    denom = jnp.maximum(denom, 1e-16)
    return exp / denom[segment_ids]


def planned_path_wanted(num_edges: int, num_segments: int) -> bool:
    """THE dispatch policy for the planned sorted-segment kernel on a
    padded (E, N) shape: the shape must sit on the winning side of the
    ROOFLINE-seeded crossover table
    (ops/pallas_segment.planned_profitable — oc20-class shapes measured
    0.48-0.77x vs the XLA scatter and must never take the kernel) and
    the backend must be TPU. HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused]
    forces the planned path anywhere (interpret mode off-TPU); =xla
    forces the scatter. Shared by the jitted-step dispatch
    (``_plan_dispatch``) and the loader's decision to pay the
    host-side edge sort (GraphLoader.segment_plan_enabled) — one
    policy, so plans are attached exactly where they are consumed."""
    impl = _segment_impl()
    if impl.startswith("pallas"):
        return True
    if impl == "xla" or jax.default_backend() != "tpu":
        return False
    from hydragnn_tpu.ops.pallas_segment import planned_profitable

    return planned_profitable(num_edges, num_segments)


def _plan_dispatch(batch) -> bool:
    """Planned-kernel dispatch for a batch: a block plan must be
    present (collate with_segment_plan) AND the shared shape/backend
    policy must want it. Shapes are trace-time constants, so the
    decision compiles away."""
    if batch.seg_window is None:
        return False
    return planned_path_wanted(batch.num_edges, batch.num_nodes)


def aggregate_receivers(
    msg: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver-side message aggregation [E, F] -> [N, F].

    Dispatches to the Pallas sorted-segment kernel when the batch
    carries a block plan (collate with_segment_plan=True), we're on
    TPU, AND the padded shape is on the kernel's winning side of the
    measured crossover table (``_plan_dispatch``) — or anywhere when
    HYDRAGNN_TPU_SEGMENT_IMPL=pallas[_fused] forces it (interpret mode
    off-TPU); falls back to the XLA scatter path otherwise. Both apply
    the edge mask.
    """
    if use_plan is None:
        use_plan = _plan_dispatch(batch)
    if use_plan and batch.seg_window is not None:
        from hydragnn_tpu.ops.pallas_segment import segment_sum_planned

        data = jnp.where(_bcast(batch.edge_mask, msg), msg, 0)
        return segment_sum_planned(
            data,
            batch.seg_perm,
            batch.seg_ids,
            batch.seg_valid,
            batch.seg_window,
            batch.num_nodes,
        )
    return segment_sum(
        msg, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def aggregate_receivers_product(
    a: jax.Array, b: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver aggregation of an elementwise product: segment_sum(a*b)
    where a is typically gathered sender features and b the per-edge
    filter (the SchNet message pipeline). With a batch block plan the
    reduce runs through the planned Pallas kernel; the in-kernel
    multiply variant is opt-in (HYDRAGNN_TPU_SEGMENT_IMPL=pallas_fused)
    until the roofline measurement shows it beating the unfused plan —
    XLA fuses the multiply into the plan gather on the default path."""
    if use_plan is None:
        use_plan = _plan_dispatch(batch)
    if use_plan and batch.seg_window is not None:
        if _segment_impl() == "pallas_fused":
            from hydragnn_tpu.ops.pallas_segment import (
                segment_sum_product_planned,
            )

            # masking ONE operand zeroes the product; the kernel also
            # ANDs valid into the one-hot
            return segment_sum_product_planned(
                jnp.where(_bcast(batch.edge_mask, a), a, 0),
                b,
                batch.seg_perm,
                batch.seg_ids,
                batch.seg_valid,
                batch.seg_window,
                batch.num_nodes,
            )
        return aggregate_receivers(a * b, batch, use_plan=True)
    return segment_sum(
        a * b, batch.receivers, batch.num_nodes, mask=batch.edge_mask
    )


def aggregate_receivers_mean(
    msg: jax.Array, batch, *, use_plan: Optional[bool] = None
) -> jax.Array:
    """Receiver-side MEAN aggregation [E, F] -> [N, F] through the same
    planned-kernel dispatch as ``aggregate_receivers`` (sum via the
    winning path, then divide by the masked in-degree). Bit-identical
    to ``segment_mean(msg, batch.receivers, ...)`` on the scatter path
    — same masked sum, same count clamp."""
    total = aggregate_receivers(msg, batch, use_plan=use_plan)
    count = degree(
        batch.receivers, batch.num_nodes, mask=batch.edge_mask,
        dtype=msg.dtype,
    )
    count = jnp.maximum(count, 1)
    return total / _bcast_trailing(count, total)


def _segment_impl() -> str:
    import os

    return os.environ.get("HYDRAGNN_TPU_SEGMENT_IMPL", "")


def degree(
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    ones = jnp.ones(segment_ids.shape[0], dtype=dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def _bcast(mask: jax.Array, data: jax.Array) -> jax.Array:
    """Reshape a [K] mask to broadcast against [K, ...] data."""
    return mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))


def _bcast_trailing(vec: jax.Array, data: jax.Array) -> jax.Array:
    return vec.reshape(vec.shape + (1,) * (data.ndim - vec.ndim))
