"""Symmetric tensor contraction — the core MACE n-body product op.

From-scratch JAX equivalent of the reference's e3nn-based
``SymmetricContraction`` (hydragnn/utils/model/mace_utils/modules/
symmetric_contraction.py:29-242) and the U-matrix generation it relies
on (mace_utils/tools/cg.py:94 ``U_matrix_real``).

``u_matrix_real(lmax_in, l_out, nu)`` builds an orthonormal basis of
permutation-symmetric equivariant maps  Sym^nu(V) -> irrep l_out, where
V = ⊕_{l<=lmax_in} R^{2l+1} is the concatenated spherical-harmonic
space (dim M = (lmax_in+1)^2). Construction: recursively couple factors
with the real Clebsch-Gordan tensors from ``hydragnn_tpu.ops.e3``,
symmetrize over factor permutations, and orthonormalize via SVD. The
result spans the same space as e3nn's U matrices (up to an orthonormal
re-mixing that the learned weights absorb).

The runtime contraction follows MACE's descending-correlation einsum
chain so that weights for every correlation order share the same
[num_elements, num_params, channels] layout.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from hydragnn_tpu.ops.e3 import real_wigner_3j, sh_dim


def _block(l: int) -> slice:
    return slice(l * l, (l + 1) * (l + 1))


@lru_cache(maxsize=None)
def _coupling_maps(
    lmax_in: int, nu: int, lam_cap: int
) -> Tuple[Tuple[int, np.ndarray], ...]:
    """All CG-chain tensors coupling nu factors of V to any irrep lam.

    Returns tuples (lam, T) with T of shape [2*lam+1, M, ..., M] (nu
    M-axes). ``lam_cap`` prunes intermediates that cannot reach the
    final target.
    """
    M = sh_dim(lmax_in)
    if nu == 1:
        out = []
        for l in range(lmax_in + 1):
            t = np.zeros((2 * l + 1, M))
            t[:, _block(l)] = np.eye(2 * l + 1)
            out.append((l, t))
        return tuple(out)
    prev = _coupling_maps(lmax_in, nu - 1, lam_cap + lmax_in)
    out = []
    for lam_prev, tp in prev:
        for l in range(lmax_in + 1):
            for lam in range(abs(lam_prev - l), lam_prev + l + 1):
                if lam > lam_cap:
                    continue
                cg = real_wigner_3j(lam_prev, l, lam)  # [2lp+1, 2l+1, 2lam+1]
                # T_new[c, ..., i_nu] = sum_{a,b} cg[a,b,c] tp[a, ...] e_l[b -> i]
                t = np.einsum("abc,a...->cb...", cg, tp)
                full = np.zeros(t.shape[:1] + (M,) + t.shape[2:])
                full[:, _block(l)] = t
                # move the new factor axis to the end
                full = np.moveaxis(full, 1, -1)
                out.append((lam, full))
    return tuple(out)


@lru_cache(maxsize=None)
def u_matrix_real(lmax_in: int, l_out: int, nu: int) -> np.ndarray:
    """Orthonormal symmetric coupling basis [2*l_out+1, M^nu..., P].

    P = number of independent symmetrized paths; P may be 0 (returned
    as a trailing axis of size 0) when no coupling reaches ``l_out``.
    """
    import itertools

    M = sh_dim(lmax_in)
    cands = [
        t for lam, t in _coupling_maps(lmax_in, nu, l_out) if lam == l_out
    ]
    if not cands:
        return np.zeros((2 * l_out + 1,) + (M,) * nu + (0,))
    perms = list(itertools.permutations(range(nu)))
    sym = []
    for t in cands:
        acc = np.zeros_like(t)
        for p in perms:
            axes = (0,) + tuple(1 + np.argsort(p))
            acc = acc + np.transpose(t, axes)
        acc /= len(perms)
        if np.abs(acc).max() > 1e-10:
            sym.append(acc)
    if not sym:
        return np.zeros((2 * l_out + 1,) + (M,) * nu + (0,))
    flat = np.stack([t.reshape(-1) for t in sym])  # [n_cand, D]
    # Orthonormal basis of the span.
    u, s, vh = np.linalg.svd(flat, full_matrices=False)
    keep = s > 1e-8 * s[0]
    basis = vh[keep]  # [P, D]
    P = basis.shape[0]
    out = basis.reshape((P, 2 * l_out + 1) + (M,) * nu)
    return np.moveaxis(out, 0, -1)


# Factor-axis einsum letters; must avoid b (batch), c (channels),
# e (elements), i (contracted factor), k (params), z (output m).
_ABC = "dfghjl"


class SymmetricContraction(nn.Module):
    """x [N, C, M], node one-hot y [N, Z] -> [N, C * sum(2l+1 for l_out)].

    Per-element weights [Z, P, C] for every (l_out, correlation) pair,
    contracted through MACE's descending chain: the highest correlation
    term is built first, lower-order terms are added via re-weighted
    contractions with x (reference symmetric_contraction.py:92-242).
    """

    lmax_in: int
    lmax_out: int
    correlation: int
    num_elements: int

    @nn.compact
    def __call__(self, x: jax.Array, y: jax.Array) -> jax.Array:
        outs = []
        for l_out in range(self.lmax_out + 1):
            outs.append(self._contract_irrep(x, y, l_out))
        return jnp.concatenate(outs, axis=-1)

    def _contract_irrep(self, x, y, l_out: int) -> jax.Array:
        n, c, m = x.shape
        nu = self.correlation
        us = {
            i: u_matrix_real(self.lmax_in, l_out, i) for i in range(1, nu + 1)
        }
        dim_out = 2 * l_out + 1
        # m-axis subscript exists only for l_out > 0 (e3nn squeezes l=0).
        mo = "z" if l_out > 0 else ""

        def w(i):
            p = us[i].shape[-1]
            return self.param(
                f"w{l_out}_{i}",
                lambda key, shape: jax.random.normal(key, shape)
                / max(shape[1], 1),
                (self.num_elements, p, c),
            )

        u_nu = jnp.asarray(
            us[nu].squeeze(0) if l_out == 0 else us[nu], x.dtype
        )
        # main: out[b, c, (z), i1..i_{nu-1}] =
        #   U[(z), i1..i_nu, k] W[e,k,c] x[b,c,i_nu] y[b,e]
        ii = _ABC[: nu - 1]
        sub = f"{mo}{ii}ik,ekc,bci,be->bc{mo}{ii}"
        out = jnp.einsum(sub, u_nu, w(nu), x, y)
        for i in range(nu - 1, 0, -1):
            u_i = jnp.asarray(
                us[i].squeeze(0) if l_out == 0 else us[i], x.dtype
            )
            if us[i].shape[-1] == 0:
                c_tensor = out
            else:
                jj = _ABC[:i]
                sub_w = f"{mo}{jj}k,ekc,be->bc{mo}{jj}"
                c_tensor = jnp.einsum(sub_w, u_i, w(i), y) + out
            kk = _ABC[: i - 1]
            sub_f = f"bc{mo}{kk}i,bci->bc{mo}{kk}"
            out = jnp.einsum(sub_f, c_tensor, x)
        # out: [N, C] (l=0) or [N, C, 2l+1]
        if l_out == 0:
            return out[..., None] if out.ndim == 2 else out
        return out
