"""Pallas TPU kernel: sorted-segment sum as blocked MXU matmuls.

The message-passing hot op (SURVEY.md §7 step 2: "Pallas kernels for
gather→MLP→segment-reduce fusion") — a segment-sum over edges sorted by
receiver, computed as a chain of small one-hot matmuls on the MXU
instead of a scatter-add:

  for each edge block b (size BE, all of whose receivers fall inside
  one BN-aligned node window w_b):
      onehot[n, e] = (seg[e] - BN * w_b == n) & valid[e]   # VPU compare
      out[window w_b] += onehot @ data_block               # MXU [BN,BE]@[BE,F]

Host-side ``plan_sorted_blocks`` splits the sorted edge list into such
blocks (padding at window boundaries) and emits per-block window ids —
prefetched scalars that drive the output BlockSpec index_map, so each
output tile is revisited only by consecutive grid steps (safe sequential
accumulation on TPU).

The backward pass of segment-sum is a plain gather (d_data[e] =
g[seg[e]]), wired via custom_vjp.

Measured on TPU v5e (E=32k sorted edges, N=3k nodes, F=128, bf16):
within noise of XLA's native scatter lowering (which is already
memory-bound) — the kernel's value is as the fusion point for edge
pipelines (gather+scale+reduce in one HBM pass) and as the tuning
surface for larger F. Enable via segment_sum_sorted or
HYDRAGNN_TPU_SEGMENT_IMPL=pallas (see ops/segment.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BE = 512  # edges per block
DEFAULT_BN = 256  # node window (output tile rows)

# ----------------------------------------------------------------------
# Shape-keyed Pallas-vs-XLA crossover, seeded from ROOFLINE_TPU.txt
# (TPU v5 lite). The planned kernel's MXU work scales with the BLOCK
# count (~E/be + N/bn) times the full bn x be one-hot matmul, while
# XLA's scatter is memory-bound — so the kernel wins on qm9-class
# shapes and loses badly once E (and F) grow to oc20 scale:
#   qm9_b128  N=4224  E=33792  F=128: pallas/xla reduce 1.02-1.15x
#   oc20_b32  N=8192  E=327680 F=256: reduce 0.60-0.75x, fused 0.48x
# Dispatch = verdict of the nearest measured shape in log-size space;
# re-measure with tools/roofline_segment.py and extend the table when
# new workload scales appear.
# ----------------------------------------------------------------------
PLANNED_CROSSOVER: Tuple[Tuple[int, int, bool], ...] = (
    # (num_edges, num_segments, planned kernel wins)
    (33792, 4224, True),  # qm9_b128
    (327680, 8192, False),  # oc20_b32
)


def planned_profitable(
    num_edges: int,
    num_segments: int,
    table: Tuple[Tuple[int, int, bool], ...] = PLANNED_CROSSOVER,
) -> bool:
    """True when the planned sorted-segment kernel WINS for a padded
    (E, N) shape — a pure nearest-neighbor lookup in log space over the
    measured crossover table. Backend and HYDRAGNN_TPU_SEGMENT_IMPL
    overrides live in ONE place, ``ops.segment.planned_path_wanted``
    (the production dispatch policy) — keep this function env-free so
    the two can never disagree on the grammar."""
    if not table:
        return False
    le = np.log(max(int(num_edges), 1))
    ln = np.log(max(int(num_segments), 1))
    best = min(
        table,
        key=lambda row: (le - np.log(max(row[0], 1))) ** 2
        + (ln - np.log(max(row[1], 1))) ** 2,
    )
    return bool(best[2])


def plan_sorted_blocks(
    seg_sorted: np.ndarray,
    num_segments: int,
    be: int = DEFAULT_BE,
    bn: int = DEFAULT_BN,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Split sorted segment ids into window-aligned padded blocks.

    Returns (perm, seg_padded, valid, window_id):
      perm      [B*be] int32 — index into the original edge array for
                each padded slot (0 for padding; masked by ``valid``)
      seg_padded[B*be] int32 — segment id per slot (window start for pads)
      valid     [B*be] bool
      window_id [B]    int32 — output tile row-block per edge block
    """
    seg = np.asarray(seg_sorted, np.int64)
    e = len(seg)
    n_windows = max((num_segments + bn - 1) // bn, 1)
    windows = seg // bn
    # Edge run per window (sorted ids -> contiguous runs; empty windows
    # still get one all-padding block so their output tile is zeroed).
    starts = np.searchsorted(windows, np.arange(n_windows), side="left")
    ends = np.searchsorted(windows, np.arange(n_windows), side="right")
    perm_l, seg_l, val_l, win_l = [], [], [], []
    for w in range(n_windows):
        a, b = int(starts[w]), int(ends[w])
        block_starts = list(range(a, b, be)) or [a]
        for s in block_starts:
            t = min(s + be, b)
            n_pad = be - (t - s)
            perm_l.append(
                np.concatenate(
                    [np.arange(s, t), np.zeros(n_pad, np.int64)]
                )
            )
            seg_l.append(
                np.concatenate(
                    [seg[s:t], np.full(n_pad, w * bn, np.int64)]
                )
            )
            val_l.append(
                np.concatenate(
                    [np.ones(t - s, bool), np.zeros(n_pad, bool)]
                )
            )
            win_l.append(w)
    return (
        np.concatenate(perm_l).astype(np.int32),
        np.concatenate(seg_l).astype(np.int32),
        np.concatenate(val_l),
        np.asarray(win_l, np.int32),
    )


def _kernel(
    window_ref, seg_ref, data_ref, valid_ref, out_ref, *, bn, be, b_ref=None
):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    node_base = window_ref[b] * bn
    local = seg_ref[0, :] - node_base  # [be]
    rows = jax.lax.broadcasted_iota(jnp.int32, (bn, be), 0)
    onehot = (local[None, :] == rows) & (valid_ref[0, :] != 0)[None, :]
    block = data_ref[:].astype(jnp.float32)
    if b_ref is not None:
        # fused edge pipeline: the filter multiply happens here in VMEM,
        # so the [E, F] message intermediate never round-trips HBM
        block = block * b_ref[:].astype(jnp.float32)
    # f32 data must not round through the MXU's bf16 multiplies; the
    # onehot operand is exact either way. bf16 data multiplies natively
    # (exact into the f32 MXU accumulator).
    precision = (
        jax.lax.Precision.HIGHEST
        if data_ref.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    acc = jax.lax.dot(
        onehot.astype(jnp.float32),
        block,
        precision=precision,
    )

    is_first = jnp.logical_or(
        b == 0, window_ref[b] != window_ref[jnp.maximum(b - 1, 0)]
    )

    @pl.when(is_first)
    def _():
        out_ref[:] = acc.astype(out_ref.dtype)

    @pl.when(jnp.logical_not(is_first))
    def _():
        out_ref[:] = out_ref[:] + acc.astype(out_ref.dtype)


def _kernel_mul(window_ref, seg_ref, a_ref, b_ref, valid_ref, out_ref, *, bn, be):
    _kernel(
        window_ref, seg_ref, a_ref, valid_ref, out_ref,
        bn=bn, be=be, b_ref=b_ref,
    )


def _pallas_segment_sum_impl(
    data_padded: jax.Array,  # [B*be, F] gathered+masked edge data
    seg_padded: jax.Array,  # [B*be]
    valid: jax.Array,  # [B*be]
    window_id: jax.Array,  # [B]
    *,
    b_padded: Optional[jax.Array] = None,  # optional second operand
    num_segments: int,
    bn: int,
    be: int,
):
    """Shared pallas_call builder for the plain and product kernels
    (they differ only in the optional second operand multiplied in
    VMEM)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_blocks = window_id.shape[0]
    f = data_padded.shape[1]
    n_pad = ((num_segments + bn - 1) // bn) * bn

    # 1-D int operands trip Mosaic's layout rules; ship per-block rows
    # as (8, be) tiles (sublane dim must be a multiple of 8) — each
    # block's ids replicated across the 8 sublanes.
    seg2d = jnp.repeat(seg_padded.reshape(n_blocks, 1, be), 8, axis=1)
    seg2d = seg2d.reshape(n_blocks * 8, be)
    valid2d = jnp.repeat(
        valid.astype(jnp.int32).reshape(n_blocks, 1, be), 8, axis=1
    ).reshape(n_blocks * 8, be)
    data_specs = [pl.BlockSpec((be, f), lambda b, win: (b, 0))]
    operands = [seg2d, data_padded]
    if b_padded is not None:
        data_specs.append(pl.BlockSpec((be, f), lambda b, win: (b, 0)))
        operands.append(b_padded)
        kernel = functools.partial(_kernel_mul, bn=bn, be=be)
    else:
        kernel = functools.partial(_kernel, bn=bn, be=be)
    operands.append(valid2d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # window_id drives the output index_map
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((8, be), lambda b, win: (b, 0)),
            *data_specs,
            pl.BlockSpec((8, be), lambda b, win: (b, 0)),
        ],
        out_specs=pl.BlockSpec((bn, f), lambda b, win: (win[b], 0)),
    )
    # The output tile is ALWAYS f32: a window's partial sums revisit the
    # tile across consecutive blocks, and accumulating those partials in
    # bf16 would lose precision for high-degree receivers (each block's
    # MXU matmul already accumulates in f32 internally). Cast once at
    # the end.
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, f), jnp.float32),
        grid_spec=grid_spec,
        # CPU has no Mosaic backend; interpret mode keeps the kernel
        # differentially testable on the virtual CPU mesh.
        interpret=jax.default_backend() == "cpu",
    )(window_id, *operands)
    return out[:num_segments].astype(data_padded.dtype)


@functools.partial(jax.jit, static_argnames=("num_segments", "bn", "be"))
def _pallas_segment_sum_planned(
    data_padded: jax.Array,  # [B*be, F] gathered+masked edge data
    seg_padded: jax.Array,  # [B*be]
    valid: jax.Array,  # [B*be]
    window_id: jax.Array,  # [B]
    *,
    num_segments: int,
    bn: int,
    be: int,
):
    return _pallas_segment_sum_impl(
        data_padded, seg_padded, valid, window_id,
        num_segments=num_segments, bn=bn, be=be,
    )


@functools.partial(jax.jit, static_argnames=("num_segments", "bn", "be"))
def _pallas_segment_sum_product_planned(
    a_padded: jax.Array,  # [B*be, F] first operand in plan-slot order
    b_padded: jax.Array,  # [B*be, F] second operand in plan-slot order
    seg_padded: jax.Array,  # [B*be]
    valid: jax.Array,  # [B*be]
    window_id: jax.Array,  # [B]
    *,
    num_segments: int,
    bn: int,
    be: int,
):
    """segment_sum(a * b) with the elementwise product inside the kernel
    (VMEM). NOTE: whether this nets HBM traffic vs the unfused
    ``plan(a * b)`` depends on whether XLA fuses the multiply into the
    plan gather (both permuted operands are still materialized outside
    the kernel here) — tools/roofline_segment.py's ``pallas_fused`` row
    measures it; keep the unfused path unless that row wins."""
    return _pallas_segment_sum_impl(
        a_padded, seg_padded, valid, window_id,
        b_padded=b_padded, num_segments=num_segments, bn=bn, be=be,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def segment_sum_product_planned(
    a: jax.Array,  # [E, F] e.g. gathered sender features, edge order
    b: jax.Array,  # [E, F] e.g. filter weights, edge order
    perm: jax.Array,  # [B*be] plan slot -> edge index
    seg_padded: jax.Array,  # [B*be]
    valid: jax.Array,  # [B*be] bool
    window_id: jax.Array,  # [B]
    num_segments: int,
    bn: int = DEFAULT_BN,
    be: int = DEFAULT_BE,
) -> jax.Array:
    """Differentiable fused segment_sum(a * b) over a block plan.

    Equivalent to ``segment_sum_planned(a * b, ...)`` with the multiply
    inside the Pallas kernel. Experimental: see the traffic caveat on
    ``_pallas_segment_sum_product_planned`` — measure with
    tools/roofline_segment.py before preferring this over the unfused
    planned path.
    """
    # masking one operand zeroes the product (the kernel also ANDs
    # valid into the one-hot); b is permuted unmasked
    mask = valid[:, None].astype(a.dtype)
    return _pallas_segment_sum_product_planned(
        a[perm] * mask, b[perm],
        seg_padded, valid, window_id,
        num_segments=num_segments, bn=bn, be=be,
    )


def _product_fwd(a, b, perm, seg_padded, valid, window_id, num_segments, bn, be):
    out = segment_sum_product_planned(
        a, b, perm, seg_padded, valid, window_id, num_segments, bn, be
    )
    return out, (a, b, perm, seg_padded, valid)


def _product_bwd(num_segments, bn, be, res, g):
    a, b, perm, seg_padded, valid = res
    # d/da segment_sum(a*b)[n] = b[e] * g[seg[e]]; pull back per slot,
    # scatter to edge order by perm (padding slots masked out).
    mask = valid[:, None].astype(g.dtype)
    slot_g = g[seg_padded] * mask
    d_a = jnp.zeros(a.shape, g.dtype).at[perm].add(slot_g * b[perm])
    d_b = jnp.zeros(b.shape, g.dtype).at[perm].add(slot_g * a[perm])
    return (d_a, d_b, None, None, None, None)


segment_sum_product_planned.defvjp(_product_fwd, _product_bwd)


class SortedSegmentPlan:
    """Host-side reusable plan for a fixed (sorted) edge layout.

    The padded batches produced by ``collate`` have a static edge
    layout per bucket, so one plan serves every batch of that shape.
    """

    def __init__(
        self,
        seg_sorted: np.ndarray,
        num_segments: int,
        be: int = DEFAULT_BE,
        bn: int = DEFAULT_BN,
    ):
        perm, seg_p, valid, window = plan_sorted_blocks(
            seg_sorted, num_segments, be, bn
        )
        self.num_segments = int(num_segments)
        self.be, self.bn = be, bn
        self.perm = jnp.asarray(perm)
        self.seg_padded = jnp.asarray(seg_p)
        self.valid = jnp.asarray(valid)
        self.window_id = jnp.asarray(window)

    def __call__(self, data: jax.Array) -> jax.Array:
        """segment-sum of [E, F] edge data laid out as planned."""
        gathered = data[self.perm] * self.valid[:, None].astype(data.dtype)
        return _pallas_segment_sum_planned(
            gathered,
            self.seg_padded,
            self.valid,
            self.window_id,
            num_segments=self.num_segments,
            bn=self.bn,
            be=self.be,
        )

    def reduce_product(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """Fused segment-sum of ``a * b`` (multiply in-kernel;
        experimental — see segment_sum_product_planned)."""
        return segment_sum_product_planned(
            a, b, self.perm, self.seg_padded, self.valid, self.window_id,
            self.num_segments, self.bn, self.be,
        )


def plan_blocks_static(
    seg_sorted: np.ndarray,
    num_segments: int,
    n_blocks_static: int,
    be: int = DEFAULT_BE,
    bn: int = DEFAULT_BN,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """plan_sorted_blocks padded to a STATIC block count so plans can be
    batch data inside one compiled step (bucketed loaders have static
    E/N, and B <= ceil(E/be) + ceil(N/bn) =: n_blocks_static). Padding
    blocks repeat the last window with no valid slots (accumulate
    zeros)."""
    perm, seg_p, valid, window = plan_sorted_blocks(
        seg_sorted, num_segments, be, bn
    )
    b = len(window)
    if b > n_blocks_static:
        raise ValueError(
            f"plan needs {b} blocks > static bound {n_blocks_static}"
        )
    pad = n_blocks_static - b
    if pad:
        perm = np.concatenate([perm, np.zeros(pad * be, np.int32)])
        seg_p = np.concatenate(
            [seg_p, np.full(pad * be, int(window[-1]) * bn, np.int32)]
        )
        valid = np.concatenate([valid, np.zeros(pad * be, bool)])
        window = np.concatenate(
            [window, np.full(pad, window[-1], np.int32)]
        )
    return perm, seg_p, valid, window


def static_block_bound(
    num_edges: int, num_segments: int, be: int = DEFAULT_BE, bn: int = DEFAULT_BN
) -> int:
    return (num_edges + be - 1) // be + (num_segments + bn - 1) // bn


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7)
)
def segment_sum_planned(
    data: jax.Array,  # [E, F] edge data in the ORIGINAL edge order
    perm: jax.Array,  # [B*be] plan slot -> edge index
    seg_padded: jax.Array,  # [B*be]
    valid: jax.Array,  # [B*be] bool
    window_id: jax.Array,  # [B]
    num_segments: int,
    bn: int = DEFAULT_BN,
    be: int = DEFAULT_BE,
) -> jax.Array:
    """Sorted-segment sum with the block plan as RUNTIME inputs — plans
    become batch fields (collate computes them host-side), so one
    compiled step serves every batch of a bucket."""
    gathered = data[perm] * valid[:, None].astype(data.dtype)
    return _pallas_segment_sum_planned(
        gathered, seg_padded, valid, window_id,
        num_segments=num_segments, bn=bn, be=be,
    )


def _planned_fwd(data, perm, seg_padded, valid, window_id, num_segments, bn, be):
    out = segment_sum_planned(
        data, perm, seg_padded, valid, window_id, num_segments, bn, be
    )
    return out, (data.shape, perm, seg_padded, valid)


def _planned_bwd(num_segments, bn, be, res, g):
    shape, perm, seg_padded, valid = res
    # d out[n] / d data[e] = [e contributes to n]; pull back through the
    # plan: slot grad = g[seg[slot]] * valid, scattered to edges by perm.
    slot_grad = g[seg_padded] * valid[:, None].astype(g.dtype)
    d_data = jnp.zeros(shape, g.dtype).at[perm].add(slot_grad)
    return (d_data, None, None, None, None)


segment_sum_planned.defvjp(_planned_fwd, _planned_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_sorted(
    data: jax.Array, seg_sorted: jax.Array, num_segments: int
) -> jax.Array:
    """Differentiable sorted-segment sum via the Pallas kernel.

    ``seg_sorted`` must be non-decreasing. The block plan is built
    host-side per unique id layout (cheap for bucketed batches), so this
    entry point must be called OUTSIDE jit; inside a jitted step,
    pre-build a ``SortedSegmentPlan`` and call it directly (its arrays
    become compile-time constants).
    """
    return _fwd_impl(data, seg_sorted, num_segments)


def _fwd_impl(data, seg_sorted, num_segments):
    plan = _plan_cache(
        np.asarray(jax.device_get(seg_sorted), np.int32).tobytes(),
        int(data.shape[0]),
        int(num_segments),
    )
    return plan(data)


@functools.lru_cache(maxsize=64)
def _plan_cache(seg_bytes: bytes, e: int, num_segments: int):
    seg = np.frombuffer(seg_bytes, np.int32, count=e)
    return SortedSegmentPlan(seg, num_segments)


def _vjp_fwd(data, seg_sorted, num_segments):
    return _fwd_impl(data, seg_sorted, num_segments), seg_sorted


def _vjp_bwd(num_segments, seg_sorted, g):
    # d/d data of a segment sum = broadcast back: gather rows.
    return (g[seg_sorted], None)


segment_sum_sorted.defvjp(_vjp_fwd, _vjp_bwd)
