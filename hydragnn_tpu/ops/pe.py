"""Positional encodings for global attention.

Host-side (numpy) Laplacian eigenvector PE and relative PE, computed at
preprocessing time like the reference (AddLaplacianEigenvectorPE in
hydragnn/preprocess/serialized_dataset_loader.py:183-189 and the rel_pe
construction feeding hydragnn/globalAtt/gps.py).
"""

from __future__ import annotations

import numpy as np


def laplacian_pe(edge_index: np.ndarray, num_nodes: int, k: int) -> np.ndarray:
    """First k non-trivial eigenvectors of the normalized graph Laplacian.

    Returns [num_nodes, k]; sign-fixed by making the max-|.| entry of each
    vector positive (eigenvector sign is arbitrary).
    """
    A = np.zeros((num_nodes, num_nodes))
    if edge_index.size:
        A[edge_index[1], edge_index[0]] = 1.0
        A[edge_index[0], edge_index[1]] = 1.0
    deg = A.sum(axis=1)
    d_inv_sqrt = np.where(deg > 0, 1.0 / np.sqrt(np.maximum(deg, 1e-12)), 0.0)
    L = np.eye(num_nodes) - d_inv_sqrt[:, None] * A * d_inv_sqrt[None, :]
    vals, vecs = np.linalg.eigh(L)
    order = np.argsort(vals)
    vecs = vecs[:, order]
    # Skip the trivial constant eigenvector (eigenvalue ~0).
    pe = vecs[:, 1 : k + 1]
    if pe.shape[1] < k:
        pe = np.pad(pe, ((0, 0), (0, k - pe.shape[1])))
    # Deterministic sign.
    signs = np.sign(pe[np.argmax(np.abs(pe), axis=0), np.arange(pe.shape[1])])
    signs = np.where(signs == 0, 1.0, signs)
    return (pe * signs).astype(np.float32)


def relative_pe(edge_index: np.ndarray, pe: np.ndarray) -> np.ndarray:
    """Per-edge relative PE: pe[sender] - pe[receiver]."""
    if edge_index.size == 0:
        return np.zeros((0, pe.shape[1]), dtype=pe.dtype)
    return (pe[edge_index[0]] - pe[edge_index[1]]).astype(pe.dtype)
