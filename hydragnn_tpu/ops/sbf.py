"""DimeNet spherical basis: spherical Bessel x Legendre angular functions.

Functional JAX equivalent of the reference's SphericalBasisLayer /
BesselBasisLayer (imported from PyG in hydragnn/models/DIMEStack.py:22-27
and used via the DIMEStack rbf/sbf members). The reference relies on
sympy-generated closed forms, which are numerically unstable in bf16/f32;
here each radial basis function norm_ln * j_l(z_ln * d), d in [0,1], is
fitted once on the host with float64 Chebyshev interpolation and evaluated
on device as a single cos(k*arccos(t)) @ coeffs matmul — stable, exact to
~1e-6, and MXU-shaped.

Shapes: dist [E] -> rbf [E, num_radial]; (dist, angle, idx_kj) ->
sbf [T, num_spherical * num_radial].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.ops.rbf import envelope


# ----------------------------------------------------------------------
# Host-side float64: j_l evaluation, roots, Chebyshev interpolation.
# ----------------------------------------------------------------------

def _jl_host(l: int, x: np.ndarray) -> np.ndarray:
    """Spherical Bessel j_l on the host in float64.

    Uses scipy when present; otherwise a series/recurrence hybrid that is
    accurate to ~1e-9 absolute for l <= 8 (enough for the Chebyshev fit).
    """
    x = np.asarray(x, dtype=np.float64)
    try:
        from scipy.special import spherical_jn

        return spherical_jn(l, x)
    except ImportError:
        pass
    x_safe = np.where(np.abs(x) < 1e-12, 1e-12, x)
    out_rec = np.sin(x_safe) / x_safe
    if l >= 1:
        jm, jc = out_rec, np.sin(x_safe) / x_safe**2 - np.cos(x_safe) / x_safe
        for n in range(1, l):
            jm, jc = jc, (2 * n + 1) / x_safe * jc - jm
        out_rec = jc
    # Series near zero (float64: accurate for x < 0.5).
    t = 0.5 * x * x
    dfact = 1.0
    for k in range(l + 1):
        dfact *= 2 * k + 1
    ser = (
        x**l
        / dfact
        * (1.0 - t / (2 * l + 3) + t * t / (2.0 * (2 * l + 3) * (2 * l + 5)))
    )
    return np.where(np.abs(x) < 0.5, ser, out_rec)


@functools.lru_cache(maxsize=None)
def spherical_bessel_roots(num_spherical: int, num_radial: int) -> np.ndarray:
    """First ``num_radial`` positive roots of j_l, l = 0..num_spherical-1.

    Roots of j_l interlace those of j_{l-1}; each is found by bisection
    inside the interlacing bracket (j_0 roots are n*pi exactly).
    """
    n_extra = num_radial + num_spherical
    roots = np.zeros((num_spherical, n_extra))
    roots[0] = np.arange(1, n_extra + 1) * np.pi
    for l in range(1, num_spherical):
        for k in range(n_extra - l):
            lo, hi = roots[l - 1, k], roots[l - 1, k + 1]
            flo = float(_jl_host(l, np.array([lo + 1e-9]))[0])
            for _ in range(80):
                mid = 0.5 * (lo + hi)
                fm = float(_jl_host(l, np.array([mid]))[0])
                if fm == 0.0:
                    lo = hi = mid
                    break
                if (fm > 0) == (flo > 0):
                    lo, flo = mid, fm
                else:
                    hi = mid
            roots[l, k] = 0.5 * (lo + hi)
    return roots[:, :num_radial].copy()


@functools.lru_cache(maxsize=None)
def _radial_cheb_coeffs(
    num_spherical: int, num_radial: int, degree: int = 64
) -> np.ndarray:
    """Chebyshev coefficients [degree, S*R] of the normalized radial
    functions f_ln(d) = sqrt(2 / j_{l+1}(z_ln)^2) * j_l(z_ln * d), d in
    [0,1] mapped to t = 2d-1 in [-1,1]."""
    z = spherical_bessel_roots(num_spherical, num_radial)  # [S, R]
    norm = np.zeros_like(z)
    for l in range(num_spherical):
        norm[l] = np.sqrt(2.0 / _jl_host(l + 1, z[l]) ** 2)

    K = degree
    theta = (np.arange(K) + 0.5) * np.pi / K
    t_nodes = np.cos(theta)  # Chebyshev nodes in [-1,1]
    d_nodes = 0.5 * (t_nodes + 1.0)  # map to [0,1]

    # Sample all (l, n) functions at the nodes: [K, S, R]
    f = np.zeros((K, num_spherical, num_radial))
    for l in range(num_spherical):
        for n in range(num_radial):
            f[:, l, n] = norm[l, n] * _jl_host(l, z[l, n] * d_nodes)

    # DCT-based Chebyshev coefficients: c_k = (2-delta_k0)/K sum f cos(k theta)
    kth = np.outer(np.arange(K), theta)  # [K, K]
    weights = np.cos(kth)  # [k, node]
    c = 2.0 / K * weights @ f.reshape(K, -1)  # [K, S*R]
    c[0] *= 0.5
    return c  # [degree, S*R]


# ----------------------------------------------------------------------
# Device-side evaluation.
# ----------------------------------------------------------------------

def chebyshev_eval(t: jax.Array, coeffs: jax.Array) -> jax.Array:
    """Evaluate Chebyshev series sum_k c_k T_k(t) for a coefficient matrix
    [K, F]: one cos(k*arccos t) feature map and a matmul."""
    K = coeffs.shape[0]
    tc = jnp.clip(t, -1.0, 1.0)
    theta = jnp.arccos(tc)
    feats = jnp.cos(theta[..., None] * jnp.arange(K, dtype=t.dtype))
    return feats @ coeffs


def legendre_pl(c: jax.Array, l_max: int) -> jax.Array:
    """Legendre P_l(c) for l = 0..l_max via the stable upward recurrence."""
    p0 = jnp.ones_like(c)
    outs = [p0]
    if l_max >= 1:
        outs.append(c)
        pm, pc = p0, c
        for l in range(1, l_max):
            pm, pc = pc, ((2 * l + 1) * c * pc - l * pm) / (l + 1)
            outs.append(pc)
    return jnp.stack(outs, axis=-1)


def bessel_basis_envelope(
    dist: jax.Array, cutoff: float, num_radial: int, exponent: int = 5
) -> jax.Array:
    """DimeNet radial basis: u(d/c) * sqrt(2/c) * sin(n pi d/c)
    (reference BesselBasisLayer behavior, DIMEStack.py:70)."""
    d = dist / cutoff
    d_safe = jnp.where(d < 1e-8, 1e-8, d)
    freq = jnp.arange(1, num_radial + 1, dtype=dist.dtype) * jnp.pi
    env = envelope(d_safe, exponent)
    return env[..., None] * jnp.asarray(
        np.sqrt(2.0 / cutoff), dist.dtype
    ) * jnp.sin(freq * d_safe[..., None])


def radial_bessel_jl(
    dist_scaled: jax.Array, num_spherical: int, num_radial: int
) -> jax.Array:
    """Normalized j_l(z_ln * d) for d in [0,1] -> [..., S, R] via the
    precomputed Chebyshev table."""
    coeffs = jnp.asarray(
        _radial_cheb_coeffs(num_spherical, num_radial), dist_scaled.dtype
    )
    t = 2.0 * dist_scaled - 1.0
    flat = chebyshev_eval(t, coeffs)
    return flat.reshape(dist_scaled.shape + (num_spherical, num_radial))


def spherical_basis(
    dist: jax.Array,
    angle: jax.Array,
    idx_kj: jax.Array,
    *,
    cutoff: float,
    num_spherical: int,
    num_radial: int,
    envelope_exponent: int = 5,
) -> jax.Array:
    """2-D spherical basis a_SBF(d_kj, angle) of DimeNet.

    ``dist`` is per-edge [E]; ``angle`` per-triplet [T]; ``idx_kj`` maps
    each triplet to its k->j edge. Returns [T, num_spherical*num_radial].
    """
    d = jnp.clip(dist / cutoff, 0.0, 1.0)
    radial = radial_bessel_jl(d, num_spherical, num_radial)  # [E, S, R]
    env = envelope(jnp.where(d < 1e-8, 1e-8, d), envelope_exponent)
    radial = radial * env[:, None, None]

    # Angular: Y_l^0(angle) = sqrt((2l+1)/4pi) P_l(cos angle).
    ls = jnp.arange(num_spherical, dtype=dist.dtype)
    pl = legendre_pl(jnp.cos(angle), num_spherical - 1)  # [T, S]
    cbf = pl * jnp.sqrt((2.0 * ls + 1.0) / (4.0 * jnp.pi))

    out = radial[idx_kj] * cbf[:, :, None]  # [T, S, R]
    return out.reshape(out.shape[0], num_spherical * num_radial)
