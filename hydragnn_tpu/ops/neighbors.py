"""Neighbor-list construction: radius graphs with optional PBC.

Two tiers, replacing the reference's vesin-based ``RadiusGraphPBC``
(hydragnn/preprocess/graph_samples_checks_and_updates.py:144-417) and the
per-forward ``RadiusInteractionGraph`` (hydragnn/models/SCFStack.py:129-161):

1. ``radius_graph`` / ``radius_graph_pbc`` — host-side numpy cell-list
   builders used in preprocessing (a C++ cell-list drop-in can replace the
   inner loop; see hydragnn_tpu/native).
2. ``radius_graph_jax`` — fixed-capacity O(n^2) masked builder usable
   inside jit for dynamic-graph MLIP paths (static shapes; overflow
   reported via a count the caller can check).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _native_available() -> bool:
    """Use the C++ cell-list library unless disabled via
    HYDRAGNN_TPU_NO_NATIVE=1 (numpy fallback stays authoritative for
    differential testing)."""
    if os.environ.get("HYDRAGNN_TPU_NO_NATIVE"):
        return False
    try:
        from hydragnn_tpu.native import available

        return available()
    except Exception:
        return False


def radius_graph(
    pos: np.ndarray,
    radius: float,
    *,
    max_neighbours: Optional[int] = None,
    loop: bool = False,
) -> np.ndarray:
    """All directed edges (s, r) with |pos_s - pos_r| <= radius.

    Cell-list algorithm: O(n) bins for uniform density. Returns
    edge_index [2, E] with senders in row 0, receivers in row 1. Matches
    PyG radius_graph conventions (edges point toward the receiver whose
    neighbourhood they belong to; max_neighbours caps receiver in-degree).
    """
    pos = np.asarray(pos, dtype=np.float64)
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64)
    if not loop and _native_available():
        from hydragnn_tpu.native import radius_graph_native
        from hydragnn_tpu.native.bindings import NativeUnsupported

        try:
            ei = radius_graph_native(pos, radius)
        except NativeUnsupported:
            pass
        else:
            edge_index, _ = _cap_neighbors(
                ei[0], ei[1], pos, None, max_neighbours
            )
            return edge_index
    senders, receivers, _ = _cell_list_pairs(pos, radius, loop=loop)
    edge_index, _ = _cap_neighbors(senders, receivers, pos, None, max_neighbours)
    return edge_index


def radius_graph_pbc(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    *,
    pbc: Tuple[bool, bool, bool] = (True, True, True),
    max_neighbours: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Periodic radius graph over a triclinic cell.

    Replicates the behavior of the reference's vesin-backed builder with
    mixed-PBC support (graph_samples_checks_and_updates.py:144-417):
    neighbor images within ``radius`` across periodic faces produce edges
    with integer image shifts. Returns (edge_index [2, E],
    shift_vectors [E, 3]) where shift = image @ cell, so that
    displacement = pos[s] - pos[r] + shift.
    """
    pos = np.asarray(pos, dtype=np.float64)
    cell = np.asarray(cell, dtype=np.float64).reshape(3, 3)
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64), np.zeros((0, 3))
    if _native_available():
        from hydragnn_tpu.native import radius_graph_pbc_native
        from hydragnn_tpu.native.bindings import NativeUnsupported

        try:
            ei, sh = radius_graph_pbc_native(pos, cell, radius, tuple(pbc))
        except NativeUnsupported:
            pass
        else:
            return _cap_neighbors(ei[0], ei[1], pos, sh, max_neighbours)

    # Number of periodic images needed per axis: distance between cell
    # faces must cover the cutoff.
    recip = np.linalg.inv(cell.T)
    heights = 1.0 / np.linalg.norm(recip, axis=1)
    n_images = [
        int(np.ceil(radius / h)) if p else 0 for h, p in zip(heights, pbc)
    ]

    # Wrap atoms into the primary cell for the image search, but keep the
    # per-atom wrap offsets so returned shifts stay consistent with the
    # ORIGINAL (unwrapped) positions the caller holds:
    #   pos_w[i] = pos[i] - wrap_off[i] @ cell
    #   pos_w[s] - pos_w[r] + shift == pos[s] - pos[r] + shift_adjusted
    #   where shift_adjusted = shift + (wrap_off[r] - wrap_off[s]) @ cell.
    frac = pos @ np.linalg.inv(cell)
    wrap_off = np.zeros_like(frac)
    if any(pbc):
        wrap = np.array([1.0 if p else 0.0 for p in pbc])
        wrap_off = np.floor(frac) * wrap
        pos = (frac - wrap_off) @ cell

    senders_l, receivers_l, shifts_l = [], [], []
    r2 = radius * radius
    for ix in range(-n_images[0], n_images[0] + 1):
        for iy in range(-n_images[1], n_images[1] + 1):
            for iz in range(-n_images[2], n_images[2] + 1):
                image = np.array([ix, iy, iz], dtype=np.float64)
                shift = image @ cell
                # pairwise |pos_s + shift - pos_r|^2 <= r^2
                diff = pos[None, :, :] + shift[None, None, :] - pos[:, None, :]
                d2 = np.einsum("ijk,ijk->ij", diff, diff)
                mask = d2 <= r2
                if ix == 0 and iy == 0 and iz == 0:
                    np.fill_diagonal(mask, False)
                rcv, snd = np.nonzero(mask)
                if rcv.size:
                    senders_l.append(snd)
                    receivers_l.append(rcv)
                    shifts_l.append(np.tile(shift, (rcv.size, 1)))

    if not senders_l:
        return np.zeros((2, 0), dtype=np.int64), np.zeros((0, 3))
    senders = np.concatenate(senders_l)
    receivers = np.concatenate(receivers_l)
    shifts = np.concatenate(shifts_l)
    edge_index, shifts = _cap_neighbors(
        senders, receivers, pos, shifts, max_neighbours
    )
    # Re-express shifts against the caller's unwrapped positions.
    shifts = shifts + (wrap_off[edge_index[1]] - wrap_off[edge_index[0]]) @ cell
    return edge_index, shifts


def ensure_connected(edge_index: np.ndarray, num_nodes: int) -> np.ndarray:
    """Connectivity repair: add artificial chain edges between components.

    Mirrors the reference's repair of radius graphs whose cutoff leaves
    isolated components (graph_samples_checks_and_updates.py:300-322).
    """
    parent = np.arange(num_nodes)

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for s, r in edge_index.T:
        ra, rb = find(int(s)), find(int(r))
        if ra != rb:
            parent[ra] = rb
    roots = sorted({find(i) for i in range(num_nodes)})
    if len(roots) <= 1:
        return edge_index
    extra = []
    for a, b in zip(roots[:-1], roots[1:]):
        extra.append((a, b))
        extra.append((b, a))
    extra = np.array(extra, dtype=edge_index.dtype).T
    return np.concatenate([edge_index, extra], axis=1)


def radius_graph_jax(
    pos: jax.Array,
    radius: float,
    node_graph_idx: jax.Array,
    node_mask: jax.Array,
    max_edges: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fixed-capacity radius graph inside jit.

    Dense O(n^2) pair test restricted to same-graph unmasked pairs, then a
    top-k style compaction into ``max_edges`` slots. Returns
    (senders, receivers, edge_mask, overflow_count). Intended for small-n
    MLIP graphs; production dynamics should use jax-md style cell lists.
    """
    n = pos.shape[0]
    diff = pos[None, :, :] - pos[:, None, :]
    d2 = jnp.sum(diff * diff, axis=-1)
    same_graph = node_graph_idx[None, :] == node_graph_idx[:, None]
    valid = node_mask[None, :] & node_mask[:, None]
    eye = jnp.eye(n, dtype=bool)
    adj = (d2 <= radius * radius) & same_graph & valid & (~eye)
    flat = adj.reshape(-1)
    total = jnp.sum(flat.astype(jnp.int32))
    # Stable compaction: indices of true entries first.
    order = jnp.argsort(~flat, stable=True)
    taken = order[:max_edges]
    edge_mask = flat[taken]
    rcv = taken // n
    snd = taken % n
    pad_node = n - 1
    snd = jnp.where(edge_mask, snd, pad_node).astype(jnp.int32)
    rcv = jnp.where(edge_mask, rcv, pad_node).astype(jnp.int32)
    overflow = jnp.maximum(total - max_edges, 0)
    return snd, rcv, edge_mask, overflow


# ----------------------------------------------------------------------


def _cell_list_pairs(pos: np.ndarray, radius: float, *, loop: bool):
    """Binned pair search; returns (senders, receivers, d2) numpy arrays."""
    n = pos.shape[0]
    lo = pos.min(axis=0)
    cell_size = max(radius, 1e-12)
    bins = np.floor((pos - lo) / cell_size).astype(np.int64)
    nbins = bins.max(axis=0) + 1
    key = (bins[:, 0] * nbins[1] + bins[:, 1]) * nbins[2] + bins[:, 2]
    order = np.argsort(key, kind="stable")
    sorted_key = key[order]
    # For each atom, gather candidates from the 27 neighboring bins.
    senders_l, receivers_l, d2_l = [], [], []
    uniq, starts = np.unique(sorted_key, return_index=True)
    starts = np.append(starts, n)
    bin_of = {int(k): i for i, k in enumerate(uniq)}
    r2 = radius * radius
    offsets = [
        (dx, dy, dz)
        for dx in (-1, 0, 1)
        for dy in (-1, 0, 1)
        for dz in (-1, 0, 1)
    ]
    for bi, k in enumerate(uniq):
        members = order[starts[bi] : starts[bi + 1]]
        kz = int(k) % nbins[2]
        ky = (int(k) // nbins[2]) % nbins[1]
        kx = int(k) // (nbins[1] * nbins[2])
        cand = []
        for dx, dy, dz in offsets:
            nx, ny, nz = kx + dx, ky + dy, kz + dz
            if not (0 <= nx < nbins[0] and 0 <= ny < nbins[1] and 0 <= nz < nbins[2]):
                continue
            nk = (nx * nbins[1] + ny) * nbins[2] + nz
            bj = bin_of.get(int(nk))
            if bj is not None:
                cand.append(order[starts[bj] : starts[bj + 1]])
        cand = np.concatenate(cand)
        diff = pos[cand][None, :, :] - pos[members][:, None, :]
        d2 = np.einsum("ijk,ijk->ij", diff, diff)
        mask = d2 <= r2
        if not loop:
            mask &= members[:, None] != cand[None, :]
        ri, ci = np.nonzero(mask)
        if ri.size:
            receivers_l.append(members[ri])
            senders_l.append(cand[ci])
            d2_l.append(d2[ri, ci])
    if not senders_l:
        z = np.zeros(0, dtype=np.int64)
        return z, z, np.zeros(0)
    return (
        np.concatenate(senders_l),
        np.concatenate(receivers_l),
        np.concatenate(d2_l),
    )


def _cap_neighbors(senders, receivers, pos, shifts, max_neighbours):
    """Keep at most max_neighbours nearest senders per receiver.

    Always returns (edge_index [2, E], shifts_or_None).
    """
    if max_neighbours is None:
        return np.stack([senders, receivers]).astype(np.int64), shifts
    if shifts is None:
        d2 = np.sum((pos[senders] - pos[receivers]) ** 2, axis=1)
    else:
        d2 = np.sum((pos[senders] + shifts - pos[receivers]) ** 2, axis=1)
    keep = np.zeros(senders.shape[0], dtype=bool)
    for r in np.unique(receivers):
        idx = np.nonzero(receivers == r)[0]
        if idx.size > max_neighbours:
            idx = idx[np.argsort(d2[idx], kind="stable")[:max_neighbours]]
        keep[idx] = True
    edge_index = np.stack([senders[keep], receivers[keep]]).astype(np.int64)
    return edge_index, None if shifts is None else shifts[keep]
