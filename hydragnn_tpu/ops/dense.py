"""Masked dense (padded) per-graph layouts.

TPU-native replacement for PyG's ``to_dense_batch`` (used by GPS global
attention, reference hydragnn/globalAtt/gps.py:126-131): scatter the flat
node array into a [G, S, F] dense tensor using the precomputed
``node_slot`` index (computed host-side at collation, so no device-side
sorting is needed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.graph import GraphBatch


def to_dense_batch(
    x: jax.Array, batch: GraphBatch, max_nodes: int
) -> tuple[jax.Array, jax.Array]:
    """Flat [N, F] -> dense [G, S, F] plus validity mask [G, S].

    ``max_nodes`` (S) must be a static bound on nodes-per-graph for the
    current bucket; slots beyond a graph's size are zero/masked.
    """
    g = batch.num_graphs
    # Nodes whose slot exceeds the bound are dropped (never corrupt other
    # slots); the runner validates the bound host-side before training.
    slot = batch.node_slot
    dense = jnp.zeros((g, max_nodes) + x.shape[1:], dtype=x.dtype)
    contrib = jnp.where(
        batch.node_mask.reshape((-1,) + (1,) * (x.ndim - 1)), x, 0
    )
    dense = dense.at[batch.node_graph_idx, slot].set(contrib, mode="drop")
    mask = jnp.zeros((g, max_nodes), dtype=bool)
    mask = mask.at[batch.node_graph_idx, slot].set(batch.node_mask, mode="drop")
    return dense, mask


def from_dense_batch(
    dense: jax.Array, batch: GraphBatch, max_nodes: int
) -> jax.Array:
    """Inverse of to_dense_batch: gather dense [G, S, F] back to [N, F].

    Nodes beyond the ``max_nodes`` bound read zero (they were dropped by
    the scatter), matching to_dense_batch.
    """
    slot = jnp.minimum(batch.node_slot, max_nodes - 1)
    flat = dense[batch.node_graph_idx, slot]
    valid = batch.node_mask & (batch.node_slot < max_nodes)
    return jnp.where(
        valid.reshape((-1,) + (1,) * (flat.ndim - 1)), flat, 0
    )
