"""Routing front for the fleet serving tier (docs/SERVING.md "Fleet
tier").

The ``Router`` sits between every frontend and the N engine replicas:
one ``submit`` picks a replica under a dispatch policy, enforces the
per-replica queue bound, and applies the deadline-class degradation
policy when the tier is overloaded. It owns NO thread — submit runs on
the caller's thread and never blocks (graftlint NEVER_BLOCK_SEEDS
covers it), and every mutation it makes on a replica goes through the
handle's own atomic ``submit_inner`` so a concurrent rollover swap can
never catch a request between generations.

**Dispatch policies.**

- ``least_loaded``: the live replica with the smallest queue depth
  (queued + open-bin requests), lowest index on ties — the baseline
  that keeps tail latency flat under a uniform request mix.
- ``spec_affinity``: the request's SMALLEST fitting pack budget picks
  a home replica (budget rank modulo live replicas). A skewed size
  histogram then lands big-budget requests on the replica whose
  big-budget executable stays warm and whose bins fill with same-class
  co-tenants, instead of salting every replica's bins with occasional
  giants that force the big shape everywhere. Falls back to
  least-loaded when the home replica is saturated — affinity buys bin
  locality, never a parked request.

**Deadline classes and load shedding.** Requests carry a deadline
class (0 = best-effort batch, 1 = standard, 2 = interactive). Overload
is read per replica from two signals: queue depth against
``queue_bound``, and the batcher's OLDEST open-bin deadline anchor —
when that anchor has aged past twice the dispatch deadline, bins are
expiring faster than the engine drains them, the leading edge of a p99
collapse. The router sheds lowest-class-first: pressure level 1 sheds
class 0, level 2 (twice the bound) sheds class <= 1, level 3 (four
times the bound — the hard wall) sheds everything. Every shed is
COUNTED and emitted as a machine-readable ``shed`` row (never a silent
drop): the request handle comes back marked ``shed`` with the reason,
and ``shed_report()`` reconciles submitted == routed + shed.

**Dead-replica re-route.** When the tier's health monitor declares a
replica dead (heartbeat gap / pump thread death), ``reroute`` recovers
its unfinished requests and re-submits them through the same policy
path; requests whose class budget already expired while the corpse
held them are shed as ``expired`` rather than served uselessly late.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from hydragnn_tpu.data.graph import GraphSample, PackSpec
from hydragnn_tpu.utils import telemetry

# Deadline classes (docs/SERVING.md "Deadline classes"): higher =
# more latency-critical = shed later. The tuple order IS the shed
# order.
DEADLINE_CLASSES = {"batch": 0, "standard": 1, "interactive": 2}

ROUTER_POLICIES = ("least_loaded", "spec_affinity")


class FleetRequest:
    """One request as the ROUTER sees it. Unlike a ``ServeRequest``
    (pinned to one batcher for life), a fleet request survives its
    replica: a re-route after replica death re-submits the sample on a
    live replica and swaps ``inner`` to the new incarnation — the
    response surface (``result``/``latency_ms``) always proxies the
    one that actually served. ``shed`` requests never get a result but
    are never silent either: ``shed_reason`` says why, and the
    router's counters carry them."""

    __slots__ = (
        "sample",
        "fleet_id",
        "deadline_class",
        "t_submit",
        "replica",
        "inner",
        "shed",
        "shed_reason",
        "reroutes",
    )

    def __init__(
        self,
        sample: GraphSample,
        fleet_id: int,
        deadline_class: int,
        t_submit: float,
    ):
        self.sample = sample
        self.fleet_id = int(fleet_id)
        self.deadline_class = int(deadline_class)
        self.t_submit = float(t_submit)
        self.replica: Optional[int] = None
        self.inner = None  # ServeRequest on the current replica
        self.shed = False
        self.shed_reason: Optional[str] = None
        self.reroutes = 0

    @property
    def result(self):
        return None if self.inner is None else self.inner.result

    @property
    def latency_ms(self) -> Optional[float]:
        """End-to-end latency from the ROUTER's submit stamp — a
        re-routed request pays for its dead first replica too (that is
        the latency the client saw)."""
        if self.inner is None or self.inner.t_done is None:
            return None
        return round(1e3 * (self.inner.t_done - self.t_submit), 4)

    @property
    def done(self) -> bool:
        return self.shed or (
            self.inner is not None and self.inner.result is not None
        )


class Router:
    """Dispatch front over replica handles (module docstring).

    A replica handle is duck-typed (``ServingTier``'s ``ReplicaHandle``
    in production, fakes in tests): ``index``, ``alive``, ``qsize()``,
    ``oldest_anchor_age_s()``, ``deadline_s``, ``submit_inner(sample,
    deadline_class)`` (atomic vs rollover swap), ``track(fr)``,
    ``recover_pending()``.

    ``class_budgets_ms[c]`` is class c's end-to-end latency budget
    (None = best-effort, never expires); it only gates the EXPIRED
    shed on re-route — a request that already missed its budget inside
    a dead replica is shed, not served uselessly late.
    """

    def __init__(
        self,
        replicas: Sequence,
        budgets: Sequence[PackSpec],
        *,
        policy: str = "least_loaded",
        queue_bound: int = 64,
        class_budgets_ms: Sequence[Optional[float]] = (None, None, None),
        clock=time.monotonic,
        emit=None,
    ):
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown routing policy {policy!r}; choose from "
                f"{ROUTER_POLICIES}"
            )
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("Router needs at least one replica")
        self.policy = policy
        self.queue_bound = max(1, int(queue_bound))
        # Budgets sorted biggest-first, matching PackPlanner's order,
        # so rank 0 = the big shape and the LAST fitting rank is the
        # smallest budget that holds a request.
        self._budgets = sorted(
            budgets,
            key=lambda b: (b.num_nodes, b.num_edges),
            reverse=True,
        )
        if not self._budgets:
            raise ValueError("Router needs at least one pack budget")
        self.class_budgets_ms = tuple(
            None if v is None else float(v) for v in class_budgets_ms
        )
        self.clock = clock
        self._emit_fn = emit if emit is not None else telemetry.emit
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.submitted = 0
        self.routed = 0
        self.reroutes = 0
        self.sheds: Dict[Tuple[str, int], int] = {}

    # -- policy --------------------------------------------------------

    def _live(self) -> List:
        live = [r for r in self.replicas if r.alive]
        if not live:
            raise RuntimeError(
                "no live replicas — the whole tier is down "
                "(dead-replica re-route only covers partial failures); "
                "restart the tier"
            )
        return live

    def budget_rank(self, sample: GraphSample) -> int:
        """Rank of the smallest budget this request can SHARE (0 = the
        big shape) — the spec-affinity key. A graph qualifies for a
        budget only when it fits with room for co-tenants (at most
        half the budget's node/edge capacity): a giant that would
        monopolize every smaller budget is a big-budget request and
        ranks 0, so a skewed histogram concentrates big-budget bins on
        the big-budget home replica instead of salting every replica
        with occasional giants. Oversize requests rank 0 too; the
        batcher's own fits() check is the loud front door for those."""
        n, e = sample.num_nodes, sample.num_edges
        rank = 0
        for i, b in enumerate(self._budgets):
            if (
                b.fits(n, e, 1)
                and 2 * n <= b.num_nodes
                and 2 * e <= b.num_edges
            ):
                rank = i
        return rank

    def pressure(self, r) -> int:
        """One replica's overload level: 0 nominal; 1 when the queue
        depth reaches ``queue_bound`` OR the batcher's oldest open-bin
        anchor has aged past twice its dispatch deadline (the engine
        is behind its own deadline trigger); 2 at twice the bound; 3
        at four times (the hard wall — even interactive requests shed
        there rather than queue into certain deadline misses)."""
        depth = r.qsize()
        if depth >= 4 * self.queue_bound:
            return 3
        if depth >= 2 * self.queue_bound:
            return 2
        if depth >= self.queue_bound:
            return 1
        if r.oldest_anchor_age_s() > 2.0 * r.deadline_s:
            return 1
        return 0

    def _pick(self, sample: GraphSample, live: List):
        if self.policy == "spec_affinity":
            home = live[self.budget_rank(sample) % len(live)]
            if home.qsize() < self.queue_bound:
                return home
        return min(live, key=lambda r: (r.qsize(), r.index))

    # -- the request hot path ------------------------------------------

    def submit(
        self, sample: GraphSample, *, deadline_class: int = 1
    ) -> FleetRequest:
        """Route one request; returns its fleet handle. Never blocks:
        policy arithmetic + one atomic batcher put. Under overload the
        request may come back ``shed`` (counted, reasoned) instead of
        routed — the caller ALWAYS gets the handle back, silence is
        not an outcome."""
        fr = FleetRequest(
            sample, next(self._ids), deadline_class, self.clock()
        )
        with self._lock:
            self.submitted += 1
        live = self._live()
        r = self._pick(sample, live)
        if self.pressure(r) > fr.deadline_class:
            # The policy's choice is overloaded for this class — try
            # the globally least-loaded escape hatch before shedding
            # (affinity must degrade to balance, not to drops).
            alt = min(live, key=lambda x: (x.qsize(), x.index))
            if self.pressure(alt) > fr.deadline_class:
                return self._shed(fr, alt, "overload")
            r = alt
        self._route(fr, r)
        return fr

    def _route(self, fr: FleetRequest, r) -> None:
        fr.replica = r.index
        fr.inner = r.submit_inner(fr.sample, fr.deadline_class)
        r.track(fr)
        with self._lock:
            self.routed += 1

    def _shed(self, fr: FleetRequest, r, reason: str) -> FleetRequest:
        fr.shed = True
        fr.shed_reason = reason
        key = (reason, fr.deadline_class)
        with self._lock:
            self.sheds[key] = self.sheds.get(key, 0) + 1
        self._emit_fn(
            {
                "t": "shed",
                "reason": reason,
                "class": fr.deadline_class,
                "fleet_id": fr.fleet_id,
                "replica": None if r is None else r.index,
                "queue_depth": None if r is None else r.qsize(),
            }
        )
        return fr

    # -- failure handling ----------------------------------------------

    def class_budget_ms(self, deadline_class: int) -> Optional[float]:
        c = int(deadline_class)
        if 0 <= c < len(self.class_budgets_ms):
            return self.class_budgets_ms[c]
        return None

    def reroute(self, dead) -> dict:
        """Recover a dead replica's unfinished requests and re-route
        them through the normal policy path (their shed protections
        included). Requests whose class budget already expired in the
        corpse are shed as ``expired`` — serving them late would only
        push live requests' p99 up. Returns (and emits) the
        machine-readable ``reroute`` accounting row. The caller must
        have stopped the dead replica's dispatch loop first — recovery
        reads batcher state the loop owns when alive."""
        pending = dead.recover_pending()
        now = self.clock()
        moved = expired = 0
        for fr in pending:
            budget = self.class_budget_ms(fr.deadline_class)
            if budget is not None and 1e3 * (now - fr.t_submit) > budget:
                self._shed(fr, None, "expired")
                expired += 1
                continue
            fr.reroutes += 1
            live = [r for r in self.replicas if r.alive]
            if not live:
                self._shed(fr, None, "no_live_replica")
                continue
            self._route(fr, self._pick(fr.sample, live))
            moved += 1
        with self._lock:
            self.reroutes += moved
        row = {
            "t": "reroute",
            "from_replica": dead.index,
            "recovered": len(pending),
            "moved": moved,
            "shed_expired": expired,
        }
        self._emit_fn(row)
        return row

    # -- accounting ----------------------------------------------------

    def shed_report(self) -> dict:
        """Machine-readable shed/route accounting: ``submitted ==
        routed_first + shed_at_submit`` (re-routes and their expiry
        sheds are additive on top). The bench/drill gates read THIS,
        not log lines."""
        with self._lock:
            by_class: Dict[str, int] = {}
            by_reason: Dict[str, int] = {}
            for (reason, cls), n in self.sheds.items():
                by_reason[reason] = by_reason.get(reason, 0) + n
                by_class[str(cls)] = by_class.get(str(cls), 0) + n
            return {
                "submitted": self.submitted,
                "routed": self.routed,
                "routed_first": self.routed - self.reroutes,
                "reroutes": self.reroutes,
                "shed_total": sum(self.sheds.values()),
                "shed_by_class": by_class,
                "shed_by_reason": by_reason,
            }
