"""Online serving: deadline-driven dynamic batching into AOT-compiled
pack shapes (docs/SERVING.md).

The request-level complement of the offline training/eval entry points:
``DynamicBatcher`` first-fit-fills incoming graphs into the fitted
``PackSpec`` budget shapes under a latency deadline (the same
``PackPlanner`` core the epoch packer drives), ``ServingEngine`` runs a
small fixed set of startup-warmed AOT executables over the dispatched
bins with double-buffered H2D, and the admission gate refuses to serve
a snapshot containing non-finite weights.
"""

from hydragnn_tpu.serve.admission import AdmissionError, admit_state
from hydragnn_tpu.serve.batcher import DynamicBatcher, ServeRequest
from hydragnn_tpu.serve.engine import (
    ServingEngine,
    ServingSettings,
    serving_settings,
)

__all__ = [
    "AdmissionError",
    "admit_state",
    "DynamicBatcher",
    "ServeRequest",
    "ServingEngine",
    "ServingSettings",
    "serving_settings",
]
