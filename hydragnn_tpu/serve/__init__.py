"""Online serving: deadline-driven dynamic batching into AOT-compiled
pack shapes (docs/SERVING.md).

The request-level complement of the offline training/eval entry points:
``DynamicBatcher`` first-fit-fills incoming graphs into the fitted
``PackSpec`` budget shapes under a latency deadline (the same
``PackPlanner`` core the epoch packer drives), ``ServingEngine`` runs a
small fixed set of startup-warmed AOT executables over the dispatched
bins with double-buffered H2D, and the admission gate refuses to serve
a snapshot containing non-finite weights.

The FLEET tier (docs/SERVING.md "Fleet tier") is the production shape:
``ServingTier`` replicates N engines behind a ``Router`` with
least-loaded / spec-affinity dispatch, deadline-class load shedding,
zero-downtime snapshot rollover, and dead-replica re-route over the
fleet-telemetry substrate.
"""

from hydragnn_tpu.serve.admission import AdmissionError, admit_state
from hydragnn_tpu.serve.batcher import DynamicBatcher, ServeRequest
from hydragnn_tpu.serve.engine import (
    ServingEngine,
    ServingSettings,
    serving_settings,
)
from hydragnn_tpu.serve.fleet import (
    FleetSettings,
    ReplicaHandle,
    ServingTier,
    fleet_settings,
)
from hydragnn_tpu.serve.router import (
    DEADLINE_CLASSES,
    ROUTER_POLICIES,
    FleetRequest,
    Router,
)

__all__ = [
    "AdmissionError",
    "admit_state",
    "DynamicBatcher",
    "ServeRequest",
    "ServingEngine",
    "ServingSettings",
    "serving_settings",
    "DEADLINE_CLASSES",
    "ROUTER_POLICIES",
    "FleetRequest",
    "Router",
    "FleetSettings",
    "ReplicaHandle",
    "ServingTier",
    "fleet_settings",
]
