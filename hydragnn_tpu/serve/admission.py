"""Serving admission gate: a model snapshot is never served unverified.

The checkpoint writer's validate-finite gate (utils/checkpoint.py,
docs/DURABILITY.md "Divergence recovery") keeps a diverged run from
PUBLISHING corruption; this is the same scan generalized to the load
side — a snapshot that reached disk through an older run, a foreign
tool, or a gate-disabled writer is still refused at serving time, with
the offending leaves NAMED so the operator knows what to do instead of
staring at NaN predictions.
"""

from __future__ import annotations

import jax

from hydragnn_tpu.utils.checkpoint import nonfinite_leaves


class AdmissionError(RuntimeError):
    """A snapshot failed the serving admission gate (non-finite
    weights). Message lists the offending leaves and the recovery
    path."""


def admit_state(state, *, source: str = "snapshot") -> dict:
    """Gate a TrainState (or any params/batch_stats pytree) for
    serving: every floating leaf must be finite, or the engine refuses
    to warm a single executable. Returns ``{"leaves": n, "host":
    tree}`` on admission — the host-materialized tree rides along so
    the caller (the engine bakes host weights into its executables)
    never pays the D2H transfer twice.

    The scan materializes the tree on host once — admission runs at
    snapshot-load time, never on the request path.
    """
    host = jax.device_get(state)
    bad = nonfinite_leaves(host)
    if bad:
        detail = ", ".join(
            f"{path} ({n_bad}/{size} non-finite)"
            for path, n_bad, size in bad[:8]
        )
        more = len(bad) - min(len(bad), 8)
        raise AdmissionError(
            f"REFUSING to serve {source}: {len(bad)} leaf/leaves "
            f"contain NaN/Inf — {detail}"
            + (f" (+{more} more)" if more > 0 else "")
            + ". A diverged or corrupted checkpoint must never reach "
            "traffic. Recover the last good snapshot (the writer's "
            "validate-finite gate keeps 'latest' clean — "
            "docs/DURABILITY.md) or retrain; see docs/SERVING.md "
            "\"Admission\"."
        )
    return {
        "leaves": len(jax.tree_util.tree_leaves(host)),
        "host": host,
    }
