"""Deadline-driven dynamic batching into pack-budget shapes.

The online half of the bin-packed batch former (docs/PACKING.md):
where the epoch packer feeds ``PackPlanner`` a full shuffled epoch in
FFD order, the ``DynamicBatcher`` feeds it requests AS THEY ARRIVE —
first-fit into open bins under the largest fitted budget — and decides
WHEN a bin stops waiting for co-tenants:

- **full**: the bin has no graph slot left — nothing more can join;
- **pressure**: more than ``max_open_bins`` bins are open, so the
  planner froze the fullest out of the scan — capacity pressure says
  it will not fill further;
- **deadline**: the bin's OLDEST request has waited ``deadline_ms`` —
  a partially-filled bin dispatches rather than holding a response
  hostage to hypothetical future co-tenants. This is the tail-latency
  contract: batching can add at most the deadline to any request's
  queue wait.

Dispatched bins are downshifted to the smallest fitted budget that
holds them (``PackPlanner.assign_budget``), so the compiled-shape set
the engine warms at startup is exactly the budget set regardless of
traffic.

Pull-driven on purpose: ``next_bin`` is called by the engine's
dispatch loop (one consumer), while ``submit`` is thread-safe for any
number of frontends. The batcher itself never touches the device —
graftlint's host-sync rule seeds this hot path (a stray ``.item()``
here would fence every dispatch).
"""

from __future__ import annotations

import itertools
import queue
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

from hydragnn_tpu.data.graph import GraphSample, PackSpec
from hydragnn_tpu.data.padschedule import OpenBin, PackPlanner


class ServeRequest:
    """One in-flight request: the sample, its enqueue timestamp (the
    latency zero point), its deadline class (0 = best-effort batch, 1 =
    standard, 2 = interactive — the fleet router's shed ordering,
    docs/SERVING.md "Deadline classes"; a bare batcher ignores it), and
    the slots the engine fills at response time. Plain attributes, no
    locking — a request is owned by the submitting thread until
    ``submit`` and by the dispatch loop after."""

    __slots__ = (
        "sample",
        "req_id",
        "t_enqueue",
        "deadline_class",
        "result",
        "t_done",
        "latency_ms",
    )

    def __init__(
        self,
        sample: GraphSample,
        req_id: int,
        t_enqueue: float,
        deadline_class: int = 1,
    ):
        self.sample = sample
        self.req_id = int(req_id)
        self.t_enqueue = float(t_enqueue)
        self.deadline_class = int(deadline_class)
        self.result = None
        self.t_done: Optional[float] = None
        self.latency_ms: Optional[float] = None


class DynamicBatcher:
    """FFD-fill incoming graphs into ``PackSpec`` budget bins under a
    latency deadline (module docstring has the dispatch triggers).

    ``clock`` is injectable (tests drive deadlines deterministically
    with a fake clock); production uses ``time.monotonic``.
    """

    def __init__(
        self,
        budgets: Sequence[PackSpec],
        *,
        deadline_ms: float = 25.0,
        max_open_bins: int = 4,
        clock=time.monotonic,
    ):
        self.planner = PackPlanner(budgets, open_window=max_open_bins)
        self.deadline_s = max(float(deadline_ms), 0.0) / 1e3
        self.clock = clock
        self._q: "queue.Queue[ServeRequest]" = queue.Queue()
        self._ready: deque = deque()  # (reason, OpenBin)
        self._ids = itertools.count()
        self._closed = False

    # -- frontend side -------------------------------------------------

    def submit(
        self, sample: GraphSample, *, deadline_class: int = 1
    ) -> ServeRequest:
        """Enqueue one graph; returns its request handle (the engine
        fills ``result``/``latency_ms``). Thread-safe; never blocks.
        Raises when the graph exceeds the largest budget — an
        unservable request must fail at the door, not poison a bin.
        ``deadline_class`` is carried for the fleet router's shed
        accounting; the batcher itself batches all classes alike."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        if not self.planner.fits(sample.num_nodes, sample.num_edges):
            raise ValueError(
                f"request ({sample.num_nodes} nodes, "
                f"{sample.num_edges} edges) exceeds the largest pack "
                f"budget {self.planner.big} — refit budgets "
                "(fit_pack_budgets) over a histogram that covers it"
            )
        req = ServeRequest(
            sample, next(self._ids), self.clock(), deadline_class
        )
        # put_nowait, structurally: the queue is unbounded today, but
        # the never-blocks contract must survive someone adding a
        # maxsize — overflow policy is the front door's fits() check,
        # never a parked frontend thread.
        self._q.put_nowait(req)
        return req

    def close(self) -> None:
        """No further submits; ``next_bin`` drains what remains and
        then returns None."""
        self._closed = True

    def qsize(self) -> int:
        """Undispatched requests (queued + sitting in open bins) — the
        live queue-depth gauge the serve telemetry rows carry."""
        return self._q.qsize() + sum(
            len(b.tags) for b in self.planner.open_bins
        )

    def oldest_anchor_age_s(self, now: Optional[float] = None) -> float:
        """Age of the OLDEST open-bin deadline anchor (``b.meta["t0"]``
        — the same timestamp the deadline trigger fires on). When this
        exceeds the dispatch deadline the engine is falling behind its
        own deadline trigger: bins are expiring faster than they
        dispatch, the leading edge of a p99 collapse. The fleet
        router's shed policy keys off it (docs/SERVING.md "Load
        shedding"). 0.0 with no open bins. Read-only and safe from the
        frontend thread: the snapshot copy tolerates the dispatch
        loop's concurrent placement."""
        anchors = [
            b.meta["t0"]
            for b in list(self.planner.open_bins)
            if "t0" in b.meta
        ]
        if not anchors:
            return 0.0
        t = self.clock() if now is None else float(now)
        return max(t - min(anchors), 0.0)

    # -- dispatch side (single consumer: the engine loop) --------------

    def _place(self, req: ServeRequest) -> None:
        b = self.planner.add(
            req, req.sample.num_nodes, req.sample.num_edges
        )
        # The deadline anchors at the bin's OLDEST member: requests are
        # placed in arrival order, so the first placement stamps it.
        if "t0" not in b.meta:
            b.meta["t0"] = req.t_enqueue
        if b.graph_room == 0:
            self.planner.pop(b)
            self._ready.append(("full", b))
        for fb in self.planner.take_frozen():
            self._ready.append(("pressure", fb))

    def _earliest_expiry(self) -> Optional[Tuple[float, OpenBin]]:
        best = None
        for b in self.planner.open_bins:
            t = b.meta["t0"] + self.deadline_s
            if best is None or t < best[0]:
                best = (t, b)
        return best

    def next_bin(
        self, timeout: Optional[float] = None
    ) -> Optional[Tuple[str, OpenBin]]:
        """Block until a bin is dispatchable and return ``(reason,
        bin)``; reasons per the module docstring. ``timeout`` bounds
        the wait when no deadline is pending (None = wait for traffic
        indefinitely unless closed). Returns None when the wait ran
        out with nothing to dispatch — or, after ``close()``, when
        everything has drained (remaining bins flush as
        ``"flush"``)."""
        t_give_up = None if timeout is None else self.clock() + float(timeout)
        while True:
            # Pull whatever has arrived into the planner first: a
            # ready bin must reflect every request that beat it here.
            while True:
                try:
                    self._place(self._q.get_nowait())
                except queue.Empty:
                    break
            if self._ready:
                return self._ready.popleft()
            now = self.clock()
            expiry = self._earliest_expiry()
            if expiry is not None and expiry[0] <= now:
                self.planner.pop(expiry[1])
                return "deadline", expiry[1]
            if self._closed and self._q.empty():
                for b in self.planner.drain():
                    self._ready.append(("flush", b))
                if self._ready:
                    return self._ready.popleft()
                return None
            waits = [
                t
                for t in (
                    None if expiry is None else expiry[0],
                    t_give_up,
                )
                if t is not None
            ]
            try:
                # No pending deadline and no caller bound: poll at a
                # coarse 50ms so a concurrent close() stays responsive
                # (the engine always passes a timeout; this is the
                # bare-batcher fallback).
                req = self._q.get(
                    timeout=(
                        max(min(waits) - now, 0.0) if waits else 0.05
                    )
                )
            except queue.Empty:
                if t_give_up is not None and self.clock() >= t_give_up:
                    return None  # caller's wait bound wins
                continue
            self._place(req)

    def bin_spec(self, b: OpenBin) -> PackSpec:
        """The dispatched bin's budget: smallest fitted shape holding
        its totals — identical downshift arithmetic to the epoch
        packer, so serving compiles exactly the budget set."""
        return self.planner.assign_budget(
            b.tot_nodes, b.tot_edges, len(b.tags)
        )

    def bin_requests(self, b: OpenBin) -> List[ServeRequest]:
        """Members in arrival order (tag insertion order)."""
        return list(b.tags)
